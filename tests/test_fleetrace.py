"""Fleet trace capture (ISSUE 9, tpusched/obs/fleetrace.py): watch-boundary
event capture into crash-safe rotating JSONL segments.

Covers the capture contract end to end: event kinds and dual stamps, the
bind-commit/bind-decision pair, segment rotation + WAL-style compaction
(fresh snapshot at the head of the surviving segment), crash recovery (a
torn tail segment is tolerated on read, capture resumes into a FRESH
segment), the bounded-queue shed-don't-block discipline under a concurrent
scrape soak (the test_obs_bounds mirror), the /debug/fleetrace endpoint,
and shadow isolation (a telemetry=False scheduler's binds are never
journaled).
"""
from __future__ import annotations

import json
import os
import threading
import time
import urllib.request

from tpusched import obs
from tpusched.api.resources import TPU, make_resources
from tpusched.apiserver import APIServer
from tpusched.apiserver import server as srv
from tpusched.config.profiles import tpu_gang_profile
from tpusched.obs import fleetrace
from tpusched.obs.fleetrace import (FleetTraceRecorder, load_trace,
                                    read_all, read_records)
from tpusched.testing import (TestCluster, make_node, make_pod,
                              make_pod_group, make_tpu_pool)


def _segment_files(directory):
    return sorted(f for f in os.listdir(directory)
                  if f.startswith("fleet-") and f.endswith(".jsonl"))


# -- capture end to end -------------------------------------------------------


def test_capture_records_cluster_events_with_dual_stamps(tmp_path):
    # arm the PROCESS-GLOBAL recorder: that is the instance a live
    # scheduler holds, so bind-decision attribution lands in the trace
    rec = obs.default_fleetrecorder()
    assert not rec.enabled
    with TestCluster(profile=tpu_gang_profile(permit_wait_s=10,
                                              denied_s=1)) as c:
        topo, nodes = make_tpu_pool("pool-0", dims=(4, 4, 4))
        c.api.create(srv.TPU_TOPOLOGIES, topo)
        c.add_nodes(nodes)
        rec.attach(c.api, str(tmp_path))
        assert rec.enabled

        c.api.create(srv.POD_GROUPS, make_pod_group(
            "g0", min_member=2, tpu_slice_shape="2x2x1",
            tpu_accelerator="tpu-v5p"))
        pods = [make_pod(f"g0-{i}", pod_group="g0", limits={TPU: 2},
                         requests=make_resources(cpu=1, memory="1Gi"))
                for i in range(2)]
        c.create_pods(pods)
        assert c.wait_for_pods_scheduled([p.key for p in pods], timeout=15)
        # node health transition + quota change + deletes
        node = c.api.get(srv.NODES, nodes[0].meta.key)
        node.spec.unschedulable = True
        c.api.update(srv.NODES, node)
        from tpusched.testing import make_elastic_quota
        eq = make_elastic_quota("team-a", "default",
                                min={TPU: 4}, max={TPU: 8})
        c.api.create(srv.ELASTIC_QUOTAS, eq)
        c.api.delete(srv.PODS, pods[0].key)
        rec.flush()
        rec.detach()
        assert not rec.enabled

    trace = load_trace(str(tmp_path))
    by_kind = trace.events_by_kind()
    assert by_kind["pod-arrival"] == 2
    assert by_kind["bind-commit"] == 2
    assert by_kind["bind-decision"] == 2
    assert by_kind["podgroup-add"] == 1
    assert by_kind["node-health"] == 1
    assert by_kind["quota-add"] == 1
    assert by_kind["pod-delete"] == 1
    # snapshot carries the fleet that existed at attach
    assert len(trace.objects[srv.NODES]) == len(nodes)
    assert len(trace.objects[srv.TPU_TOPOLOGIES]) == 1

    # every event dual-stamped, stamps monotone in capture order
    monos = [e["mono"] for e in trace.events]
    assert all("wall" in e for e in trace.events)
    assert monos == sorted(monos)

    # arrivals carry the FULL spec + gang membership; commits the node
    arrival = trace.arrivals()[0]
    assert arrival["gang"] == "default/g0"
    assert arrival["object"]["spec"]["containers"]
    binds = dict(trace.recorded_binds())
    assert set(binds) == {p.key for p in pods}
    decision = trace.bind_decisions()[pods[0].key]
    assert decision["scheduler"] == "tpusched"
    assert decision["gang"] == "default/g0"
    assert decision["e2e_s"] >= 0
    assert decision["attempts"] >= 1
    # decision and commit agree on the placement
    assert decision["node"] == binds[pods[0].key]


def test_shadow_scheduler_binds_never_reach_an_armed_recorder(tmp_path):
    """A telemetry=False scheduler holds a private DISARMED recorder: its
    trial binds must not be journaled even while the process-global
    recorder is armed on the same API server."""
    from tpusched.plugins import default_registry
    from tpusched.sched import Scheduler
    api = APIServer()
    cap = make_resources(cpu=64, memory="256Gi")
    cap[TPU] = 8
    api.create(srv.NODES, make_node("n-0", capacity=cap))
    rec = FleetTraceRecorder()
    rec.attach(api, str(tmp_path))
    try:
        shadow = Scheduler(api, default_registry(),
                           tpu_gang_profile(permit_wait_s=5, denied_s=1),
                           telemetry=False)
        assert not shadow._fleet.enabled
        shadow._fleet.record_bind_decision("default/x", "n-0")
    finally:
        rec.flush()
        rec.detach()
    kinds = [r.get("kind") for r in read_records(str(tmp_path))]
    assert "bind-decision" not in kinds


# -- segments: rotation, compaction, crash recovery ---------------------------


def test_segment_rotation_and_compaction_keep_directory_bounded(tmp_path):
    """WAL-style compaction: over the segment budget, the new segment
    opens with a FRESH state snapshot and older segments are deleted — so
    the directory stays bounded AND replayable: snapshot + retained
    events still cover every live object."""
    api = APIServer()
    rec = FleetTraceRecorder()
    rec.attach(api, str(tmp_path), segment_bytes=96 * 1024, max_segments=3)
    all_keys = set()
    try:
        for i in range(2500):
            p = make_pod(f"p-{i:05d}")
            all_keys.add(p.key)
            api.create(srv.PODS, p)
        assert rec.flush(60)
    finally:
        rec.detach()
    segs = _segment_files(str(tmp_path))
    # rotation happened AND compaction deleted the oldest segments
    assert len(segs) >= 2
    assert segs[0] != "fleet-00000001.jsonl"
    trace = load_trace(str(tmp_path))
    assert trace.segments == len(segs)
    # replayable from the oldest retained byte: last snapshot + events
    # after it still describe every pod ever created (none were deleted)
    covered = {o.meta.key for o in trace.objects[srv.PODS]} \
        | {e["pod"] for e in trace.arrivals()}
    assert covered == all_keys


def test_torn_tail_segment_tolerated_and_capture_resumes_fresh(tmp_path):
    """The crash-recovery contract: a half-written tail line is tolerated
    on reopen (every event before the tear readable), and a re-attached
    capture NEVER appends to the torn segment — it resumes into a fresh
    one whose events are all readable too."""
    api = APIServer()
    rec = FleetTraceRecorder()
    rec.attach(api, str(tmp_path))
    for i in range(10):
        api.create(srv.PODS, make_pod(f"pre-{i}"))
    rec.flush()
    rec.detach()

    seg = os.path.join(str(tmp_path), _segment_files(str(tmp_path))[-1])
    whole = open(seg, "rb").read()
    torn_at = whole.rfind(b"\n", 0, len(whole) - 10)
    with open(seg, "wb") as f:        # crash mid-append: torn JSON tail
        f.write(whole[:torn_at + 30])
    records, torn = read_all(str(tmp_path))
    assert torn == 1
    pre = [r for r in records if r.get("kind") == "pod-arrival"]
    assert 1 <= len(pre) <= 10        # everything before the tear readable

    api2 = APIServer()
    rec2 = FleetTraceRecorder()
    rec2.attach(api2, str(tmp_path))
    for i in range(5):
        api2.create(srv.PODS, make_pod(f"post-{i}"))
    rec2.flush()
    rec2.detach()
    segs = _segment_files(str(tmp_path))
    assert len(segs) == 2             # resumed into a FRESH segment
    records2, torn2 = read_all(str(tmp_path))
    assert torn2 == 1                 # old tear still isolated
    post = [r for r in records2 if r.get("kind") == "pod-arrival"
            and r["pod"].startswith("default/post-")]
    assert len(post) == 5             # post-crash capture fully readable
    # and load_trace picks the fresh capture's snapshot
    trace = load_trace(str(tmp_path))
    assert trace.torn
    assert {e["pod"] for e in trace.arrivals()} == {
        f"default/post-{i}" for i in range(5)}


def test_flushed_events_hit_disk_without_detach(tmp_path):
    """Per-batch flush (persistence.Journal discipline): a process that
    exits WITHOUT detach() — SIGKILL, plain sys.exit, daemon-thread
    teardown — must lose at most the in-flight batch, never the whole
    Python-buffered tail of the open segment.  After flush() returns, the
    bytes are on disk even though the recorder is still armed."""
    api = APIServer()
    rec = FleetTraceRecorder()
    rec.attach(api, str(tmp_path))
    try:
        for i in range(8):
            api.create(srv.PODS, make_pod(f"live-{i}"))
        assert rec.flush()
        # read the directory while capture is STILL armed: no detach, no
        # close — this is what a post-mortem of a killed process sees
        records, torn = read_all(str(tmp_path))
        assert torn == 0
        arrivals = [r for r in records if r.get("kind") == "pod-arrival"]
        assert len(arrivals) == 8
    finally:
        rec.detach()


# -- bounds under concurrent scrape (test_obs_bounds mirror) ------------------


def test_capture_queue_budget_sheds_and_counts_under_soak(tmp_path):
    """10k events against a tiny queue budget with concurrent status()
    scrapes and readers: the queue never exceeds its budget, drops are
    counted (not silently lost), nothing blocks, and the recorder survives
    a concurrent detach."""
    api = APIServer()
    rec = FleetTraceRecorder()
    rec.attach(api, str(tmp_path), queue_budget=64,
               segment_bytes=256 * 1024, max_segments=3)
    stop = threading.Event()
    scrape_errors = []

    def scraper():
        while not stop.is_set():
            try:
                s = rec.status()
                assert s["queue_depth"] <= 64
                list(read_records(str(tmp_path)))
            except Exception as e:  # pragma: no cover - failure recorder
                scrape_errors.append(e)
                return
    threads = [threading.Thread(target=scraper, name=f"scrape-{i}",
                                daemon=True) for i in range(3)]
    for t in threads:
        t.start()
    for i in range(10_000):
        rec._enqueue("pod-delete", payload={"pod": f"default/p-{i}",
                                            "node": "", "gang": ""})
    rec.flush(30)
    stop.set()
    for t in threads:
        t.join(timeout=5)
    assert not scrape_errors
    status = rec.status()
    dropped = status["dropped"]
    rec.detach()
    assert status["queue_depth"] <= 64
    records, torn = read_all(str(tmp_path))
    assert torn == 0
    # nothing silently lost: every enqueue was either written to disk or
    # counted as dropped at the budget
    deletes = [r for r in records if r.get("kind") == "pod-delete"]
    assert len(deletes) + dropped == 10_000
    # the 64-entry budget against a tight producer loop DID shed (the
    # soak is non-vacuous) — and shedding never blocked the producer
    assert dropped > 0


def test_metrics_families_feed_from_capture(tmp_path):
    from tpusched.util.metrics import (fleetrace_bytes_total,
                                       fleetrace_dropped_total,
                                       fleetrace_events_total)
    ev0 = fleetrace_events_total.value()
    by0 = fleetrace_bytes_total.value()
    dr0 = fleetrace_dropped_total.value()
    api = APIServer()
    rec = FleetTraceRecorder()
    rec.attach(api, str(tmp_path), queue_budget=16)
    for i in range(500):
        api.create(srv.PODS, make_pod(f"m-{i}"))
    rec.flush()
    rec.detach()
    assert fleetrace_events_total.value() > ev0
    assert fleetrace_bytes_total.value() > by0
    # per-kind attribution exists
    assert fleetrace_events_total.with_labels("pod-arrival").value() > 0
    # the tiny budget under a tight creation loop sheds at least sometimes;
    # whether it did here is machine-dependent — the counter must simply
    # never go backwards
    assert fleetrace_dropped_total.value() >= dr0


# -- debug endpoint -----------------------------------------------------------


def test_debug_fleetrace_endpoint(tmp_path):
    from tpusched.util.httpserve import MetricsServer
    api = APIServer()
    rec = FleetTraceRecorder()
    old = obs.default_fleetrecorder()
    obs.install_fleetrecorder(rec)
    server = MetricsServer(port=0).start()
    try:
        url = f"http://127.0.0.1:{server.port}/debug/fleetrace"
        with urllib.request.urlopen(url, timeout=5) as r:
            payload = json.loads(r.read().decode())
        assert payload == {"enabled": False, "schema_version": 1}

        rec.attach(api, str(tmp_path))
        api.create(srv.PODS, make_pod("dbg-0"))
        rec.flush()
        with urllib.request.urlopen(url, timeout=5) as r:
            payload = json.loads(r.read().decode())
        assert payload["enabled"] is True
        assert payload["directory"] == str(tmp_path)
        assert payload["events_by_kind"].get("pod-arrival") == 1
        assert payload["bytes_written"] > 0
        assert payload["segments"] == 1
        assert payload["dropped"] == 0
    finally:
        server.stop()
        rec.detach()
        obs.install_fleetrecorder(old)


# -- misc contracts -----------------------------------------------------------


def test_attach_is_idempotent_and_reattach_elsewhere_detaches(tmp_path):
    api = APIServer()
    rec = FleetTraceRecorder()
    d1, d2 = str(tmp_path / "a"), str(tmp_path / "b")
    rec.attach(api, d1)
    rec.attach(api, d1)               # idempotent: same dir, same api
    api.create(srv.PODS, make_pod("x-0"))
    rec.attach(api, d2)               # moves: detaches from d1 first
    api.create(srv.PODS, make_pod("x-1"))
    rec.flush()
    rec.detach()
    k1 = [r.get("pod") for r in read_records(d1)
          if r.get("kind") == "pod-arrival"]
    k2 = [r.get("pod") for r in read_records(d2)
          if r.get("kind") == "pod-arrival"]
    assert k1 == ["default/x-0"]
    assert k2 == ["default/x-1"]


def test_heartbeat_only_node_updates_not_recorded(tmp_path):
    api = APIServer()
    node = make_node("hb-0", capacity={"cpu": 4, "memory": "8Gi"})
    api.create(srv.NODES, node)
    rec = FleetTraceRecorder()
    rec.attach(api, str(tmp_path))
    live = api.get(srv.NODES, node.meta.key)
    live.status.last_heartbeat_time = time.time()
    api.update(srv.NODES, live)
    rec.flush()
    rec.detach()
    kinds = [r.get("kind") for r in read_records(str(tmp_path))]
    assert "node-update" not in kinds and "node-health" not in kinds


def test_workload_fingerprint_stable_and_sensitive():
    ev = [{"kind": "pod-arrival", "pod": "default/a", "gang": "",
           "mono": 1.0, "wall": 2.0,
           "object": {"spec": {"priority": 0}}},
          {"kind": "bind-commit", "pod": "default/a", "node": "n1",
           "mono": 1.1, "wall": 2.1}]
    f1 = fleetrace.workload_fingerprint(ev)
    # stamps and recorded placements do NOT change the workload identity
    ev2 = json.loads(json.dumps(ev))
    ev2[0]["mono"] = 9.9
    ev2[1]["node"] = "n2"
    assert fleetrace.workload_fingerprint(ev2) == f1
    # the workload itself does
    ev3 = json.loads(json.dumps(ev))
    ev3[0]["object"]["spec"]["priority"] = 7
    assert fleetrace.workload_fingerprint(ev3) != f1
    # pod-delete's node is bind-commit reality leaking through the
    # teardown event: the same workload captured under two scoring
    # policies places (and therefore deletes) pods on different nodes,
    # and MUST still fingerprint identically
    ev4 = ev + [{"kind": "pod-delete", "pod": "default/a", "node": "n1",
                 "gang": "", "mono": 1.2, "wall": 2.2}]
    ev5 = json.loads(json.dumps(ev4))
    ev5[-1]["node"] = "n2"
    assert fleetrace.workload_fingerprint(ev5) == \
        fleetrace.workload_fingerprint(ev4)
    # but a node EVENT's node name is the workload
    ev6 = [{"kind": "node-delete", "node": "n1", "mono": 1.0, "wall": 2.0}]
    ev7 = json.loads(json.dumps(ev6))
    ev7[0]["node"] = "n2"
    assert fleetrace.workload_fingerprint(ev7) != \
        fleetrace.workload_fingerprint(ev6)
    # ... as is WHICH health transition a node took
    ev8 = [{"kind": "node-health", "node": "n1", "health_from": "",
            "health_to": "NotReady", "mono": 1.0, "wall": 2.0}]
    ev9 = json.loads(json.dumps(ev8))
    ev9[0]["health_to"] = ""
    ev9[0]["health_from"] = "NotReady"
    assert fleetrace.workload_fingerprint(ev9) != \
        fleetrace.workload_fingerprint(ev8)
    # ... and the node's size (status.capacity/allocatable), while
    # heartbeat stamps stay capture noise
    ev10 = [{"kind": "node-add", "node": "n1", "mono": 1.0, "wall": 2.0,
             "object": {"spec": {"unschedulable": False},
                        "status": {"capacity": {"google.com/tpu": 4},
                                   "allocatable": {"google.com/tpu": 4},
                                   "last_heartbeat_time": 10.0}}}]
    ev11 = json.loads(json.dumps(ev10))
    ev11[0]["object"]["status"]["last_heartbeat_time"] = 99.0
    assert fleetrace.workload_fingerprint(ev11) == \
        fleetrace.workload_fingerprint(ev10)
    ev12 = json.loads(json.dumps(ev10))
    ev12[0]["object"]["status"]["allocatable"]["google.com/tpu"] = 8
    assert fleetrace.workload_fingerprint(ev12) != \
        fleetrace.workload_fingerprint(ev10)
