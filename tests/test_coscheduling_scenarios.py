"""Gang-contention scenario table — the reference's integration matrix
(/root/reference/test/integration/coscheduling_test.go:47,126-353: nine cases
of gangs + regular pods contending for one node's memory) rebuilt over the
in-process cluster.

Determinism: every scenario creates ALL its objects before the scheduler
loop starts, so the first pop order is exactly the Coscheduling queue-sort
order (priority desc → PG creation time → key) with no informer-timing
races — the property the reference approximates by creating pods quickly
and polling.
"""
import pytest

from tpusched.api.resources import MEMORY, PODS
from tpusched.apiserver import server as srv
from tpusched.config.types import CoschedulingArgs
from tpusched.fwk import PluginProfile
from tpusched.testing import (TestCluster, make_node, make_pod,
                              make_pod_group, make_resources)

MID, HIGH = 100, 1000


def contention_profile(permit_wait_s=3, denied_s=1):
    """Coscheduling over the default fit filter — the reference's default
    profile + coscheduling extension points
    (test/integration/coscheduling_test.go:73-90)."""
    return PluginProfile(
        queue_sort="Coscheduling",
        pre_filter=["Coscheduling"],
        filter=["NodeUnschedulable", "NodeSelector", "NodeResourcesFit"],
        post_filter=["Coscheduling"],
        reserve=["Coscheduling"],
        permit=["Coscheduling"],
        bind=["DefaultBinder"],
        post_bind=["Coscheduling"],
        plugin_args={"Coscheduling": CoschedulingArgs(
            permit_waiting_time_seconds=permit_wait_s,
            denied_pg_expiration_time_seconds=denied_s)},
    )


def mem_node(name="fake-node", memory=300):
    # the reference's fake-node: 32 pods, 300 memory units
    return make_node(name, capacity={MEMORY: memory, PODS: 32, "cpu": 320000})


def gang_pod(name, group, mem, priority=MID):
    return make_pod(name, pod_group=group, priority=priority,
                    requests=make_resources(memory=mem))


def regular_pod(name, mem, priority=MID):
    return make_pod(name, priority=priority,
                    requests=make_resources(memory=mem))


# Each row: (name, pods, pod_groups, expected scheduled pod names).
# pods = list of (name, group-or-None, mem, priority) in creation order;
# pod_groups = list of (name, min_member, min_resources-or-None).
SCENARIOS = [
    ("equal priority, sequentially pg1 meets min and pg2 does not",
     [(f"t1-p1-{i}", "pg1-1", 50, MID) for i in range(1, 4)]
     + [(f"t1-p2-{i}", "pg1-2", 100, MID) for i in range(1, 5)],
     [("pg1-1", 3, None), ("pg1-2", 4, None)],
     ["t1-p1-1", "t1-p1-2", "t1-p1-3"]),

    ("equal priority, interleaved pg1 meets min and pg2 does not",
     [("t2-p1-1", "pg2-1", 50, MID), ("t2-p2-1", "pg2-2", 100, MID),
      ("t2-p1-2", "pg2-1", 50, MID), ("t2-p2-2", "pg2-2", 100, MID),
      ("t2-p1-3", "pg2-1", 50, MID), ("t2-p2-3", "pg2-2", 100, MID),
      ("t2-p2-4", "pg2-2", 100, MID)],
     [("pg2-1", 3, None), ("pg2-2", 4, None)],
     ["t2-p1-1", "t2-p1-2", "t2-p1-3"]),

    ("pg1 below min alongside regular pods: only regulars bind",
     [("t3-p1-1", "pg3-1", 50, MID), ("t3-p2", None, 100, MID),
      ("t3-p1-2", "pg3-1", 50, MID), ("t3-p3", None, 100, MID),
      ("t3-p1-3", "pg3-1", 50, MID)],
     [("pg3-1", 4, None)],  # only 3 members exist
     ["t3-p2", "t3-p3"]),

    ("different priority, sequential: only the high-priority gang fits",
     [(f"t4-p1-{i}", "pg4-1", 100, MID) for i in range(1, 4)]
     + [(f"t4-p2-{i}", "pg4-2", 50, HIGH) for i in range(1, 4)],
     [("pg4-1", 3, None), ("pg4-2", 3, None)],
     ["t4-p2-1", "t4-p2-2", "t4-p2-3"]),

    ("different priority, interleaved: only the high-priority gang fits",
     [("t5-p1-1", "pg5-1", 100, MID), ("t5-p2-1", "pg5-2", 50, HIGH),
      ("t5-p1-2", "pg5-1", 100, MID), ("t5-p2-2", "pg5-2", 50, HIGH),
      ("t5-p1-3", "pg5-1", 100, MID), ("t5-p2-3", "pg5-2", 50, HIGH)],
     [("pg5-1", 3, None), ("pg5-2", 3, None)],
     ["t5-p2-1", "t5-p2-2", "t5-p2-3"]),

    ("high-priority regulars starve a mid-priority gang",
     [("t6-p1-1", "pg6-1", 50, MID), ("t6-p2", None, 100, HIGH),
      ("t6-p1-2", "pg6-1", 50, MID), ("t6-p3", None, 100, HIGH),
      ("t6-p1-3", "pg6-1", 50, MID), ("t6-p4", None, 100, HIGH)],
     [("pg6-1", 3, None)],
     ["t6-p2", "t6-p3", "t6-p4"]),

    ("three gangs, one fits: pg1 meets min, pg2/pg3 cannot",
     [("t7-p1-1", "pg7-1", 50, MID), ("t7-p2-1", "pg7-2", 100, MID),
      ("t7-p3-1", "pg7-3", 100, MID), ("t7-p1-2", "pg7-1", 50, MID),
      ("t7-p2-2", "pg7-2", 100, MID), ("t7-p3-2", "pg7-3", 100, MID),
      ("t7-p1-3", "pg7-1", 50, MID), ("t7-p2-3", "pg7-2", 100, MID),
      ("t7-p3-3", "pg7-3", 100, MID), ("t7-p2-4", "pg7-2", 100, MID),
      ("t7-p3-4", "pg7-3", 100, MID)],
     [("pg7-1", 3, None), ("pg7-2", 4, None), ("pg7-3", 4, None)],
     ["t7-p1-1", "t7-p1-2", "t7-p1-3"]),

    ("three gangs with minResources: the 400-unit gangs are gated early",
     [("t8-p1-1", "pg8-1", 50, MID), ("t8-p2-1", "pg8-2", 100, MID),
      ("t8-p3-1", "pg8-3", 100, MID), ("t8-p1-2", "pg8-1", 50, MID),
      ("t8-p2-2", "pg8-2", 100, MID), ("t8-p3-2", "pg8-3", 100, MID),
      ("t8-p1-3", "pg8-1", 50, MID), ("t8-p2-3", "pg8-2", 100, MID),
      ("t8-p3-3", "pg8-3", 100, MID), ("t8-p2-4", "pg8-2", 100, MID),
      ("t8-p3-4", "pg8-3", 100, MID)],
     [("pg8-1", 3, {MEMORY: 150}), ("pg8-2", 4, {MEMORY: 400}),
      ("pg8-3", 4, {MEMORY: 400})],
     ["t8-p1-1", "t8-p1-2", "t8-p1-3"]),

    ("two gangs with minResources: pg1 meets min, pg2's 400 > capacity",
     [("t9-p1-1", "pg9-1", 50, MID), ("t9-p2-1", "pg9-2", 100, MID),
      ("t9-p1-2", "pg9-1", 50, MID), ("t9-p2-2", "pg9-2", 100, MID),
      ("t9-p1-3", "pg9-1", 50, MID), ("t9-p2-3", "pg9-2", 100, MID),
      ("t9-p2-4", "pg9-2", 100, MID)],
     [("pg9-1", 3, {MEMORY: 150}), ("pg9-2", 4, {MEMORY: 400})],
     ["t9-p1-1", "t9-p1-2", "t9-p1-3"]),
]


@pytest.mark.parametrize("name,pods,pod_groups,expected",
                         SCENARIOS, ids=[s[0] for s in SCENARIOS])
def test_gang_contention(name, pods, pod_groups, expected):
    c = TestCluster(profile=contention_profile())
    c.add_nodes([mem_node()])
    for pg_name, min_member, min_res in pod_groups:
        c.api.create(srv.POD_GROUPS, make_pod_group(
            pg_name, min_member=min_member, min_resources=min_res))
    objs = []
    for pname, group, mem, prio in pods:
        p = (gang_pod(pname, group, mem, prio) if group
             else regular_pod(pname, mem, prio))
        objs.append(p)
    c.create_pods(objs)
    with c:
        want = [f"default/{n}" for n in expected]
        assert c.wait_for_pods_scheduled(want, timeout=20), \
            f"{name}: expected {expected} to schedule"
        others = [p.key for p in objs if p.key not in want]
        assert c.wait_for_pods_unscheduled(others, hold=1.0), \
            f"{name}: expected {others} to stay pending"
