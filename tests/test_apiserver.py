"""API-server semantics tables: create/update/patch/delete contracts,
resource versions, bind conflicts, watch delivery and replay — the
storage-layer behavior every informer, controller, and scheduler path sits
on (the reference delegates all of this to a real kube-apiserver; here it
must be pinned by its own tests)."""
import pytest

from tpusched.api.core import Binding
from tpusched.apiserver import server as srv
from tpusched.testing import make_node, make_pod


def test_create_conflict_and_get_notfound():
    api = srv.APIServer()
    api.create(srv.PODS, make_pod("p"))
    with pytest.raises(srv.Conflict):
        api.create(srv.PODS, make_pod("p"))
    with pytest.raises(srv.NotFound):
        api.get(srv.PODS, "default/ghost")
    assert api.try_get(srv.PODS, "default/ghost") is None


def test_resource_version_bumps_on_every_mutation():
    api = srv.APIServer()
    created = api.create(srv.PODS, make_pod("p"))
    rv0 = created.meta.resource_version
    patched = api.patch(srv.PODS, "default/p",
                        lambda p: p.meta.labels.update({"a": "1"}))
    assert patched.meta.resource_version > rv0
    other = api.create(srv.PODS, make_pod("q"))
    # one global monotonic sequence across objects (etcd-style)
    assert other.meta.resource_version > patched.meta.resource_version


def test_update_requires_existing_object():
    api = srv.APIServer()
    with pytest.raises(srv.NotFound):
        api.update(srv.PODS, make_pod("nope"))


def test_patch_mutation_is_atomic_against_reads():
    """patch() applies the mutation to the live object under the store lock;
    a mutation that raises must leave the object unchanged."""
    api = srv.APIServer()
    api.create(srv.PODS, make_pod("p"))
    before = api.get(srv.PODS, "default/p")

    def bad(p):
        p.meta.labels["half"] = "written"
        raise RuntimeError("mutation failed mid-way")

    with pytest.raises(RuntimeError):
        api.patch(srv.PODS, "default/p", bad)
    after = api.get(srv.PODS, "default/p")
    assert after.meta.resource_version == before.meta.resource_version
    assert "half" not in after.meta.labels


def test_reads_return_copies_not_store_references():
    """get() hands out copies: caller-side mutation must not write through
    to the store (the scheduler deepcopies before assuming for this
    contract)."""
    api = srv.APIServer()
    api.create(srv.PODS, make_pod("p"))
    got = api.get(srv.PODS, "default/p")
    got.meta.labels["rogue"] = "edit"
    assert "rogue" not in api.get(srv.PODS, "default/p").meta.labels


def test_list_namespace_filter():
    api = srv.APIServer()
    api.create(srv.PODS, make_pod("a", namespace="team-a"))
    api.create(srv.PODS, make_pod("b", namespace="team-b"))
    assert [p.meta.name for p in api.list(srv.PODS, namespace="team-a")] == ["a"]
    assert len(api.list(srv.PODS)) == 2


def test_bind_sets_node_and_conflicts_when_rebinding():
    api = srv.APIServer()
    api.create(srv.NODES, make_node("n1"))
    api.create(srv.NODES, make_node("n2"))
    api.create(srv.PODS, make_pod("p"))
    api.bind(Binding(pod_key="default/p", node_name="n1",
                     annotations={"chip": "0"}))
    bound = api.get(srv.PODS, "default/p")
    assert bound.spec.node_name == "n1"
    assert bound.meta.annotations["chip"] == "0"   # annotations ride the bind
    with pytest.raises(srv.Conflict):
        api.bind(Binding(pod_key="default/p", node_name="n2"))


def test_watch_delivery_order_and_types():
    api = srv.APIServer()
    seen = []
    api.add_watch(srv.PODS, lambda ev: seen.append(
        (ev.type, ev.object.meta.name)))
    api.create(srv.PODS, make_pod("p"))
    api.patch(srv.PODS, "default/p", lambda p: None or
              p.meta.labels.update({"x": "1"}))
    api.delete(srv.PODS, "default/p")
    assert seen == [(srv.ADDED, "p"), (srv.MODIFIED, "p"), (srv.DELETED, "p")]


def test_watch_replay_delivers_existing_objects_as_adds():
    api = srv.APIServer()
    api.create(srv.PODS, make_pod("old1"))
    api.create(srv.PODS, make_pod("old2"))
    seen = []
    api.add_watch(srv.PODS, lambda ev: seen.append((ev.type,
                                                    ev.object.meta.name)),
                  replay=True)
    assert sorted(seen) == [(srv.ADDED, "old1"), (srv.ADDED, "old2")]
    api.create(srv.PODS, make_pod("new"))
    assert seen[-1] == (srv.ADDED, "new")


def test_modified_events_carry_old_object():
    api = srv.APIServer()
    api.create(srv.PODS, make_pod("p"))
    olds = []
    api.add_watch(srv.PODS, lambda ev: olds.append(ev.old_object)
                  if ev.type == srv.MODIFIED else None)
    api.patch(srv.PODS, "default/p",
              lambda p: p.meta.labels.update({"gen": "2"}))
    assert len(olds) == 1
    assert "gen" not in olds[0].meta.labels   # the pre-mutation snapshot


def test_events_ring_records_most_recent():
    api = srv.APIServer()
    api.create(srv.PODS, make_pod("p"))
    api.record_event("default/p", "Pod", "Warning", "FailedScheduling", "no")
    api.record_event("default/p", "Pod", "Normal", "Scheduled", "ok")
    evs = api.events()
    assert [e.reason for e in evs[-2:]] == ["FailedScheduling", "Scheduled"]


def test_lease_acquire_renew_and_steal_after_expiry():
    now = [1000.0]
    api = srv.APIServer(clock=lambda: now[0])
    assert api.acquire_or_renew_lease("lock", "a", lease_duration=10)
    assert not api.acquire_or_renew_lease("lock", "b", lease_duration=10)
    assert api.lease_holder("lock") == "a"
    # holder renews within the window
    now[0] += 8
    assert api.acquire_or_renew_lease("lock", "a", lease_duration=10)
    # non-holder acquires only after expiry
    now[0] += 9
    assert not api.acquire_or_renew_lease("lock", "b", lease_duration=10)
    now[0] += 2
    assert api.acquire_or_renew_lease("lock", "b", lease_duration=10)
    assert api.lease_holder("lock") == "b"


def test_concurrent_writers_lose_no_events():
    """8 writer threads over disjoint keys with a live watcher: every
    mutation's event arrives, per-key streams are ordered (single writer per
    key ⇒ create < patches < delete), and nothing deadlocks. Pins the
    write-path sharing discipline under real concurrency."""
    import threading
    from collections import defaultdict

    api = srv.APIServer()
    per_key = defaultdict(list)
    log_lock = threading.Lock()

    def handler(ev):
        with log_lock:
            per_key[ev.object.meta.key].append(ev.type)

    api.add_watch(srv.PODS, handler)
    PATCHES = 20

    def writer(t):
        for i in range(5):
            p = make_pod(f"w{t}-p{i}")
            api.create(srv.PODS, p)
            for _ in range(PATCHES):
                api.patch(srv.PODS, p.key,
                          lambda live: live.meta.labels.__setitem__("x", "y"))
            api.delete(srv.PODS, p.key)

    threads = [threading.Thread(target=writer, args=(t,)) for t in range(8)]
    for th in threads:
        th.start()
    for th in threads:
        th.join(timeout=30)
        assert not th.is_alive(), "writer deadlocked"

    assert len(per_key) == 40
    for key, evs in per_key.items():
        assert len(evs) == 2 + PATCHES, (key, len(evs))
        assert evs[0] == srv.ADDED and evs[-1] == srv.DELETED, (key, evs[:3])
        assert all(e == srv.MODIFIED for e in evs[1:-1]), key
    assert api.list(srv.PODS) == []


def test_clientset_token_bucket_budget():
    """--qps/--burst budget (options.go:43-44 analog): burst drains free,
    then calls pace at ~1/qps; qps=0 means unthrottled."""
    import time as _t
    from tpusched.apiserver.client import Clientset, _TokenBucket

    b = _TokenBucket(qps=50.0, burst=5)
    t0 = _t.perf_counter()
    for _ in range(5):
        b.wait()                       # burst: free
    burst_t = _t.perf_counter() - t0
    assert burst_t < 0.05
    t0 = _t.perf_counter()
    for _ in range(5):
        b.wait()                       # paced at 50qps ⇒ ~100ms for 5
    paced_t = _t.perf_counter() - t0
    assert 0.05 <= paced_t < 1.0

    # unthrottled clientset round-trip incl. the Bind subresource
    api = srv.APIServer()
    cs = Clientset(api)
    from tpusched.testing import make_node
    api.create(srv.NODES, make_node("n1"))
    cs.pods.create(make_pod("p"))
    from tpusched.api.core import Binding
    cs.pods.bind(Binding(pod_key="default/p", node_name="n1"))
    assert cs.pods.get("default/p").spec.node_name == "n1"
