"""MultiSlice DCN-aware scoring tests (BASELINE eval config #5: multi-slice
job as N PodGroups sharing multislice_set, slices pulled toward nearby DCN
domains)."""
from tpusched.api.resources import TPU
from tpusched.api.topology import LABEL_DCN_DOMAIN
from tpusched.apiserver import server as srv
from tpusched.config.profiles import tpu_gang_profile
from tpusched.plugins.topologymatch import POOL_ANNOTATION
from tpusched.testing import (TestCluster, make_pod, make_pod_group,
                              make_tpu_pool)


def add_pool(c, name, dcn_domain, dims=(4, 4, 4)):
    topo, nodes = make_tpu_pool(name, dims=dims, dcn_domain=dcn_domain)
    c.api.create(srv.TPU_TOPOLOGIES, topo)
    c.add_nodes(nodes)


def slice_pg(c, set_name, index, members=16, shape="4x4x4"):
    name = f"{set_name}-slice-{index}"
    c.api.create(srv.POD_GROUPS, make_pod_group(
        name, min_member=members, tpu_slice_shape=shape,
        tpu_accelerator="tpu-v5p", multislice_set=set_name,
        multislice_index=index))
    pods = [make_pod(f"{name}-{i}", pod_group=name, limits={TPU: 4})
            for i in range(members)]
    c.create_pods(pods)
    return pods


def pool_of(c, pods):
    pools = {c.pod(p.key).meta.annotations[POOL_ANNOTATION] for p in pods}
    assert len(pools) == 1
    return pools.pop()


def test_second_slice_prefers_same_dcn_domain():
    with TestCluster(profile=tpu_gang_profile(permit_wait_s=5, denied_s=1)) as c:
        # pin slice-0 deterministically: only one pool exists when it lands
        add_pool(c, "first", "zoneA/rack1")
        s0 = slice_pg(c, "llama70b", 0)
        assert c.wait_for_pods_scheduled([p.key for p in s0], timeout=20)
        assert pool_of(c, s0) == "first"
        add_pool(c, "near", "zoneA/rack1")     # same domain as slice-0
        add_pool(c, "far", "zoneB/rack9")
        s1 = slice_pg(c, "llama70b", 1)
        assert c.wait_for_pods_scheduled([p.key for p in s1], timeout=20)
        # the second slice must pick the pool sharing the first's DCN domain
        assert pool_of(c, s1) == "near"


def test_adjacent_zone_beats_remote_zone_then_degrades_to_remote():
    """DCN proximity prefers the anchor rack, degrades to the adjacent rack
    when it is full, and still admits in the remote zone when the whole
    anchor zone is full — a preference, never a gate."""
    with TestCluster(profile=tpu_gang_profile(permit_wait_s=5, denied_s=1)) as c:
        add_pool(c, "a1", "zoneA/rack1")
        s0 = slice_pg(c, "job", 0)
        assert c.wait_for_pods_scheduled([p.key for p in s0], timeout=20)
        assert pool_of(c, s0) == "a1"
        add_pool(c, "a2", "zoneA/rack2")   # adjacent (same zone, other rack)
        add_pool(c, "b1", "zoneB/rack1")   # remote
        s1 = slice_pg(c, "job", 1)
        assert c.wait_for_pods_scheduled([p.key for p in s1], timeout=20)
        assert pool_of(c, s1) == "a2", "slice-1 went to the remote zone"
        # the whole anchor zone is now full: the remote zone still admits
        s2 = slice_pg(c, "job", 2)
        assert c.wait_for_pods_scheduled([p.key for p in s2], timeout=20)
        assert pool_of(c, s2) == "b1"


def test_four_slice_job_spreads_over_four_pools():
    """4× v5p-64 multi-slice job: every slice whole-pool, all in one zone."""
    with TestCluster(profile=tpu_gang_profile(permit_wait_s=10, denied_s=1)) as c:
        for i in range(4):
            add_pool(c, f"pool-{i}", f"zoneA/rack{i % 2}")
        all_pods = {}
        for idx in range(4):
            pods = slice_pg(c, "big", idx)
            assert c.wait_for_pods_scheduled([p.key for p in pods], timeout=30)
            all_pods[idx] = pods
        pools = {idx: pool_of(c, pods) for idx, pods in all_pods.items()}
        assert len(set(pools.values())) == 4  # one pool per slice

