"""Seeded chaos soak (the tentpole gate, also `make chaos-smoke`).

Thousands of scheduling cycles under rotating injected fault phases —
transient unavailability, conflict storms, lost-response binds, stale
NotFound races, Event failures, a forced terminal mid-gang bind outage and
a total outage — with the C1–C5 invariants from testing/chaos.py asserted
at every quiesce point:

  no pod lost, no double-bind, gangs all-or-nothing at quiescence, the
  equivalence-cache differential oracle exact throughout, degraded mode
  trips and recovers, and every rolled-back gang binds once faults clear.

CHAOS_SOAK_CYCLES raises the cycle floor (the Makefile's chaos-smoke gate
runs 5000; the in-suite default keeps tier-1 wall time sane while still
covering every phase at four-digit cycle counts). Failures reproduce from
the printed seed.
"""
import os

from tpusched.testing import run_chaos_soak

SEED = 20260802
# In-suite floor: every fault phase plus the forced-rollback and outage
# rounds at four-digit cycle counts, without paying the full 5k soak twice
# per `make tier1` (chaos-smoke already runs it at CHAOS_SOAK_CYCLES=5000).
DEFAULT_CYCLES = 1200


def test_chaos_soak_invariants_hold():
    min_cycles = int(os.environ.get("CHAOS_SOAK_CYCLES", DEFAULT_CYCLES))
    report = run_chaos_soak(seed=SEED, min_cycles=min_cycles)
    print(report.summary())          # -s / failure output: the repro line
    assert report.cycles >= min_cycles, report.summary()
    # the adversary actually showed up: faults were injected, the client
    # retried, and at least one terminal mid-gang failure forced a rollback
    assert report.injections > 0
    assert report.retries > 0
    assert report.rollbacks >= 1
    assert report.degraded_tripped
    assert report.ok, "\n".join([report.summary()] + report.violations)


def test_chaos_soak_alternate_seed_quick():
    """A second seed at a small cycle floor: the invariants are seed-
    independent, and a rule-ordering regression that only one RNG stream
    hits still gets a chance to surface."""
    report = run_chaos_soak(seed=7, min_cycles=400, gangs_per_round=3,
                            members=3, nodes=6)
    assert report.ok, "\n".join([report.summary()] + report.violations)


# In-suite floor for the node-churn soak (hardware-as-adversary): every
# node fault phase — heartbeat loss, node kill with bound gang members,
# cordon storm, flapping Ready, API blips — at least once, without paying
# the full 5k soak twice per `make tier1` (chaos-smoke runs it at
# CHAOS_NODE_CHURN_CYCLES=5000).
DEFAULT_CHURN_CYCLES = 150


def test_node_churn_soak_no_wedged_gangs():
    """C6: under node churn every gang that loses hardware re-reaches
    fully-Bound on existing, Ready nodes (or a clean terminal phase) —
    never a permanent wedge — while C1/C2/C3 keep holding."""
    from tpusched.testing import run_node_churn_soak

    min_cycles = int(os.environ.get("CHAOS_NODE_CHURN_CYCLES",
                                    DEFAULT_CHURN_CYCLES))
    report = run_node_churn_soak(seed=SEED, min_cycles=min_cycles)
    print(report.summary())          # -s / failure output: the repro line
    assert report.cycles >= min_cycles, report.summary()
    # the adversary showed up: nodes died, pods were evicted, gangs were
    # actually repaired — not a quiet run that proved nothing
    assert report.node_kills >= 1, report.summary()
    assert report.not_ready_transitions >= 1, report.summary()
    assert report.evictions >= 1, report.summary()
    assert report.repairs >= 1, report.summary()
    # every phase ran at least once (5-round floor), incl. api-blips
    assert report.rounds >= 5, report.summary()
    assert report.injections >= 1, report.summary()
    assert report.ok, "\n".join([report.summary()] + report.violations)
