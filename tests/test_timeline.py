"""Health timeline + anomaly sentinel units (ISSUE 20, the pytest half
of ``make incident-smoke``).

The load-bearing claims:

- budgets HOLD under a 10k-tick soak with concurrent scrapes — entries
  and approximate bytes never exceed their caps, and every evicted
  sample is counted (overflow accounted, never stored);
- rate families store per-second deltas of cumulative counters (first
  tick: 0.0 — no baseline yet), gauge families store values as-is;
- a raising family or listener is counted and skipped, never propagated
  into the (housekeeping) caller;
- ``arm_on``/``disarm`` drive the VirtualClock deadline registry — the
  replay determinism contract's tick plumbing;
- sentinel hysteresis: ``enter_ticks`` consecutive abnormal samples to
  fire, ``clear_ticks`` normal ones to re-arm, cooldown bounding the
  firing volume of an oscillating condition;
- the stock ``bind_rate_collapse`` detector judges a collapse against
  the HEALTHY trailing baseline (the sample under evaluation joins the
  baseline only after evaluation).
"""
import threading

import pytest

from tpusched.obs.sentinel import (AnomalySentinel, BaselineView, Detector,
                                   default_detectors)
from tpusched.obs.timeline import HealthTimeline
from tpusched.util.clock import VirtualClock


def _mk(interval_s: float = 1.0, **kw) -> HealthTimeline:
    kw.setdefault("publish", False)
    return HealthTimeline(interval_s=interval_s, **kw)


# -- family sampling ----------------------------------------------------------

def test_gauge_and_rate_families():
    tl = _mk()
    state = {"gauge": 5.0, "counter": 0.0}
    tl.register_family("depth", lambda: state["gauge"])
    tl.register_family("binds", lambda: state["counter"], kind="rate")

    s0 = tl.tick(now=10.0)
    assert s0["v"]["depth"] == 5.0
    assert s0["v"]["binds"] == 0.0          # first rate tick: no baseline

    state["gauge"], state["counter"] = 7.0, 30.0
    s1 = tl.tick(now=12.0)                  # +30 over 2s -> 15/s
    assert s1["v"]["depth"] == 7.0
    assert s1["v"]["binds"] == pytest.approx(15.0)

    state["counter"] = 20.0                 # counter reset (restart):
    s2 = tl.tick(now=13.0)                  # negative delta clamps to 0
    assert s2["v"]["binds"] == 0.0


def test_none_reading_omits_family_from_sample():
    tl = _mk()
    tl.register_family("sometimes", lambda: None)
    tl.register_family("always", lambda: 1.0)
    s = tl.tick(now=1.0)
    assert "sometimes" not in s["v"] and s["v"]["always"] == 1.0


def test_raising_family_is_counted_and_skipped():
    tl = _mk()
    tl.register_family("bad", lambda: 1 / 0)
    tl.register_family("good", lambda: 2.0)
    s = tl.tick(now=1.0)
    assert s["v"] == {"good": 2.0}
    assert tl.stats()["errors_total"] == 1


def test_register_replaces_and_unregister_drops():
    tl = _mk()
    tl.register_family("f", lambda: 1.0)
    tl.register_family("f", lambda: 2.0)        # replace, same name
    assert tl.tick(now=1.0)["v"]["f"] == 2.0
    tl.unregister_family("f")
    assert tl.tick(now=2.0)["v"] == {}
    with pytest.raises(ValueError):
        tl.register_family("g", lambda: 0.0, kind="exotic")


def test_raising_listener_is_counted_and_others_still_run():
    tl = _mk()
    tl.register_family("f", lambda: 1.0)
    seen = []
    tl.add_listener(lambda s: 1 / 0)
    tl.add_listener(seen.append)
    tl.tick(now=1.0)
    assert len(seen) == 1
    assert tl.stats()["errors_total"] == 1


# -- budgets ------------------------------------------------------------------

def test_entry_budget_evicts_oldest_and_counts_overflow():
    tl = _mk(max_samples=10)
    tl.register_family("f", lambda: 0.0)
    for i in range(25):
        tl.tick(now=float(i))
    st = tl.stats()
    assert st["entries"] == 10
    assert st["samples_total"] == 25
    assert st["overflow_total"] == 15
    # the RING kept the newest: oldest stored tick is t=15
    assert tl.samples()[0]["t"] == 15.0


def test_byte_budget_binds_independently_of_entry_budget():
    tl = _mk(max_samples=100000, max_bytes=2048)
    tl.register_family("a-reasonably-long-family-name", lambda: 1.0)
    for i in range(500):
        tl.tick(now=float(i))
    st = tl.stats()
    assert st["approx_bytes"] <= 2048
    assert st["entries"] < 500
    assert st["overflow_total"] == 500 - st["entries"]


def test_soak_10k_ticks_under_concurrent_scrapes():
    """10k ticks racing scrape threads: budgets hold at every observed
    instant, no exception escapes, and at the end every sample ever
    committed is either stored or counted as overflow."""
    tl = _mk(max_samples=256, max_bytes=64 << 10)
    state = {"n": 0.0}
    tl.register_family("binds", lambda: state["n"], kind="rate")
    tl.register_family("depth", lambda: state["n"] % 97)
    stop = threading.Event()
    violations = []

    def scrape():
        while not stop.is_set():
            st = tl.stats()
            if st["entries"] > tl.max_samples \
                    or st["approx_bytes"] > tl.max_bytes:
                violations.append(st)
            tl.window(50.0, now=state["n"])
            tl.dump(10.0)
            tl.census()

    threads = [threading.Thread(target=scrape, daemon=True)
               for _ in range(4)]
    for t in threads:
        t.start()
    try:
        for i in range(10_000):
            state["n"] += 3.0
            tl.tick(now=float(i))
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10)
    assert not violations, violations[:3]
    st = tl.stats()
    assert st["samples_total"] == 10_000
    assert st["entries"] == 256
    assert st["overflow_total"] == 10_000 - 256
    assert st["errors_total"] == 0


# -- windows / census ---------------------------------------------------------

def test_window_filters_by_horizon():
    tl = _mk()
    tl.register_family("f", lambda: 1.0)
    for i in range(10):
        tl.tick(now=float(i))
    assert len(tl.window(3.5, now=9.0)) == 4       # t in {5.5..9} -> 6..9
    assert len(tl.window(100.0, now=9.0)) == 10
    assert tl.latest()["t"] == 9.0


def test_census_carries_no_wall_stamps():
    """The census is the byte-identical replay-comparison view: counts
    and family names only — a wall stamp would differ across two replays
    of one trace by construction."""
    tl = _mk()
    tl.register_family("f", lambda: 1.0)
    tl.tick(now=1.0)
    census = tl.census()
    assert set(census) == {"samples_total", "overflow_total", "entries",
                           "families"}
    assert census["samples_total"] == 1 and census["families"] == ["f"]


# -- clock plumbing -----------------------------------------------------------

def test_arm_on_registers_virtual_deadline_and_rearm_follows_ticks():
    vc = VirtualClock(start=100.0)
    tl = _mk(interval_s=2.0)
    tl.register_family("f", lambda: 1.0)
    tl.arm_on(vc)
    assert vc.armed_count() == 1
    assert vc.next_deadline() == pytest.approx(102.0)
    tl.tick(now=104.0)                    # tick re-arms at now+interval
    assert vc.next_deadline() == pytest.approx(106.0)
    assert vc.armed_count() == 1          # the stale token was cancelled


def test_disarm_cancels_and_stops_rearming():
    vc = VirtualClock(start=0.0)
    tl = _mk(interval_s=1.0)
    tl.arm_on(vc)
    tl.disarm()
    assert vc.armed_count() == 0
    tl.tick(now=5.0)                      # ticking no longer re-arms
    assert vc.armed_count() == 0
    assert tl.stats()["armed"] is False


def test_maybe_tick_is_interval_gated():
    tl = _mk(interval_s=1.0)
    tl.register_family("f", lambda: 1.0)
    assert tl.maybe_tick(now=10.0) is True
    assert tl.maybe_tick(now=10.5) is False
    assert tl.maybe_tick(now=11.0) is True
    assert tl.stats()["samples_total"] == 2


# -- sentinel hysteresis ------------------------------------------------------

def _always(detail):
    return lambda v, base: detail if v.get("bad") else None


def _sample(t, **v):
    return {"t": t, "wall": 1e9 + t, "v": v}


def test_sentinel_fires_after_enter_ticks_and_cooldown_bounds_volume():
    sn = AnomalySentinel(detectors=[
        Detector("d", _always({"reason": "x"}), enter_ticks=3,
                 clear_ticks=2, cooldown_ticks=4)], publish=False)
    fired = []
    for i in range(10):
        fired += sn.on_sample(_sample(float(i), bad=1))
    # fired once at the 3rd abnormal tick; then active + cooldown hold
    assert [f["t"] for f in fired] == [2.0]
    assert sn.census() == {"d": 1}
    st = sn.stats()
    assert st["ticks_total"] == 10 and st["detectors"]["d"]["active"]


def test_sentinel_clear_ticks_rearm_then_refire():
    sn = AnomalySentinel(detectors=[
        Detector("d", _always({"reason": "x"}), enter_ticks=2,
                 clear_ticks=2, cooldown_ticks=0)], publish=False)
    t = [0.0]

    def feed(bad, n):
        out = []
        for _ in range(n):
            out += sn.on_sample(_sample(t[0], bad=bad))
            t[0] += 1.0
        return out

    assert len(feed(1, 3)) == 1           # enters at the 2nd abnormal
    assert feed(0, 1) == []               # one normal tick: still active
    assert len(feed(1, 4)) == 0           # re-abnormal while active: no dup
    feed(0, 2)                            # clear_ticks normals: re-armed
    assert len(feed(1, 2)) == 1           # fires again
    assert sn.census() == {"d": 2}


def test_sentinel_raising_detector_counted_not_propagated():
    def boom(v, base):
        raise RuntimeError("detector bug")
    sn = AnomalySentinel(detectors=[Detector("boom", boom),
                                    Detector("ok", _always({"reason": "x"}),
                                             enter_ticks=1)],
                         publish=False)
    fired = sn.on_sample(_sample(0.0, bad=1))
    assert [f["detector"] for f in fired] == ["ok"]
    assert sn.stats()["errors_total"] == 1


def test_sentinel_on_firing_hook_and_firing_shape():
    got = []
    sn = AnomalySentinel(detectors=[Detector("d", _always({"reason": "x",
                                                           "k": 2.0}),
                                             enter_ticks=1)],
                         publish=False, on_firing=got.append)
    sn.on_sample(_sample(7.0, bad=1, depth=3.0))
    assert len(got) == 1
    f = got[0]
    assert f["detector"] == "d" and f["t"] == 7.0
    assert f["detail"]["reason"] == "x"
    assert f["values"] == {"bad": 1, "depth": 3.0}


def test_bind_rate_collapse_judged_against_healthy_baseline():
    """The stock detector: healthy binds at 10/s, then a collapse to
    0.5/s with pods pending — fires exactly enter_ticks into the
    collapse, because the baseline excludes the sample under
    evaluation."""
    dets = {d.name: d for d in default_detectors()}
    sn = AnomalySentinel(detectors=[dets["bind_rate_collapse"]],
                         publish=False)
    fired = []
    for i in range(30):
        fired += sn.on_sample(_sample(float(i), bind_rate=10.0,
                                      pending_pods=20.0))
    assert fired == []                    # healthy: never fires
    for i in range(30, 40):
        fired += sn.on_sample(_sample(float(i), bind_rate=0.5,
                                      pending_pods=20.0))
    assert len(fired) == 1
    assert fired[0]["t"] == 32.0          # 3rd collapsed tick (enter=3)
    detail = fired[0]["detail"]
    assert detail["bind_rate"] == 0.5 and detail["baseline"] > 5.0


def test_bind_rate_collapse_needs_pending_work():
    """Zero bind rate with an EMPTY queue is an idle fleet, not an
    incident."""
    dets = {d.name: d for d in default_detectors()}
    sn = AnomalySentinel(detectors=[dets["bind_rate_collapse"]],
                         publish=False)
    for i in range(20):
        sn.on_sample(_sample(float(i), bind_rate=10.0, pending_pods=20.0))
    fired = []
    for i in range(20, 30):
        fired += sn.on_sample(_sample(float(i), bind_rate=0.0,
                                      pending_pods=0.0))
    assert fired == []


def test_degraded_entry_is_an_edge_detector():
    dets = {d.name: d for d in default_detectors()}
    sn = AnomalySentinel(detectors=[dets["degraded_mode_entry"]],
                         publish=False)
    fired = sn.on_sample(_sample(0.0, degraded=0.0))
    fired += sn.on_sample(_sample(1.0, degraded=1.0))     # the edge
    assert [f["detector"] for f in fired] == ["degraded_mode_entry"]


def test_sentinel_attach_moves_between_timelines():
    tl1, tl2 = _mk(), _mk()
    tl1.register_family("bad", lambda: 1.0)
    tl2.register_family("bad", lambda: 1.0)
    sn = AnomalySentinel(detectors=[Detector("d", _always({"reason": "x"}),
                                             enter_ticks=1,
                                             cooldown_ticks=0,
                                             clear_ticks=1)],
                         publish=False)
    sn.attach(tl1)
    sn.attach(tl2)                        # move: tl1 listener removed
    tl1.tick(now=1.0)
    assert sn.stats()["ticks_total"] == 0
    tl2.tick(now=1.0)
    assert sn.stats()["ticks_total"] == 1


def test_baseline_view_mean_prev_window():
    b = BaselineView()
    for i in range(40):
        b.push({"x": float(i)})
    assert b.ticks() == 30                # bounded trailing window
    assert b.prev("x") == 39.0
    assert b.mean("x") == pytest.approx(sum(range(10, 40)) / 30)
    assert b.mean("missing") is None and b.prev("missing") is None
