"""KV-cache inference (prefill + decode) against the training forward, for
both MHA and grouped-query attention configs."""
from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpusched.jaxbridge import decode, workload

MHA = workload.ModelConfig.tiny()
GQA = dataclasses.replace(MHA, n_kv_heads=1)


def _setup(cfg, batch=2):
    params = workload.init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (batch, cfg.seq),
                                0, cfg.vocab)
    return params, tokens


@pytest.mark.parametrize("cfg", [MHA, GQA], ids=["mha", "gqa"])
def test_prefill_matches_forward(cfg):
    params, tokens = _setup(cfg)
    full = workload.forward(params, tokens, cfg)
    cache = decode.init_kv_cache(cfg, tokens.shape[0], cfg.seq)
    pre, cache = decode.prefill(params, cache, tokens, cfg)
    np.testing.assert_allclose(pre, full, atol=2e-5, rtol=2e-5)
    # the cache now holds K/V for every position, GQA-sized
    assert cache[0]["k"].shape == (2, cfg.seq, cfg.kv_heads,
                                   cfg.d_model // cfg.n_heads)


@pytest.mark.parametrize("cfg", [MHA, GQA], ids=["mha", "gqa"])
def test_incremental_decode_matches_forward(cfg):
    """Teacher-forced stepwise decode reproduces the training forward's
    logits at every position past the prompt."""
    params, tokens = _setup(cfg)
    split = cfg.seq // 2
    full = workload.forward(params, tokens, cfg)

    cache = decode.init_kv_cache(cfg, tokens.shape[0], cfg.seq)
    _, cache = decode.prefill(params, cache, tokens[:, :split], cfg)
    step = jax.jit(decode.decode_step, static_argnames=("cfg",))
    for pos in range(split, cfg.seq):
        logits, cache = step(params, cache, tokens[:, pos], pos, cfg)
        np.testing.assert_allclose(logits, full[:, pos], atol=3e-5, rtol=3e-5)


def test_gqa_cache_is_smaller():
    hd = MHA.d_model // MHA.n_heads
    mha_cache = decode.init_kv_cache(MHA, 1, 32)
    gqa_cache = decode.init_kv_cache(GQA, 1, 32)
    assert mha_cache[0]["k"].shape[2] == MHA.n_heads
    assert gqa_cache[0]["k"].shape[2] == 1  # n_heads/kv ratio × smaller
    assert gqa_cache[0]["k"].shape == (1, 32, 1, hd)


def test_gqa_params_are_smaller_and_train_step_runs():
    p_mha = workload.init_params(jax.random.PRNGKey(0), MHA)
    p_gqa = workload.init_params(jax.random.PRNGKey(0), GQA)
    assert p_gqa["layers"][0]["wk"].shape[1] < p_mha["layers"][0]["wk"].shape[1]
    tokens = jax.random.randint(jax.random.PRNGKey(2), (2, GQA.seq), 0, GQA.vocab)
    _, loss = workload.sgd_train_step(p_gqa, tokens, GQA)
    assert bool(jnp.isfinite(loss))


def test_generate_greedy_is_deterministic():
    params, tokens = _setup(MHA)
    gen = jax.jit(decode.generate, static_argnames=("cfg", "steps"))
    out = gen(params, tokens[:, :8], MHA, steps=6)
    assert out.shape == (2, 7)
    out2 = gen(params, tokens[:, :8], MHA, steps=6)
    np.testing.assert_array_equal(out, out2)


def test_invalid_gqa_config_fails_fast():
    with pytest.raises(ValueError, match="n_kv_heads"):
        dataclasses.replace(MHA, n_kv_heads=3)  # 2 heads % 3 != 0


# -- sampling -----------------------------------------------------------------

def test_sample_temperature_zero_is_greedy():
    cfg = workload.ModelConfig.tiny()
    params = workload.init_params(jax.random.PRNGKey(0), cfg)
    prompt = jnp.asarray([[1, 2, 3, 4, 5, 6, 7, 8]], jnp.int32)
    greedy = decode.generate(params, prompt, cfg, steps=6)
    sampled = decode.sample(params, prompt, cfg, steps=6,
                            key=jax.random.PRNGKey(9), temperature=0.0)
    assert (greedy == sampled).all()


def test_sample_top_k_one_is_greedy():
    cfg = workload.ModelConfig.tiny()
    params = workload.init_params(jax.random.PRNGKey(0), cfg)
    prompt = jnp.asarray([[1, 2, 3, 4, 5, 6, 7, 8]], jnp.int32)
    greedy = decode.generate(params, prompt, cfg, steps=6)
    sampled = decode.sample(params, prompt, cfg, steps=6,
                            key=jax.random.PRNGKey(9), temperature=1.0,
                            top_k=1)
    assert (greedy == sampled).all()


def test_sample_token_distribution_matches_softmax():
    """Statistical: categorical draws over a tiny vocab track the softmax."""
    logits = jnp.log(jnp.asarray([[0.5, 0.3, 0.15, 0.05]], jnp.float32))
    keys = jax.random.split(jax.random.PRNGKey(0), 4000)
    draws = jax.vmap(lambda k: decode.sample_token(logits, k))(keys)
    counts = jnp.bincount(draws.reshape(-1), length=4) / 4000.0
    np.testing.assert_allclose(counts, [0.5, 0.3, 0.15, 0.05], atol=0.04)


def test_sample_token_top_k_masks_tail():
    logits = jnp.log(jnp.asarray([[0.4, 0.3, 0.2, 0.1]], jnp.float32))
    keys = jax.random.split(jax.random.PRNGKey(1), 500)
    draws = jax.vmap(lambda k: decode.sample_token(logits, k, top_k=2))(keys)
    assert set(np.unique(draws)) <= {0, 1}
    # renormalized over the kept pair: 4:3 ratio
    counts = jnp.bincount(draws.reshape(-1), length=4) / 500.0
    np.testing.assert_allclose(counts[:2], [4 / 7, 3 / 7], atol=0.06)


def test_sample_token_top_p_nucleus():
    # token 0 alone carries 0.6 ≥ p → nucleus of exactly one token
    logits = jnp.log(jnp.asarray([[0.6, 0.2, 0.15, 0.05]], jnp.float32))
    keys = jax.random.split(jax.random.PRNGKey(2), 200)
    draws = jax.vmap(lambda k: decode.sample_token(logits, k, top_p=0.5))(keys)
    assert set(np.unique(draws)) == {0}
    # p=0.85: nucleus {0, 1, 2} (cum 0.6, 0.8, 0.95: third still needed)
    draws2 = jax.vmap(lambda k: decode.sample_token(logits, k, top_p=0.85))(keys)
    assert set(np.unique(draws2)) <= {0, 1, 2}
    assert 2 in np.unique(draws2)


def test_sample_temperature_sharpens():
    """Low temperature concentrates mass on the argmax token."""
    logits = jnp.log(jnp.asarray([[0.4, 0.3, 0.2, 0.1]], jnp.float32))
    keys = jax.random.split(jax.random.PRNGKey(3), 500)
    cold = jax.vmap(lambda k: decode.sample_token(logits, k,
                                                  temperature=0.1))(keys)
    # T=0.1 ⇒ p ∝ p_orig^10: token 0 holds ~0.945 of the mass
    frac0 = float(jnp.mean((cold == 0).astype(jnp.float32)))
    assert frac0 > 0.9


def test_sample_token_top_p_zero_is_near_greedy():
    """top_p=0.0 keeps exactly the rank-0 token — the most restrictive
    nucleus, never mask-everything-and-go-uniform."""
    logits = jnp.log(jnp.asarray([[0.4, 0.3, 0.2, 0.1]], jnp.float32))
    keys = jax.random.split(jax.random.PRNGKey(4), 100)
    draws = jax.vmap(lambda k: decode.sample_token(logits, k, top_p=0.0))(keys)
    assert set(np.unique(draws)) == {0}


def test_continuous_batch_per_sequence_positions():
    """decode_step with a (b,) position array: two sequences at DIFFERENT
    decode positions in one batch must produce exactly the logits each
    yields when decoded alone — the continuous-batching contract."""
    cfg = MHA
    params = workload.init_params(jax.random.PRNGKey(0), cfg)
    toks = jax.random.randint(jax.random.PRNGKey(7), (2, 12), 0, cfg.vocab)
    starts = (8, 5)
    max_seq = 16

    # independent single-sequence references, two steps each
    solo_logits = []
    solo_caches = []
    for r, s0 in enumerate(starts):
        cache = decode.init_kv_cache(cfg, 1, max_seq)
        _, cache = decode.prefill(params, cache, toks[r:r + 1, :s0], cfg)
        l1, cache = decode.decode_step(params, cache, toks[r:r + 1, s0],
                                       s0, cfg)
        l2, cache = decode.decode_step(params, cache, toks[r:r + 1, s0 + 1],
                                       s0 + 1, cfg)
        solo_logits.append((l1, l2))
        solo_caches.append(cache)

    # batched with per-row positions: prefill each row into a shared
    # batched cache (what a serving loop does when a request joins)
    cache = decode.init_kv_cache(cfg, 2, max_seq)
    for r, s0 in enumerate(starts):
        row = decode.init_kv_cache(cfg, 1, max_seq)
        _, row = decode.prefill(params, row, toks[r:r + 1, :s0], cfg)
        for i in range(cfg.n_layers):
            cache[i]["k"] = cache[i]["k"].at[r].set(row[i]["k"][0])
            cache[i]["v"] = cache[i]["v"].at[r].set(row[i]["v"][0])

    pos = jnp.asarray(starts)
    l1, cache = decode.decode_step(
        params, cache, jnp.stack([toks[0, 8], toks[1, 5]]), pos, cfg)
    l2, cache = decode.decode_step(
        params, cache, jnp.stack([toks[0, 9], toks[1, 6]]), pos + 1, cfg)
    for r in range(2):
        np.testing.assert_allclose(l1[r], solo_logits[r][0][0],
                                   atol=3e-5, rtol=3e-5)
        np.testing.assert_allclose(l2[r], solo_logits[r][1][0],
                                   atol=3e-5, rtol=3e-5)


def test_moe_decode_matches_dropless_forward():
    """MoE inference is DROPLESS end-to-end: a token's expert output is a
    pure function of the token, so KV-cache decode continues exactly the
    function prefill computed. (Capacity-based routing cannot have this
    property — see the companion test.)"""
    from tpusched.jaxbridge.workload import forward, init_params

    cfg = dataclasses.replace(workload.ModelConfig.tiny(), n_experts=4,
                              moe_top_k=2)
    params = init_params(jax.random.PRNGKey(0), cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0, cfg.vocab,
                                dtype=jnp.int32)
    steps = 6
    got = np.asarray(decode.generate(params, prompt, cfg, steps))
    seq = np.asarray(prompt)
    for _ in range(steps + 1):
        logits = forward(params, jnp.asarray(seq), cfg, dropless=True)
        nxt = np.asarray(jnp.argmax(logits[:, -1], axis=-1))
        seq = np.concatenate([seq, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(got, seq[:, 8:8 + steps + 1])


def test_moe_capacity_routing_is_batch_dependent():
    """Why inference must be dropless: under capacity routing a token's
    output depends on which OTHER tokens won capacity slots, so the same
    prefix through different batch shapes yields different logits — the
    training path trades exactness for the hardware-efficient dispatch
    (and the load-balance aux), which is fine for training and wrong for
    decode."""
    from tpusched.jaxbridge.workload import forward, init_params

    cfg = dataclasses.replace(workload.ModelConfig.tiny(), n_experts=4,
                              moe_top_k=2, moe_capacity_factor=1.0)
    params = init_params(jax.random.PRNGKey(0), cfg)
    tok8 = jax.random.randint(jax.random.PRNGKey(2), (1, 8), 0, cfg.vocab,
                              dtype=jnp.int32)
    tok16 = jnp.concatenate([tok8, tok8 + 1], axis=1)   # same first 8
    short = np.asarray(forward(params, tok8, cfg))[0, :8]
    long = np.asarray(forward(params, tok16, cfg))[0, :8]
    # capacity contention from the extra tokens moves the shared prefix's
    # logits; dropless leaves them untouched
    assert not np.allclose(short, long, atol=1e-5)
    short_d = np.asarray(forward(params, tok8, cfg, dropless=True))[0, :8]
    long_d = np.asarray(forward(params, tok16, cfg, dropless=True))[0, :8]
    np.testing.assert_allclose(short_d, long_d, atol=1e-5)


def test_int8_kv_cache_decode_tracks_exact():
    """Opt-in int8 KV cache: greedy decode over the quantized cache must
    track the exact-cache decode closely (symmetric per-(row, kv-head)
    scales bound the error), and prefill logits must stay within
    quantization tolerance of the exact path. Deterministic: fixed seeds,
    no flake surface."""
    cfg8 = dataclasses.replace(workload.ModelConfig.tiny(),
                               kv_cache_dtype="int8")
    cfg = workload.ModelConfig.tiny()
    params = workload.init_params(jax.random.PRNGKey(0), cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 12), 0, cfg.vocab,
                                dtype=jnp.int32)
    # prefill logits: quantization error enters only via the cache, which
    # prefill attention does NOT read (fresh k/v) — logits must be equal
    c8 = decode.init_kv_cache(cfg8, 2, 48)
    ce = decode.init_kv_cache(cfg, 2, 48)
    l8, c8 = decode.prefill(params, c8, prompt, cfg8)
    le, ce = decode.prefill(params, ce, prompt, cfg)
    np.testing.assert_allclose(np.asarray(l8), np.asarray(le), atol=1e-5)
    assert c8[0]["k"].dtype == jnp.int8 and "ks" in c8[0]
    # memory: int8 values + f32/hd scales ≈ (1 + 4/hd)/4 of f32 cache
    exact_bytes = ce[0]["k"].nbytes
    q_bytes = c8[0]["k"].nbytes + c8[0]["ks"].nbytes
    assert q_bytes < 0.6 * exact_bytes
    # decode: tokens may diverge where quantization flips a near-tie, but
    # on a fixed seed the two streams agree overwhelmingly
    g8 = np.asarray(decode.generate(params, prompt, cfg8, steps=24))
    ge = np.asarray(decode.generate(params, prompt, cfg, steps=24))
    agreement = float((g8 == ge).mean())
    assert agreement >= 0.8, f"int8 KV diverged too much: {agreement:.2f}"


def test_int8_kv_arena_scope():
    """The int8 arena composes with monolithic admission (round 5 — the
    insert programs quantize through decode's write discipline); chunked
    prefill is refused: its queries would attend DEQUANTIZED history
    where monolithic attends fresh values, silently breaking the
    chunk-size-invariance contract."""
    from tpusched.jaxbridge.serve import ServeEngine
    cfg8 = dataclasses.replace(workload.ModelConfig.tiny(),
                               kv_cache_dtype="int8")
    params = workload.init_params(jax.random.PRNGKey(0), cfg8)
    eng = ServeEngine(params, cfg8, slots=2, max_seq=64, prompt_bucket=16)
    assert eng.cache[0]["k"].dtype == jnp.int8 and "ks" in eng.cache[0]
    with pytest.raises(ValueError, match="monolithic admission"):
        ServeEngine(params, cfg8, slots=2, max_seq=64, prompt_bucket=16,
                    chunk_prefill=4)
    # the natural misconfiguration fails loudly at config construction
    with pytest.raises(ValueError, match="kv_cache_dtype"):
        dataclasses.replace(workload.ModelConfig.tiny(),
                            kv_cache_dtype=jnp.int8)
