"""KV-cache inference (prefill + decode) against the training forward, for
both MHA and grouped-query attention configs."""
from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpusched.jaxbridge import decode, workload

MHA = workload.ModelConfig.tiny()
GQA = dataclasses.replace(MHA, n_kv_heads=1)


def _setup(cfg, batch=2):
    params = workload.init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (batch, cfg.seq),
                                0, cfg.vocab)
    return params, tokens


@pytest.mark.parametrize("cfg", [MHA, GQA], ids=["mha", "gqa"])
def test_prefill_matches_forward(cfg):
    params, tokens = _setup(cfg)
    full = workload.forward(params, tokens, cfg)
    cache = decode.init_kv_cache(cfg, tokens.shape[0], cfg.seq)
    pre, cache = decode.prefill(params, cache, tokens, cfg)
    np.testing.assert_allclose(pre, full, atol=2e-5, rtol=2e-5)
    # the cache now holds K/V for every position, GQA-sized
    assert cache[0]["k"].shape == (2, cfg.seq, cfg.kv_heads,
                                   cfg.d_model // cfg.n_heads)


@pytest.mark.parametrize("cfg", [MHA, GQA], ids=["mha", "gqa"])
def test_incremental_decode_matches_forward(cfg):
    """Teacher-forced stepwise decode reproduces the training forward's
    logits at every position past the prompt."""
    params, tokens = _setup(cfg)
    split = cfg.seq // 2
    full = workload.forward(params, tokens, cfg)

    cache = decode.init_kv_cache(cfg, tokens.shape[0], cfg.seq)
    _, cache = decode.prefill(params, cache, tokens[:, :split], cfg)
    step = jax.jit(decode.decode_step, static_argnames=("cfg",))
    for pos in range(split, cfg.seq):
        logits, cache = step(params, cache, tokens[:, pos], pos, cfg)
        np.testing.assert_allclose(logits, full[:, pos], atol=3e-5, rtol=3e-5)


def test_gqa_cache_is_smaller():
    hd = MHA.d_model // MHA.n_heads
    mha_cache = decode.init_kv_cache(MHA, 1, 32)
    gqa_cache = decode.init_kv_cache(GQA, 1, 32)
    assert mha_cache[0]["k"].shape[2] == MHA.n_heads
    assert gqa_cache[0]["k"].shape[2] == 1  # n_heads/kv ratio × smaller
    assert gqa_cache[0]["k"].shape == (1, 32, 1, hd)


def test_gqa_params_are_smaller_and_train_step_runs():
    p_mha = workload.init_params(jax.random.PRNGKey(0), MHA)
    p_gqa = workload.init_params(jax.random.PRNGKey(0), GQA)
    assert p_gqa["layers"][0]["wk"].shape[1] < p_mha["layers"][0]["wk"].shape[1]
    tokens = jax.random.randint(jax.random.PRNGKey(2), (2, GQA.seq), 0, GQA.vocab)
    _, loss = workload.sgd_train_step(p_gqa, tokens, GQA)
    assert bool(jnp.isfinite(loss))


def test_generate_greedy_is_deterministic():
    params, tokens = _setup(MHA)
    gen = jax.jit(decode.generate, static_argnames=("cfg", "steps"))
    out = gen(params, tokens[:, :8], MHA, steps=6)
    assert out.shape == (2, 7)
    out2 = gen(params, tokens[:, :8], MHA, steps=6)
    np.testing.assert_array_equal(out, out2)


def test_invalid_gqa_config_fails_fast():
    with pytest.raises(ValueError, match="n_kv_heads"):
        dataclasses.replace(MHA, n_kv_heads=3)  # 2 heads % 3 != 0
