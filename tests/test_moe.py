"""MoE model family: routing, capacity, parity with dense, ep sharding.

The reference repo has no model code; this is the second flagship family
(Mixtral-style top-k MoE) the scheduler's gangs train, with GShard
capacity-based dispatch and expert parallelism over the ``ep`` mesh axis.
"""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpusched.jaxbridge import compat

# see tests/test_pipeline.py: partial-auto manual axes need jax.shard_map
needs_modern_shard_map = pytest.mark.skipif(
    not compat.have_modern_shard_map(),
    reason="needs jax.shard_map (partial-auto manual axes unsupported "
           "on the legacy experimental API)")

from tpusched.jaxbridge import workload
from tpusched.jaxbridge.workload import (ModelConfig, forward, init_params,
                                         loss_fn, make_sharded_train_step)


def moe_tiny(**kw):
    base = dict(vocab=128, d_model=32, n_layers=2, n_heads=2, d_ff=64,
                seq=16, n_experts=4, moe_top_k=2)
    base.update(kw)
    return ModelConfig(**base)


def test_moe_forward_shapes_and_finite():
    cfg = moe_tiny()
    params = init_params(jax.random.PRNGKey(0), cfg)
    assert params["layers"][0]["w_gate"].shape == (4, 32, 64)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, cfg.seq), 0,
                                cfg.vocab, dtype=jnp.int32)
    logits, aux = forward(params, tokens, cfg, with_aux=True)
    assert logits.shape == (2, cfg.seq, cfg.vocab)
    assert np.isfinite(np.asarray(logits)).all()
    # balanced-routing aux is ~1 (= E * sum_e (1/E)*(1/E) * E); always > 0
    assert 0.0 < float(aux)


def test_single_expert_equals_dense():
    """E=1, top-1, ample capacity: the MoE layer must reduce exactly to the
    dense SwiGLU with that expert's weights — gate weight is 1 after
    renormalization, no token is dropped."""
    cfg = moe_tiny(n_experts=1, moe_top_k=1, moe_capacity_factor=4.0)
    params = init_params(jax.random.PRNGKey(2), cfg)
    dense_cfg = moe_tiny(n_experts=0)
    dense_params = jax.tree_util.tree_map(lambda x: x, params)
    for layer in dense_params["layers"]:
        layer.pop("router")
        for w in ("w_gate", "w_up", "w_down"):
            layer[w] = layer[w][0]          # drop the E axis
    tokens = jax.random.randint(jax.random.PRNGKey(3), (2, cfg.seq), 0,
                                cfg.vocab, dtype=jnp.int32)
    got = forward(params, tokens, cfg)
    want = forward(dense_params, tokens, dense_cfg)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)


def test_capacity_overflow_drops_tokens_to_residual():
    """With capacity 4 and every token routed to one expert, overflowing
    tokens must pass through (MLP contribution zero), not corrupt others."""
    cfg = moe_tiny(n_experts=2, moe_top_k=1, moe_capacity_factor=0.25)
    params = init_params(jax.random.PRNGKey(4), cfg)
    # force all tokens to expert 0 via a huge router bias toward it
    for layer in params["layers"]:
        router = np.zeros((cfg.d_model, 2), np.float32)
        router[:, 0] = 1.0
        layer["router"] = jnp.asarray(router) * 100.0
    tokens = jnp.zeros((1, cfg.seq), jnp.int32)
    logits = forward(params, tokens, cfg)
    assert np.isfinite(np.asarray(logits)).all()
    # capacity = max(4, int(0.25 * 1 * 16 / 2) rounded) = 4 of 16 tokens
    # served; the run must still be finite and well-formed (drops are silent)


def test_moe_train_step_decreases_loss():
    cfg = moe_tiny()
    params = init_params(jax.random.PRNGKey(5), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(6), (4, cfg.seq), 0,
                                cfg.vocab, dtype=jnp.int32)
    step = jax.jit(lambda p, t: workload.sgd_train_step(p, t, cfg, lr=1e-1))
    losses = []
    for _ in range(8):
        params, loss = step(params, tokens)
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    assert all(np.isfinite(losses))


def test_moe_sharded_step_with_ep_axis():
    """Full MoE train step jitted over a dp×ep×tp mesh: expert weights shard
    E over ep, the dispatch einsum reshards tokens→experts (the all_to_all),
    and the step runs on the virtual 8-device CPU mesh."""
    from tpusched.jaxbridge.mesh import build_named_mesh
    mesh = build_named_mesh({"dp": 2, "ep": 2, "tp": 2})
    cfg = moe_tiny(n_experts=4)
    with mesh:
        step, pshard, tshard = make_sharded_train_step(mesh, cfg)
        params = init_params(jax.random.PRNGKey(7), cfg)
        params = jax.device_put(params, pshard)
        ws = params["layers"][0]["w_gate"]
        assert ws.sharding.spec == jax.sharding.PartitionSpec("ep", None, "tp")
        tokens = jax.device_put(
            jax.random.randint(jax.random.PRNGKey(8), (4, cfg.seq), 0,
                               cfg.vocab, dtype=jnp.int32), tshard)
        params, loss = step(params, tokens)
        assert np.isfinite(float(loss))


def test_moe_decode_path():
    """KV-cache generate() works for the MoE family (shared block tail)."""
    from tpusched.jaxbridge.decode import generate
    cfg = moe_tiny()
    params = init_params(jax.random.PRNGKey(9), cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(10), (2, 8), 0, cfg.vocab,
                                dtype=jnp.int32)
    toks = generate(params, prompt, cfg, steps=4)
    assert toks.shape == (2, 5)
    assert (np.asarray(toks) >= 0).all() and (np.asarray(toks) < cfg.vocab).all()


@needs_modern_shard_map
def test_moe_ringflash_full_matrix_mesh():
    """The complete parallelism composition on one mesh: data (dp), expert
    (ep), sequence (sp, ring-flash attention), tensor (tp). Loss must match
    the same model run with plain GSPMD attention on the same mesh."""
    import pytest
    from tpusched.jaxbridge.mesh import build_named_mesh
    mesh = build_named_mesh({"dp": 1, "ep": 2, "sp": 2, "tp": 2})
    cfg_naive = dataclasses.replace(workload.ModelConfig.tiny(), n_experts=4)
    cfg_rf = dataclasses.replace(cfg_naive, attn="ringflash")
    tokens = jax.random.randint(jax.random.PRNGKey(5), (2, cfg_rf.seq),
                                0, cfg_rf.vocab, dtype=jnp.int32)
    losses = {}
    for name, cfg in (("ringflash", cfg_rf), ("naive", cfg_naive)):
        step, pshard, tshard = workload.make_sharded_train_step(mesh, cfg)
        params = jax.device_put(workload.init_params(jax.random.PRNGKey(0),
                                                     cfg), pshard)
        toks = jax.device_put(tokens, tshard)
        _, loss = step(params, toks)
        losses[name] = float(loss)
    assert losses["ringflash"] == pytest.approx(losses["naive"], abs=1e-4)


def test_moe_train_step_flops_accounting():
    """VERDICT r3 #7: the MoE FLOP budget counts router, expert SwiGLU
    (padding slots included) and the dispatch/combine einsums explicitly."""
    import dataclasses
    from tpusched.jaxbridge.measure import moe_flops_note, train_step_flops
    from tpusched.jaxbridge.workload import ModelConfig

    moe = ModelConfig.mixtral_like(seq=1024)
    dense_same = dataclasses.replace(moe, n_experts=0)
    f_moe = train_step_flops(moe, 1)
    f_dense = train_step_flops(dense_same, 1)
    assert f_moe > f_dense  # top-2 of 8 experts + dispatch > one dense MLP
    # dispatch terms are O(tokens^2): doubling seq must more than double
    # the MoE-dense gap
    moe2 = dataclasses.replace(moe, seq=2048)
    dense2 = dataclasses.replace(dense_same, seq=2048)
    gap1 = f_moe - f_dense
    gap2 = train_step_flops(moe2, 1) - train_step_flops(dense2, 1)
    assert gap2 > 2.5 * gap1
    note = moe_flops_note(moe, 1)
    assert "dispatch" in note and "E=8" in note


def test_decode_bandwidth_accounting():
    """Decode roofline numerator: weight streaming dominates at b1, the KV
    term grows linearly with batch and context."""
    from tpusched.jaxbridge.measure import (decode_bytes_per_token,
                                            decode_bandwidth_utilization)
    from tpusched.jaxbridge.workload import ModelConfig

    cfg = ModelConfig.llama_like(seq=512)
    b1 = decode_bytes_per_token(cfg, 1, 128)
    b8 = decode_bytes_per_token(cfg, 8, 128)
    long = decode_bytes_per_token(cfg, 8, 512)
    assert b8 > b1                      # KV term scales with batch
    assert long > b8                    # and with live context
    kv1 = b8 - b1                       # 7 extra sequences' KV at ctx 128
    assert abs((long - b8) - kv1 * (8 / 7) * 3) / (long - b8) < 0.01
    # the embedding TABLE is gathered (batch rows), not streamed: doubling
    # the vocab must grow bytes/step by exactly one v*d matrix (the
    # out-projection) — charging embed+out would grow it by two
    import dataclasses as _dc
    cfg2v = _dc.replace(cfg, vocab=2 * cfg.vocab)
    itemsize = 2  # bf16
    assert (decode_bytes_per_token(cfg2v, 1, 128) - b1
            == cfg.vocab * cfg.d_model * itemsize)
    # MoE: dropless decode streams ALL E expert stacks + the f32 router;
    # vs the dense config of the same proportions, the delta per layer is
    # (E-1) extra SwiGLU stacks (bf16) + the router (f32)
    moe = ModelConfig.mixtral_like(seq=512)
    dense_twin = _dc.replace(moe, n_experts=0)
    delta = (decode_bytes_per_token(moe, 1, 128)
             - decode_bytes_per_token(dense_twin, 1, 128))
    L, d, f, E = moe.n_layers, moe.d_model, moe.d_ff, moe.n_experts
    assert delta == L * ((E - 1) * 3 * d * f * 2 + d * E * 4)
    # off-TPU the peak is unknown: utilization must decline to answer;
    # on a recognized chip it must answer with a positive fraction
    from tpusched.jaxbridge.measure import device_peak_hbm_gbps
    util = decode_bandwidth_utilization(cfg, 8, 128, 1000.0)
    if device_peak_hbm_gbps() is None:
        assert util is None
    else:
        assert util is not None and util > 0
