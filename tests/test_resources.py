"""Unit tests: resource quantities and pod request math
(reference analog: pkg/util/resource_test.go)."""
from tpusched.api.resources import (CPU, MEMORY, TPU, TPU_MEMORY,
                                    add_resources, make_resources,
                                    parse_quantity, resources_fit,
                                    sub_resources)
from tpusched.testing import make_pod
from tpusched.util.podutil import pod_effective_request


def test_parse_quantity_cpu():
    assert parse_quantity("2", CPU) == 2000
    assert parse_quantity("500m", CPU) == 500
    assert parse_quantity(1.5, CPU) == 1500


def test_parse_quantity_memory():
    assert parse_quantity("1Gi", MEMORY) == 2**30
    assert parse_quantity("512Mi", MEMORY) == 512 * 2**20
    assert parse_quantity("1G", MEMORY) == 10**9


def test_make_resources():
    r = make_resources(cpu="2", memory="4Gi", tpu=4, tpu_memory=1024)
    assert r[CPU] == 2000
    assert r[MEMORY] == 4 * 2**30
    assert r[TPU] == 4
    assert r[TPU_MEMORY] == 1024


def test_resource_arithmetic():
    a = {CPU: 1000, TPU: 2}
    b = {CPU: 500, MEMORY: 10}
    assert add_resources(a, b) == {CPU: 1500, TPU: 2, MEMORY: 10}
    assert sub_resources(a, b) == {CPU: 500, TPU: 2, MEMORY: -10}
    assert resources_fit({CPU: 500}, {CPU: 500})
    assert not resources_fit({CPU: 501}, {CPU: 500})
    assert not resources_fit({TPU: 1}, {CPU: 500})


def test_pod_effective_request_max_of_init_containers():
    # max(Σ containers, max(initContainers)) per resource (resource.go:50-78)
    pod = make_pod("p", requests={CPU: 1000})
    from tpusched.api.core import Container
    pod.spec.containers.append(Container(name="c2", requests={CPU: 500}))
    pod.spec.init_containers.append(Container(name="init", requests={CPU: 2000}))
    req = pod_effective_request(pod)
    assert req[CPU] == 2000  # init dominates
    pod.spec.init_containers[0].requests[CPU] = 1200
    assert pod_effective_request(pod)[CPU] == 1500  # sum dominates


def test_qos_classes():
    from tpusched.api.core import QOS_BEST_EFFORT, QOS_BURSTABLE, QOS_GUARANTEED
    best_effort = make_pod("be")
    assert best_effort.qos_class() == QOS_BEST_EFFORT
    burstable = make_pod("bu", requests={CPU: 100})
    assert burstable.qos_class() == QOS_BURSTABLE
    guaranteed = make_pod("gu", requests={CPU: 100, MEMORY: 100},
                          limits={CPU: 100, MEMORY: 100})
    assert guaranteed.qos_class() == QOS_GUARANTEED
