"""Continuous-batching serving engine (jaxbridge/serve.py). The load-bearing
contract: continuous batching is RESULT-IDENTICAL to running each request
alone — slot isolation is structural, so admission order, mixed lengths,
and mid-flight joins must never change any request's greedy output."""
import numpy as np
import pytest

jax = pytest.importorskip("jax")

from tpusched.jaxbridge.decode import generate
from tpusched.jaxbridge.serve import Request, ServeEngine, measure_serving
from tpusched.jaxbridge.workload import ModelConfig, init_params


@pytest.fixture(scope="module")
def model():
    cfg = ModelConfig.tiny()
    params = init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def _prompt(rng, lo, hi, vocab):
    return rng.integers(0, vocab, size=rng.integers(lo, hi),
                        dtype=np.int32)


@pytest.mark.parametrize("seed", [5, 23, 404])
def test_engine_matches_solo_generation(model, seed):
    """8 requests with mixed prompt/generation lengths through a 3-slot
    engine: every completion must equal generate() run alone — across
    several random mixes, since slot reuse order depends on the draw."""
    cfg, params = model
    rng = np.random.default_rng(seed)
    reqs = [Request(rid=i, prompt=_prompt(rng, 3, 17, cfg.vocab),
                    max_new_tokens=int(rng.integers(2, 9)))
            for i in range(8)]
    eng = ServeEngine(params, cfg, slots=3, max_seq=64, prompt_bucket=24)
    for r in reqs:
        eng.submit(r)
    done = eng.run_until_drained()
    assert sorted(c.rid for c in done) == list(range(8))
    for c in done:
        req = next(r for r in reqs if r.rid == c.rid)
        solo = np.asarray(generate(params, req.prompt[None, :], cfg,
                                   steps=req.max_new_tokens - 1))[0]
        np.testing.assert_array_equal(c.tokens, solo)


def test_mid_flight_admission_fills_freed_slots(model):
    """More requests than slots: later requests must be admitted as slots
    free up (continuous), not after the whole first batch drains."""
    cfg, params = model
    rng = np.random.default_rng(7)
    # slot hog (long) + short requests: shorts cycle through the other slot
    reqs = [Request(rid=0, prompt=_prompt(rng, 4, 8, cfg.vocab),
                    max_new_tokens=24)]
    reqs += [Request(rid=i, prompt=_prompt(rng, 4, 8, cfg.vocab),
                     max_new_tokens=3) for i in range(1, 6)]
    eng = ServeEngine(params, cfg, slots=2, max_seq=64, prompt_bucket=16)
    for r in reqs:
        eng.submit(r)
    done = eng.run_until_drained()
    by_rid = {c.rid: c for c in done}
    # the shorts were admitted while the hog still ran: each next short's
    # admission tick follows the previous one's finish, all before the
    # hog finished
    hog_finish = by_rid[0].finished_tick
    for i in range(2, 6):
        assert by_rid[i].admitted_tick >= by_rid[i - 1].finished_tick
    assert by_rid[1].finished_tick < hog_finish
    assert by_rid[5].admitted_tick < hog_finish


def test_eos_ends_generation_early(model):
    cfg, params = model
    rng = np.random.default_rng(11)
    prompt = _prompt(rng, 5, 9, cfg.vocab)
    solo = np.asarray(generate(params, prompt[None, :], cfg, steps=19))[0]
    eos = int(solo[2])                      # a token greedy WILL produce
    eng = ServeEngine(params, cfg, slots=2, max_seq=64, prompt_bucket=16)
    eng.submit(Request(rid=0, prompt=prompt, max_new_tokens=20,
                       eos_token=eos))
    done = eng.run_until_drained()
    assert len(done) == 1
    assert done[0].tokens[-1] == eos
    assert len(done[0].tokens) == 3         # stopped at the eos, not at 20


def test_submit_validates_bounds(model):
    cfg, params = model
    eng = ServeEngine(params, cfg, slots=1, max_seq=32, prompt_bucket=8)
    with pytest.raises(ValueError, match="bucket"):
        eng.submit(Request(rid=0, prompt=np.zeros(9, np.int32),
                           max_new_tokens=1))
    with pytest.raises(ValueError, match="max_seq"):
        eng.submit(Request(rid=0, prompt=np.zeros(8, np.int32),
                           max_new_tokens=32))


def test_measure_serving_reports_occupancy(model):
    cfg, params = model
    rng = np.random.default_rng(3)
    reqs = [Request(rid=i, prompt=_prompt(rng, 3, 9, cfg.vocab),
                    max_new_tokens=int(rng.integers(2, 7)))
            for i in range(6)]
    out = measure_serving(cfg, params, reqs, slots=2, max_seq=48,
                          prompt_bucket=16)
    assert out["tokens"] == sum(r.max_new_tokens for r in reqs)
    assert 0 < out["occupancy"] <= 1.0
    assert out["tokens_per_s"] > 0


def test_measure_serving_reporter_reports_true_rate(model):
    # the in-band report's throughput must equal the measured tokens/s —
    # not the per-tick rate inflated by the tick count
    from tpusched.jaxbridge.measure import GoodputReporter
    cfg, params = model
    rng = np.random.default_rng(5)
    reqs = [Request(rid=i, prompt=_prompt(rng, 3, 9, cfg.vocab),
                    max_new_tokens=int(rng.integers(2, 7)))
            for i in range(6)]
    batches = []

    class _CS:
        def report_status(self, reports):
            batches.append(list(reports))

    rep = GoodputReporter(_CS(), "default/srv-0", gang="default/srv",
                          min_interval_s=0.0)
    out = measure_serving(cfg, params, reqs, slots=2, max_seq=48,
                          prompt_bucket=16, reporter=rep)
    [batch] = batches
    [r] = batch
    assert r.throughput == pytest.approx(out["tokens_per_s"], rel=1e-6)
    assert r.step == out["ticks"]


def test_tp_sharded_engine_matches_unsharded(model):
    """Tensor-parallel serving on a tp=2 mesh (virtual CPU devices): the
    sharded engine's greedy completions must equal the unsharded solo
    outputs — GSPMD's inserted collectives may not change the math."""
    from jax.sharding import Mesh
    cfg, params = model
    devices = np.array(jax.devices()[:2])
    mesh = Mesh(devices, ("tp",))
    rng = np.random.default_rng(9)
    reqs = [Request(rid=i, prompt=_prompt(rng, 4, 12, cfg.vocab),
                    max_new_tokens=int(rng.integers(2, 6)))
            for i in range(4)]
    eng = ServeEngine(params, cfg, slots=2, max_seq=64, prompt_bucket=16,
                      mesh=mesh)
    for r in reqs:
        eng.submit(r)
    done = eng.run_until_drained()
    for c in done:
        req = next(r for r in reqs if r.rid == c.rid)
        solo = np.asarray(generate(params, req.prompt[None, :], cfg,
                                   steps=req.max_new_tokens - 1))[0]
        np.testing.assert_array_equal(c.tokens, solo)


def test_prompt_buckets_pick_smallest_fit(model):
    """Multi-bucket prefill: a short prompt compiles/uses the small bucket,
    a long one the big bucket — and parity still holds for both."""
    cfg, params = model
    rng = np.random.default_rng(13)
    eng = ServeEngine(params, cfg, slots=2, max_seq=64,
                      prompt_bucket=(8, 24))
    short = Request(rid=0, prompt=_prompt(rng, 3, 8, cfg.vocab),
                    max_new_tokens=3)
    long_ = Request(rid=1, prompt=_prompt(rng, 12, 24, cfg.vocab),
                    max_new_tokens=3)
    eng.submit(short)
    eng.submit(long_)
    done = eng.run_until_drained()
    assert set(eng._prefill_by_bucket) == {8, 24}
    for c in done:
        req = short if c.rid == 0 else long_
        solo = np.asarray(generate(params, req.prompt[None, :], cfg,
                                   steps=req.max_new_tokens - 1))[0]
        np.testing.assert_array_equal(c.tokens, solo)


@pytest.mark.parametrize("chunk", [4, 7, 16])
def test_chunked_prefill_matches_solo_generation(model, chunk):
    """chunk_prefill streams the prompt in through the decode-shaped chunk
    program instead of one monolithic insert; the result contract is
    unchanged — every completion equals generate() run alone. Chunk sizes
    straddle the prompt lengths: single-chunk, ragged-final-chunk, and
    exact-multiple cases all occur across the draw."""
    cfg, params = model
    rng = np.random.default_rng(11)
    reqs = [Request(rid=i, prompt=_prompt(rng, 3, 17, cfg.vocab),
                    max_new_tokens=int(rng.integers(2, 9)))
            for i in range(8)]
    eng = ServeEngine(params, cfg, slots=3, max_seq=64, prompt_bucket=24,
                      chunk_prefill=chunk)
    for r in reqs:
        eng.submit(r)
    done = eng.run_until_drained()
    assert sorted(c.rid for c in done) == list(range(8))
    for c in done:
        req = next(r for r in reqs if r.rid == c.rid)
        solo = np.asarray(generate(params, req.prompt[None, :], cfg,
                                   steps=req.max_new_tokens - 1))[0]
        np.testing.assert_array_equal(c.tokens, solo)


def test_chunked_prefill_interleaves_with_resident_decode(model):
    """The point of chunking: a resident sequence keeps producing tokens
    on every tick WHILE a long prompt streams in — a monolithic prefill
    would stall it for the whole insert."""
    cfg, params = model
    eng = ServeEngine(params, cfg, slots=2, max_seq=64, prompt_bucket=32,
                      chunk_prefill=4)
    rng = np.random.default_rng(3)
    resident = Request(rid=0,
                       prompt=rng.integers(0, cfg.vocab, 4, dtype=np.int32),
                       max_new_tokens=40)
    eng.submit(resident)
    for _ in range(4):          # resident admitted and decoding
        eng.tick()
    assert eng.req[0] is not None and eng.prefill_off[0] is None
    long_req = Request(rid=1,
                       prompt=rng.integers(0, cfg.vocab, 32, dtype=np.int32),
                       max_new_tokens=2)
    eng.submit(long_req)
    before = len(eng.generated[0])
    eng.tick()                  # admits the long prompt + first chunk
    prefill_ticks = 1
    while any(off is not None for off in eng.prefill_off):
        eng.tick()
        prefill_ticks += 1
    assert prefill_ticks >= 32 // 4 - 1       # genuinely streamed in chunks
    # the resident decoded on EVERY prefill tick — zero head-of-line stall
    assert len(eng.generated[0]) - before >= prefill_ticks
    done = eng.run_until_drained()
    for c in done:
        req = resident if c.rid == 0 else long_req
        solo = np.asarray(generate(params, req.prompt[None, :], cfg,
                                   steps=req.max_new_tokens - 1))[0]
        np.testing.assert_array_equal(c.tokens, solo)


def test_chunk_prefill_rejects_arena_overrun(model):
    """A final chunk whose full-extent write would cross max_seq is a
    construction-time error: dynamic_update_slice CLAMPS the start index,
    which would silently overwrite earlier prompt rows with K/V encoded
    for later positions — corruption, never an exception, so the engine
    must refuse the geometry up front."""
    cfg, params = model
    with pytest.raises(ValueError, match="chunk-aligned"):
        ServeEngine(params, cfg, slots=2, max_seq=64, prompt_bucket=60,
                    chunk_prefill=48)   # ceil(60/48)*48 = 96 > 64
    # the same chunk size with room to spare is fine
    ServeEngine(params, cfg, slots=2, max_seq=128, prompt_bucket=60,
                chunk_prefill=48)


def test_chunked_prefill_on_tp_mesh_matches_solo(model):
    """chunk_prefill composed with tensor-parallel serving: the chunk
    program's dynamic_update_slice/dynamic_slice on the kv-sharded arena
    must preserve shardings (GSPMD) and greedy parity simultaneously."""
    from jax.sharding import Mesh
    cfg, params = model
    mesh = Mesh(np.array(jax.devices()[:2]), ("tp",))
    rng = np.random.default_rng(17)
    reqs = [Request(rid=i, prompt=_prompt(rng, 6, 16, cfg.vocab),
                    max_new_tokens=int(rng.integers(2, 6)))
            for i in range(4)]
    eng = ServeEngine(params, cfg, slots=2, max_seq=64, prompt_bucket=16,
                      mesh=mesh, chunk_prefill=6)
    for r in reqs:
        eng.submit(r)
    done = eng.run_until_drained()
    assert sorted(c.rid for c in done) == list(range(4))
    for c in done:
        req = next(r for r in reqs if r.rid == c.rid)
        solo = np.asarray(generate(params, req.prompt[None, :], cfg,
                                   steps=req.max_new_tokens - 1))[0]
        np.testing.assert_array_equal(c.tokens, solo)


def test_prefix_caching_matches_solo_on_full_prompt(model):
    """Prefix caching: requests sharing a registered prefix copy its K/V
    device-side and prefill only their suffix — greedy output must equal
    generate() on the CONCATENATED prompt, interleaved with non-prefix
    tenants reusing the same slots (stale slot_prefix must never leak)."""
    cfg, params = model
    rng = np.random.default_rng(21)
    prefix = rng.integers(0, cfg.vocab, 12, dtype=np.int32)
    eng = ServeEngine(params, cfg, slots=2, max_seq=64, prompt_bucket=16,
                      chunk_prefill=5)
    eng.register_prefix("sys", prefix)
    reqs = []
    for i in range(6):
        if i % 2 == 0:
            reqs.append(Request(rid=i,
                                prompt=_prompt(rng, 4, 10, cfg.vocab),
                                max_new_tokens=int(rng.integers(2, 6)),
                                prefix_id="sys"))
        else:       # plain tenant between prefix tenants, same slots
            reqs.append(Request(rid=i,
                                prompt=_prompt(rng, 4, 10, cfg.vocab),
                                max_new_tokens=int(rng.integers(2, 6))))
    for r in reqs:
        eng.submit(r)
    done = eng.run_until_drained()
    assert sorted(c.rid for c in done) == list(range(6))
    for c in done:
        req = next(r for r in reqs if r.rid == c.rid)
        full = (np.concatenate([prefix, req.prompt])
                if req.prefix_id else req.prompt)
        assert c.prompt_len == len(full)
        solo = np.asarray(generate(params, full[None, :], cfg,
                                   steps=req.max_new_tokens - 1))[0]
        np.testing.assert_array_equal(c.tokens, solo)


def test_prefix_caching_validation(model):
    cfg, params = model
    rng = np.random.default_rng(23)
    prefix = rng.integers(0, cfg.vocab, 8, dtype=np.int32)
    mono = ServeEngine(params, cfg, slots=1, max_seq=64, prompt_bucket=16)
    with pytest.raises(ValueError, match="chunk"):
        mono.register_prefix("sys", prefix)
    eng = ServeEngine(params, cfg, slots=1, max_seq=32, prompt_bucket=8,
                      chunk_prefill=4)
    eng.register_prefix("sys", prefix)
    with pytest.raises(ValueError, match="unknown prefix_id"):
        eng.submit(Request(rid=0, prompt=np.zeros(4, np.int32),
                           max_new_tokens=1, prefix_id="nope"))
    with pytest.raises(ValueError, match="non-empty suffix"):
        eng.submit(Request(rid=0, prompt=np.zeros(0, np.int32),
                           max_new_tokens=1, prefix_id="sys"))
    with pytest.raises(ValueError, match="max_seq"):
        # prefix 8 + suffix 8 + 17 generated > 32
        eng.submit(Request(rid=0, prompt=np.zeros(8, np.int32),
                           max_new_tokens=17, prefix_id="sys"))


def test_prefix_caching_on_tp_mesh_matches_solo(model):
    """Prefix caching composed with tensor-parallel serving: the prefix
    K/V computed from tp-sharded params and memcpy'd into the kv-sharded
    arena must preserve shardings (GSPMD) and greedy parity."""
    from jax.sharding import Mesh
    cfg, params = model
    mesh = Mesh(np.array(jax.devices()[:2]), ("tp",))
    rng = np.random.default_rng(29)
    prefix = rng.integers(0, cfg.vocab, 9, dtype=np.int32)
    eng = ServeEngine(params, cfg, slots=2, max_seq=64, prompt_bucket=16,
                      mesh=mesh, chunk_prefill=6)
    eng.register_prefix("sys", prefix)
    reqs = [Request(rid=i, prompt=_prompt(rng, 4, 10, cfg.vocab),
                    max_new_tokens=int(rng.integers(2, 5)),
                    prefix_id="sys")
            for i in range(3)]
    for r in reqs:
        eng.submit(r)
    done = eng.run_until_drained()
    for c in done:
        req = next(r for r in reqs if r.rid == c.rid)
        full = np.concatenate([prefix, req.prompt])
        solo = np.asarray(generate(params, full[None, :], cfg,
                                   steps=req.max_new_tokens - 1))[0]
        np.testing.assert_array_equal(c.tokens, solo)


def test_prefix_reregistration_does_not_affect_queued_requests(model):
    """The resolved prefix entry is pinned at submit: re-registering the
    same prefix_id (even with a different length) before admission must
    not retroactively change — or un-validate — an already-queued
    request. The completion reflects the prefix that was registered when
    the request was submitted."""
    cfg, params = model
    rng = np.random.default_rng(31)
    old = rng.integers(0, cfg.vocab, 8, dtype=np.int32)
    eng = ServeEngine(params, cfg, slots=1, max_seq=64, prompt_bucket=16,
                      chunk_prefill=4)
    eng.register_prefix("sys", old)
    suffix = _prompt(rng, 4, 8, cfg.vocab)
    eng.submit(Request(rid=0, prompt=suffix, max_new_tokens=4,
                       prefix_id="sys"))
    # a longer prefix takes the id BEFORE the queued request admits; a
    # re-resolve at admission would shift every offset and corrupt rows
    eng.register_prefix("sys", rng.integers(0, cfg.vocab, 20,
                                            dtype=np.int32))
    done = eng.run_until_drained()
    full = np.concatenate([old, suffix])
    assert done[0].prompt_len == len(full)
    solo = np.asarray(generate(params, full[None, :], cfg, steps=3))[0]
    np.testing.assert_array_equal(done[0].tokens, solo)


def test_register_prefix_rejects_unusable_length(model):
    """A prefix so long that no chunk-aligned suffix + generation fits
    max_seq must fail AT REGISTRATION (before paying KV compute), not on
    every later submit."""
    cfg, params = model
    eng = ServeEngine(params, cfg, slots=1, max_seq=32, prompt_bucket=8,
                      chunk_prefill=8)
    with pytest.raises(ValueError, match="room"):
        eng.register_prefix("big", np.zeros(26, np.int32))  # 26+8 > 32
    eng.register_prefix("ok", np.zeros(24, np.int32))       # 24+8 == 32


def test_moe_engine_matches_solo_generation(model):
    """The serving engine over an MoE config: continuous batching, chunked
    prefill, and the lock-step decode tick must all route through the
    DROPLESS MoE path, keeping completions solo-identical (the capacity
    path would make a slot's tokens depend on its neighbors' routing)."""
    import dataclasses
    cfg = dataclasses.replace(ModelConfig.tiny(), n_experts=4, moe_top_k=2)
    params = init_params(jax.random.PRNGKey(3), cfg)
    rng = np.random.default_rng(37)
    reqs = [Request(rid=i, prompt=_prompt(rng, 3, 14, cfg.vocab),
                    max_new_tokens=int(rng.integers(2, 7)))
            for i in range(6)]
    for chunk in (None, 5):
        eng = ServeEngine(params, cfg, slots=3, max_seq=64, prompt_bucket=16,
                          chunk_prefill=chunk)
        for r in reqs:
            eng.submit(r)
        done = eng.run_until_drained()
        assert sorted(c.rid for c in done) == list(range(6))
        for c in done:
            req = next(r for r in reqs if r.rid == c.rid)
            solo = np.asarray(generate(params, req.prompt[None, :], cfg,
                                       steps=req.max_new_tokens - 1))[0]
            np.testing.assert_array_equal(c.tokens, solo)


@pytest.mark.parametrize("seed", [51, 77, 1234])
def test_serving_soak_composed_features(model, seed):
    """Randomized composition torture: chunked prefill + prefix caching +
    EOS early-stop + mixed lengths + slot churn in ONE engine run, every
    completion checked against solo generation on its full prompt. The
    serving analog of the scheduler's randomized soak — features that are
    each correct alone can still interact (slot reuse between prefix and
    plain tenants, chunk streams racing admissions, EOS mid-prefill)."""
    cfg, params = model
    rng = np.random.default_rng(seed)
    eng = ServeEngine(params, cfg, slots=3, max_seq=64, prompt_bucket=20,
                      chunk_prefill=int(rng.integers(3, 8)))
    prefix = rng.integers(0, cfg.vocab, int(rng.integers(6, 12)),
                          dtype=np.int32)
    eng.register_prefix("sys", prefix)
    reqs, fulls = [], {}
    for i in range(10):
        use_prefix = bool(rng.integers(0, 2))
        prompt = _prompt(rng, 3, 14, cfg.vocab)
        gen = int(rng.integers(2, 9))
        full = np.concatenate([prefix, prompt]) if use_prefix else prompt
        solo = np.asarray(generate(params, full[None, :], cfg,
                                   steps=gen - 1))[0]
        eos = None
        if rng.integers(0, 3) == 0 and gen >= 3:
            # pick a token greedy WILL emit mid-generation: the engine
            # must stop there, shortening the completion
            eos = int(solo[1])
        reqs.append(Request(rid=i, prompt=prompt, max_new_tokens=gen,
                            eos_token=eos,
                            prefix_id="sys" if use_prefix else None))
        fulls[i] = (full, solo, eos)
    for r in reqs:
        eng.submit(r)
    done = eng.run_until_drained()
    assert sorted(c.rid for c in done) == list(range(10))
    for c in done:
        full, solo, eos = fulls[c.rid]
        assert c.prompt_len == len(full)
        if eos is not None and eos in list(solo):
            stop = list(solo).index(eos)
            np.testing.assert_array_equal(c.tokens, solo[:stop + 1])
        else:
            np.testing.assert_array_equal(c.tokens, solo)


@pytest.mark.parametrize("seed", [61, 88])
def test_speculative_engine_matches_plain_engine(model, seed):
    """Batched speculation in the engine: per-slot draft proposals + one
    arena-wide verify stream must produce completions IDENTICAL to the
    plain engine on the same request set (which itself is solo-exact) —
    across mixed lengths, EOS early-stops, and slot churn. An unrelated
    random draft exercises heavy rejection; stats must account every
    round."""
    import dataclasses
    cfg, params = model
    draft_cfg = dataclasses.replace(cfg, n_layers=1, d_model=32, n_heads=2,
                                    d_ff=64)
    draft_params = init_params(jax.random.PRNGKey(500 + seed), draft_cfg)
    rng = np.random.default_rng(seed)
    reqs = []
    for i in range(8):
        prompt = _prompt(rng, 3, 15, cfg.vocab)
        gen = int(rng.integers(2, 10))
        eos = None
        if rng.integers(0, 3) == 0 and gen >= 4:
            solo = np.asarray(generate(params, prompt[None, :], cfg,
                                       steps=gen - 1))[0]
            eos = int(solo[1])
        reqs.append(Request(rid=i, prompt=prompt, max_new_tokens=gen,
                            eos_token=eos))
    plain = ServeEngine(params, cfg, slots=3, max_seq=64, prompt_bucket=16)
    spec = ServeEngine(params, cfg, slots=3, max_seq=64, prompt_bucket=16,
                       draft_params=draft_params, draft_cfg=draft_cfg,
                       spec_k=3)
    for eng in (plain, spec):
        for r in reqs:
            eng.submit(r)
    done_p = {c.rid: c for c in plain.run_until_drained()}
    done_s = {c.rid: c for c in spec.run_until_drained()}
    assert set(done_s) == set(range(8))
    for rid in done_s:
        np.testing.assert_array_equal(done_s[rid].tokens,
                                      done_p[rid].tokens)
    assert spec.spec_stats["rounds"] > 0
    assert spec.spec_stats["drafted"] >= spec.spec_stats["accepted"]


def test_speculative_engine_perfect_draft_compresses_rounds(model):
    """Draft == target: every proposal accepted, so each slot emits
    spec_k+1 tokens per round — total rounds collapse well below the
    token count (the batched analog of the perfect-draft bound)."""
    cfg, params = model
    rng = np.random.default_rng(3)
    reqs = [Request(rid=i, prompt=_prompt(rng, 4, 10, cfg.vocab),
                    max_new_tokens=12) for i in range(4)]
    spec = ServeEngine(params, cfg, slots=4, max_seq=64, prompt_bucket=16,
                       draft_params=params, draft_cfg=cfg, spec_k=3)
    plain = ServeEngine(params, cfg, slots=4, max_seq=64, prompt_bucket=16)
    for eng in (spec, plain):
        for r in reqs:
            eng.submit(r)
    done_s = {c.rid: c for c in spec.run_until_drained()}
    done_p = {c.rid: c for c in plain.run_until_drained()}
    for rid in done_s:
        np.testing.assert_array_equal(done_s[rid].tokens,
                                      done_p[rid].tokens)
    assert spec.spec_stats["accepted"] == spec.spec_stats["drafted"]
    # 12 tokens per slot, 4 per round after the admission token:
    # ceil(11/4) = 3 rounds per slot, all slots in parallel
    assert spec.spec_stats["rounds"] <= 4
    assert plain.tick_count > spec.tick_count


def test_speculative_engine_validation(model):
    import dataclasses
    cfg, params = model
    dcfg = dataclasses.replace(cfg, n_layers=1)
    dp = init_params(jax.random.PRNGKey(1), dcfg)
    with pytest.raises(ValueError, match="draft_cfg"):
        ServeEngine(params, cfg, draft_params=dp, max_seq=64,
                    prompt_bucket=16)
    with pytest.raises(ValueError, match="request_keyed"):
        # sampled speculation needs position-stable randomness
        ServeEngine(params, cfg, draft_params=dp, draft_cfg=dcfg,
                    temperature=0.5, max_seq=64, prompt_bucket=16)
    ServeEngine(params, cfg, draft_params=dp, draft_cfg=dcfg,
                temperature=0.5, request_keyed=True, max_seq=64,
                prompt_bucket=16)   # ...and composes with it
    with pytest.raises(ValueError, match="monolithic"):
        ServeEngine(params, cfg, draft_params=dp, draft_cfg=dcfg,
                    chunk_prefill=4, max_seq=64, prompt_bucket=16)
    eng = ServeEngine(params, cfg, draft_params=dp, draft_cfg=dcfg,
                      slots=1, max_seq=64, prompt_bucket=16)
    with pytest.raises(ValueError, match="non-empty prompt"):
        eng.submit(Request(rid=0, prompt=np.zeros(0, np.int32),
                           max_new_tokens=2))


def test_speculative_engine_rejects_arena_overrun(model):
    """The last round's verify span can overshoot the final accepted
    position by spec_k+1 rows; a budget without that headroom would be
    silently clamp-corrupted — must refuse at submit."""
    import dataclasses
    cfg, params = model
    dcfg = dataclasses.replace(cfg, n_layers=1)
    dp = init_params(jax.random.PRNGKey(2), dcfg)
    eng = ServeEngine(params, cfg, slots=1, max_seq=64, prompt_bucket=16,
                      draft_params=dp, draft_cfg=dcfg, spec_k=4)
    with pytest.raises(ValueError, match="overshoot"):
        eng.submit(Request(rid=0, prompt=np.zeros(16, np.int32),
                           max_new_tokens=44))   # 16+44+5 > 64
    eng.submit(Request(rid=0, prompt=np.zeros(16, np.int32),
                       max_new_tokens=43))       # 16+43+5 == 64: fits
    with pytest.raises(ValueError, match="draft_cfg without"):
        ServeEngine(params, cfg, draft_cfg=dcfg, max_seq=64,
                    prompt_bucket=16)


def test_speculative_engine_rejects_impossible_warmup_geometry(model):
    """A geometry the constructor accepts must be one warmup()/full-bucket
    submits can use: bucket + spec_k + 3 > max_seq means no full-bucket
    request could ever be admitted — refuse at construction, not at
    warmup-time deep inside first use."""
    import dataclasses
    cfg, params = model
    dcfg = dataclasses.replace(cfg, n_layers=1)
    dp = init_params(jax.random.PRNGKey(3), dcfg)
    with pytest.raises(ValueError, match="speculative geometry"):
        ServeEngine(params, cfg, slots=1, max_seq=24, prompt_bucket=16,
                    draft_params=dp, draft_cfg=dcfg, spec_k=6)  # 16+6+3>24
    # single-bucket boundary (16+6+3 == 25) compiles and warms up
    eng = ServeEngine(params, cfg, slots=1, max_seq=25, prompt_bucket=16,
                      draft_params=dp, draft_cfg=dcfg, spec_k=6)
    eng.warmup()
    # multi-bucket boundary: only the SMALLEST bucket warms with 2 new
    # tokens, so the largest needs just spec_k+2 headroom — (8,16) at
    # max_seq 24 is valid (8+6+3=17, 16+6+2=24) and must not be rejected
    eng = ServeEngine(params, cfg, slots=1, max_seq=24, prompt_bucket=(8, 16),
                      draft_params=dp, draft_cfg=dcfg, spec_k=6)
    eng.warmup()


def test_speculative_idle_slots_stay_finite(model):
    """With fewer requests than slots, the never-used slots sit at pos=0;
    the fused draft/verify programs must not compute a query row at
    position -1 (all-masked softmax => NaN). Run with debug_nans armed so
    any NaN in ANY batch row — active or idle — fails loudly."""
    import dataclasses
    cfg, params = model
    dcfg = dataclasses.replace(cfg, n_layers=1)
    dp = init_params(jax.random.PRNGKey(4), dcfg)
    eng = ServeEngine(params, cfg, slots=3, max_seq=64, prompt_bucket=16,
                      draft_params=dp, draft_cfg=dcfg, spec_k=3)
    rng = np.random.default_rng(5)
    eng.submit(Request(rid=0, prompt=_prompt(rng, 4, 10, cfg.vocab),
                       max_new_tokens=6))       # 1 request, 3 slots
    jax.config.update("jax_debug_nans", True)
    try:
        done = eng.run_until_drained()
    finally:
        jax.config.update("jax_debug_nans", False)
    assert len(done) == 1 and len(done[0].tokens) == 6


def test_speculative_engine_on_tp_mesh_matches_plain(model):
    """Speculative decoding over a 2-way tensor-parallel mesh (draft and
    target arenas both tp-sharded): emitted streams must equal the plain
    single-device engine token-for-token, and with a self-draft the accept
    path must genuinely engage."""
    from jax.sharding import Mesh
    cfg, params = model
    mesh = Mesh(np.array(jax.devices()[:2]), ("tp",))
    spec = ServeEngine(params, cfg, slots=2, max_seq=64, prompt_bucket=16,
                       mesh=mesh, draft_params=params, draft_cfg=cfg,
                       spec_k=3)
    plain = ServeEngine(params, cfg, slots=2, max_seq=64, prompt_bucket=16)
    rng = np.random.default_rng(17)
    reqs = [Request(rid=i, prompt=_prompt(rng, 3, 14, cfg.vocab),
                    max_new_tokens=int(rng.integers(3, 8)))
            for i in range(5)]
    for e in (spec, plain):
        for r in reqs:
            e.submit(r)
    got = {c.rid: list(c.tokens) for c in spec.run_until_drained()}
    want = {c.rid: list(c.tokens) for c in plain.run_until_drained()}
    assert got == want
    acc = spec.spec_stats["accepted"] / max(1, spec.spec_stats["drafted"])
    assert acc > 0.5   # self-draft: near-total acceptance


def test_int8_kv_arena_matches_solo_int8(model):
    """int8 KV arena (round 5): the engine's monolithic admission
    quantizes slot inserts exactly like solo prefill (fresh-KV prefill
    attention, per-(row, head) quant at write, fused dequant at cached
    reads), so continuous batching over the QUANTIZED arena is
    result-identical to solo int8 generate — the same parity contract the
    exact arena carries, at half the KV bytes."""
    import dataclasses
    cfg, params = model
    i8 = dataclasses.replace(cfg, kv_cache_dtype="int8")
    eng = ServeEngine(params, i8, slots=3, max_seq=64, prompt_bucket=16)
    rng = np.random.default_rng(29)
    reqs = [Request(rid=i, prompt=_prompt(rng, 3, 15, cfg.vocab),
                    max_new_tokens=int(rng.integers(2, 9)))
            for i in range(8)]
    for r in reqs:
        eng.submit(r)
    for c in eng.run_until_drained():
        req = next(r for r in reqs if r.rid == c.rid)
        solo = np.asarray(generate(params, req.prompt[None, :], i8,
                                   steps=req.max_new_tokens - 1))[0]
        np.testing.assert_array_equal(c.tokens, solo,
                                      err_msg=f"request {c.rid}")


def test_int8_speculative_matches_plain_int8(model):
    """Speculative decoding over an int8 TARGET arena (draft stays exact,
    enforced): the verify span writes/reads the same quantized rows
    sequential decode would, so emitted streams equal the plain int8
    engine token-for-token."""
    import dataclasses
    cfg, params = model
    i8 = dataclasses.replace(cfg, kv_cache_dtype="int8")
    spec = ServeEngine(params, i8, slots=2, max_seq=64, prompt_bucket=16,
                       draft_params=params, draft_cfg=cfg, spec_k=3)
    plain = ServeEngine(params, i8, slots=2, max_seq=64, prompt_bucket=16)
    rng = np.random.default_rng(31)
    reqs = [Request(rid=i, prompt=_prompt(rng, 3, 14, cfg.vocab),
                    max_new_tokens=int(rng.integers(3, 8)))
            for i in range(5)]
    for e in (spec, plain):
        for r in reqs:
            e.submit(r)
    got = {c.rid: list(c.tokens) for c in spec.run_until_drained()}
    want = {c.rid: list(c.tokens) for c in plain.run_until_drained()}
    assert got == want


def test_int8_arena_on_tp_mesh(model):
    """int8 arena + tensor-parallel mesh: values AND scale planes shard
    over kv_heads; parity against single-device int8 engine holds."""
    import dataclasses
    from jax.sharding import Mesh
    cfg, params = model
    i8 = dataclasses.replace(cfg, kv_cache_dtype="int8")
    mesh = Mesh(np.array(jax.devices()[:2]), ("tp",))
    sharded = ServeEngine(params, i8, slots=2, max_seq=64,
                          prompt_bucket=16, mesh=mesh)
    solo = ServeEngine(params, i8, slots=2, max_seq=64, prompt_bucket=16)
    assert "ks" in sharded.cache[0]
    rng = np.random.default_rng(37)
    reqs = [Request(rid=i, prompt=_prompt(rng, 4, 12, cfg.vocab),
                    max_new_tokens=5) for i in range(4)]
    for e in (sharded, solo):
        for r in reqs:
            e.submit(r)
    got = {c.rid: list(c.tokens) for c in sharded.run_until_drained()}
    want = {c.rid: list(c.tokens) for c in solo.run_until_drained()}
    assert got == want


def test_request_keyed_sampling_is_batching_invariant_and_solo_exact(model):
    """Request-keyed sampled serving (round 5): every token draws
    fold_in(fold_in(engine_key, rid), absolute_row), so a request's
    sampled stream is a pure function of (key, rid, rows) — IDENTICAL
    across slot counts, submission orders, and neighbors, and equal to
    decode.sample_position_keyed run solo. Sampled serving gets the same
    batching-invariance law greedy serving always had."""
    from tpusched.jaxbridge.decode import sample_position_keyed
    cfg, params = model
    rng = np.random.default_rng(43)
    reqs = [Request(rid=i, prompt=_prompt(rng, 3, 14, cfg.vocab),
                    max_new_tokens=int(rng.integers(3, 9)))
            for i in range(6)]

    def run(slots, order):
        eng = ServeEngine(params, cfg, slots=slots, max_seq=64,
                          prompt_bucket=16, temperature=0.8, top_k=24,
                          seed=5, request_keyed=True)
        for i in order:
            eng.submit(reqs[i])
        return {c.rid: list(c.tokens) for c in eng.run_until_drained()}

    a = run(2, range(6))
    b = run(4, list(reversed(range(6))))
    assert a == b                      # batching/order invariance
    chunked = ServeEngine(params, cfg, slots=3, max_seq=64,
                          prompt_bucket=16, temperature=0.8, top_k=24,
                          seed=5, request_keyed=True, chunk_prefill=5)
    for r in reqs:
        chunked.submit(r)
    c = {cm.rid: list(cm.tokens) for cm in chunked.run_until_drained()}
    assert c == a                      # chunk-size invariance composes
    for r in reqs:
        key_r = jax.random.fold_in(jax.random.PRNGKey(5), r.rid)
        solo = np.asarray(sample_position_keyed(
            params, r.prompt[None, :], cfg, r.max_new_tokens - 1, key_r,
            temperature=0.8, top_k=24))[0]
        assert a[r.rid] == list(solo), f"request {r.rid}"
    with pytest.raises(ValueError, match="request_keyed"):
        ServeEngine(params, cfg, slots=2, max_seq=64, prompt_bucket=16,
                    request_keyed=True)   # greedy consumes no randomness


def test_request_keyed_composes_with_tp_mesh(model):
    """Request-keyed sampling on a tensor-parallel mesh: the vmapped
    per-slot fold_in/categorical runs under GSPMD over sharded logits and
    must emit exactly the single-device request-keyed streams."""
    from jax.sharding import Mesh
    cfg, params = model
    mesh = Mesh(np.array(jax.devices()[:2]), ("tp",))
    rng = np.random.default_rng(67)
    reqs = [Request(rid=i, prompt=_prompt(rng, 3, 12, cfg.vocab),
                    max_new_tokens=5) for i in range(4)]

    def run(**kw):
        eng = ServeEngine(params, cfg, slots=2, max_seq=64,
                          prompt_bucket=16, temperature=0.8, top_k=24,
                          seed=5, request_keyed=True, **kw)
        for r in reqs:
            eng.submit(r)
        return {c.rid: list(c.tokens) for c in eng.run_until_drained()}

    assert run(mesh=mesh) == run()


def test_request_keyed_composes_with_int8_arena(model):
    """Orthogonal features compose: the quantized arena under
    request-keyed sampling still equals the solo position-keyed sampler
    run with the same int8 cfg (monolithic admission on both sides)."""
    import dataclasses
    from tpusched.jaxbridge.decode import sample_position_keyed
    cfg, params = model
    i8 = dataclasses.replace(cfg, kv_cache_dtype="int8")
    eng = ServeEngine(params, i8, slots=2, max_seq=64, prompt_bucket=16,
                      temperature=0.8, top_k=24, seed=5,
                      request_keyed=True)
    rng = np.random.default_rng(59)
    reqs = [Request(rid=i, prompt=_prompt(rng, 3, 12, cfg.vocab),
                    max_new_tokens=5) for i in range(4)]
    for r in reqs:
        eng.submit(r)
    got = {c.rid: list(c.tokens) for c in eng.run_until_drained()}
    for r in reqs:
        key_r = jax.random.fold_in(jax.random.PRNGKey(5), r.rid)
        solo = np.asarray(sample_position_keyed(
            params, r.prompt[None, :], i8, r.max_new_tokens - 1, key_r,
            temperature=0.8, top_k=24))[0]
        assert got[r.rid] == list(solo), f"request {r.rid}"


def test_sampled_speculative_serving_matches_solo(model):
    """Sampled speculative SERVING (request-keyed): per-request outputs
    must equal solo spec_decode.speculative_sample with
    fold_in(engine_key, rid) — same proposal, acceptance, residual, and
    bonus streams at the same absolute rows — for a WEAK draft (real
    rejections exercised)."""
    import dataclasses
    from tpusched.jaxbridge.spec_decode import speculative_sample
    cfg, params = model
    dcfg = dataclasses.replace(cfg, n_layers=1)
    dp = init_params(jax.random.PRNGKey(9), dcfg)
    eng = ServeEngine(params, cfg, slots=2, max_seq=64, prompt_bucket=16,
                      temperature=0.8, top_k=24, seed=5,
                      request_keyed=True, draft_params=dp, draft_cfg=dcfg,
                      spec_k=3)
    rng = np.random.default_rng(47)
    reqs = [Request(rid=i, prompt=_prompt(rng, 3, 12, cfg.vocab),
                    max_new_tokens=int(rng.integers(3, 8)))
            for i in range(4)]
    for r in reqs:
        eng.submit(r)
    got = {c.rid: list(c.tokens) for c in eng.run_until_drained()}
    assert eng.spec_stats["accepted"] < eng.spec_stats["drafted"], (
        "weak draft should see rejections — the residual path never ran")
    for r in reqs:
        key_r = jax.random.fold_in(jax.random.PRNGKey(5), r.rid)
        solo, _ = speculative_sample(params, cfg, dp, dcfg,
                                     r.prompt[None, :],
                                     r.max_new_tokens - 1, key_r, k=3,
                                     temperature=0.8, top_k=24)
        assert got[r.rid] == list(solo[0]), f"request {r.rid}"


def test_sampled_speculative_composes_with_int8_arena(model):
    """KEP-303's composition matrix row: sampled speculation over an int8
    TARGET arena still equals solo speculative_sample with the same int8
    cfg (quantized rows are identical on both sides; the acceptance math
    divides the same adjusted distributions)."""
    import dataclasses
    from tpusched.jaxbridge.spec_decode import speculative_sample
    cfg, params = model
    i8 = dataclasses.replace(cfg, kv_cache_dtype="int8")
    dcfg = dataclasses.replace(cfg, n_layers=1)
    dp = init_params(jax.random.PRNGKey(9), dcfg)
    eng = ServeEngine(params, i8, slots=2, max_seq=64, prompt_bucket=16,
                      temperature=0.8, top_k=24, seed=5,
                      request_keyed=True, draft_params=dp, draft_cfg=dcfg,
                      spec_k=3)
    rng = np.random.default_rng(61)
    reqs = [Request(rid=i, prompt=_prompt(rng, 3, 12, cfg.vocab),
                    max_new_tokens=5) for i in range(3)]
    for r in reqs:
        eng.submit(r)
    got = {c.rid: list(c.tokens) for c in eng.run_until_drained()}
    for r in reqs:
        key_r = jax.random.fold_in(jax.random.PRNGKey(5), r.rid)
        solo, _ = speculative_sample(params, i8, dp, dcfg,
                                     r.prompt[None, :],
                                     r.max_new_tokens - 1, key_r, k=3,
                                     temperature=0.8, top_k=24)
        assert got[r.rid] == list(solo[0]), f"request {r.rid}"


def test_sampled_speculative_self_draft_is_position_keyed(model):
    """Self-draft sampled speculation through the ENGINE collapses to the
    canonical position-keyed sampler — the full chain: batched sampled
    speculative serving == solo speculative_sample == solo
    sample_position_keyed."""
    from tpusched.jaxbridge.decode import sample_position_keyed
    cfg, params = model
    eng = ServeEngine(params, cfg, slots=2, max_seq=64, prompt_bucket=16,
                      temperature=0.8, top_k=24, seed=5,
                      request_keyed=True, draft_params=params,
                      draft_cfg=cfg, spec_k=3)
    rng = np.random.default_rng(53)
    reqs = [Request(rid=i, prompt=_prompt(rng, 3, 12, cfg.vocab),
                    max_new_tokens=6) for i in range(3)]
    for r in reqs:
        eng.submit(r)
    got = {c.rid: list(c.tokens) for c in eng.run_until_drained()}
    acc = eng.spec_stats["accepted"] / max(1, eng.spec_stats["drafted"])
    assert acc == 1.0
    for r in reqs:
        key_r = jax.random.fold_in(jax.random.PRNGKey(5), r.rid)
        solo = np.asarray(sample_position_keyed(
            params, r.prompt[None, :], cfg, r.max_new_tokens - 1, key_r,
            temperature=0.8, top_k=24))[0]
        assert got[r.rid] == list(solo), f"request {r.rid}"


def test_sampled_engine_is_deterministic_and_bounded(model):
    """Non-greedy serving (temperature/top-k/top-p): no solo-parity
    contract exists (RNG consumption differs by construction), but the
    sampled path must still be deterministic for a fixed engine seed,
    respect token-range/length bounds, and differ from greedy (the
    sampler is actually in the loop)."""
    cfg, params = model
    rng = np.random.default_rng(41)
    reqs = [Request(rid=i, prompt=_prompt(rng, 4, 10, cfg.vocab),
                    max_new_tokens=8) for i in range(4)]

    def run(seed, temperature):
        eng = ServeEngine(params, cfg, slots=2, max_seq=64,
                          prompt_bucket=16, temperature=temperature,
                          top_k=20, top_p=0.9, seed=seed)
        for r in reqs:
            eng.submit(r)
        return {c.rid: list(c.tokens) for c in eng.run_until_drained()}

    a = run(7, 0.8)
    b = run(7, 0.8)
    assert a == b                        # same seed ⇒ same stream
    for toks in a.values():
        assert len(toks) == 8
        assert all(0 <= t < cfg.vocab for t in toks)
    greedy = run(7, 0.0)
    assert a != greedy                   # the sampler is really sampling
