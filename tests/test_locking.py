"""util/locking.py: the runtime half of lock discipline.

Covers the ISSUE-6 acceptance points: lock-order cycle detection (a new
edge closing a cycle in the acquisition-order graph is a potential
deadlock), guarded-by runtime assertions (mutating declared state without
the declared lock is recorded at the mutation site), thread confinement,
Condition integration (wait/notify keeps the recorder's per-thread stack
exact), and ZERO overhead when debug mode is off (structural: off-mode
objects are the plain stdlib types — there is no wrapper to pay for).
"""
from __future__ import annotations

import threading

import pytest

from tpusched.util import locking


@pytest.fixture(autouse=True)
def _reset_locking():
    prev = locking.set_debug(False)
    locking.recorder().reset()
    yield
    locking.set_debug(prev)
    locking.recorder().reset()


def _run_in_thread(fn, name="t2"):
    t = threading.Thread(target=fn, name=name, daemon=True)
    t.start()
    t.join(timeout=10)
    assert not t.is_alive()


# -- zero overhead off ---------------------------------------------------------


def test_guarded_lock_off_mode_is_plain_stdlib_lock():
    lk = locking.GuardedLock("x")
    assert type(lk).__name__ == "RLock"           # threading.RLock factory
    nk = locking.GuardedLock("y", reentrant=False)
    assert type(nk) is type(threading.Lock())


def test_guarded_by_off_mode_leaves_instances_untouched():
    @locking.guarded_by("_lock", "_d")
    class Foo:
        def __init__(self):
            self._lock = locking.GuardedLock("Foo")
            self._d = {}

        def bad(self):
            self._d["k"] = 1          # unguarded — but debug is off

    f = Foo()
    assert type(f) is Foo                        # no class swap
    assert type(f._d) is dict                    # no container proxy
    f.bad()
    assert locking.recorder().violations() == []
    # declaration metadata is still present for the static rule
    assert Foo.__tpulint_guarded__ == {"_lock": ("_d",)}


def test_annotated_production_classes_are_plain_when_off():
    from tpusched.sched.cache import Cache
    c = Cache()
    assert type(c) is Cache
    assert type(c._pods) is dict
    assert type(c._lock).__name__ == "RLock"


# -- lock-order recorder --------------------------------------------------------


def test_cycle_detected_across_threads():
    locking.set_debug(True)
    a, b = locking.GuardedLock("A"), locking.GuardedLock("B")
    with a:
        with b:
            pass

    def inverted():
        with b:
            with a:
                pass
    _run_in_thread(inverted)
    cycles = locking.recorder().cycles()
    assert len(cycles) == 1
    assert "B -> A -> B" in cycles[0] or "A -> B -> A" in cycles[0]


def test_consistent_order_is_not_a_cycle():
    locking.set_debug(True)
    a, b = locking.GuardedLock("A"), locking.GuardedLock("B")
    for _ in range(3):
        with a:
            with b:
                pass

    def same_order():
        with a:
            with b:
                pass
    _run_in_thread(same_order)
    assert locking.recorder().cycles() == []
    assert locking.recorder().report()["edges"] == ["A -> B"]


def test_three_way_cycle_detected():
    locking.set_debug(True)
    a, b, c = (locking.GuardedLock(n) for n in "ABC")
    with a:
        with b:
            pass
    with b:
        with c:
            pass
    with c:
        with a:
            pass
    cycles = locking.recorder().cycles()
    assert len(cycles) == 1 and "C -> A" in cycles[0]


def test_reentrant_reacquisition_is_not_an_edge():
    locking.set_debug(True)
    a = locking.GuardedLock("A")
    with a:
        with a:                      # same instance: reentrancy, not order
            pass
    assert locking.recorder().report()["edges"] == []
    assert locking.recorder().cycles() == []


def test_distinct_instances_of_one_name_are_an_ordering_fact():
    locking.set_debug(True)
    a1, a2 = locking.GuardedLock("sib"), locking.GuardedLock("sib")
    with a1:
        with a2:                     # AB/BA risk between siblings
            pass
    assert "sib -> sib" in locking.recorder().report()["edges"]
    assert locking.recorder().cycles()     # self-edge = cycle


def test_strict_mode_raises_on_cycle():
    locking.set_debug(True)
    rec = locking.recorder()
    rec.strict = True
    try:
        a, b = locking.GuardedLock("A"), locking.GuardedLock("B")
        with a:
            with b:
                pass
        with b:
            with pytest.raises(locking.LockOrderError):
                a.acquire()
    finally:
        rec.strict = False
        # unwind whatever strict left half-acquired
        locking.recorder().reset()


def test_release_by_non_owner_recorded():
    locking.set_debug(True)
    a = locking.GuardedLock("A", reentrant=False)
    a.acquire()

    def release_foreign():
        a.release()
    _run_in_thread(release_foreign)
    assert any("released by non-owner" in v
               for v in locking.recorder().violations())


def test_liveness_witness_counts_acquires():
    locking.set_debug(True)
    a = locking.GuardedLock("A")
    with a:
        pass
    assert locking.recorder().report()["acquires"] >= 1


# -- guarded-by runtime assertions ---------------------------------------------


def _make_guarded():
    @locking.guarded_by("_lock", "_d", "_items", "_tags", "_n")
    class Box:
        def __init__(self):
            self._lock = locking.GuardedLock("Box")
            self._d = {}
            self._items = []
            self._tags = set()
            self._n = 0

        def good(self):
            with self._lock:
                self._d["a"] = 1
                self._items.append(2)
                self._tags.add(3)
                self._n = 4

        def bad_item(self):
            self._d["x"] = 1

        def bad_rebind(self):
            self._n = 9

        def bad_swap(self):
            self._d = {}

    return Box


def test_guarded_mutations_under_lock_are_clean():
    locking.set_debug(True)
    box = _make_guarded()()
    box.good()
    assert locking.recorder().violations() == []


def test_unguarded_container_mutation_recorded():
    locking.set_debug(True)
    box = _make_guarded()()
    box.bad_item()
    v = locking.recorder().violations()
    assert len(v) == 1 and "Box._d.__setitem__ without _lock" in v[0]


def test_unguarded_scalar_rebind_recorded():
    locking.set_debug(True)
    box = _make_guarded()()
    box.bad_rebind()
    assert any("Box._n.rebind without _lock" in v
               for v in locking.recorder().violations())


def test_container_swap_is_checked_and_rewrapped():
    locking.set_debug(True)
    box = _make_guarded()()
    box.bad_swap()                      # unguarded rebind of _d
    assert any("_d.rebind" in v for v in locking.recorder().violations())
    locking.recorder().reset()
    box.bad_item()                      # the REPLACEMENT dict is guarded too
    assert any("_d.__setitem__" in v
               for v in locking.recorder().violations())


def test_condition_guard_integration():
    locking.set_debug(True)

    @locking.guarded_by("_cv", "_q")
    class Q:
        def __init__(self):
            self._cv = threading.Condition(locking.GuardedLock("Q"))
            self._q = []

        def put(self, x):
            with self._cv:
                self._q.append(x)
                self._cv.notify_all()

        def take(self):
            with self._cv:
                while not self._q:
                    self._cv.wait(0.05)
                return self._q.pop()

    q = Q()
    got = []

    def consumer():
        got.append(q.take())
    t = threading.Thread(target=consumer, name="consumer", daemon=True)
    t.start()
    q.put(42)
    t.join(timeout=10)
    assert got == [42]
    assert locking.recorder().violations() == []


def test_production_cache_clean_under_debug():
    """The annotated Cache, exercised through its public API in debug mode,
    produces zero violations — the annotations match reality."""
    locking.set_debug(True)
    from tpusched.sched.cache import Cache
    from tpusched.testing.wrappers import make_node, make_pod
    c = Cache()
    assert type(c._pods).__name__ == "_GuardedDict"
    c.add_node(make_node("n1"))
    p = make_pod("p1")
    c.assume_pod(p, "n1")
    c.snapshot()
    c.finish_binding(p)
    c.add_pod(p)
    c.remove_pod(p)
    c.remove_node(make_node("n1"))
    assert locking.recorder().violations() == []


# -- thread confinement ----------------------------------------------------------


def test_thread_confined_flags_cross_thread_use():
    locking.set_debug(True)

    @locking.thread_confined
    class Conf:
        def __init__(self):
            self.x = 0

        def touch(self):
            self.x += 1

    c = Conf()
    c.touch()
    assert locking.recorder().violations() == []
    _run_in_thread(c.touch, name="intruder")
    v = locking.recorder().violations()
    assert len(v) == 1 and "confined to its first caller" in v[0]


def test_thread_confined_off_mode_untouched():
    @locking.thread_confined
    class Conf:
        def __init__(self):
            self.x = 0

        def touch(self):
            self.x += 1

    c = Conf()
    assert type(c) is Conf
    _run_in_thread(c.touch, name="intruder")
    assert locking.recorder().violations() == []


def test_equivcache_is_confined_in_debug_mode():
    locking.set_debug(True)
    from tpusched.sched.equivcache import EquivalenceCache
    ec = EquivalenceCache()
    ec.get("k")                          # claims the owner thread
    _run_in_thread(lambda: ec.get("k"), name="foreign-loop")
    assert any("EquivalenceCache" in v
               for v in locking.recorder().violations())
