"""util/locking.py: the runtime half of lock discipline.

Covers the ISSUE-6 acceptance points: lock-order cycle detection (a new
edge closing a cycle in the acquisition-order graph is a potential
deadlock), guarded-by runtime assertions (mutating declared state without
the declared lock is recorded at the mutation site), thread confinement,
Condition integration (wait/notify keeps the recorder's per-thread stack
exact), and ZERO overhead when debug mode is off (structural: off-mode
objects are the plain stdlib types — there is no wrapper to pay for).
"""
from __future__ import annotations

import threading
import time

import pytest

from tpusched.util import locking


@pytest.fixture(autouse=True)
def _reset_locking():
    prev = locking.set_debug(False)
    prev_tel = locking.set_telemetry(False)
    locking.recorder().reset()
    yield
    locking.set_debug(prev)
    locking.set_telemetry(prev_tel)
    locking.recorder().reset()


def _run_in_thread(fn, name="t2"):
    t = threading.Thread(target=fn, name=name, daemon=True)
    t.start()
    t.join(timeout=10)
    assert not t.is_alive()


# -- zero overhead off ---------------------------------------------------------


def test_guarded_lock_off_mode_is_plain_stdlib_lock():
    lk = locking.GuardedLock("x")
    assert type(lk).__name__ == "RLock"           # threading.RLock factory
    nk = locking.GuardedLock("y", reentrant=False)
    assert type(nk) is type(threading.Lock())


def test_guarded_by_off_mode_leaves_instances_untouched():
    @locking.guarded_by("_lock", "_d")
    class Foo:
        def __init__(self):
            self._lock = locking.GuardedLock("Foo")
            self._d = {}

        def bad(self):
            self._d["k"] = 1          # unguarded — but debug is off

    f = Foo()
    assert type(f) is Foo                        # no class swap
    assert type(f._d) is dict                    # no container proxy
    f.bad()
    assert locking.recorder().violations() == []
    # declaration metadata is still present for the static rule
    assert Foo.__tpulint_guarded__ == {"_lock": ("_d",)}


def test_annotated_production_classes_are_plain_when_off():
    from tpusched.sched.cache import Cache
    c = Cache()
    assert type(c) is Cache
    assert type(c._pods) is dict
    assert type(c._lock).__name__ == "RLock"


# -- lock-order recorder --------------------------------------------------------


def test_cycle_detected_across_threads():
    locking.set_debug(True)
    a, b = locking.GuardedLock("A"), locking.GuardedLock("B")
    with a:
        with b:
            pass

    def inverted():
        with b:
            with a:
                pass
    _run_in_thread(inverted)
    cycles = locking.recorder().cycles()
    assert len(cycles) == 1
    assert "B -> A -> B" in cycles[0] or "A -> B -> A" in cycles[0]


def test_consistent_order_is_not_a_cycle():
    locking.set_debug(True)
    a, b = locking.GuardedLock("A"), locking.GuardedLock("B")
    for _ in range(3):
        with a:
            with b:
                pass

    def same_order():
        with a:
            with b:
                pass
    _run_in_thread(same_order)
    assert locking.recorder().cycles() == []
    assert locking.recorder().report()["edges"] == ["A -> B"]


def test_three_way_cycle_detected():
    locking.set_debug(True)
    a, b, c = (locking.GuardedLock(n) for n in "ABC")
    with a:
        with b:
            pass
    with b:
        with c:
            pass
    with c:
        with a:
            pass
    cycles = locking.recorder().cycles()
    assert len(cycles) == 1 and "C -> A" in cycles[0]


def test_reentrant_reacquisition_is_not_an_edge():
    locking.set_debug(True)
    a = locking.GuardedLock("A")
    with a:
        with a:                      # same instance: reentrancy, not order
            pass
    assert locking.recorder().report()["edges"] == []
    assert locking.recorder().cycles() == []


def test_distinct_instances_of_one_name_are_an_ordering_fact():
    locking.set_debug(True)
    a1, a2 = locking.GuardedLock("sib"), locking.GuardedLock("sib")
    with a1:
        with a2:                     # AB/BA risk between siblings
            pass
    assert "sib -> sib" in locking.recorder().report()["edges"]
    assert locking.recorder().cycles()     # self-edge = cycle


def test_strict_mode_raises_on_cycle():
    locking.set_debug(True)
    rec = locking.recorder()
    rec.strict = True
    try:
        a, b = locking.GuardedLock("A"), locking.GuardedLock("B")
        with a:
            with b:
                pass
        with b:
            with pytest.raises(locking.LockOrderError):
                a.acquire()
    finally:
        rec.strict = False
        # unwind whatever strict left half-acquired
        locking.recorder().reset()


def test_release_by_non_owner_recorded():
    locking.set_debug(True)
    a = locking.GuardedLock("A", reentrant=False)
    a.acquire()

    def release_foreign():
        a.release()
    _run_in_thread(release_foreign)
    assert any("released by non-owner" in v
               for v in locking.recorder().violations())


def test_liveness_witness_counts_acquires():
    locking.set_debug(True)
    a = locking.GuardedLock("A")
    with a:
        pass
    assert locking.recorder().report()["acquires"] >= 1


# -- guarded-by runtime assertions ---------------------------------------------


def _make_guarded():
    @locking.guarded_by("_lock", "_d", "_items", "_tags", "_n")
    class Box:
        def __init__(self):
            self._lock = locking.GuardedLock("Box")
            self._d = {}
            self._items = []
            self._tags = set()
            self._n = 0

        def good(self):
            with self._lock:
                self._d["a"] = 1
                self._items.append(2)
                self._tags.add(3)
                self._n = 4

        def bad_item(self):
            self._d["x"] = 1

        def bad_rebind(self):
            self._n = 9

        def bad_swap(self):
            self._d = {}

    return Box


def test_guarded_mutations_under_lock_are_clean():
    locking.set_debug(True)
    box = _make_guarded()()
    box.good()
    assert locking.recorder().violations() == []


def test_unguarded_container_mutation_recorded():
    locking.set_debug(True)
    box = _make_guarded()()
    box.bad_item()
    v = locking.recorder().violations()
    assert len(v) == 1 and "Box._d.__setitem__ without _lock" in v[0]


def test_unguarded_scalar_rebind_recorded():
    locking.set_debug(True)
    box = _make_guarded()()
    box.bad_rebind()
    assert any("Box._n.rebind without _lock" in v
               for v in locking.recorder().violations())


def test_container_swap_is_checked_and_rewrapped():
    locking.set_debug(True)
    box = _make_guarded()()
    box.bad_swap()                      # unguarded rebind of _d
    assert any("_d.rebind" in v for v in locking.recorder().violations())
    locking.recorder().reset()
    box.bad_item()                      # the REPLACEMENT dict is guarded too
    assert any("_d.__setitem__" in v
               for v in locking.recorder().violations())


def test_condition_guard_integration():
    locking.set_debug(True)

    @locking.guarded_by("_cv", "_q")
    class Q:
        def __init__(self):
            self._cv = threading.Condition(locking.GuardedLock("Q"))
            self._q = []

        def put(self, x):
            with self._cv:
                self._q.append(x)
                self._cv.notify_all()

        def take(self):
            with self._cv:
                while not self._q:
                    self._cv.wait(0.05)
                return self._q.pop()

    q = Q()
    got = []

    def consumer():
        got.append(q.take())
    t = threading.Thread(target=consumer, name="consumer", daemon=True)
    t.start()
    q.put(42)
    t.join(timeout=10)
    assert got == [42]
    assert locking.recorder().violations() == []


def test_production_cache_clean_under_debug():
    """The annotated Cache, exercised through its public API in debug mode,
    produces zero violations — the annotations match reality."""
    locking.set_debug(True)
    from tpusched.sched.cache import Cache
    from tpusched.testing.wrappers import make_node, make_pod
    c = Cache()
    assert type(c._pods).__name__ == "_GuardedDict"
    c.add_node(make_node("n1"))
    p = make_pod("p1")
    c.assume_pod(p, "n1")
    c.snapshot()
    c.finish_binding(p)
    c.add_pod(p)
    c.remove_pod(p)
    c.remove_node(make_node("n1"))
    assert locking.recorder().violations() == []


# -- thread confinement ----------------------------------------------------------


def test_thread_confined_flags_cross_thread_use():
    locking.set_debug(True)

    @locking.thread_confined
    class Conf:
        def __init__(self):
            self.x = 0

        def touch(self):
            self.x += 1

    c = Conf()
    c.touch()
    assert locking.recorder().violations() == []
    _run_in_thread(c.touch, name="intruder")
    v = locking.recorder().violations()
    assert len(v) == 1 and "confined to its first caller" in v[0]


def test_thread_confined_off_mode_untouched():
    @locking.thread_confined
    class Conf:
        def __init__(self):
            self.x = 0

        def touch(self):
            self.x += 1

    c = Conf()
    assert type(c) is Conf
    _run_in_thread(c.touch, name="intruder")
    assert locking.recorder().violations() == []


def test_equivcache_is_confined_in_debug_mode():
    locking.set_debug(True)
    from tpusched.sched.equivcache import EquivalenceCache
    ec = EquivalenceCache()
    ec.get("k")                          # claims the owner thread
    _run_in_thread(lambda: ec.get("k"), name="foreign-loop")
    assert any("EquivalenceCache" in v
               for v in locking.recorder().violations())


# -- contention telemetry mode (ISSUE 7) ---------------------------------------


def test_telemetry_off_mode_is_plain_stdlib_lock():
    """The structural zero-overhead pin for TELEMETRY mode, same contract
    as debug mode: both modes off ⇒ the factory returns the plain stdlib
    lock — there is no wrapper to pay for."""
    lk = locking.GuardedLock("tel-off")
    assert type(lk).__name__ == "RLock"
    nk = locking.GuardedLock("tel-off-n", reentrant=False)
    assert type(nk) is type(threading.Lock())
    locking.set_telemetry(True)
    tk = locking.GuardedLock("tel-on")
    assert type(tk) is locking._TelemetryLock
    locking.set_telemetry(False)
    lk2 = locking.GuardedLock("tel-off-again")
    assert type(lk2).__name__ == "RLock"


def test_debug_wins_when_both_modes_requested():
    locking.set_debug(True)
    locking.set_telemetry(True)
    lk = locking.GuardedLock("both-modes")
    assert type(lk) is locking._InstrumentedLock


def test_contention_histograms_record_wait_and_hold():
    """Forced contention: one thread holds for ~20 ms while another blocks
    acquiring. The wait histogram must record exactly the contended
    acquire (uncontended ones never observe) and the hold histogram the
    long hold (the contender's own µs-hold stays below the threshold)."""
    import time as _t

    from tpusched.util.metrics import lock_hold_seconds, lock_wait_seconds

    locking.set_telemetry(True)
    lk = locking.GuardedLock("test.Contended")
    wait_h = lock_wait_seconds.with_labels("test.Contended")
    hold_h = lock_hold_seconds.with_labels("test.Contended")
    wait0, hold0 = wait_h.count(), hold_h.count()

    # uncontended acquire/release: nothing observed anywhere
    with lk:
        pass
    assert wait_h.count() == wait0
    assert hold_h.count() == hold0

    t2_done = threading.Event()

    def contender():
        with lk:
            pass
        t2_done.set()

    with lk:
        t = threading.Thread(target=contender, name="tel-contender",
                             daemon=True)
        t.start()
        _t.sleep(0.02)                 # contender blocks against this hold
    assert t2_done.wait(5)
    t.join(timeout=5)
    assert wait_h.count() == wait0 + 1          # exactly the contended one
    assert wait_h.quantile(0.5) >= 0.005        # it really waited ~20 ms
    assert hold_h.count() == hold0 + 1          # only the long hold
    assert hold_h.quantile(0.5) >= 0.01


def test_reentrant_telemetry_hold_spans_outermost_acquire():
    from tpusched.util.metrics import lock_hold_seconds

    locking.set_telemetry(True)
    lk = locking.GuardedLock("test.Reentrant")
    h = lock_hold_seconds.with_labels("test.Reentrant")
    before = h.count()
    import time as _t
    with lk:
        with lk:                       # reentrant: no inner hold segment
            _t.sleep(0.003)
    assert h.count() == before + 1     # one hold, outer-acquire to final
    assert h.quantile(0.5) >= 0.002    # release, covering the sleep


def test_condition_wait_is_not_charged_as_hold():
    """queue.pop()'s Condition wait is idle time, not a hold: a telemetry
    lock under threading.Condition must end the hold at wait() and start a
    fresh one at wakeup — a consumer blocking 50 ms on an empty queue must
    not read as a 50 ms lock hold."""
    from tpusched.util.metrics import lock_hold_seconds

    locking.set_telemetry(True)
    lk = locking.GuardedLock("test.CondTel")
    cv = threading.Condition(lk)
    h = lock_hold_seconds.with_labels("test.CondTel")
    before = h.count()
    with cv:
        cv.wait(0.05)                  # both hold segments are ~µs
    assert h.count() == before
    assert not lk.locked()


def test_contended_acquire_publishes_lock_attribution():
    """While blocked on a contended acquire the waiter publishes
    'blocked on <lock>' into the profiler's attribution context — the
    sampler attributes those samples to the lock, which is exactly the
    'Filter spends N% under the cache lock' signal."""
    from tpusched.util import tracectx

    locking.set_telemetry(True)
    lk = locking.GuardedLock("test.AttrLock")
    ident = {}
    started = threading.Event()
    t2_done = threading.Event()

    def contender():
        ident["v"] = threading.get_ident()
        started.set()
        with lk:
            pass
        t2_done.set()

    with lk:
        t = threading.Thread(target=contender, name="tel-attr-contender",
                             daemon=True)
        t.start()
        assert started.wait(5)
        deadline = time.monotonic() + 5
        seen = ""
        while time.monotonic() < deadline:
            seen = tracectx.attribution(ident["v"])[2]
            if seen == "test.AttrLock":
                break
            time.sleep(0.001)
        assert seen == "test.AttrLock"
    assert t2_done.wait(5)
    t.join(timeout=5)
    assert tracectx.attribution(ident["v"])[2] == ""   # restored
