"""Trimaran load-aware scoring tests — mirrors the reference's scoring math
suites (targetloadpacking_test.go, loadvariationriskbalancing_test.go,
analysis_test.go; SURVEY §4 'biggest suites')."""
import time

from tpusched.api.resources import CPU, make_resources
from tpusched.config.types import (LoadVariationRiskBalancingArgs,
                                   TargetLoadPackingArgs)
from tpusched.fwk import CycleState, PluginProfile
from tpusched.plugins.trimaran import (AVERAGE, CPU_TYPE, MEMORY_TYPE, STD,
                                       LoadVariationRiskBalancing, Metric,
                                       NodeMetrics, PodAssignEventHandler,
                                       ServiceClient, TargetLoadPacking,
                                       WatcherMetrics, Window)
from tpusched.plugins.trimaran.loadvariationriskbalancing import ResourceStats
from tpusched.testing import make_node, make_pod, new_test_framework


def metrics_for(node_values, window_end=None):
    data = {}
    for node, metrics in node_values.items():
        data[node] = NodeMetrics(metrics=metrics)
    return WatcherMetrics(timestamp=time.time(),
                          window=Window(start=0, end=window_end or time.time()),
                          data=data)


def minimal_profile():
    return PluginProfile(filter=["NodeResourcesFit"], bind=["DefaultBinder"])


def make_handle(nodes):
    fw, handle, api = new_test_framework(minimal_profile(), nodes=nodes)
    return handle


def test_tlp_score_curve():
    """Score rises to 100 at the target utilization then falls (:253-269)."""
    node = make_node("n1", capacity=make_resources(cpu=10, memory="64Gi"))
    handle = make_handle([node])

    def provider():
        return metrics_for({"n1": [Metric(type=CPU_TYPE, operator=AVERAGE,
                                          value=util[0])]})
    util = [0.0]
    plugin = TargetLoadPacking(TargetLoadPackingArgs(), handle, provider=provider)
    pod = make_pod("p")  # no cpu → default 1000m prediction = 10% of 10 cores

    util[0] = 0.0
    plugin.collector.update_metrics()
    s, _ = plugin.score(CycleState(), pod, "n1")
    assert s == round((100 - 40) * 10 / 40 + 40)  # predicted 10%

    util[0] = 30.0  # +10% pod → exactly at 40% target
    plugin.collector.update_metrics()
    s, _ = plugin.score(CycleState(), pod, "n1")
    assert s == 100

    util[0] = 60.0  # predicted 70% → penalised: 40*(100-70)/60 = 20
    plugin.collector.update_metrics()
    s, _ = plugin.score(CycleState(), pod, "n1")
    assert s == 20

    util[0] = 95.0  # predicted 105% → min score
    plugin.collector.update_metrics()
    s, _ = plugin.score(CycleState(), pod, "n1")
    assert s == 0


def test_tlp_missing_metrics_min_score():
    node = make_node("n1")
    handle = make_handle([node])
    plugin = TargetLoadPacking(TargetLoadPackingArgs(), handle,
                               provider=lambda: None)
    s, status = plugin.score(CycleState(), make_pod("p"), "n1")
    assert s == 0 and status.is_success()


def test_tlp_counts_recently_assigned_pods():
    node = make_node("n1", capacity=make_resources(cpu=10, memory="64Gi"))
    handle = make_handle([node])
    now = time.time()
    plugin = TargetLoadPacking(
        TargetLoadPackingArgs(), handle,
        provider=lambda: metrics_for(
            {"n1": [Metric(type=CPU_TYPE, operator=AVERAGE, value=0.0)]},
            window_end=now))
    plugin.collector.update_metrics()
    # a pod bound moments ago, invisible to the metrics window
    recent = make_pod("recent", requests={CPU: 2000}, node_name="n1")
    plugin.event_handler._record(recent)
    pod = make_pod("p")   # default 1000m
    s, _ = plugin.score(CycleState(), pod, "n1")
    # predicted = (0 + 1000 + 2000*1.5)/10000 = 40% → score 100
    assert s == 100


def test_tlp_prediction_rules():
    handle = make_handle([make_node("n1")])
    plugin = TargetLoadPacking(TargetLoadPackingArgs(), handle,
                               provider=lambda: None)
    from tpusched.api.core import Container
    assert plugin.predict_utilisation(Container(limits={CPU: 3000})) == 3000
    assert plugin.predict_utilisation(Container(requests={CPU: 1000})) == 1500
    assert plugin.predict_utilisation(Container()) == 1000


def test_lvrb_risk_formula():
    """risk = (mu + margin*sigma^(1/sensitivity))/2 (analysis.go:48-78)."""
    rs = ResourceStats(used_avg=50.0, used_stdev=10.0, req=0.0, capacity=100.0)
    assert round(rs.compute_score(1.0, 1.0)) == 70     # (0.5+0.1)/2=0.3
    rs = ResourceStats(used_avg=0.0, used_stdev=0.0, req=0.0, capacity=100.0)
    assert round(rs.compute_score(1.0, 1.0)) == 100
    # sensitivity < 1 amplifies variance: sigma^(1/0.5)=sigma^2
    rs = ResourceStats(used_avg=0.0, used_stdev=50.0, req=0.0, capacity=100.0)
    assert round(rs.compute_score(1.0, 0.5)) == round((1 - 0.25 / 2) * 100)


def test_lvrb_combines_cpu_memory_min():
    node = make_node("n1", capacity=make_resources(cpu=10, memory="1Gi"))
    handle = make_handle([node])
    plugin = LoadVariationRiskBalancing(
        LoadVariationRiskBalancingArgs(), handle,
        provider=lambda: metrics_for({"n1": [
            Metric(type=CPU_TYPE, operator=AVERAGE, value=40.0),
            Metric(type=CPU_TYPE, operator=STD, value=20.0),
            Metric(type=MEMORY_TYPE, operator=AVERAGE, value=80.0),
            Metric(type=MEMORY_TYPE, operator=STD, value=0.0),
        ]}))
    plugin.collector.update_metrics()
    s, _ = plugin.score(CycleState(), make_pod("p"), "n1")
    # cpu risk=(0.4+0.2)/2=0.3→70; mem risk=0.4→60; min = 60
    assert s == 60


def test_service_client_http_roundtrip():
    """The reference integration tier fakes the watcher at the HTTP layer
    (targetloadpacking_test.go:56-95); same here with the shared double."""
    from tpusched.testing import FakeWatcher
    with FakeWatcher(window_end=100) as w:
        w.node_metrics = {"n1": [{"type": "CPU", "operator": "Average",
                                  "value": 42.5}]}
        client = ServiceClient(w.address)
        m = client.get_latest_watcher_metrics()
        assert m is not None
        assert m.data["n1"].metrics[0].value == 42.5
        assert m.window.end == 100


def test_assign_handler_cleanup():
    fw, handle, api = new_test_framework(minimal_profile())
    now = [1000.0]
    h = PodAssignEventHandler(handle.informer_factory, clock=lambda: now[0],
                              auto_cleanup=False)
    h._record(make_pod("old", node_name="n1"))
    now[0] += 120
    h._record(make_pod("new", node_name="n1"))
    h.cleanup()
    pods = [p.name for _, p in h.recent_pods("n1")]
    assert pods == ["new"]


def test_assign_handler_stop_detaches_from_informer():
    """After stop(), informer events must no longer feed the cache (the
    handler's registration is removed, not just its GC thread)."""
    fw, handle, api = new_test_framework(minimal_profile())
    h = PodAssignEventHandler(handle.informer_factory, auto_cleanup=False)
    from tpusched.apiserver import server as srv
    p1 = make_pod("p1", node_name="n1")
    api.create(srv.PODS, p1)
    assert [p.name for _, p in h.recent_pods("n1")] == ["p1"]
    h.stop()
    api.create(srv.PODS, make_pod("p2", node_name="n1"))
    assert [p.name for _, p in h.recent_pods("n1")] == ["p1"]
