"""Gang admission under team quotas — Coscheduling and CapacityScheduling
composed in ONE profile, the production shape neither plugin's own suite
exercises: all-or-nothing admission gated by ElasticQuota, and quota
reclamation that preempts another team's borrowers to make room for a whole
gang (reference composes the same way: both are framework plugins in one
scheduler, SURVEY §1).
"""
from tpusched.api.resources import TPU
from tpusched.apiserver import server as srv
from tpusched.config.types import CoschedulingArgs
from tpusched.fwk import PluginProfile
from tpusched.testing import (TestCluster, make_elastic_quota, make_pod,
                              make_pod_group, make_tpu_node, wait_until)


def gang_quota_profile(permit_wait_s=10, denied_s=1):
    return PluginProfile(
        queue_sort="Coscheduling",
        pre_filter=["Coscheduling", "CapacityScheduling"],
        filter=["NodeUnschedulable", "NodeResourcesFit", "TpuSlice"],
        post_filter=["Coscheduling", "CapacityScheduling"],
        score=[("TpuSlice", 1)],
        reserve=["TpuSlice", "CapacityScheduling", "Coscheduling"],
        permit=["Coscheduling"],
        bind=["TpuSlice"],
        post_bind=["Coscheduling"],
        plugin_args={"Coscheduling": CoschedulingArgs(
            permit_waiting_time_seconds=permit_wait_s,
            denied_pg_expiration_time_seconds=denied_s)},
    )


def team_quota(c, team, min_chips, max_chips):
    c.api.create(srv.ELASTIC_QUOTAS, make_elastic_quota(
        f"{team}-quota", team, min={TPU: min_chips}, max={TPU: max_chips}))


def gang(c, name, team, members, chips=4, priority=0):
    c.api.create(srv.POD_GROUPS, make_pod_group(
        name, namespace=team, min_member=members))
    pods = [make_pod(f"{name}-{i}", namespace=team, pod_group=name,
                     limits={TPU: chips}, priority=priority)
            for i in range(members)]
    c.create_pods(pods)
    return pods


def test_gang_over_quota_wholly_denied_until_quota_raised():
    """A gang needing more than its team's quota: NO member binds even though
    the cluster has room (the gang's 3rd member would overrun max and the
    aggregate-min borrowing gate — one team means usable == min); raising the
    quota admits the whole gang."""
    with TestCluster(profile=gang_quota_profile()) as c:
        nodes = [make_tpu_node(f"h{i}", chips=4) for i in range(8)]
        c.add_nodes(nodes)
        team_quota(c, "team-a", min_chips=8, max_chips=8)
        pods = gang(c, "big", "team-a", members=4)  # 16 chips > quota 8
        assert c.wait_for_pods_unscheduled([p.key for p in pods], hold=1.5)

        def raise_quota(eq):
            eq.spec.min[TPU] = 16
            eq.spec.max[TPU] = 16
        c.api.patch(srv.ELASTIC_QUOTAS, "team-a/team-a-quota", raise_quota)
        # the gang's LAST rejection was Coscheduling's denied-window fast-fail
        # (PostFilter denied the group when quota failed a member), so the EQ
        # update alone doesn't requeue it — a Node event does (Coscheduling
        # registers Node add|update), as a real cluster's constant event
        # stream would; the 30s unschedulable flush is the backstop
        import time as _t
        _t.sleep(1.2)  # let the denied-PG TTL lapse
        c.api.patch(srv.NODES, nodes[0].meta.key, lambda n: None)
        assert c.wait_for_pods_scheduled([p.key for p in pods], timeout=20)


def test_gang_reclaims_min_by_preempting_borrowers():
    """team-a borrows the whole pool with regular pods; team-b's gang (its
    guaranteed min) preempts borrowers and admits ATOMICALLY — no partial
    gang while victims drain (BASELINE eval #4 shape, gang-composed)."""
    with TestCluster(profile=gang_quota_profile(permit_wait_s=20)) as c:
        c.add_nodes([make_tpu_node(f"h{i}", chips=4) for i in range(8)])
        team_quota(c, "team-a", min_chips=16, max_chips=32)
        team_quota(c, "team-b", min_chips=16, max_chips=32)
        borrowers = [make_pod(f"a-{i}", namespace="team-a", limits={TPU: 4})
                     for i in range(8)]    # 32 chips: 16 min + 16 borrowed
        c.create_pods(borrowers)
        assert c.wait_for_pods_scheduled([p.key for p in borrowers])

        pods = gang(c, "reclaim", "team-b", members=4)  # 16 chips = b's min
        assert c.wait_for_pods_scheduled([p.key for p in pods], timeout=30)
        # exactly the borrowed surplus was evicted (team-a keeps its min)
        surviving = [b for b in borrowers if c.pod(b.key) is not None]
        assert len(surviving) == 4, f"{len(surviving)} team-a pods survive"


def test_gang_within_min_unaffected_by_other_teams_gangs():
    """Both teams run gangs within their min simultaneously — neither is
    denied or preempted."""
    with TestCluster(profile=gang_quota_profile()) as c:
        c.add_nodes([make_tpu_node(f"h{i}", chips=4) for i in range(8)])
        team_quota(c, "team-a", min_chips=16, max_chips=32)
        team_quota(c, "team-b", min_chips=16, max_chips=32)
        a = gang(c, "job-a", "team-a", members=4)
        b = gang(c, "job-b", "team-b", members=4)
        keys = [p.key for p in a + b]
        assert c.wait_for_pods_scheduled(keys, timeout=20)
        assert all(c.pod(k) is not None for k in keys)


def test_full_stack_slice_gang_under_quota_with_topology():
    """The full-stack profile end to end: a slice-shaped gang under a team
    quota lands as one contiguous torus block with chip annotations; a
    second team's slice gang reclaims its min by preempting the first
    team's borrowed SECOND slice — torus fitting, gang atomicity, and
    quota-aware preemption composed in one scheduler."""
    from tpusched.config.profiles import full_stack_profile
    from tpusched.plugins.topologymatch import COORD_ANNOTATION
    from tpusched.testing import make_tpu_pool

    prof = full_stack_profile(permit_wait_s=20, denied_s=1)
    with TestCluster(profile=prof) as c:
        topo, nodes = make_tpu_pool("pool", dims=(4, 4, 8))  # 128 chips
        c.api.create(srv.TPU_TOPOLOGIES, topo)
        c.add_nodes(nodes)
        team_quota(c, "team-a", min_chips=64, max_chips=128)
        team_quota(c, "team-b", min_chips=64, max_chips=128)

        def slice_gang(team, name):
            c.api.create(srv.POD_GROUPS, make_pod_group(
                name, namespace=team, min_member=16,
                tpu_slice_shape="4x4x4", tpu_accelerator="tpu-v5p"))
            ps = [make_pod(f"{name}-{i}", namespace=team, pod_group=name,
                           limits={TPU: 4}) for i in range(16)]
            c.create_pods(ps)
            return ps

        a1 = slice_gang("team-a", "a-first")   # within min
        assert c.wait_for_pods_scheduled([p.key for p in a1], timeout=30)
        a2 = slice_gang("team-a", "a-borrow")  # borrowed: 128 used vs min 64
        assert c.wait_for_pods_scheduled([p.key for p in a2], timeout=30)
        # every member carries torus coords; each gang is 16 distinct hosts
        for gang_pods in (a1, a2):
            coords = {c.pod(p.key).meta.annotations[COORD_ANNOTATION]
                      for p in gang_pods}
            assert len(coords) == 16

        b1 = slice_gang("team-b", "b-reclaim")  # b's min: must evict a2
        assert c.wait_for_pods_scheduled([p.key for p in b1], timeout=40)
        # team-a keeps its guaranteed first slice, loses the borrowed one
        assert all(c.pod(p.key) is not None for p in a1)
        assert all(c.pod(p.key) is None for p in a2)
