"""Parallel Filter/Score machinery: Parallelizer semantics, the vectorized
NodeResourcesFit batch path's parity with its per-node path, and the
CycleState atomic memo used by parallel score plugins."""
import threading

import pytest

from tpusched.api.resources import TPU, make_resources
from tpusched.fwk import CycleState, PluginProfile
from tpusched.fwk.nodeinfo import NodeInfo
from tpusched.plugins.defaults import NodeResourcesFit
from tpusched.testing import make_node, make_pod, make_tpu_node
from tpusched.util.parallelize import Parallelizer


def test_until_runs_every_item():
    par = Parallelizer(4)
    hit = [0] * 100

    def work(i):
        hit[i] += 1

    par.until(100, work)
    par.close()
    assert hit == [1] * 100


def test_until_early_stop_bounded():
    par = Parallelizer(4)
    lock = threading.Lock()
    done = []

    def work(i):
        with lock:
            done.append(i)

    par.until(1000, work, stop=lambda: len(done) >= 10)
    par.close()
    # stop is checked between items: bounded overshoot, not a full sweep
    assert 10 <= len(done) < 1000


def test_until_propagates_errors():
    par = Parallelizer(4)

    def work(i):
        if i == 37:
            raise RuntimeError("boom")

    with pytest.raises(RuntimeError, match="boom"):
        par.until(64, work)
    par.close()


def test_serial_mode_is_inline_and_ordered():
    par = Parallelizer(1)
    seen = []
    par.until(10, seen.append, stop=lambda: len(seen) >= 5)
    assert seen == [0, 1, 2, 3, 4]   # deterministic serial early stop
    assert par.map(lambda i: i * i, 5) == [0, 1, 4, 9, 16]


def test_map_ordered_under_parallelism():
    par = Parallelizer(8)
    assert par.map(lambda i: i * 2, 500) == [i * 2 for i in range(500)]
    par.close()


# -- batch filter parity ------------------------------------------------------

def _infos():
    nodes = [make_node(f"n{i}", capacity=make_resources(
        cpu=(i % 5) * 1000, memory=f"{(i % 7) + 1}Gi", pods=3))
        for i in range(40)]
    for i, n in enumerate(nodes):
        if i % 3 == 0:
            n.status.allocatable[TPU] = 4
    return [NodeInfo(n) for n in nodes]


@pytest.mark.parametrize("limits", [
    {},                                  # cpu/pods-only request
    {TPU: 2},                            # extended resource
])
def test_filter_batch_matches_per_node(limits):
    plugin = NodeResourcesFit()
    pod = make_pod("p", requests=make_resources(cpu=2000, memory="4Gi"),
                   limits=limits)
    infos = _infos()
    batch = plugin.filter_batch(CycleState(), pod, infos)
    for info, got in zip(infos, batch):
        want = plugin.filter(CycleState(), pod, info)
        if want.is_success():
            assert got is None, info.node.name
        else:
            assert got is not None, info.node.name
            assert sorted(got.reasons) == sorted(want.reasons), info.node.name


def test_read_or_init_single_container_across_threads():
    state = CycleState()
    containers = []
    barrier = threading.Barrier(8)

    def work():
        barrier.wait()
        containers.append(id(state.read_or_init("k", dict)))

    threads = [threading.Thread(target=work) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(set(containers)) == 1


def test_scheduler_parallel_profile_schedules_gang():
    """End-to-end: a gang schedules identically under forced parallelism."""
    from tpusched.apiserver import server as srv
    from tpusched.config.profiles import tpu_gang_profile
    from tpusched.testing import (TestCluster, make_pod_group, make_tpu_pool)

    profile = tpu_gang_profile(permit_wait_s=10, denied_s=1)
    profile.parallelism = 8
    with TestCluster(profile=profile) as c:
        topo, nodes = make_tpu_pool("pool-a", dims=(4, 4, 4))
        c.api.create(srv.TPU_TOPOLOGIES, topo)
        c.add_nodes(nodes)
        c.api.create(srv.POD_GROUPS,
                     make_pod_group("gang", min_member=16,
                                    tpu_slice_shape="4x4x4",
                                    tpu_accelerator="tpu-v5p"))
        pods = [make_pod(f"w-{i}", pod_group="gang", limits={TPU: 4})
                for i in range(16)]
        c.create_pods(pods)
        assert c.wait_for_pods_scheduled([p.key for p in pods], timeout=30)
        used = {}
        for p in pods:
            used[c.pod(p.key).spec.node_name] = used.get(
                c.pod(p.key).spec.node_name, 0) + 1
        assert len(used) == 16 and all(v == 1 for v in used.values())
