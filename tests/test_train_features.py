"""Training-side features on the virtual 8-device CPU mesh: gradient
accumulation, vocab-parallel (tensor-parallel) cross-entropy, and the
mixed-precision (f32 master / bf16 compute) policy."""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpusched.jaxbridge import mesh as meshlib
from tpusched.jaxbridge import workload as wl


from tpusched.jaxbridge import compat

# see tests/test_pipeline.py: the pipeline path needs jax.shard_map
needs_modern_shard_map = pytest.mark.skipif(
    not compat.have_modern_shard_map(),
    reason="pipeline path needs jax.shard_map (legacy experimental API "
           "cannot express it)")


def need_devices(n=8):
    if len(jax.devices()) < n:
        pytest.skip(f"needs {n} virtual devices")


def tokens_for(cfg, batch, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.integers(0, cfg.vocab, (batch, cfg.seq)), jnp.int32)


# -- vocab-parallel cross-entropy --------------------------------------------

def test_cross_entropy_sharded_form_matches_gather_form():
    """The logsumexp/iota form must agree with take_along_axis log_softmax
    bit-for-bit-ish on identical logits."""
    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.normal(size=(2, 16, 64)), jnp.float32)
    targets = jnp.asarray(rng.integers(0, 64, (2, 16)), jnp.int32)
    ref = wl._cross_entropy(logits, targets, vocab_spec=None)
    # vocab_spec path without a mesh: pass a no-op constraint via identity
    # by faking the constraint — use jax.sharding only under a mesh; here
    # exercise the math by calling the sharded branch pieces directly
    m = jax.lax.stop_gradient(jnp.max(logits, axis=-1, keepdims=True))
    lse = jnp.log(jnp.sum(jnp.exp(logits - m), axis=-1)) + m[..., 0]
    ids = jax.lax.broadcasted_iota(targets.dtype, logits.shape, 2)
    tl = jnp.sum(jnp.where(ids == targets[..., None], logits, 0.0), axis=-1)
    got = jnp.mean(lse - tl)
    assert jnp.allclose(ref, got, atol=1e-6)


def test_vocab_parallel_loss_matches_replicated():
    """Same params, same tokens: the vocab-parallel step must produce the
    same loss as the replicated-logits step (GSPMD semantics preserved)."""
    need_devices()
    cfg = wl.ModelConfig.tiny()
    cfg_vp = dataclasses.replace(cfg, vocab_parallel_loss=True)
    mesh = meshlib.build_named_mesh({"dp": 2, "tp": 4})

    losses = {}
    for name, c in (("repl", cfg), ("vp", cfg_vp)):
        step, pshard, tshard = wl.make_sharded_train_step(mesh, c)
        params = jax.device_put(wl.init_params(jax.random.PRNGKey(0), c),
                                pshard)
        toks = jax.device_put(tokens_for(c, 4), tshard)
        _, loss = step(params, toks)
        losses[name] = float(loss)
    assert losses["vp"] == pytest.approx(losses["repl"], rel=1e-4)


def test_vocab_parallel_out_matrix_sharded_over_vocab():
    need_devices()
    cfg = dataclasses.replace(wl.ModelConfig.tiny(), vocab_parallel_loss=True)
    mesh = meshlib.build_named_mesh({"dp": 2, "tp": 4})
    step, pshard, tshard = wl.make_sharded_train_step(mesh, cfg)
    params = jax.device_put(wl.init_params(jax.random.PRNGKey(0), cfg), pshard)
    out = params["out"]  # (d, vocab): vocab dim sharded 4-way over tp
    assert out.addressable_shards[0].data.shape[1] == cfg.vocab // 4


# -- gradient accumulation ----------------------------------------------------

def test_accum_step_matches_large_batch():
    """accum_steps×B microbatches must land within numerical noise of one
    (accum_steps·B)-batch step: same mean-of-token-means loss (equal-sized
    microbatches), near-identical SGD update."""
    need_devices()
    import optax
    cfg = wl.ModelConfig.tiny()
    mesh = meshlib.build_named_mesh({"dp": 2, "tp": 2})
    tx = optax.sgd(1e-2)

    toks = tokens_for(cfg, 8, seed=3)

    step, init_opt, pshard, tshard = wl.make_optax_train_step(mesh, cfg, tx)
    params = jax.device_put(wl.init_params(jax.random.PRNGKey(0), cfg), pshard)
    opt = init_opt(params)
    big_params, _, big_loss = step(params, opt, jax.device_put(toks, tshard))

    astep, ainit, apshard, stack_shard = wl.make_accum_train_step(
        mesh, cfg, tx, accum_steps=4)
    params2 = jax.device_put(wl.init_params(jax.random.PRNGKey(0), cfg),
                             apshard)
    opt2 = ainit(params2)
    stack = jax.device_put(toks.reshape(4, 2, cfg.seq), stack_shard)
    acc_params, _, acc_loss = astep(params2, opt2, stack)

    assert float(acc_loss) == pytest.approx(float(big_loss), rel=1e-5)
    diffs = jax.tree_util.tree_map(
        lambda a, b: float(jnp.max(jnp.abs(a.astype(jnp.float32)
                                           - b.astype(jnp.float32)))),
        acc_params, big_params)
    assert max(jax.tree_util.tree_leaves(diffs)) < 1e-5


def test_accum_step_runs_with_adamw_and_moe():
    need_devices()
    import optax
    cfg = dataclasses.replace(wl.ModelConfig.tiny(), n_experts=4)
    mesh = meshlib.build_named_mesh({"dp": 2, "ep": 2, "tp": 2})
    step, init_opt, pshard, stack_shard = wl.make_accum_train_step(
        mesh, cfg, optax.adamw(1e-3), accum_steps=2)
    params = jax.device_put(wl.init_params(jax.random.PRNGKey(0), cfg), pshard)
    opt = init_opt(params)
    stack = jax.device_put(
        tokens_for(cfg, 4, seed=1).reshape(2, 2, cfg.seq), stack_shard)
    params, opt, loss = step(params, opt, stack)
    assert jnp.isfinite(loss)


# -- mixed precision ----------------------------------------------------------

def mp_config(**kw):
    return dataclasses.replace(wl.ModelConfig.tiny(), dtype=jnp.bfloat16,
                               param_dtype=jnp.float32, **kw)


def test_mixed_precision_masters_stay_f32():
    need_devices()
    import optax
    cfg = mp_config()
    mesh = meshlib.build_named_mesh({"dp": 2, "tp": 2})
    step, init_opt, pshard, tshard = wl.make_optax_train_step(
        mesh, cfg, optax.adamw(1e-3))
    params = jax.device_put(wl.init_params(jax.random.PRNGKey(0), cfg), pshard)
    assert params["embed"].dtype == jnp.float32          # master weights f32
    opt = init_opt(params)
    toks = jax.device_put(tokens_for(cfg, 4), tshard)
    params, opt, loss = step(params, opt, toks)
    assert jnp.isfinite(loss)
    assert params["embed"].dtype == jnp.float32          # stays f32
    # adam moments in master precision too
    mus = [l for l in jax.tree_util.tree_leaves(opt)
           if hasattr(l, "dtype") and l.ndim >= 2]
    assert all(m.dtype == jnp.float32 for m in mus)


def test_cast_params_for_compute_policy():
    cfg = mp_config(n_experts=2)
    params = wl.init_params(jax.random.PRNGKey(0), cfg)
    cast = wl.cast_params_for_compute(params, cfg)
    assert cast["embed"].dtype == jnp.bfloat16
    assert cast["layers"][0]["w_gate"].dtype == jnp.bfloat16
    # the MoE router deliberately stays f32 (f32 softmax logits)
    assert cast["layers"][0]["router"].dtype == jnp.float32
    # no-op policy returns the same tree untouched
    plain = wl.ModelConfig.tiny()
    p2 = wl.init_params(jax.random.PRNGKey(0), plain)
    assert wl.cast_params_for_compute(p2, plain) is p2


def test_vocab_parallel_with_sequence_parallel_mesh():
    """vocab_spec must keep the seq dim on sp — regression for the spec that
    pinned it None and all-gathered the f32 logits along seq."""
    need_devices()
    cfg = dataclasses.replace(wl.ModelConfig.tiny(), vocab_parallel_loss=True)
    mesh = meshlib.build_named_mesh({"dp": 2, "sp": 2, "tp": 2})
    ts = wl.TrainShardings(mesh, cfg)
    assert ts.vocab_spec.spec == jax.sharding.PartitionSpec(
        ("dp",), "sp", "tp")
    step, pshard, tshard = wl.make_sharded_train_step(mesh, cfg)
    params = jax.device_put(wl.init_params(jax.random.PRNGKey(0), cfg), pshard)
    _, loss = step(params, jax.device_put(tokens_for(cfg, 4), tshard))
    assert jnp.isfinite(loss)


def test_accum_short_final_stack_averages_correctly():
    """A stack shorter than the constructor's accum_steps must still divide
    by the actual microbatch count — regression for silent grad scaling."""
    need_devices()
    import optax
    cfg = wl.ModelConfig.tiny()
    mesh = meshlib.build_named_mesh({"dp": 2, "tp": 2})
    tx = optax.sgd(1e-2)
    toks = tokens_for(cfg, 4, seed=7)

    astep, ainit, pshard, sshard = wl.make_accum_train_step(
        mesh, cfg, tx, accum_steps=4)
    params = jax.device_put(wl.init_params(jax.random.PRNGKey(0), cfg), pshard)
    opt = ainit(params)
    short = jax.device_put(toks.reshape(2, 2, cfg.seq), sshard)
    acc_params, _, acc_loss = astep(params, opt, short)

    step, init_opt, pshard2, tshard = wl.make_optax_train_step(mesh, cfg, tx)
    params2 = jax.device_put(wl.init_params(jax.random.PRNGKey(0), cfg),
                             pshard2)
    ref_params, _, ref_loss = step(params2, init_opt(params2),
                                   jax.device_put(toks, tshard))
    assert float(acc_loss) == pytest.approx(float(ref_loss), rel=1e-5)
    diffs = jax.tree_util.tree_map(
        lambda a, b: float(jnp.max(jnp.abs(a - b))), acc_params, ref_params)
    assert max(jax.tree_util.tree_leaves(diffs)) < 1e-5


def test_mixed_precision_decode_path():
    """Serving a mixed-precision-trained model: prefill/generate must cast
    the f32 masters to the bf16 compute/cache dtype (regression: dtype
    mismatch crash in dynamic_update_slice)."""
    from tpusched.jaxbridge import decode
    cfg = mp_config()
    params = wl.init_params(jax.random.PRNGKey(0), cfg)
    out = decode.generate(params, tokens_for(cfg, 2)[:, :8], cfg, steps=4)
    assert out.shape == (2, 5)
    # greedy decode agrees with a pure-bf16 copy of the same weights
    cfg_bf16 = dataclasses.replace(cfg, param_dtype=None)
    cast = wl.cast_params_for_compute(params, cfg)
    out2 = decode.generate(cast, tokens_for(cfg, 2)[:, :8], cfg_bf16, steps=4)
    assert (out == out2).all()


@needs_modern_shard_map
def test_mixed_precision_pipeline_path():
    """Pipeline-parallel training under the f32-master policy (regression:
    bf16 buffers vs f32 activations crash at trace time)."""
    need_devices()
    from tpusched.jaxbridge import pipeline
    cfg = mp_config()
    mesh = meshlib.build_named_mesh({"pp": 2, "dp": 4})
    step, shardings, tshard = pipeline.make_pipeline_train_step(
        mesh, cfg, n_micro=2)
    params = jax.device_put(
        pipeline.init_pipeline_params(jax.random.PRNGKey(0), cfg), shardings)
    new_params, loss = step(params, jax.device_put(tokens_for(cfg, 4), tshard))
    assert jnp.isfinite(loss)
    assert new_params[1].dtype == jnp.float32   # embed master stays f32


def test_mixed_precision_tracks_pure_f32_early():
    """One step from identical inits: bf16-compute loss should be close to
    the f32 loss (sanity that the cast sits only on the compute path)."""
    need_devices()
    mesh = meshlib.build_named_mesh({"dp": 2, "tp": 2})
    losses = {}
    for name, cfg in (("f32", wl.ModelConfig.tiny()), ("mp", mp_config())):
        step, pshard, tshard = wl.make_sharded_train_step(mesh, cfg)
        params = jax.device_put(wl.init_params(jax.random.PRNGKey(0), cfg),
                                pshard)
        toks = jax.device_put(tokens_for(cfg, 4), tshard)
        _, loss = step(params, toks)
        losses[name] = float(loss)
    assert losses["mp"] == pytest.approx(losses["f32"], rel=0.05)
