"""Property tests for the injectable clock substrate (util/clock).

The VirtualClock is the engine deterministic replay trusts for every
timeout in the system — these pin its laws: time never runs backward,
armed deadlines fire in deadline order, a cancelled timer never fires,
re-arming fires at the new instant, and wait_until never over-advances.
Hypothesis drives the properties where available; the seeded-fuzz
stand-ins below keep the same machines exercised when it is not.
"""
import random
import time

import pytest

from tpusched.util.clock import (CallableClock, VirtualClock, WALL,
                                 WallClock, as_clock)


# -- normalization ------------------------------------------------------------


def test_as_clock_normalizes_every_legacy_spelling():
    assert as_clock(None) is WALL
    assert as_clock(time.time) is WALL
    assert as_clock(time.monotonic) is WALL
    vc = VirtualClock()
    assert as_clock(vc) is vc
    fake = as_clock(lambda: 42.0)
    assert isinstance(fake, CallableClock)
    assert fake.now() == fake.wall() == 42.0
    assert fake.arm("x", 99.0) == 0        # registry is a no-op
    with pytest.raises(TypeError):
        as_clock(3)


def test_wall_clock_is_transparent():
    w = WallClock()
    assert not w.virtual
    m0 = time.monotonic()
    assert w.now() >= m0
    assert abs(w.wall() - time.time()) < 1.0
    assert w.arm("anything", w.now() + 1e9) == 0    # no registry, no leak
    t0 = time.monotonic()
    w.wait_until(t0 - 100.0)                        # past deadline: no sleep
    assert time.monotonic() - t0 < 0.5


# -- the op machines the properties drive -------------------------------------


def _drive(clk: VirtualClock, ops):
    """Apply (op, value) steps, asserting monotonicity after each."""
    last = clk.now()
    for op, val in ops:
        if op == "advance":
            clk.advance(val)
        elif op == "advance_to":
            clk.advance_to(val)
        elif op == "arm":
            clk.arm(f"t{val:.3f}", val)
        elif op == "fire":
            clk.advance_to_next_deadline()
        elif op == "wait_until":
            clk.wait_until(val)
        elif op == "sleep":
            clk.sleep(val)
        now = clk.now()
        assert now >= last, (op, val)
        last = now


def _fire_all(clk: VirtualClock):
    fired = []
    while True:
        hit = clk.advance_to_next_deadline()
        if hit is None:
            return fired
        fired.append(hit[1])
        assert clk.now() >= hit[1]          # time reached the deadline


_OP_KINDS = ("advance", "advance_to", "arm", "fire", "wait_until", "sleep")


try:
    from hypothesis import given, settings
    from hypothesis import strategies as st
    HAVE_HYPOTHESIS = True

    _OPS = st.lists(
        st.tuples(st.sampled_from(_OP_KINDS),
                  st.floats(0, 200, allow_nan=False)),
        max_size=60)

    @settings(max_examples=200, deadline=None)
    @given(ops=_OPS)
    def test_virtual_time_is_monotonic(ops):
        """now() never decreases under ANY op interleaving — advancing
        to a past instant, firing a lapsed deadline, a stale wait_until
        — none may move time backward."""
        _drive(VirtualClock(), ops)

    @settings(max_examples=200, deadline=None)
    @given(deadlines=st.lists(st.floats(0, 1000, allow_nan=False),
                              min_size=1, max_size=40))
    def test_deadlines_fire_in_deadline_order(deadlines):
        clk = VirtualClock()
        for i, d in enumerate(deadlines):
            clk.arm(f"d{i}", d)
        assert _fire_all(clk) == sorted(deadlines)
        assert clk.fired_total() == len(deadlines)
        assert clk.armed_count() == 0

    @settings(max_examples=200, deadline=None)
    @given(deadlines=st.lists(st.floats(0, 1000, allow_nan=False),
                              min_size=2, max_size=30),
           data=st.data())
    def test_cancelled_timers_never_fire_and_rearm_fires_at_new_instant(
            deadlines, data):
        clk = VirtualClock()
        tokens = [clk.arm(f"d{i}", d) for i, d in enumerate(deadlines)]
        cancel_idx = data.draw(st.integers(0, len(tokens) - 1))
        clk.cancel(tokens[cancel_idx])
        new_deadline = data.draw(st.floats(0, 1000, allow_nan=False))
        clk.arm(f"d{cancel_idx}", new_deadline)
        expected = sorted([d for i, d in enumerate(deadlines)
                           if i != cancel_idx] + [new_deadline])
        assert _fire_all(clk) == expected

    @settings(max_examples=200, deadline=None)
    @given(start=st.floats(0, 100, allow_nan=False),
           target=st.floats(0, 100, allow_nan=False))
    def test_wait_until_never_over_advances(start, target):
        clk = VirtualClock(start=start)
        clk.wait_until(target)
        assert clk.now() == max(start, target)    # exactly, never past
except ImportError:   # the seeded-fuzz stand-ins below still run
    HAVE_HYPOTHESIS = False


# -- deterministic stand-ins (run with or without hypothesis) -----------------


def test_seeded_fuzz_virtual_time_is_monotonic():
    for seed in range(20):
        rng = random.Random(20260804 + seed)
        ops = [(rng.choice(_OP_KINDS), rng.uniform(0, 200))
               for _ in range(rng.randrange(5, 60))]
        _drive(VirtualClock(), ops)


def test_seeded_fuzz_deadlines_fire_in_order_with_cancel_and_rearm():
    for seed in range(20):
        rng = random.Random(707 + seed)
        deadlines = [rng.uniform(0, 1000)
                     for _ in range(rng.randrange(2, 30))]
        clk = VirtualClock()
        tokens = [clk.arm(f"d{i}", d) for i, d in enumerate(deadlines)]
        cancel_idx = rng.randrange(len(tokens))
        clk.cancel(tokens[cancel_idx])
        new_deadline = rng.uniform(0, 1000)
        clk.arm(f"d{cancel_idx}", new_deadline)
        expected = sorted([d for i, d in enumerate(deadlines)
                           if i != cancel_idx] + [new_deadline])
        assert _fire_all(clk) == expected
        assert clk.armed_count() == 0


def test_wait_until_exact():
    clk = VirtualClock(start=5.0)
    clk.wait_until(3.0)
    assert clk.now() == 5.0              # stale target: no move
    clk.wait_until(8.25)
    assert clk.now() == 8.25             # exact, never past


def test_fire_respects_limit_and_does_not_move_time():
    clk = VirtualClock()
    clk.arm("late", 10.0)
    assert clk.advance_to_next_deadline(limit=5.0) is None
    assert clk.now() == 0.0                   # a refused fire is free
    assert clk.advance_to_next_deadline(limit=10.0) is None   # exclusive
    hit = clk.advance_to_next_deadline(limit=10.1)
    assert hit == ("late", 10.0) and clk.now() == 10.0


def test_wall_offset_and_wall_scale_arming():
    clk = VirtualClock(start=100.0, wall0=1_000_100.0)
    assert clk.wall() == pytest.approx(1_000_100.0)
    clk.arm("w", 1_000_103.5, wall=True)      # wall scale → mono 103.5
    clk.arm("m", 102.0)
    assert clk.advance_to_next_deadline()[0] == "m"
    label, deadline = clk.advance_to_next_deadline()
    assert label == "w" and deadline == pytest.approx(103.5)
    assert clk.wall() == pytest.approx(1_000_103.5)


def test_fired_log_and_label_census():
    clk = VirtualClock()
    for i in range(5):
        clk.arm("backoff", float(i))
    clk.arm("permit", 2.5)
    while clk.advance_to_next_deadline() is not None:
        pass
    assert clk.fired_total() == 6
    assert clk.fired_by_label() == {"backoff": 5, "permit": 1}
    labels = [lbl for _, lbl in clk.fired()]
    assert labels.count("permit") == 1
    # log instants are nondecreasing (the fire order IS time order)
    instants = [t for t, _ in clk.fired()]
    assert instants == sorted(instants)
