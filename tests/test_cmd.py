"""CLI binaries: config decoding → fully-wired scheduler, controller options.

Analog of the reference's cmd tier test (cmd/scheduler/main_test.go:48
TestSetup, 644 LoC): boot the real options stack and assert the
fully-defaulted profile wiring for every plugin.
"""
import json
import textwrap

import pytest

from tpusched.apiserver import APIServer
from tpusched.cmd import controller as ctl_cmd
from tpusched.cmd import scheduler as sched_cmd
from tpusched.plugins import default_registry
from tpusched.sched import Scheduler


def test_every_canned_profile_wires_fully():
    """Every canned profile must instantiate every plugin it names."""
    for name, factory in sched_cmd.CANNED_PROFILES.items():
        profile = factory()
        s = Scheduler(APIServer(), default_registry(), profile)
        try:
            for plugin_name in profile.all_plugin_names():
                assert plugin_name in s.framework.plugins, (name, plugin_name)
        finally:
            s.stop()


def test_validate_only_prints_resolved_profile(capsys, tmp_path):
    cfg = tmp_path / "sched.yaml"
    cfg.write_text(textwrap.dedent("""
        apiVersion: tpusched.config.tpu.dev/v1beta1
        kind: TpuSchedulerConfiguration
        profiles:
        - schedulerName: gangsched
          plugins:
            queueSort:
              enabled: [{name: Coscheduling}]
              disabled: [{name: "*"}]
            permit: {enabled: [{name: Coscheduling}]}
            filter: {enabled: [{name: TpuSlice}]}
            score: {enabled: [{name: TpuSlice, weight: 3}]}
            bind:
              disabled: [{name: DefaultBinder}]
              enabled: [{name: TpuSlice}]
          pluginConfig:
          - name: Coscheduling
            args: {permitWaitingTimeSeconds: 5}
    """))
    rc = sched_cmd.main(["--config", str(cfg), "--validate-only"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out)[0]
    assert out["schedulerName"] == "gangsched"
    assert out["queueSort"] == "Coscheduling"
    assert out["filter"][-1] == "TpuSlice"
    assert out["score"] == [{"name": "TpuSlice", "weight": 3}]
    assert out["bind"] == ["TpuSlice"]
    # the framework actually instantiated the named plugins
    assert "Coscheduling" in out["plugins"] and "TpuSlice" in out["plugins"]


def test_validate_only_canned_default(capsys):
    rc = sched_cmd.main(["--validate-only"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out)[0]
    assert out["queueSort"] == "Coscheduling"     # tpu-gang default
    assert out["permit"] == ["Coscheduling", "MultiSlice"]
    assert out["bind"] == ["TpuSlice"]


def test_bad_config_is_an_error(tmp_path):
    cfg = tmp_path / "bad.yaml"
    cfg.write_text("apiVersion: nope/v9\nkind: TpuSchedulerConfiguration\nprofiles: [{}]\n")
    from tpusched.config.scheme import ConfigError
    with pytest.raises(ConfigError):
        sched_cmd.main(["--config", str(cfg), "--validate-only"])


def test_multi_profile_config_hosts_every_profile(tmp_path, capsys):
    """Upstream hosts all of a config's profiles in one process; pods choose
    by spec.schedulerName. --validate-only reports them all, and two live
    schedulers over one API server each bind their own pods."""
    cfg = tmp_path / "multi.yaml"
    cfg.write_text(textwrap.dedent("""
        apiVersion: tpusched.config.tpu.dev/v1beta1
        kind: TpuSchedulerConfiguration
        profiles:
        - schedulerName: sched-a
        - schedulerName: sched-b
          plugins:
            queueSort:
              enabled: [{name: QOSSort}]
              disabled: [{name: "*"}]
    """))
    rc = sched_cmd.main(["--config", str(cfg), "--validate-only"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    assert [p["schedulerName"] for p in out] == ["sched-a", "sched-b"]
    assert out[1]["queueSort"] == "QOSSort"

    # live: both profiles schedule their own pods against one API server
    from tpusched.apiserver import server as srv
    from tpusched.cmd.scheduler import resolve_profiles
    from tpusched.testing import make_node, make_pod

    args = sched_cmd.build_parser().parse_args(["--config", str(cfg)])
    api = APIServer()
    scheds = [Scheduler(api, default_registry(), p)
              for p in resolve_profiles(args)]
    api.create(srv.NODES, make_node("n1"))
    try:
        for s in scheds:
            s.run()
        pa = make_pod("pa", scheduler_name="sched-a", requests={"cpu": 100})
        pb = make_pod("pb", scheduler_name="sched-b", requests={"cpu": 100})
        px = make_pod("px", scheduler_name="nobody", requests={"cpu": 100})
        for p in (pa, pb, px):
            api.create(srv.PODS, p)
        import time
        deadline = time.monotonic() + 10
        def bound(k):
            pod = api.peek(srv.PODS, k)
            return pod is not None and pod.spec.node_name
        while time.monotonic() < deadline and not (
                bound("default/pa") and bound("default/pb")):
            time.sleep(0.02)
        assert bound("default/pa") and bound("default/pb")
        assert not bound("default/px")  # no profile claims it
    finally:
        for s in scheds:
            s.stop()


def test_controller_options_mirror_flags():
    args = ctl_cmd.build_parser().parse_args(
        ["--qps", "50", "--burst", "100", "--workers", "3",
         "--enable-leader-election"])
    opts = ctl_cmd.options_from_args(args)
    assert opts.api_qps == 50 and opts.api_burst == 100
    assert opts.workers == 3 and opts.enable_leader_election


def test_controller_defaults_match_reference_budget():
    """qps=5 burst=10 workers=1 (options.go:43-45)."""
    opts = ctl_cmd.options_from_args(ctl_cmd.build_parser().parse_args([]))
    assert (opts.api_qps, opts.api_burst, opts.workers) == (5.0, 10, 1)
    assert not opts.enable_leader_election
