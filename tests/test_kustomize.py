"""Kustomize overlays render-check (VERDICT r4 missing #3).

No kubectl/kustomize binary ships in this image, so a minimal resolver walks
``config/default`` the way kustomize would — recursing into resource
directories' kustomization.yaml, loading every referenced file — and asserts
the composed object set is the full install. Drift between
``config/crd/bases`` (kustomize's load-restricted copies) and the canonical
``manifests/crds`` fails here AND in `make verify`.
"""
import os

import yaml

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def resolve_kustomization(root):
    """Collect every YAML doc reachable from root's kustomization.yaml."""
    kfile = os.path.join(root, "kustomization.yaml")
    assert os.path.exists(kfile), f"missing {kfile}"
    with open(kfile, encoding="utf-8") as f:
        k = yaml.safe_load(f) or {}
    docs = []
    for res in k.get("resources") or []:
        path = os.path.normpath(os.path.join(root, res))
        if os.path.isdir(path):
            docs += resolve_kustomization(path)
        else:
            assert os.path.exists(path), f"{kfile} references missing {res}"
            # kustomize's load restrictor: files must live under the root
            assert os.path.commonpath([path, root]) == root, (
                f"{kfile}: {res} escapes the kustomization root")
            with open(path, encoding="utf-8") as f:
                docs += [d for d in yaml.safe_load_all(f) if d]
    return docs


def test_default_overlay_composes_the_full_install():
    docs = resolve_kustomization(os.path.join(REPO, "config", "default"))
    kinds = sorted(f"{d['kind']}/{d['metadata']['name']}" for d in docs)
    by_kind = {}
    for d in docs:
        by_kind.setdefault(d["kind"], []).append(d)
    assert len(by_kind["CustomResourceDefinition"]) == 3, kinds
    deployments = {d["metadata"]["name"] for d in by_kind["Deployment"]}
    assert deployments == {"tpusched-scheduler", "tpusched-controller"}
    assert "Namespace" in by_kind
    assert "ServiceAccount" in by_kind
    assert "ClusterRole" in by_kind and "ClusterRoleBinding" in by_kind


def test_crd_bases_match_canonical_manifests():
    base_dir = os.path.join(REPO, "config", "crd", "bases")
    canon_dir = os.path.join(REPO, "manifests", "crds")
    names = sorted(os.listdir(canon_dir))
    assert sorted(os.listdir(base_dir)) == names
    for n in names:
        with open(os.path.join(base_dir, n), encoding="utf-8") as a, \
                open(os.path.join(canon_dir, n), encoding="utf-8") as b:
            assert a.read() == b.read(), (
                f"config/crd/bases/{n} drifted from manifests/crds/{n}; "
                f"run: cp manifests/crds/{n} config/crd/bases/{n}")


def test_manager_commands_parse_against_the_real_clis():
    """Every flag the Deployments pass must be accepted by the binaries'
    own parsers — a manifest referencing a removed flag fails here, not at
    rollout."""
    from tpusched.cmd import controller as ctl
    from tpusched.cmd import scheduler as sched
    with open(os.path.join(REPO, "config", "manager", "manager.yaml"),
              encoding="utf-8") as f:
        docs = [d for d in yaml.safe_load_all(f) if d]
    parsers = {"tpusched-scheduler": sched.build_parser(),
               "tpusched-controller": ctl.build_parser()}
    checked = 0
    for d in docs:
        if d["kind"] != "Deployment":
            continue
        cmd = d["spec"]["template"]["spec"]["containers"][0]["command"]
        assert cmd[:2] == ["python", "-m"]
        flags = cmd[3:]
        args = parsers[d["metadata"]["name"]].parse_args(flags)
        assert args.kubeconfig == "in-cluster"
        checked += 1
    assert checked == 2
