"""Test env: force JAX onto a virtual 8-device CPU mesh.

The image's sitecustomize pins the axon TPU platform programmatically, so an
env var alone is not enough — jax.config.update must override it. XLA_FLAGS
is still read lazily at CPU-backend init, so setting it here (before any
jax.devices() call) is in time.
"""
import os
import sys

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

try:
    import jax
    jax.config.update("jax_platforms", "cpu")
    # Persistent compilation cache: the suite's wall clock is dominated by
    # XLA compiles of per-engine jit closures (serve/train/attention tests
    # rebuild engines constantly). With the cache, every re-compile of an
    # identical program is a disk hit — run 2+ of the suite drops from
    # ~22 min toward the pure-execution floor. Safe across versions: cache
    # keys include the jax/XLA fingerprint.
    _cache_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              os.pardir, ".jax_cache")
    jax.config.update("jax_compilation_cache_dir",
                      os.path.abspath(_cache_dir))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
except ImportError:
    pass

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _close_harness_frameworks():
    """Release background plugin resources (collector refresh threads etc.)
    of frameworks built via tpusched.testing.harness after every test."""
    yield
    from tpusched.testing import harness
    harness.close_all()


# The hot-path sampling profiler is ALWAYS-ON in production (any live
# Scheduler starts the process-global sampler and nothing stops it — that
# is the point), but in the unit suite that means the first scheduler-
# constructing test leaves a 100 Hz sampler sweeping sys._current_frames()
# for the remaining ~12 minutes of the run. On the 2-core CI box that
# ambient load is enough to tip marginal timing assertions in unrelated
# stress tests. Keep profiling OPT-IN here: tests that exercise the
# profiler flip the switch (and install their own instance) explicitly.
os.environ.setdefault("TPUSCHED_PROFILE", "0")


@pytest.fixture(autouse=True, scope="session")
def _profiler_opt_in_for_tests():
    yield
    from tpusched import obs
    obs.default_profiler().stop()
