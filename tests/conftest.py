"""Test env: force JAX onto a virtual 8-device CPU mesh.

The image's sitecustomize pins the axon TPU platform programmatically, so an
env var alone is not enough — jax.config.update must override it. XLA_FLAGS
is still read lazily at CPU-backend init, so setting it here (before any
jax.devices() call) is in time.
"""
import os
import sys

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

try:
    import jax
    jax.config.update("jax_platforms", "cpu")
except ImportError:
    pass

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _close_harness_frameworks():
    """Release background plugin resources (collector refresh threads etc.)
    of frameworks built via tpusched.testing.harness after every test."""
    yield
    from tpusched.testing import harness
    harness.close_all()
