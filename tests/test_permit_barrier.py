"""Permit-barrier unit tables — the waitingPods map is the in-process gang
barrier (SURVEY §5 'distributed comm backend': the framework's waitingPods
map IS the barrier; upstream scheduler.go:524,557). This framework is our
own code (the reference vendors upstream's), so its resolution semantics
get direct tables: allow-all, first-rejection-wins, deadline expiry,
exactly-once callbacks, and resolution races.
"""
import threading
import time

from tpusched.fwk import CycleState, PluginProfile, Status
from tpusched.fwk.interfaces import PermitPlugin
from tpusched.testing import make_pod, new_test_framework


class FakePermit(PermitPlugin):
    """Permit plugin returning a configurable wait per pod."""
    NAME = "FakePermit"
    timeout_s = 5.0

    def __init__(self, args, handle):
        pass

    @classmethod
    def new(cls, args, handle):
        return cls(args, handle)

    def permit(self, state, pod, node_name):
        return Status.wait(), self.timeout_s


def barrier_framework(timeout_s=5.0):
    from tpusched.plugins import default_registry
    FakePermit.timeout_s = timeout_s
    registry = default_registry()
    registry.register(FakePermit.NAME, FakePermit.new)
    profile = PluginProfile(permit=[FakePermit.NAME],
                            bind=["DefaultBinder"])
    fw, handle, api = new_test_framework(profile, registry=registry)
    return fw


def park(fw, name):
    pod = make_pod(name)
    st = fw.run_permit_plugins(CycleState(), pod, "n1")
    assert st.is_wait()
    return pod


def test_allow_from_every_plugin_resolves_success():
    fw = barrier_framework()
    pod = park(fw, "p")
    wp = fw.get_waiting_pod(pod.meta.uid)
    assert wp.get_pending_plugins() == [FakePermit.NAME]
    wp.allow(FakePermit.NAME)
    assert wp.wait().is_success()


def test_first_rejection_wins_even_after_allow_race():
    fw = barrier_framework()
    pod = park(fw, "p")
    wp = fw.get_waiting_pod(pod.meta.uid)
    wp.reject(FakePermit.NAME, "lost the race")
    wp.allow(FakePermit.NAME)  # late allow must not flip the verdict
    st = wp.wait()
    assert st.is_unschedulable() and "lost the race" in st.message()


def test_deadline_expiry_rejects_with_timeout_message():
    fw = barrier_framework(timeout_s=0.1)
    pod = park(fw, "p")
    wp = fw.get_waiting_pod(pod.meta.uid)
    st = wp.wait()  # blocks until the 0.1s deadline
    assert st.is_unschedulable() and "timeout" in st.message()


def test_callbacks_fire_exactly_once_each():
    fw = barrier_framework()
    pod = park(fw, "p")
    wp = fw.get_waiting_pod(pod.meta.uid)
    hits = []
    wp.add_done_callback(lambda st: hits.append(("a", st.is_success())))
    wp.add_done_callback(lambda st: hits.append(("b", st.is_success())))
    wp.allow(FakePermit.NAME)
    wp.allow(FakePermit.NAME)   # idempotent: no second firing
    assert hits == [("a", True), ("b", True)]
    # post-resolution registration fires immediately, once
    wp.add_done_callback(lambda st: hits.append(("late", st.is_success())))
    assert hits[-1] == ("late", True)


def test_notify_on_permit_removes_entry_before_callback():
    fw = barrier_framework()
    pod = park(fw, "p")
    seen = []

    def cb(st):
        # by callback time the pod has left the waiting map — a retry of the
        # same pod must be able to park again without colliding
        seen.append((st.is_success(), fw.get_waiting_pod(pod.meta.uid)))
    fw.notify_on_permit(pod, cb)
    fw.get_waiting_pod(pod.meta.uid).allow(FakePermit.NAME)
    deadline = time.monotonic() + 2
    while not seen and time.monotonic() < deadline:
        time.sleep(0.01)
    assert seen == [(True, None)]


def test_iterate_over_waiting_pods_sees_all_parked():
    fw = barrier_framework()
    pods = [park(fw, f"p{i}") for i in range(5)]
    names = []
    fw.iterate_over_waiting_pods(lambda wp: names.append(wp.pod.name))
    assert sorted(names) == [f"p{i}" for i in range(5)]
    # reject them all (the PostFilter mass-reject path)
    fw.iterate_over_waiting_pods(lambda wp: wp.reject("t", "mass"))
    for p in pods:
        assert fw.get_waiting_pod(p.meta.uid).wait().is_unschedulable()


def test_concurrent_allow_and_expiry_single_resolution():
    """A deadline racing an allow must produce exactly one verdict and one
    callback firing (no double resolution)."""
    for _ in range(20):
        fw = barrier_framework(timeout_s=0.02)
        pod = park(fw, "p")
        wp = fw.get_waiting_pod(pod.meta.uid)
        hits = []
        wp.add_done_callback(lambda st: hits.append(st.is_success()))
        t = threading.Thread(target=lambda: wp.allow(FakePermit.NAME))
        time.sleep(0.015)   # land near the deadline
        t.start()
        t.join()
        wp.wait()
        time.sleep(0.03)    # let a late sweeper expiry (if any) fire
        assert len(hits) == 1, hits


def test_on_pod_waiting_fires_after_registration():
    """The post-registration hook contract: a plugin that asked to Wait is
    called back AFTER its pod is visible to iterate_over_waiting_pods, so
    a mass-rejection that raced the park can be re-checked (and the pod
    resolved) instead of stranding until the permit deadline."""
    seen = []

    class HookedPermit(FakePermit):
        NAME = "HookedPermit"

        def on_pod_waiting(self, waiting_pod):
            # the pod must already be registered: reject() from here must
            # resolve the real barrier entry, not a pre-registration ghost
            parked = []
            fw.iterate_over_waiting_pods(
                lambda wp: parked.append(wp.pod.meta.uid))
            seen.append((waiting_pod.pod.meta.name,
                         waiting_pod.pod.meta.uid in parked))
            waiting_pod.reject(self.NAME, "re-checked and denied")

    from tpusched.plugins import default_registry
    registry = default_registry()
    registry.register(HookedPermit.NAME, HookedPermit.new)
    profile = PluginProfile(permit=[HookedPermit.NAME],
                            bind=["DefaultBinder"])
    from tpusched.testing import new_test_framework
    fw, handle, api = new_test_framework(profile, registry=registry)
    pod = make_pod("racer")
    st = fw.run_permit_plugins(CycleState(), pod, "n1")
    assert st.is_wait()                      # the cycle still parked it...
    assert seen == [("racer", True)]         # ...hook ran post-registration
    got = fw.wait_on_permit(pod)             # ...but it is already resolved
    assert got.is_unschedulable()
    assert "re-checked and denied" in got.message()
