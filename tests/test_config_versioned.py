"""Versioned YAML config decoding: defaults merge, strict fields, conversion.

Mirrors the reference's config round-trip/defaulting tier:
apis/config/scheme/scheme_test.go (YAML through the real codec, strict) and
apis/config/v1beta{2,3}/defaults_test.go.
"""
import textwrap

import pytest

from tpusched.config import types as t
from tpusched.config import versioned as v
from tpusched.config.scheme import ConfigError

COSCHED_YAML = textwrap.dedent("""
    apiVersion: tpusched.config.tpu.dev/v1beta1
    kind: TpuSchedulerConfiguration
    leaderElection:
      leaderElect: false
    clientConnection:
      qps: 50
      burst: 100
    profiles:
    - schedulerName: tpusched
      plugins:
        queueSort:
          enabled:
          - name: Coscheduling
          disabled:
          - name: "*"
        preFilter:
          enabled:
          - name: Coscheduling
        postFilter:
          enabled:
          - name: Coscheduling
        permit:
          enabled:
          - name: Coscheduling
        reserve:
          enabled:
          - name: Coscheduling
        postBind:
          enabled:
          - name: Coscheduling
      pluginConfig:
      - name: Coscheduling
        args:
          permitWaitingTimeSeconds: 10
          deniedPGExpirationTimeSeconds: 3
""")


def test_decode_coscheduling_profile():
    cfg = v.loads(COSCHED_YAML)
    p = cfg.profile("tpusched")
    assert p.queue_sort == "Coscheduling"
    assert p.pre_filter == ["Coscheduling"]
    assert p.post_filter == ["Coscheduling"]
    assert p.permit == ["Coscheduling"]
    assert p.post_bind == ["Coscheduling"]
    # default filter set survives untouched
    assert p.filter == ["NodeUnschedulable", "NodeName", "NodeSelector",
                        "TaintToleration", "NodeResourcesFit"]
    assert p.bind == ["DefaultBinder"]
    args = p.plugin_args["Coscheduling"]
    assert args.permit_waiting_time_seconds == 10
    assert args.denied_pg_expiration_time_seconds == 3
    assert cfg.client_connection.qps == 50
    assert cfg.client_connection.burst == 100


def test_defaults_without_plugin_config():
    cfg = v.loads(textwrap.dedent("""
        apiVersion: tpusched.config.tpu.dev/v1beta1
        kind: TpuSchedulerConfiguration
        profiles:
        - schedulerName: tpusched
          plugins:
            permit: {enabled: [{name: Coscheduling}]}
          pluginConfig:
          - name: Coscheduling
            args: {}
    """))
    args = cfg.profile().plugin_args["Coscheduling"]
    # v1beta3/defaults.go:29-30 in the reference
    assert args.permit_waiting_time_seconds == t.DEFAULT_PERMIT_WAITING_TIME_SECONDS == 60
    assert args.denied_pg_expiration_time_seconds == t.DEFAULT_DENIED_PG_EXPIRATION_TIME_SECONDS == 20


def test_custom_bind_replaces_default_binder():
    cfg = v.loads(textwrap.dedent("""
        apiVersion: tpusched.config.tpu.dev/v1beta1
        kind: TpuSchedulerConfiguration
        profiles:
        - schedulerName: tpusched
          plugins:
            bind:
              disabled: [{name: DefaultBinder}]
              enabled: [{name: TpuSlice}]
            score:
              enabled: [{name: TpuSlice, weight: 2}]
    """))
    p = cfg.profile()
    assert p.bind == ["TpuSlice"]
    assert p.score == [("TpuSlice", 2)]


@pytest.mark.parametrize("mutation,msg", [
    ({"apiVersion": "bogus/v1"}, "unsupported apiVersion"),
    ({"kind": "KubeSchedulerConfiguration"}, "unsupported kind"),
    ({"bogusField": 1}, "unknown field"),
    ({"profiles": None}, "at least one profile"),
])
def test_strict_top_level(mutation, msg):
    import yaml
    raw = yaml.safe_load(COSCHED_YAML)
    raw.update(mutation)
    with pytest.raises(ConfigError, match=msg):
        v.decode(raw)


def test_strict_unknown_args_field():
    bad = COSCHED_YAML.replace("permitWaitingTimeSeconds", "permitWaitingTimeSecs")
    with pytest.raises(ConfigError, match="unknown field"):
        v.loads(bad)


def test_strict_unknown_extension_point():
    with pytest.raises(ConfigError, match="unknown extension point"):
        v.loads(textwrap.dedent("""
            apiVersion: tpusched.config.tpu.dev/v1beta1
            kind: TpuSchedulerConfiguration
            profiles:
            - schedulerName: tpusched
              plugins:
                preemptAggressively: {enabled: [{name: X}]}
        """))


def test_double_enable_rejected():
    with pytest.raises(ConfigError, match="enabled twice"):
        v.loads(textwrap.dedent("""
            apiVersion: tpusched.config.tpu.dev/v1beta1
            kind: TpuSchedulerConfiguration
            profiles:
            - schedulerName: tpusched
              plugins:
                permit:
                  enabled: [{name: Coscheduling}, {name: Coscheduling}]
        """))


def test_multi_queue_sort_rejected():
    with pytest.raises(ConfigError, match="exactly one queueSort"):
        v.loads(textwrap.dedent("""
            apiVersion: tpusched.config.tpu.dev/v1beta1
            kind: TpuSchedulerConfiguration
            profiles:
            - schedulerName: tpusched
              plugins:
                queueSort:
                  enabled: [{name: Coscheduling}, {name: QOSSort}]
        """))


def test_v1alpha1_conversion_renames():
    cfg = v.loads(textwrap.dedent("""
        apiVersion: tpusched.config.tpu.dev/v1alpha1
        kind: TpuSchedulerConfiguration
        profiles:
        - schedulerName: tpusched
          plugins:
            permit: {enabled: [{name: Coscheduling}]}
          pluginConfig:
          - name: Coscheduling
            args:
              permitWaitingSeconds: 7
              deniedPGExpirationSeconds: 2
          - name: MultiSlice
            args:
              dcnDomainScore: 90
    """))
    args = cfg.profile().plugin_args["Coscheduling"]
    assert args.permit_waiting_time_seconds == 7
    assert args.denied_pg_expiration_time_seconds == 2
    assert cfg.profile().plugin_args["MultiSlice"].same_domain_score == 90


def test_v1alpha1_conflicting_legacy_and_current():
    with pytest.raises(ConfigError, match="both legacy"):
        v.loads(textwrap.dedent("""
            apiVersion: tpusched.config.tpu.dev/v1alpha1
            kind: TpuSchedulerConfiguration
            profiles:
            - schedulerName: tpusched
              pluginConfig:
              - name: Coscheduling
                args:
                  permitWaitingSeconds: 7
                  permitWaitingTimeSeconds: 9
        """))


def test_round_trip_encode_decode():
    cfg = v.loads(COSCHED_YAML)
    re = v.decode(v.encode(cfg))
    assert re.profile("tpusched") == cfg.profile("tpusched")
    assert re.client_connection == cfg.client_connection
    assert re.leader_election == cfg.leader_election


def test_duplicate_scheduler_names_rejected():
    import yaml
    raw = yaml.safe_load(COSCHED_YAML)
    raw["profiles"] = raw["profiles"] * 2
    with pytest.raises(ConfigError, match="duplicate schedulerName"):
        v.decode(raw)


def test_percentage_of_nodes_to_score_decodes():
    cfg = v.loads(textwrap.dedent("""
        apiVersion: tpusched.config.tpu.dev/v1beta1
        kind: TpuSchedulerConfiguration
        profiles:
        - schedulerName: tpusched
          percentageOfNodesToScore: 100
    """))
    assert cfg.profiles[0].percentage_of_nodes_to_score == 100


def test_percentage_of_nodes_to_score_rejects_out_of_range():
    with pytest.raises(ConfigError):
        v.loads(textwrap.dedent("""
            apiVersion: tpusched.config.tpu.dev/v1beta1
            kind: TpuSchedulerConfiguration
            profiles:
            - schedulerName: tpusched
              percentageOfNodesToScore: 150
        """))


# -- per-plugin args decode + defaults tables ---------------------------------
# (the reference's defaults_test.go sweep, v1beta3/defaults.go:29-160)

def _decode_args(plugin, args_yaml=""):
    cfg = v.loads(textwrap.dedent(f"""
        apiVersion: tpusched.config.tpu.dev/v1beta1
        kind: TpuSchedulerConfiguration
        profiles:
        - schedulerName: tpusched
          pluginConfig:
          - name: {plugin}
            args: {{{args_yaml}}}
    """))
    return cfg.profile().plugin_args[plugin]


@pytest.mark.parametrize("plugin,expected_defaults", [
    ("TpuSlice", {"score_mode": "binpack"}),
    ("Coscheduling", {"permit_waiting_time_seconds": 60,
                      "denied_pg_expiration_time_seconds": 20}),
    ("TopologyMatch", {"scoring_strategy": "LeastAllocated",
                       "resource_weights": {"google.com/tpu": 1}}),
    ("MultiSlice", {"same_domain_score": 100, "adjacent_domain_score": 50}),
    ("NodeResourcesAllocatable", {"mode": "Least",
                                  "resources": [{"name": "cpu", "weight": 1 << 20},
                                                {"name": "memory", "weight": 1}]}),
    ("TargetLoadPacking", {"target_utilization": 40,
                           "default_requests_cpu_millis": 1000,
                           "default_requests_multiplier": 1.5,
                           "watcher_address": "",
                           "metrics_refresh_interval_seconds": 30}),
    ("LoadVariationRiskBalancing", {"safe_variance_margin": 1.0,
                                    "safe_variance_sensitivity": 1.0,
                                    "watcher_address": "",
                                    "metrics_refresh_interval_seconds": 30}),
    ("PreemptionToleration", {"min_candidate_nodes_percentage": 10,
                              "min_candidate_nodes_absolute": 100}),
    ("CapacityScheduling", {}),
])
def test_empty_args_yield_reference_defaults(plugin, expected_defaults):
    args = _decode_args(plugin)
    for field_name, want in expected_defaults.items():
        assert getattr(args, field_name) == want, (plugin, field_name)


@pytest.mark.parametrize("plugin,args_yaml,field_name,want", [
    ("TpuSlice", "scoreMode: spread", "score_mode", "spread"),
    ("TopologyMatch", "scoringStrategy: BalancedAllocation",
     "scoring_strategy", "BalancedAllocation"),
    ("MultiSlice", "sameDomainScore: 7", "same_domain_score", 7),
    ("NodeResourcesAllocatable", "mode: Most", "mode", "Most"),
    ("TargetLoadPacking", "targetUtilization: 70", "target_utilization", 70),
    ("TargetLoadPacking", "defaultRequestsMultiplier: 2.0",
     "default_requests_multiplier", 2.0),
    ("LoadVariationRiskBalancing", "safeVarianceSensitivity: 2.5",
     "safe_variance_sensitivity", 2.5),
    ("PreemptionToleration", "minCandidateNodesAbsolute: 5",
     "min_candidate_nodes_absolute", 5),
])
def test_camel_case_field_decode_table(plugin, args_yaml, field_name, want):
    assert getattr(_decode_args(plugin, args_yaml), field_name) == want


@pytest.mark.parametrize("plugin", sorted(
    __import__("tpusched.config.scheme", fromlist=["ARGS_SCHEME"]).ARGS_SCHEME))
def test_unknown_field_rejected_for_every_plugin(plugin):
    with pytest.raises(ConfigError, match="unknown field"):
        _decode_args(plugin, "bogusKnob: 1")


def test_partial_args_keep_other_defaults():
    args = _decode_args("TargetLoadPacking", "targetUtilization: 55")
    assert args.target_utilization == 55
    assert args.default_requests_multiplier == 1.5     # untouched default
    assert args.metrics_refresh_interval_seconds == 30


def test_plugin_args_validate_hook_rejects_out_of_range():
    """Args types may define validate(); decode surfaces it as ConfigError so
    --validate-only catches range errors (no silent clamping at score time)."""
    import pytest
    from tpusched.config.scheme import ConfigError, decode_plugin_args
    with pytest.raises(ConfigError, match="packingWeight"):
        decode_plugin_args("TopologyMatch", {"packingWeight": 7})
    with pytest.raises(ConfigError, match="scoringStrategy"):
        decode_plugin_args("TopologyMatch", {"scoringStrategy": "Best"})
    args = decode_plugin_args("TopologyMatch", {"packingWeight": 0.0})
    assert args.packing_weight == 0.0


# -- podInitialBackoffSeconds / podMaxBackoffSeconds --------------------------

BACKOFF_YAML = textwrap.dedent("""
    apiVersion: tpusched.config.tpu.dev/v1beta1
    kind: TpuSchedulerConfiguration
    podInitialBackoffSeconds: {init}
    podMaxBackoffSeconds: {max}
    profiles:
    - schedulerName: tpusched
""")


def test_backoff_seconds_decoded_onto_profiles():
    cfg = v.loads(BACKOFF_YAML.format(init=0.25, max=5))
    assert cfg.profiles[0].pod_initial_backoff_s == 0.25
    assert cfg.profiles[0].pod_max_backoff_s == 5.0


def test_backoff_absent_means_none_not_zero():
    cfg = v.loads(textwrap.dedent("""
        apiVersion: tpusched.config.tpu.dev/v1beta1
        kind: TpuSchedulerConfiguration
        profiles:
        - schedulerName: tpusched
    """))
    assert cfg.profiles[0].pod_initial_backoff_s is None
    assert cfg.profiles[0].pod_max_backoff_s is None


def test_backoff_explicit_zero_preserved():
    """0 = retry immediately (upstream allows it); must survive decode."""
    cfg = v.loads(BACKOFF_YAML.format(init=0, max=0))
    assert cfg.profiles[0].pod_initial_backoff_s == 0.0
    assert cfg.profiles[0].pod_max_backoff_s == 0.0


def test_backoff_max_below_default_initial_rejected():
    """podMaxBackoffSeconds below the EFFECTIVE initial (1 s default when
    unset) must fail validation, not be silently exceeded at runtime."""
    with pytest.raises(ConfigError):
        v.loads(textwrap.dedent("""
            apiVersion: tpusched.config.tpu.dev/v1beta1
            kind: TpuSchedulerConfiguration
            podMaxBackoffSeconds: 0.5
            profiles:
            - schedulerName: tpusched
        """))


def test_backoff_negative_rejected():
    with pytest.raises(ConfigError):
        v.loads(BACKOFF_YAML.format(init=-1, max=10))


def test_backoff_max_less_than_initial_rejected():
    with pytest.raises(ConfigError):
        v.loads(BACKOFF_YAML.format(init=4, max=2))


def test_backoff_round_trips_through_encode():
    cfg = v.loads(BACKOFF_YAML.format(init=0.25, max=5))
    wire = v.encode(cfg)
    assert wire["podInitialBackoffSeconds"] == 0.25
    assert wire["podMaxBackoffSeconds"] == 5.0
    again = v.decode(wire)
    assert again.profiles[0].pod_initial_backoff_s == 0.25
    # unset stays absent on the wire
    wire2 = v.encode(v.loads(textwrap.dedent("""
        apiVersion: tpusched.config.tpu.dev/v1beta1
        kind: TpuSchedulerConfiguration
        profiles:
        - schedulerName: tpusched
    """)))
    assert "podInitialBackoffSeconds" not in wire2
