"""Versioned YAML config decoding: defaults merge, strict fields, conversion.

Mirrors the reference's config round-trip/defaulting tier:
apis/config/scheme/scheme_test.go (YAML through the real codec, strict) and
apis/config/v1beta{2,3}/defaults_test.go.
"""
import textwrap

import pytest

from tpusched.config import types as t
from tpusched.config import versioned as v
from tpusched.config.scheme import ConfigError

COSCHED_YAML = textwrap.dedent("""
    apiVersion: tpusched.config.tpu.dev/v1beta1
    kind: TpuSchedulerConfiguration
    leaderElection:
      leaderElect: false
    clientConnection:
      qps: 50
      burst: 100
    profiles:
    - schedulerName: tpusched
      plugins:
        queueSort:
          enabled:
          - name: Coscheduling
          disabled:
          - name: "*"
        preFilter:
          enabled:
          - name: Coscheduling
        postFilter:
          enabled:
          - name: Coscheduling
        permit:
          enabled:
          - name: Coscheduling
        reserve:
          enabled:
          - name: Coscheduling
        postBind:
          enabled:
          - name: Coscheduling
      pluginConfig:
      - name: Coscheduling
        args:
          permitWaitingTimeSeconds: 10
          deniedPGExpirationTimeSeconds: 3
""")


def test_decode_coscheduling_profile():
    cfg = v.loads(COSCHED_YAML)
    p = cfg.profile("tpusched")
    assert p.queue_sort == "Coscheduling"
    assert p.pre_filter == ["Coscheduling"]
    assert p.post_filter == ["Coscheduling"]
    assert p.permit == ["Coscheduling"]
    assert p.post_bind == ["Coscheduling"]
    # default filter set survives untouched
    assert p.filter == ["NodeUnschedulable", "NodeName", "NodeSelector",
                        "TaintToleration", "NodeResourcesFit"]
    assert p.bind == ["DefaultBinder"]
    args = p.plugin_args["Coscheduling"]
    assert args.permit_waiting_time_seconds == 10
    assert args.denied_pg_expiration_time_seconds == 3
    assert cfg.client_connection.qps == 50
    assert cfg.client_connection.burst == 100


def test_defaults_without_plugin_config():
    cfg = v.loads(textwrap.dedent("""
        apiVersion: tpusched.config.tpu.dev/v1beta1
        kind: TpuSchedulerConfiguration
        profiles:
        - schedulerName: tpusched
          plugins:
            permit: {enabled: [{name: Coscheduling}]}
          pluginConfig:
          - name: Coscheduling
            args: {}
    """))
    args = cfg.profile().plugin_args["Coscheduling"]
    # v1beta3/defaults.go:29-30 in the reference
    assert args.permit_waiting_time_seconds == t.DEFAULT_PERMIT_WAITING_TIME_SECONDS == 60
    assert args.denied_pg_expiration_time_seconds == t.DEFAULT_DENIED_PG_EXPIRATION_TIME_SECONDS == 20


def test_custom_bind_replaces_default_binder():
    cfg = v.loads(textwrap.dedent("""
        apiVersion: tpusched.config.tpu.dev/v1beta1
        kind: TpuSchedulerConfiguration
        profiles:
        - schedulerName: tpusched
          plugins:
            bind:
              disabled: [{name: DefaultBinder}]
              enabled: [{name: TpuSlice}]
            score:
              enabled: [{name: TpuSlice, weight: 2}]
    """))
    p = cfg.profile()
    assert p.bind == ["TpuSlice"]
    assert p.score == [("TpuSlice", 2)]


@pytest.mark.parametrize("mutation,msg", [
    ({"apiVersion": "bogus/v1"}, "unsupported apiVersion"),
    ({"kind": "KubeSchedulerConfiguration"}, "unsupported kind"),
    ({"bogusField": 1}, "unknown field"),
    ({"profiles": None}, "at least one profile"),
])
def test_strict_top_level(mutation, msg):
    import yaml
    raw = yaml.safe_load(COSCHED_YAML)
    raw.update(mutation)
    with pytest.raises(ConfigError, match=msg):
        v.decode(raw)


def test_strict_unknown_args_field():
    bad = COSCHED_YAML.replace("permitWaitingTimeSeconds", "permitWaitingTimeSecs")
    with pytest.raises(ConfigError, match="unknown field"):
        v.loads(bad)


def test_strict_unknown_extension_point():
    with pytest.raises(ConfigError, match="unknown extension point"):
        v.loads(textwrap.dedent("""
            apiVersion: tpusched.config.tpu.dev/v1beta1
            kind: TpuSchedulerConfiguration
            profiles:
            - schedulerName: tpusched
              plugins:
                preemptAggressively: {enabled: [{name: X}]}
        """))


def test_double_enable_rejected():
    with pytest.raises(ConfigError, match="enabled twice"):
        v.loads(textwrap.dedent("""
            apiVersion: tpusched.config.tpu.dev/v1beta1
            kind: TpuSchedulerConfiguration
            profiles:
            - schedulerName: tpusched
              plugins:
                permit:
                  enabled: [{name: Coscheduling}, {name: Coscheduling}]
        """))


def test_multi_queue_sort_rejected():
    with pytest.raises(ConfigError, match="exactly one queueSort"):
        v.loads(textwrap.dedent("""
            apiVersion: tpusched.config.tpu.dev/v1beta1
            kind: TpuSchedulerConfiguration
            profiles:
            - schedulerName: tpusched
              plugins:
                queueSort:
                  enabled: [{name: Coscheduling}, {name: QOSSort}]
        """))


def test_v1alpha1_conversion_renames():
    cfg = v.loads(textwrap.dedent("""
        apiVersion: tpusched.config.tpu.dev/v1alpha1
        kind: TpuSchedulerConfiguration
        profiles:
        - schedulerName: tpusched
          plugins:
            permit: {enabled: [{name: Coscheduling}]}
          pluginConfig:
          - name: Coscheduling
            args:
              permitWaitingSeconds: 7
              deniedPGExpirationSeconds: 2
          - name: MultiSlice
            args:
              dcnDomainScore: 90
    """))
    args = cfg.profile().plugin_args["Coscheduling"]
    assert args.permit_waiting_time_seconds == 7
    assert args.denied_pg_expiration_time_seconds == 2
    assert cfg.profile().plugin_args["MultiSlice"].same_domain_score == 90


def test_v1alpha1_conflicting_legacy_and_current():
    with pytest.raises(ConfigError, match="both legacy"):
        v.loads(textwrap.dedent("""
            apiVersion: tpusched.config.tpu.dev/v1alpha1
            kind: TpuSchedulerConfiguration
            profiles:
            - schedulerName: tpusched
              pluginConfig:
              - name: Coscheduling
                args:
                  permitWaitingSeconds: 7
                  permitWaitingTimeSeconds: 9
        """))


def test_round_trip_encode_decode():
    cfg = v.loads(COSCHED_YAML)
    re = v.decode(v.encode(cfg))
    assert re.profile("tpusched") == cfg.profile("tpusched")
    assert re.client_connection == cfg.client_connection
    assert re.leader_election == cfg.leader_election


def test_duplicate_scheduler_names_rejected():
    import yaml
    raw = yaml.safe_load(COSCHED_YAML)
    raw["profiles"] = raw["profiles"] * 2
    with pytest.raises(ConfigError, match="duplicate schedulerName"):
        v.decode(raw)


def test_percentage_of_nodes_to_score_decodes():
    cfg = v.loads(textwrap.dedent("""
        apiVersion: tpusched.config.tpu.dev/v1beta1
        kind: TpuSchedulerConfiguration
        profiles:
        - schedulerName: tpusched
          percentageOfNodesToScore: 100
    """))
    assert cfg.profiles[0].percentage_of_nodes_to_score == 100


def test_percentage_of_nodes_to_score_rejects_out_of_range():
    with pytest.raises(ConfigError):
        v.loads(textwrap.dedent("""
            apiVersion: tpusched.config.tpu.dev/v1beta1
            kind: TpuSchedulerConfiguration
            profiles:
            - schedulerName: tpusched
              percentageOfNodesToScore: 150
        """))
