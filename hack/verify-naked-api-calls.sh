#!/usr/bin/env bash
# Naked-API-call lint: all scheduler-side API traffic must flow through the
# retrying Clientset (tpusched/apiserver/client.py) — its error taxonomy,
# capped-backoff retries, per-call deadlines and degraded-mode hooks are the
# resilience contract, and a direct store call silently opts out of all of
# it. Two patterns fail the build:
#
#   1. `self._api.` anywhere outside tpusched/apiserver/ — the raw store
#      handle is an apiserver-package implementation detail;
#   2. direct CRUD/bind/record_event on a bare `self.api` inside the
#      scheduling core (sched/, fwk/, plugins/) — the scheduler owns a
#      clientset precisely so its read/write/failure paths keep the retry
#      layer (reads go through informer caches, writes through the client).
#
# Informer wiring (add_watch/peek/current_resource_version) and the
# controllers' store bootstrap are intentionally out of scope.
set -o errexit -o nounset -o pipefail
cd "$(dirname "$0")/.."

# testing/ is exempt (harness plumbing talks to the raw store on purpose:
# fixtures and watch monitors must not be attacked by the fault injector)
bad_raw=$(grep -rn --include='*.py' 'self\._api\.' tpusched/ \
  | grep -v '^tpusched/apiserver/' \
  | grep -v '^tpusched/testing/' \
  || true)

bad_core=$(grep -rnE --include='*.py' \
  'self\.api\.(create|get|try_get|list|update|patch|delete|bind|record_event)\(' \
  tpusched/sched/ tpusched/fwk/ tpusched/plugins/ \
  || true)

if [[ -n "$bad_raw$bad_core" ]]; then
  echo "ERROR: direct API-server calls bypassing the retry layer" >&2
  echo "(use the Clientset — see tpusched/apiserver/client.py):" >&2
  [[ -n "$bad_raw" ]] && echo "$bad_raw" >&2
  [[ -n "$bad_core" ]] && echo "$bad_core" >&2
  exit 1
fi
echo "naked-api-call verify OK"
