#!/usr/bin/env bash
# Thin wrapper: the naked-API-call lint is now a tpulint AST rule
# (tpusched/analysis/rules/api_calls.py) — raw `self._api.` access outside
# tpusched/apiserver/ and direct CRUD/bind/record_event verbs on `self.api`
# inside the scheduling core bypass the Clientset retry layer.  This script
# keeps the historical Makefile target; `make verify` runs the whole rule
# suite in one interpreter pass via `make lint`.
set -o errexit -o nounset -o pipefail
cd "$(dirname "$0")/.."
exec python -m tpusched.cmd.lint --rules naked-api-calls
