#!/usr/bin/env bash
# Real-TPU test tier: pallas kernel parity (fwd+bwd, MHA/GQA/MQA), a jitted
# end-to-end train step, and the KV-cache decode path — all on the actual
# chip, so the Mosaic lowering is never hardware-untested in-repo.
#
# Opt-in (round-1 verdict item 2): the CI tiers (hack/unit-test.sh,
# hack/integration-test.sh) force a virtual CPU mesh; this one needs a TPU
# and SKIPS (exit 0) cleanly when none is present.
set -o errexit -o nounset -o pipefail
cd "$(dirname "${BASH_SOURCE[0]}")/.."

# per-test timeout guard is in tests_tpu/conftest.py (subprocess probe);
# the outer timeout bounds a wedged-tunnel hang of the whole tier
exec timeout --signal=INT --kill-after=60 3600 python -m pytest tests_tpu/ -q "$@"
