#!/usr/bin/env bash
# Integration tier — analog of /root/reference/hack/integration-test.sh:35-37
# (40-minute budget): the TestCluster-driven end-to-end suites (real
# scheduler + controllers against the in-memory API server) plus the JAX
# workload bridge.
set -o errexit -o nounset -o pipefail
cd "$(dirname "$0")/.."
exec timeout 2400 python -m pytest -q \
  tests/test_integration_basic.py tests/test_jaxbridge.py \
  tests/test_coscheduling.py tests/test_capacity.py tests/test_topology.py \
  tests/test_multislice.py tests/test_controllers.py \
  "$@"
