#!/usr/bin/env bash
# CRD drift check — analog of /root/reference/hack/verify-crdgen.sh: the
# published CRD schemas in manifests/crds/ must cover every field of the API
# dataclasses (tests/test_manifests.py::test_crd_spec_fields_cover_dataclasses).
set -o errexit -o nounset -o pipefail
cd "$(dirname "$0")/.."
exec python -m pytest tests/test_manifests.py -q "$@"
