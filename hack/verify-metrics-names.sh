#!/usr/bin/env bash
# Metric-name lint: every metric registered in tpusched/ must follow the
# Prometheus naming contract this repo standardizes on —
#
#   1. `tpusched_` prefix (one namespace for the whole control plane);
#   2. counters end `_total`, histograms end `_seconds` (the unit suffix —
#      every histogram here is a duration), gauges never end `_total`;
#   3. no duplicate registrations of one name from multiple sites
#      (gauge_func is exempt: per-scheduler re-registration under fresh
#      label sets is its designed lifecycle).
#
# A name that breaks the convention ships a dashboard/alert footgun that
# can never be renamed cheaply once scraped — fail the build instead.
set -o errexit -o nounset -o pipefail
cd "$(dirname "$0")/.."

python - <<'EOF'
import pathlib
import re
import sys

pat = re.compile(
    r'REGISTRY\.(counter_vec|gauge_vec|histogram_vec|counter|gauge_func'
    r'|gauge|histogram)\(\s*\n?\s*"([^"]+)"')
seen = {}
bad = []
for path in sorted(pathlib.Path("tpusched").rglob("*.py")):
    text = path.read_text(encoding="utf-8")
    for m in pat.finditer(text):
        kind, name = m.group(1), m.group(2)
        site = f"{path}:{text[:m.start()].count(chr(10)) + 1}"
        if not name.startswith("tpusched_"):
            bad.append(f"{site}: {name}: missing tpusched_ prefix")
        if kind in ("counter", "counter_vec") \
                and not name.endswith("_total"):
            bad.append(f"{site}: {name}: counters must end _total")
        if kind in ("histogram", "histogram_vec") \
                and not name.endswith("_seconds"):
            bad.append(f"{site}: {name}: histograms must end _seconds")
        if kind in ("gauge", "gauge_vec", "gauge_func") \
                and name.endswith("_total"):
            bad.append(f"{site}: {name}: gauges must not end _total")
        prev = seen.get(name)
        if prev is not None and kind != "gauge_func":
            bad.append(f"{site}: {name}: duplicate registration "
                       f"(also at {prev})")
        seen.setdefault(name, site)
if bad:
    print("ERROR: metric naming violations:", file=sys.stderr)
    for b in bad:
        print(f"  {b}", file=sys.stderr)
    sys.exit(1)
print(f"metrics-names verify OK ({len(seen)} metric names)")
EOF
