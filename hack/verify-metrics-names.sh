#!/usr/bin/env bash
# Thin wrapper: the Prometheus naming lint is now a tpulint AST rule
# (tpusched/analysis/rules/metrics_names.py) — tpusched_ prefix, _total/
# _seconds suffix conventions, no duplicate registrations.  This script
# keeps the historical Makefile target; `make verify` runs the whole rule
# suite in one interpreter pass via `make lint`.
set -o errexit -o nounset -o pipefail
cd "$(dirname "$0")/.."
exec python -m tpusched.cmd.lint --rules metrics-names
