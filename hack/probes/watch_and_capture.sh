#!/bin/bash
# Round-5 TPU-evidence watcher: probe the axon tunnel every 4 min (bounded
# subprocess — a wedged claim hangs backend init indefinitely); the moment
# it recovers, capture on-chip evidence serially: tests_tpu tier first,
# then the full bench. ONE chip client at a time — two clients racing for
# the single-chip claim is what orphaned it in round 4.
LOG=/root/repo/hack/tpu-probe-r5.log
TIER=/root/repo/hack/probes/tpu_tier_r5.log
BENCHLOG=/root/repo/hack/probes/bench_r5_onchip.log
cd /root/repo || exit 1
for i in $(seq 1 200); do
  ts=$(date -u +%H:%M:%S)
  out=$(timeout 120 python -c "import jax; print(jax.default_backend())" 2>/dev/null | tail -1)
  if [ "$out" = "tpu" ]; then
    echo "$ts probe $i: LIVE - starting capture (critical tier, rest of tier, bench)" >> "$LOG"
    # critical subset FIRST (the tests that have never executed on
    # hardware + this round's additions): if the tunnel wedges mid-tier,
    # the marginal evidence is already on disk. -u + -v: every test
    # result line flushes to the log as it happens.
    CRIT="moe or seq8192 or adamw or remat or vocab or serve or speculative or decode or budget or xl or flagship"
    echo "=== tests_tpu CRITICAL subset started $(date -u +%FT%TZ) ===" >> "$TIER"
    timeout --signal=INT --kill-after=60 3600 python -u -m pytest tests_tpu/ -v -k "$CRIT" >> "$TIER" 2>&1
    echo "critical rc=$? finished $(date -u +%FT%TZ)" >> "$TIER"
    echo "=== tests_tpu remainder started $(date -u +%FT%TZ) ===" >> "$TIER"
    timeout --signal=INT --kill-after=60 3600 python -u -m pytest tests_tpu/ -v -k "not ($CRIT)" >> "$TIER" 2>&1
    echo "remainder rc=$? finished $(date -u +%FT%TZ)" >> "$TIER"
    echo "=== bench started $(date -u +%FT%TZ) ===" >> "$BENCHLOG"
    timeout --signal=INT --kill-after=60 5400 python -u bench.py >> "$BENCHLOG" 2>&1
    echo "bench rc=$? finished $(date -u +%FT%TZ)" >> "$BENCHLOG"
    echo "$(date -u +%H:%M:%S) capture complete" >> "$LOG"
    exit 0
  else
    echo "$ts probe $i: wedged (timeout/non-tpu)" >> "$LOG"
  fi
  sleep 240
done
exit 1
