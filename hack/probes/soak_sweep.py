"""Extended soak sweep (ad hoc, not CI): run the randomized soak at many
fresh set-enabled seeds to shake rare interleavings (e.g. the permit-hook
path). Each seed is a full soak round with invariant checks at quiesce.
Usage: python hack/probes/soak_sweep.py <lo> <hi>
"""
import sys

sys.path.insert(0, "tests")
sys.path.insert(0, ".")
from conftest import *  # noqa: F401,F403 — pins JAX to CPU like the suite
import test_soak_random as soak

lo, hi = int(sys.argv[1]), int(sys.argv[2])
failed = []
for seed in range(lo, hi):
    for with_sets in (True,):
        try:
            soak.test_randomized_soak_invariants(seed, with_sets)
            print(f"seed {seed} sets={with_sets}: ok", flush=True)
        except Exception as e:  # noqa: BLE001
            failed.append(seed)
            print(f"seed {seed} sets={with_sets}: FAILED {e}", flush=True)
print("failed seeds:", failed)
sys.exit(1 if failed else 0)
