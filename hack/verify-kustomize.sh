#!/bin/bash
# config/crd/bases must mirror manifests/crds byte-for-byte (kustomize's
# load restrictor forces the copies; this keeps them honest).
set -e
cd "$(dirname "$0")/.."
rc=0
for f in manifests/crds/*.yaml; do
  b="config/crd/bases/$(basename "$f")"
  if ! diff -q "$f" "$b" >/dev/null 2>&1; then
    echo "DRIFT: $b != $f (run: cp $f $b)"
    rc=1
  fi
done
exit $rc
