#!/usr/bin/env bash
# Thin wrapper: the structured-logging lint is now a tpulint AST rule
# (tpusched/analysis/rules/logging_discipline.py) — no bare print() in
# library code; log through tpusched.util.klog.  This script keeps the
# historical Makefile target; `make verify` runs the whole rule suite in
# one interpreter pass via `make lint`.
set -o errexit -o nounset -o pipefail
cd "$(dirname "$0")/.."
exec python -m tpusched.cmd.lint --rules structured-logging
