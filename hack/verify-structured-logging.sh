#!/usr/bin/env bash
# Structured-logging regression check — analog of
# /root/reference/hack/verify-structured-logging.sh:17-19 (which greps for
# non-structured klog calls). Here: library code must log through
# tpusched.util.klog (info_s/error_s/warning_s with key=value pairs), never
# bare print(). The cmd/ binaries are exempt (they print JSON to stdout by
# contract), as is testing/ (harness output).
set -o errexit -o nounset -o pipefail
cd "$(dirname "$0")/.."

bad=$(grep -rn --include='*.py' '\bprint(' tpusched/ \
  | grep -v '^tpusched/cmd/' \
  | grep -v '^tpusched/testing/' \
  || true)

if [[ -n "$bad" ]]; then
  echo "ERROR: bare print() in library code — use tpusched.util.klog:" >&2
  echo "$bad" >&2
  exit 1
fi
echo "structured-logging verify OK"
