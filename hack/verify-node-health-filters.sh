#!/usr/bin/env bash
# Node-health filter lint: every placement-producing plugin path must
# consult node readiness. `api.core.node_health_error` is the single shared
# judgement (unschedulable spec, Ready=False condition, not-ready taint) —
# a Filter that skips it can admit a NotReady node, and a gang retrying
# after a node failure would land right back on the dead hardware the
# lifecycle controller just drained.
#
# Rule: every file under tpusched/plugins/ that defines a `def filter(self`
# extension point must reference node_health_error (directly, or via a
# helper defined in the same file). Candidate-set builders that pre-select
# hosts for slice windows (TopologyMatch._occupancy) are covered by the
# same file-level check.
set -o errexit -o nounset -o pipefail
cd "$(dirname "$0")/.."

fail=0
while IFS= read -r f; do
  if ! grep -q 'node_health_error' "$f"; then
    echo "ERROR: $f defines a Filter but never consults node_health_error" >&2
    echo "       (import it from tpusched.api.core and reject unhealthy" >&2
    echo "       nodes before any placement arithmetic)" >&2
    fail=1
  fi
done < <(grep -rl --include='*.py' 'def filter(self' tpusched/plugins/)

# the helper itself must keep covering all three health facts — a refactor
# that drops one silently weakens every filter at once
for fact in 'spec.unschedulable' 'node_ready' 'TAINT_NODE_NOT_READY'; do
  if ! grep -A 20 'def node_health_error' tpusched/api/core.py \
      | grep -q "$fact"; then
    echo "ERROR: api/core.py node_health_error no longer checks $fact" >&2
    fail=1
  fi
done

if [[ "$fail" -ne 0 ]]; then
  exit 1
fi
echo "node-health filter verify OK"
