#!/usr/bin/env bash
# Thin wrapper: the node-health filter lint is now a tpulint AST rule
# (tpusched/analysis/rules/node_health.py) — every plugin file defining a
# Filter must consult api.core.node_health_error, and the helper itself
# must keep covering all three health facts.  This script keeps the
# historical Makefile target; `make verify` runs the whole rule suite in
# one interpreter pass via `make lint`.
set -o errexit -o nounset -o pipefail
cd "$(dirname "$0")/.."
exec python -m tpusched.cmd.lint --rules node-health-filters
