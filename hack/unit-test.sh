#!/usr/bin/env bash
# Unit tier — analog of /root/reference/hack/unit-test.sh:24-28 (go test over
# cmd/pkg/apis): every suite except the slow end-to-end integration files.
set -o errexit -o nounset -o pipefail
cd "$(dirname "$0")/.."
exec python -m pytest tests/ -q \
  --ignore=tests/test_integration_basic.py \
  --ignore=tests/test_jaxbridge.py \
  "$@"
