{{/*
Naming/label helpers for the tpuslice-scheduler chart — the chart-parity
analog of /root/reference/manifests/flexgpu/templates/_helpers.tpl, written
against this chart's values schema.
*/}}

{{- define "tpuslice.name" -}}
{{- default .Chart.Name .Values.nameOverride | trunc 63 | trimSuffix "-" }}
{{- end }}

{{- define "tpuslice.fullname" -}}
{{- if .Values.fullnameOverride }}
{{- .Values.fullnameOverride | trunc 63 | trimSuffix "-" }}
{{- else }}
{{- $name := default .Chart.Name .Values.nameOverride }}
{{- if contains $name .Release.Name }}
{{- .Release.Name | trunc 63 | trimSuffix "-" }}
{{- else }}
{{- printf "%s-%s" .Release.Name $name | trunc 63 | trimSuffix "-" }}
{{- end }}
{{- end }}
{{- end }}

{{- define "tpuslice.chart" -}}
{{- printf "%s-%s" .Chart.Name .Chart.Version | replace "+" "_" | trunc 63 | trimSuffix "-" }}
{{- end }}

{{- define "tpuslice.selectorLabels" -}}
app.kubernetes.io/name: {{ include "tpuslice.name" . }}
app.kubernetes.io/instance: {{ .Release.Name }}
{{- end }}

{{- define "tpuslice.labels" -}}
helm.sh/chart: {{ include "tpuslice.chart" . }}
{{ include "tpuslice.selectorLabels" . }}
{{- if .Chart.AppVersion }}
app.kubernetes.io/version: {{ .Chart.AppVersion | quote }}
{{- end }}
app.kubernetes.io/managed-by: {{ .Release.Service }}
{{- end }}

{{- define "tpuslice.serviceAccountName" -}}
{{- if .Values.serviceAccount.create }}
{{- default (include "tpuslice.fullname" .) .Values.serviceAccount.name }}
{{- else }}
{{- default "default" .Values.serviceAccount.name }}
{{- end }}
{{- end }}
