"""tpusched — a TPU-native scheduling framework.

A brand-new, from-scratch rebuild of the capabilities of
WLBF/flex-gpu-scheduler (a kubernetes-sigs/scheduler-plugins fork, 100% Go;
see SURVEY.md): a scheduling framework with QueueSort / PreFilter / Filter /
PostFilter / Score / Reserve / Permit / Bind / PostBind extension points,
hosting a TPU-native plugin suite:

- ``plugins.tpuslice``        — fractional-TPU placement (``google.com/tpu`` chips,
                                ``google.com/tpu-memory`` HBM MB); successor of
                                the reference's pkg/flexgpu (flex_gpu.go).
- ``plugins.coscheduling``    — PodGroup gang (all-or-nothing) admission;
                                successor of pkg/coscheduling.
- ``plugins.capacity``        — ElasticQuota min/max capacity sharing with
                                quota-aware preemption; successor of
                                pkg/capacityscheduling.
- ``plugins.topologymatch``   — ICI-torus slice-shape fitting; TPU-native
                                successor of pkg/noderesourcetopology (NUMA).
- ``plugins.multislice``      — DCN-aware cross-slice scoring for multi-slice
                                jobs (new; no reference analog).
- ``plugins.trimaran``        — load-aware scoring (TargetLoadPacking,
                                LoadVariationRiskBalancing); successor of
                                pkg/trimaran.
- ``plugins.allocatable``     — NodeResourcesAllocatable scoring.
- ``plugins.preemptiontoleration``, ``plugins.podstate``, ``plugins.qossort``,
  ``plugins.crossnodepreemption`` — the remaining reference plugin suite.

The control plane is an in-memory API server (``tpusched.apiserver``) with
watch/list/patch semantics standing in for the Kubernetes API server, so the
whole framework runs hermetically (the reference's envtest analog) while
keeping the same process-boundary discipline: plugins read through informer
caches and write through a clientset.

The workloads being placed are JAX/XLA jobs; ``tpusched.jaxbridge`` maps a
gang's slice assignment onto a ``jax.sharding.Mesh`` so a scheduled PodGroup
turns directly into a sharded pjit training step.
"""

__version__ = "0.1.0"
