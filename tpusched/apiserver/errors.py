"""API error taxonomy: retriable vs terminal, shared by every API surface.

The reference rides kube-apiserver error semantics — controllers wrap writes
in `retry.RetryOnConflict` and client-go rate limiters surface 429s — so
"which failures are worth retrying" is a first-class contract, not an
accident of each call site. This module centralizes that contract for all
three API surfaces the repo has (the hermetic in-memory ``APIServer``, the
kube-mode ``KubeAPIServer``, and the fault injector wrapping either):

- ``NotFound`` / ``Conflict``: the store's own semantic errors (defined in
  ``server.py``, re-exported here). Terminal by default; Conflict is
  retriable ONLY for ``patch`` (the server re-reads the live object under
  its lock on every attempt, so a retry IS the conflict-aware
  re-read-and-retry loop). A bind Conflict is terminal HERE — the
  lost-response case (our own first attempt landed, the retry Conflicts
  against it) is resolved by the client's heal hook, which re-reads the
  pod BEFORE this classification runs (client._PodClient.bind), so a
  genuine already-bound pod fails fast without burning retries.
- ``Unavailable``: a transient infrastructure failure (apiserver blip,
  injected fault, connection reset). Always retriable.
- ``Throttled``: the client-side QPS budget could not admit the call within
  its deadline. Terminal — retrying against an exhausted budget only digs
  the hole deeper; callers back off through the scheduler's failure path.
- kube-mode ``KubeError``: retriable when the HTTP status says the server
  (not the request) was at fault — 429/5xx — and only for idempotent verbs;
  status 0 ("outcome unknown": the response was lost) is never retried
  blindly for non-idempotent verbs, the caller's failure path re-reads.
"""
from __future__ import annotations

from .server import Conflict, NotFound

__all__ = ["Conflict", "NotFound", "Unavailable", "Throttled",
           "is_retriable", "IDEMPOTENT_VERBS"]


class Unavailable(RuntimeError):
    """Transient API failure — the request may succeed if simply retried."""


class Throttled(RuntimeError):
    """Client-side QPS budget exhausted within the call's deadline."""


# Verbs whose blind retry cannot double-apply: reads, and the atomic
# read-modify-write patch (the mutator runs against the live object each
# attempt). create/update/delete/bind replays can double-apply or mask
# real conflicts and are retried only on errors proven pre-application.
IDEMPOTENT_VERBS = frozenset(("get", "try_get", "list", "patch"))


def is_retriable(verb: str, exc: BaseException) -> bool:
    """Is this (verb, error) pair worth another attempt?"""
    if isinstance(exc, Unavailable):
        return True
    if isinstance(exc, Throttled) or isinstance(exc, NotFound):
        return False
    if isinstance(exc, Conflict):
        # patch only: server-side RMW makes the retry the re-read loop.
        # bind Conflicts are either healed (lost response, resolved before
        # this runs) or genuine double-binds — terminal either way.
        return verb == "patch"
    status = getattr(exc, "status", None)   # kube.KubeError
    if isinstance(status, int):
        return (status == 429 or status >= 500) and verb in IDEMPOTENT_VERBS
    return False
