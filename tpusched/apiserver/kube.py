"""External kube-apiserver client mode: the in-memory ``APIServer`` surface
spoken over HTTP to a real Kubernetes API server.

The reference's deployment contract is "plugins hosted in the real
kube-scheduler against a real apiserver"
(/root/reference/cmd/scheduler/main.go:34-47); its integration tier boots a
genuine apiserver+etcd (/root/reference/test/integration/main_test.go:31-46)
and Bind is a POST to the pods/binding subresource
(/root/reference/pkg/flexgpu/flex_gpu.go:230-242). This module closes that
gap for the rebuild: ``KubeAPIServer`` implements the exact method surface of
``apiserver.server.APIServer`` — so the Scheduler, controllers, informers and
clientset run unmodified — but:

- reads (``get``/``list``/``peek``) are served from a local reflector cache
  kept in sync by LIST+WATCH streams per kind (client-go shared-informer
  consistency: reads may trail the server by one watch delivery, exactly the
  staleness the scheduler's assume-cache is designed for);
- writes go over HTTP. ``patch`` and ``update`` are re-encoded as RFC 7386
  merge patches computed against a fresh GET of the live object, so fields
  this framework does not model (volumes, env, probes on real pods) are
  never clobbered — see kubecodec module doc;
- ``bind`` POSTs the pods/binding subresource with annotations on the
  Binding metadata (the apiserver merges them into the pod — the device-
  index contract);
- leader election uses coordination.k8s.io/v1 Leases with resourceVersion
  preconditions (create-or-update compare-and-swap);
- durability is etcd's: ``set_persistence_sink``/``restore`` are explicit
  no-ops (matching the reference, which keeps no local persistence).

Transport is stdlib ``http.client`` — one connection per (thread, purpose);
watch streams own dedicated connections and decode the line-delimited JSON
event framing. No kubernetes client library is required.
"""
from __future__ import annotations

import base64
import collections
import json
import os
import socket
import ssl
import tempfile
import threading
import time
from http import client as httplib
from typing import Any, Callable, Dict, List, Optional, Tuple
from urllib.parse import urlencode, urlsplit

from ..api.core import Binding, Event, GangMemberStatus
from ..util import klog
from . import kubecodec as codec
from . import server as srv
from .server import (ADDED, Conflict, DELETED, MODIFIED, NotFound,
                     WatchEvent)

# Kinds the reflector mirrors (LEASES are request/response only — leader
# election must see live state, never a cache).
WATCH_KINDS: Tuple[str, ...] = tuple(codec.KINDS)

LEASE_NAMESPACE = "kube-system"


class KubeError(RuntimeError):
    def __init__(self, status: int, message: str):
        super().__init__(f"HTTP {status}: {message}")
        self.status = status


class _HistoryGone(RuntimeError):
    """Watch resume point compacted away (410 / ERROR event) — the one
    disconnect that REQUIRES a relist."""


# -- connection config --------------------------------------------------------

class ConnectionInfo:
    """Where and how to reach the apiserver: URL + TLS + bearer token."""

    def __init__(self, server: str, token: str = "",
                 ssl_context: Optional[ssl.SSLContext] = None):
        self.server = server.rstrip("/")
        self.token = token
        self.ssl_context = ssl_context
        u = urlsplit(self.server)
        self.scheme = u.scheme or "http"
        self.host = u.hostname or "127.0.0.1"
        self.port = u.port or (443 if self.scheme == "https" else 80)

    @classmethod
    def from_kubeconfig(cls, path: str,
                        context: Optional[str] = None) -> "ConnectionInfo":
        """Parse the standard kubeconfig shape: current-context → context →
        cluster (server, CA) + user (token or client cert). ``*-data``
        fields are base64 PEM; file-path fields are read as-is."""
        import yaml
        with open(os.path.expanduser(path), encoding="utf-8") as f:
            cfg = yaml.safe_load(f) or {}
        ctx_name = context or cfg.get("current-context", "")
        by_name = lambda items: {i.get("name"): i for i in items or []}
        ctx = (by_name(cfg.get("contexts")).get(ctx_name) or {}).get(
            "context") or {}
        cluster = (by_name(cfg.get("clusters")).get(
            ctx.get("cluster")) or {}).get("cluster") or {}
        user = (by_name(cfg.get("users")).get(ctx.get("user")) or {}).get(
            "user") or {}
        server = cluster.get("server", "")
        if not server:
            raise ValueError(f"kubeconfig {path}: no cluster server for "
                             f"context {ctx_name!r}")
        sslctx = None
        if server.startswith("https"):
            sslctx = ssl.create_default_context()
            ca_data = cluster.get("certificate-authority-data")
            ca_file = cluster.get("certificate-authority")
            if ca_data:
                sslctx.load_verify_locations(
                    cadata=base64.b64decode(ca_data).decode())
            elif ca_file:
                sslctx.load_verify_locations(cafile=ca_file)
            if cluster.get("insecure-skip-tls-verify"):
                sslctx.check_hostname = False
                sslctx.verify_mode = ssl.CERT_NONE
            cert_file, key_file = (user.get("client-certificate"),
                                   user.get("client-key"))
            cert_data, key_data = (user.get("client-certificate-data"),
                                   user.get("client-key-data"))
            tmp_pems = []
            if cert_data and key_data:
                # load_cert_chain is file-path only; materialize the PEMs
                # briefly and unlink the moment the context has read them
                # (leaking a private key into /tmp for the process's — or
                # filesystem's — lifetime is not acceptable)
                for blob in (cert_data, key_data):
                    f = tempfile.NamedTemporaryFile("w", suffix=".pem",
                                                    delete=False)
                    f.write(base64.b64decode(blob).decode())
                    f.close()
                    tmp_pems.append(f.name)
                cert_file, key_file = tmp_pems
            try:
                if cert_file and key_file:
                    sslctx.load_cert_chain(cert_file, key_file)
            finally:
                for pth in tmp_pems:
                    try:
                        os.unlink(pth)
                    except OSError:
                        pass
        token = user.get("token", "")
        return cls(server, token=token, ssl_context=sslctx)

    @classmethod
    def in_cluster(cls) -> "ConnectionInfo":
        """Pod-side config: service-account token + CA from the standard
        mount, server from the KUBERNETES_SERVICE_* environment."""
        sa = "/var/run/secrets/kubernetes.io/serviceaccount"
        host = os.environ.get("KUBERNETES_SERVICE_HOST", "kubernetes.default.svc")
        port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
        with open(os.path.join(sa, "token"), encoding="utf-8") as f:
            token = f.read().strip()
        sslctx = ssl.create_default_context(cafile=os.path.join(sa, "ca.crt"))
        return cls(f"https://{host}:{port}", token=token, ssl_context=sslctx)


def load_connection(kubeconfig: str) -> ConnectionInfo:
    """CLI entry: ``--kubeconfig in-cluster`` or a kubeconfig path."""
    if kubeconfig == "in-cluster":
        return ConnectionInfo.in_cluster()
    return ConnectionInfo.from_kubeconfig(kubeconfig)


# -- transport ----------------------------------------------------------------

class _Transport:
    """Blocking JSON-over-HTTP. One pooled connection per thread for unary
    requests (http.client connections are not thread-safe); watch streams
    create their own dedicated connections via ``open_stream``."""

    def __init__(self, info: ConnectionInfo, timeout: float = 30.0):
        self.info = info
        self.timeout = timeout
        self._local = threading.local()

    def _connect(self, timeout: Optional[float] = None):
        t = timeout if timeout is not None else self.timeout
        if self.info.scheme == "https":
            return httplib.HTTPSConnection(
                self.info.host, self.info.port, timeout=t,
                context=self.info.ssl_context)
        return httplib.HTTPConnection(self.info.host, self.info.port,
                                      timeout=t)

    def _headers(self, content_type: Optional[str] = None) -> Dict[str, str]:
        h = {"Accept": "application/json"}
        if self.info.token:
            h["Authorization"] = f"Bearer {self.info.token}"
        if content_type:
            h["Content-Type"] = content_type
        return h

    def request(self, method: str, path: str,
                body: Optional[Dict[str, Any]] = None,
                content_type: str = "application/json") -> Dict[str, Any]:
        """One JSON request. Retry discipline: a SEND-phase failure (the
        pooled keep-alive connection went stale) is retried once on a
        fresh connection for every verb — a request that never finished
        transmitting was not processed (Content-Length framing). A
        RESPONSE-phase failure is retried only for idempotent verbs: the
        server may have committed a write whose acknowledgment we lost,
        and blindly re-POSTing e.g. pods/binding would turn a SUCCESSFUL
        bind into a spurious Conflict. Non-idempotent verbs surface
        KubeError(0, outcome-unknown) instead — the caller's failure path
        (unreserve/retry) is the conservative recovery."""
        payload = (json.dumps(body).encode() if body is not None else None)
        idempotent = method in ("GET", "HEAD")
        last_err: Optional[Exception] = None
        for attempt in (0, 1):   # one reconnect on a stale pooled connection
            conn = getattr(self._local, "conn", None)
            if conn is None:
                conn = self._connect()
                self._local.conn = conn
            sent = False
            try:
                conn.request(method, path, body=payload,
                             headers=self._headers(
                                 content_type if payload is not None
                                 else None))
                sent = True
                resp = conn.getresponse()
                data = resp.read()
                break
            except (httplib.HTTPException, OSError) as e:
                try:
                    conn.close()
                except OSError:
                    pass
                self._local.conn = None
                last_err = e
                if sent and not idempotent:
                    raise KubeError(
                        0, f"{method} {path}: response lost after send — "
                           f"outcome unknown, not retrying a "
                           f"non-idempotent request: {e}")
        else:
            raise KubeError(0, f"connection failed: {last_err}")
        if resp.status == 404:
            raise NotFound(f"{method} {path}: not found")
        if resp.status == 409:
            raise Conflict(f"{method} {path}: conflict: "
                           f"{data[:200].decode(errors='replace')}")
        if resp.status >= 300:
            raise KubeError(resp.status,
                            f"{method} {path}: "
                            f"{data[:500].decode(errors='replace')}")
        if not data:
            return {}
        return json.loads(data)

    def open_stream(self, path: str):
        """GET a watch stream; returns (connection, response). Cancel with
        ``kill_stream`` — a plain close() does NOT unblock a reader (the
        response holds its own file object over the socket fd; only a
        shutdown() interrupts a blocked recv). The generous OS timeout is
        the backstop against a silently dead server; the watch itself is
        bounded by timeoutSeconds server-side."""
        conn = self._connect(timeout=900.0)
        conn.request("GET", path, headers=self._headers())
        resp = conn.getresponse()
        if resp.status >= 300:
            body = resp.read(500)
            conn.close()
            raise KubeError(resp.status,
                            f"watch {path}: {body.decode(errors='replace')}")
        return conn, resp

    @staticmethod
    def kill_stream(conn) -> None:
        """Interrupt a blocked watch reader from another thread."""
        try:
            if conn.sock is not None:
                conn.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            conn.close()
        except OSError:
            pass


def _scrub_patch_meta(patch: Dict[str, Any]) -> Dict[str, Any]:
    """Drop server-owned metadata from a computed merge patch (uid,
    creationTimestamp: clock-skew between a client-constructed object and
    the server's stamp must not become a write). Returns the patch, empty
    if nothing user-visible remains."""
    meta = patch.get("metadata")
    if isinstance(meta, dict):
        meta.pop("uid", None)
        meta.pop("creationTimestamp", None)
        meta.pop("resourceVersion", None)
        if not meta:
            patch.pop("metadata", None)
    return patch


# -- the APIServer-surface adapter --------------------------------------------

class KubeAPIServer:
    """Drop-in for ``apiserver.server.APIServer`` backed by a real
    kube-apiserver. Construct, then ``start()`` (initial LIST + watch
    threads per kind), then hand to Scheduler/controllers exactly like the
    in-memory server. ``stop()`` tears down the watch streams."""

    def __init__(self, info: ConnectionInfo, kinds: Tuple[str, ...] = WATCH_KINDS,
                 clock=time.time, field_manager: str = "tpusched"):
        self._clock = clock
        self._tx = _Transport(info)
        self._kinds = tuple(kinds)
        self._lock = threading.RLock()
        self._cache: Dict[str, Dict[str, Any]] = {k: {} for k in self._kinds}
        self._handlers: Dict[str, List[Callable[[WatchEvent], None]]] = {
            k: [] for k in self._kinds}
        self._rv: Dict[str, int] = {k: 0 for k in self._kinds}
        self._events: "collections.deque[Event]" = collections.deque(
            maxlen=10_000)
        self._stop = threading.Event()
        self._watchers: List[threading.Thread] = []
        self._streams: List[Any] = []
        self._synced = threading.Event()
        self.field_manager = field_manager
        # leader-election observations: lease name → ((holder, renewTime,
        # rv), local monotonic time first seen) — expiry is judged against
        # local observation age, never by comparing clocks across nodes
        self._lease_obs: Dict[str, Tuple[Tuple[str, str, str], float]] = {}
        # in-band gang runtime status reports: kube mode has no server-side
        # fan-out object, so reports from in-process emitters (the
        # clientset heartbeat piggyback) fan out locally — same surface
        # and sink contract as the in-memory APIServer
        self._status_sinks: List[Callable[[List[GangMemberStatus]], Any]] = []

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> "KubeAPIServer":
        for kind in self._kinds:
            self._initial_list(kind)
        self._synced.set()
        for kind in self._kinds:
            t = threading.Thread(target=self._watch_loop, args=(kind,),
                                 name=f"tpusched-watch-{kind}", daemon=True)
            t.start()
            self._watchers.append(t)
        return self

    def stop(self) -> None:
        self._stop.set()
        with self._lock:
            streams, self._streams = list(self._streams), []
        for conn in streams:
            _Transport.kill_stream(conn)   # unblocks the watcher's readline
        for t in self._watchers:
            t.join(timeout=5)

    # -- reflector ------------------------------------------------------------

    def _initial_list(self, kind: str) -> None:
        info = codec.KINDS[kind]
        doc = self._tx.request("GET", info.collection_path())
        rv = codec.decode_rv((doc.get("metadata") or {}).get(
            "resourceVersion"))
        fresh: Dict[str, Any] = {}
        for item in doc.get("items") or []:
            obj = info.decode(item)
            fresh[obj.meta.key] = obj
            rv = max(rv, obj.meta.resource_version)
        with self._lock:
            stale = self._cache[kind]
            self._cache[kind] = fresh
            self._rv[kind] = max(self._rv[kind], rv)
            handlers = list(self._handlers[kind])
        # relist resync (410 recovery): diff against the previous cache so
        # handlers see precisely the missed mutations
        if handlers:
            for key, obj in fresh.items():
                old = stale.get(key)
                if old is None:
                    self._dispatch(WatchEvent(ADDED, kind, obj))
                elif old.meta.resource_version != obj.meta.resource_version:
                    self._dispatch(WatchEvent(MODIFIED, kind, obj, old))
            for key, old in stale.items():
                if key not in fresh:
                    self._dispatch(WatchEvent(DELETED, kind, old))

    def _watch_loop(self, kind: str) -> None:
        info = codec.KINDS[kind]
        need_relist = False
        while not self._stop.is_set():
            if need_relist:
                # history gap (410 Gone / ERROR event): the RV we hold is
                # compacted away — relist and diff. NOT done on routine
                # disconnects: a full LIST per kind per 5-minute watch
                # expiry would be sustained apiserver load that grows with
                # cluster size; a clean re-watch from the last RV is the
                # client-go reflector contract.
                try:
                    self._initial_list(kind)
                    need_relist = False
                except (KubeError, NotFound, OSError) as e:
                    klog.V(2).info_s("relist failed; backing off",
                                     kind=kind, error=str(e))
                    self._stop.wait(1.0)
                    continue
            path = (info.collection_path() + "?" + urlencode(
                {"watch": "true", "resourceVersion": str(self._rv[kind]),
                 "allowWatchBookmarks": "true", "timeoutSeconds": "300"}))
            try:
                conn, resp = self._tx.open_stream(path)
            except KubeError as e:
                need_relist = need_relist or e.status == 410
                if not self._stop.is_set():
                    klog.V(2).info_s("watch connect failed; backing off",
                                     kind=kind, error=str(e))
                    self._stop.wait(1.0)
                continue
            except OSError as e:
                if not self._stop.is_set():
                    klog.V(2).info_s("watch connect failed; backing off",
                                     kind=kind, error=str(e))
                    self._stop.wait(1.0)
                continue
            with self._lock:
                self._streams.append(conn)
            try:
                self._consume_stream(kind, info, resp)
            except _HistoryGone:
                need_relist = True
            except Exception as e:  # noqa: BLE001
                # disconnect → re-watch from last rv. Broad on purpose:
                # http.client can surface ValueError/AttributeError when a
                # socket dies mid-chunk, and the reflector must outlive any
                # transport hiccup — but the hiccup itself stays visible
                klog.V(2).info_s("watch stream broke; re-watching",
                                 kind=kind, error=str(e))
            finally:
                with self._lock:
                    if conn in self._streams:
                        self._streams.remove(conn)
                try:
                    conn.close()
                except OSError:
                    pass

    def _consume_stream(self, kind: str, info: codec.KindInfo, resp) -> None:
        while not self._stop.is_set():
            line = resp.readline()
            if not line:
                return
            line = line.strip()
            if not line:
                continue
            ev = json.loads(line)
            etype = ev.get("type", "")
            if etype == "BOOKMARK":
                rv = codec.decode_rv(((ev.get("object") or {}).get(
                    "metadata") or {}).get("resourceVersion"))
                with self._lock:
                    self._rv[kind] = max(self._rv[kind], rv)
                continue
            if etype == "ERROR":
                # typically 410 Gone: force the relist path
                raise _HistoryGone(f"watch error event: {ev.get('object')}")
            obj = info.decode(ev.get("object") or {})
            key = obj.meta.key
            with self._lock:
                self._rv[kind] = max(self._rv[kind],
                                     obj.meta.resource_version)
                old = self._cache[kind].get(key)
                if etype == "DELETED":
                    self._cache[kind].pop(key, None)
                else:
                    self._cache[kind][key] = obj
            if etype == "ADDED":
                self._dispatch(WatchEvent(ADDED, kind, obj))
            elif etype == "MODIFIED":
                # a watch resumed mid-history can replay MODIFIEDs the cache
                # already holds; handlers tolerate duplicates (client-go
                # at-least-once), so forward as-is
                self._dispatch(WatchEvent(MODIFIED, kind, obj, old))
            elif etype == "DELETED":
                self._dispatch(WatchEvent(DELETED, kind, obj))

    def _dispatch(self, ev: WatchEvent) -> None:
        for h in list(self._handlers[ev.kind]):
            try:
                h(ev)
            except Exception as e:   # handlers must not kill the reflector
                klog.error_s(e, "watch handler panicked", kind=ev.kind)

    # -- watch fan-out (APIServer surface) ------------------------------------

    def add_watch(self, kind: str, handler: Callable[[WatchEvent], None],
                  replay: bool = True) -> None:
        with self._lock:
            existing = list(self._cache[kind].values())
            self._handlers[kind].append(handler)
        if replay:
            for o in existing:
                handler(WatchEvent(ADDED, kind, o))

    def remove_watch(self, kind: str,
                     handler: Callable[[WatchEvent], None]) -> None:
        with self._lock:
            try:
                self._handlers[kind].remove(handler)
            except ValueError:
                pass

    # -- reads (reflector cache; client-go lister consistency) ----------------

    def get(self, kind: str, key: str):
        with self._lock:
            obj = self._cache[kind].get(key)
        if obj is None:
            raise NotFound(f"{kind} {key} not found")
        return obj.deepcopy()

    def try_get(self, kind: str, key: str):
        try:
            return self.get(kind, key)
        except NotFound:
            return None

    def peek(self, kind: str, key: str):
        with self._lock:
            return self._cache[kind].get(key)

    def list(self, kind: str, namespace: Optional[str] = None,
             selector: Optional[Dict[str, str]] = None) -> List[Any]:
        with self._lock:
            return [o.deepcopy() for o in self._cache[kind].values()
                    if (namespace is None or o.meta.namespace == namespace)
                    and (not selector
                         or all(o.meta.labels.get(k) == v
                                for k, v in selector.items()))]

    def current_resource_version(self) -> int:
        with self._lock:
            return max(self._rv.values(), default=0)

    def dump_for_snapshot(self, kinds) -> Tuple[Dict[str, List[Any]], int]:
        with self._lock:
            return ({k: list(self._cache[k].values()) for k in kinds
                     if k in self._cache},
                    max(self._rv.values(), default=0))

    # -- writes (HTTP) --------------------------------------------------------

    def create(self, kind: str, obj) -> Any:
        info = codec.KINDS[kind]
        body = info.encode(obj)
        body["metadata"].pop("resourceVersion", None)
        doc = self._tx.request(
            "POST", info.collection_path(
                obj.meta.namespace if info.namespaced else None), body)
        created = info.decode(doc)
        self._observe_write(kind, created)
        return created

    def _get_live(self, kind: str, key: str) -> Tuple[Any, Dict[str, Any]]:
        info = codec.KINDS[kind]
        doc = self._tx.request("GET", info.object_path(key))
        return info.decode(doc), doc

    def update(self, kind: str, obj) -> Any:
        """PUT semantics, transported as a merge patch against the live
        object so unmodeled fields survive (kubecodec module doc). The
        caller's ``resourceVersion`` (if set) rides the patch as the
        optimistic-concurrency precondition — stale ⇒ Conflict, exactly
        the in-memory contract."""
        info = codec.KINDS[kind]
        live, raw = self._get_live(kind, obj.meta.key)
        if (obj.meta.resource_version
                and obj.meta.resource_version != live.meta.resource_version):
            raise Conflict(
                f"{kind} {obj.meta.key}: stale resourceVersion "
                f"{obj.meta.resource_version} != "
                f"{live.meta.resource_version}")
        patch = codec.merge_patch(info.encode(live), info.encode(obj))
        if not _scrub_patch_meta(patch):
            return live
        doc = self._send_patch(info, obj.meta.key, patch,
                               live.meta.resource_version)
        updated = info.decode(doc)
        self._observe_write(kind, updated)
        return updated

    def _send_patch(self, info: codec.KindInfo, key: str,
                    patch: Dict[str, Any], rv: int) -> Dict[str, Any]:
        """Transmit a computed merge patch, honoring the kind's /status
        subresource: a real apiserver IGNORES status fields written to the
        main resource, so status changes ship as a second PATCH to
        ``{path}/status`` (chained on the first PATCH's resourceVersion).
        ``mutate`` callbacks are pure, so a Conflict between the two legs
        retries cleanly from the caller's loop."""
        status_part = (patch.pop("status", None)
                       if info.status_sub else None)
        doc: Optional[Dict[str, Any]] = None
        if _scrub_patch_meta(patch):
            patch.setdefault("metadata", {})["resourceVersion"] = str(rv)
            doc = self._tx.request(
                "PATCH", info.object_path(key), patch,
                content_type="application/merge-patch+json")
            rv_str = (doc.get("metadata") or {}).get("resourceVersion")
        else:
            rv_str = str(rv)
        if status_part is not None:
            doc = self._tx.request(
                "PATCH", info.object_path(key) + "/status",
                {"metadata": {"resourceVersion": rv_str},
                 "status": status_part},
                content_type="application/merge-patch+json")
        assert doc is not None   # caller guarantees a non-empty patch
        return doc

    def patch(self, kind: str, key: str,
              mutate: Callable[[Any], None]) -> Any:
        """Atomic read-modify-write: GET live → mutate a decoded copy →
        merge-patch with an RV precondition; Conflict retries re-read (the
        reference controllers' retry-on-conflict loop, here in one
        place)."""
        info = codec.KINDS[kind]
        last: Optional[Exception] = None
        for _ in range(8):
            live, _raw = self._get_live(kind, key)
            before = info.encode(live)
            mutate(live)
            patch = codec.merge_patch(before, info.encode(live))
            if not _scrub_patch_meta(patch):
                return live
            try:
                doc = self._send_patch(info, key, patch,
                                       live.meta.resource_version)
            except Conflict as e:
                last = e
                continue
            updated = info.decode(doc)
            self._observe_write(kind, updated)
            return updated
        raise Conflict(f"{kind} {key}: patch kept conflicting: {last}")

    def delete(self, kind: str, key: str, uid=None) -> None:
        """DELETE with the in-memory server's semantics: pods go with
        gracePeriodSeconds=0 (a real apiserver's default 30 s grace would
        leave the pod Terminating, and this stack's delete-then-recreate
        flows — defrag migration, soak churn — would 409 on the recreate),
        and the cache entry is evicted immediately for read-your-writes
        symmetry with ``_observe_write`` (idempotent against the DELETED
        watch event that follows). ``uid`` maps onto
        deleteOptions.preconditions.uid (the real apiserver enforces it)."""
        info = codec.KINDS[kind]
        body = ({"kind": "DeleteOptions", "apiVersion": "v1",
                 "gracePeriodSeconds": 0} if kind == srv.PODS else None)
        if uid is not None:
            body = dict(body or {"kind": "DeleteOptions", "apiVersion": "v1"})
            body["preconditions"] = {"uid": uid}
        self._tx.request("DELETE", info.object_path(key), body)
        with self._lock:
            self._cache[kind].pop(key, None)

    def _observe_write(self, kind: str, obj) -> None:
        """Fold a write's response into the cache immediately (bounded
        read-your-writes: the watch event, when it arrives, carries the
        same or a newer RV and is idempotent to re-apply)."""
        with self._lock:
            cur = self._cache[kind].get(obj.meta.key)
            if (cur is None or cur.meta.resource_version
                    <= obj.meta.resource_version):
                self._cache[kind][obj.meta.key] = obj
            self._rv[kind] = max(self._rv[kind], obj.meta.resource_version)

    # -- subresources ---------------------------------------------------------

    def bind(self, binding: Binding) -> None:
        ns, name = binding.pod_key.split("/", 1)
        path = f"/api/v1/namespaces/{ns}/pods/{name}/binding"
        try:
            self._tx.request("POST", path, codec.encode_binding(binding))
        except Conflict:
            raise Conflict(f"pod {binding.pod_key} already bound")

    def record_event(self, object_key: str, kind: str, etype: str,
                     reason: str, message: str) -> None:
        ev = Event(object_key=object_key, kind=kind, type=etype,
                   reason=reason, message=message, timestamp=self._clock())
        with self._lock:
            self._events.append(ev)
        ns, _, name = object_key.partition("/")
        ns = ns or "default"
        body = {
            "apiVersion": "v1", "kind": "Event",
            "metadata": {"namespace": ns,
                         "name": f"{name}.{int(self._clock() * 1e6):x}"},
            "involvedObject": {"kind": kind, "name": name, "namespace": ns},
            "type": etype, "reason": reason, "message": message,
            "firstTimestamp": codec.encode_time(ev.timestamp),
            "lastTimestamp": codec.encode_time(ev.timestamp),
            "count": 1,
            "source": {"component": self.field_manager},
        }
        try:
            self._tx.request("POST", f"/api/v1/namespaces/{ns}/events", body)
        except (KubeError, NotFound, Conflict, OSError) as e:
            klog.V(4).info_s("event post failed (best-effort)",
                             error=str(e))

    # -- coordination (Leases) ------------------------------------------------

    def _lease_path(self, name: str) -> str:
        return (f"/apis/coordination.k8s.io/v1/namespaces/{LEASE_NAMESPACE}"
                f"/leases/{name}")

    def acquire_or_renew_lease(self, name: str, holder: str,
                               lease_duration: float = 15.0) -> bool:
        now = self._clock()
        body = {"apiVersion": "coordination.k8s.io/v1", "kind": "Lease",
                "metadata": {"name": name, "namespace": LEASE_NAMESPACE},
                "spec": {"holderIdentity": holder,
                         # Lease durations are whole seconds on the wire;
                         # never truncate to 0 (a 0 reads back as "absent"
                         # and defaults — an unstealable lease)
                         "leaseDurationSeconds":
                             max(1, round(lease_duration)),
                         "renewTime": codec.encode_time(now, micro=True)}}
        try:
            cur = self._tx.request("GET", self._lease_path(name))
        except NotFound:
            try:
                self._tx.request(
                    "POST",
                    f"/apis/coordination.k8s.io/v1/namespaces/"
                    f"{LEASE_NAMESPACE}/leases", body)
                return True
            except Conflict:
                return False   # lost the creation race
        spec = cur.get("spec") or {}
        cur_holder = spec.get("holderIdentity", "")
        duration = float(spec.get("leaseDurationSeconds") or 15.0)
        if cur_holder and cur_holder != holder:
            # Expiry is judged on OUR clock against OUR observations — the
            # client-go leaderelection discipline. Comparing now() to the
            # holder's self-stamped renewTime would let a campaigner whose
            # clock runs > duration ahead steal the lease from a live
            # leader (split-brain); instead, the record must be OBSERVED
            # UNCHANGED for a full duration of local monotonic time before
            # it counts as expired.
            record = (cur_holder, spec.get("renewTime", ""),
                      str((cur.get("metadata") or {}).get(
                          "resourceVersion", "")))
            seen = self._lease_obs.get(name)
            mono = time.monotonic()
            if seen is None or seen[0] != record:
                self._lease_obs[name] = (record, mono)
                return False
            if mono - seen[1] <= duration:
                return False
        body["metadata"]["resourceVersion"] = str(
            (cur.get("metadata") or {}).get("resourceVersion", ""))
        try:
            self._tx.request("PUT", self._lease_path(name), body)
            return True
        except (Conflict, NotFound):
            return False   # raced another campaigner

    def lease_holder(self, name: str) -> str:
        try:
            cur = self._tx.request("GET", self._lease_path(name))
        except NotFound:
            return ""
        return (cur.get("spec") or {}).get("holderIdentity", "")

    # -- durability surface (etcd owns it) ------------------------------------

    def set_persistence_sink(self, sink) -> None:
        if sink is not None:
            klog.info_s("kube mode: local persistence ignored "
                        "(etcd is the store)")

    def restore(self, kind: str, objects) -> None:
        raise RuntimeError("kube mode: restore() is meaningless — state "
                           "lives in etcd; do not attach a Journal")

    def restore_resource_version(self, rv: int) -> None:
        raise RuntimeError("kube mode: restore_resource_version() is "
                           "meaningless — state lives in etcd")

    def events(self) -> List[Event]:
        with self._lock:
            return list(self._events)

    # -- gang runtime status reports (heartbeat-piggybacked) -------------------

    def add_status_sink(self, sink: Callable[[List[GangMemberStatus]], Any]
                        ) -> None:
        """Same contract as ``APIServer.add_status_sink``: idempotent per
        sink object, so a re-armed consumer never double-delivers."""
        with self._lock:
            if sink not in self._status_sinks:
                self._status_sinks.append(sink)

    def remove_status_sink(self, sink) -> None:
        with self._lock:
            try:
                self._status_sinks.remove(sink)
            except ValueError:
                pass

    def report_status(self, reports: List[GangMemberStatus]) -> None:
        """In-band gang progress reports. Kube mode keeps these process-
        local (no kube resource models them): stamp unstamped reports and
        fan out outside the lock, containing sink panics — identical
        semantics to the in-memory server."""
        if not reports:
            return
        now = self._clock()
        for r in reports:
            if not r.timestamp:
                r.timestamp = now
        with self._lock:
            sinks = list(self._status_sinks)
        for sink in sinks:
            try:
                sink(reports)
            except Exception as e:  # sinks must not kill the server
                klog.error_s(e, "status sink panicked")


class KubeLease:
    """``sched.ha.FileLease``-compatible adapter over coordination.k8s.io
    Leases, so ``ha.campaign``/``ha.hold`` drive kube-native leader election
    unchanged (the reference's resourcelock swap: file → Lease object)."""

    def __init__(self, api: KubeAPIServer,
                 name: str = "tpusched-scheduler"):
        self.api = api
        self.name = name

    def acquire_or_renew(self, holder: str, duration_s: float) -> bool:
        return self.api.acquire_or_renew_lease(self.name, holder, duration_s)

    def holder(self) -> str:
        return self.api.lease_holder(self.name)

    def release(self, holder: str) -> None:
        """Graceful handoff: delete the lease iff still ours (the check-
        then-delete race loses only a few seconds of expiry wait)."""
        try:
            if self.api.lease_holder(self.name) == holder:
                self.api._tx.request(
                    "DELETE", self.api._lease_path(self.name))
        except (KubeError, NotFound, Conflict, OSError):
            pass
