"""Durability for the in-memory API server: write-ahead journal + snapshot.

The reference keeps **no local persistence**: etcd behind the kube-apiserver
is the checkpoint, and every component rebuilds in-memory state from the API
on restart (SURVEY §5; device occupancy from pod annotations,
/root/reference/pkg/flexgpu/gpu_node.go:67-120; ElasticQuota ``used`` from
pods, /root/reference/pkg/controller/elasticquota.go:212-224). Our control
plane is hermetic, so this module supplies the etcd half of that contract:

- a **write-ahead journal** (``wal.jsonl``): every store mutation is
  *enqueued under the store lock, before its watch event fires*, so WAL
  order always equals store-mutation order; the disk append itself is
  asynchronous (a dedicated writer thread), and fsync is off by default —
  an acknowledged mutation enqueued but not yet flushed can be lost on a
  hard crash. ``Journal.flush()`` gives a durability barrier, and
  ``fsync=True`` (``--state-fsync`` on the CLIs) makes every batch durable
  before the writer proceeds;
- a **snapshot** (``snapshot.json``) written at compaction time; replay =
  snapshot + WAL suffix, exactly etcd's snapshot+raft-log recovery;
- a reflective dataclass codec (all API objects are plain nested dataclasses
  with scalar leaves, so encoding is total and lossless). Replay is
  schema-drift tolerant by construction — unknown record kinds are skipped,
  unknown object fields dropped, absent fields take dataclass defaults — so
  a --state-dir written by an adjacent version replays cleanly (pinned by
  tests/test_persistence.py::test_replay_tolerates_schema_drift).

Leases are deliberately NOT persisted: leader-election state must die with
the process (a restarted process re-campaigns; holding a stale lease across
restart is the split-brain the reference's leaderelection exit-on-lost-lease
guards against, /root/reference/cmd/controller/app/server.go:84-123).
Events are best-effort observability, also skipped (k8s Events are TTL'd).
"""
from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
import typing
from typing import Any, Dict, List, Optional, Tuple

from ..api import meta as metalib
from ..api.core import Node, Pod, PodDisruptionBudget, PriorityClass
from ..api.scheduling import ElasticQuota, PodGroup
from ..api.topology import TpuTopology
from ..util import klog
from . import server as srv

# kind → dataclass; LEASES and Events intentionally absent (see module doc).
KIND_CLASSES: Dict[str, type] = {
    srv.PODS: Pod,
    srv.NODES: Node,
    srv.POD_GROUPS: PodGroup,
    srv.ELASTIC_QUOTAS: ElasticQuota,
    srv.PRIORITY_CLASSES: PriorityClass,
    srv.PDBS: PodDisruptionBudget,
    srv.TPU_TOPOLOGIES: TpuTopology,
}

SNAPSHOT_FILE = "snapshot.json"
WAL_FILE = "wal.jsonl"


# -- reflective codec ---------------------------------------------------------

def encode_object(obj: Any) -> Any:
    """Dataclass → JSON-able. Tuples become lists; the decoder restores them
    from the field's type hint."""
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return {f.name: encode_object(getattr(obj, f.name))
                for f in dataclasses.fields(obj)}
    if isinstance(obj, dict):
        return {k: encode_object(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [encode_object(v) for v in obj]
    return obj


_hints_cache: Dict[type, Dict[str, Any]] = {}


def _type_hints(cls: type) -> Dict[str, Any]:
    hints = _hints_cache.get(cls)
    if hints is None:
        hints = typing.get_type_hints(cls)
        _hints_cache[cls] = hints
    return hints


def _decode_value(tp: Any, v: Any) -> Any:
    if v is None:
        return None
    origin = typing.get_origin(tp)
    if origin is typing.Union:  # Optional[T] (and unions of scalars)
        args = [a for a in typing.get_args(tp) if a is not type(None)]
        return _decode_value(args[0], v) if len(args) == 1 else v
    if origin in (list, List):
        (et,) = typing.get_args(tp) or (Any,)
        return [_decode_value(et, x) for x in v]
    if origin in (tuple, Tuple):
        args = typing.get_args(tp)
        et = args[0] if args else Any
        return tuple(_decode_value(et, x) for x in v)
    if origin in (dict, Dict):
        args = typing.get_args(tp)
        vt = args[1] if len(args) == 2 else Any
        return {k: _decode_value(vt, x) for k, x in v.items()}
    if dataclasses.is_dataclass(tp):
        return decode_object(tp, v)
    return v


def decode_object(cls: type, data: Dict[str, Any]) -> Any:
    hints = _type_hints(cls)
    kwargs = {}
    for f in dataclasses.fields(cls):
        if f.name in data:
            kwargs[f.name] = _decode_value(hints[f.name], data[f.name])
    return cls(**kwargs)


# -- journal ------------------------------------------------------------------

class Journal:
    """Appends every store mutation to the WAL; compacts into a snapshot when
    the WAL grows past ``compact_every`` records.

    The API server invokes the sink under its store lock — there the record
    is only ENQUEUED (stored objects are never mutated after publication, so
    encoding can safely happen later). A dedicated writer thread drains the
    queue in order — WAL order == store mutation order — and does all disk
    I/O, so the control plane's lock is never held across a syscall.
    Compaction also runs on the writer thread; replay is idempotent
    (put=upsert, delete=discard-missing), so a snapshot racing a queued
    record is harmless."""

    def __init__(self, api: srv.APIServer, directory: str,
                 fsync: bool = False, compact_every: int = 50_000):
        self.api = api
        self.dir = directory
        self.fsync = fsync
        self.compact_every = compact_every
        os.makedirs(directory, exist_ok=True)
        self._file_lock = threading.Lock()      # guards WAL/snapshot files
        self._wal_path = os.path.join(directory, WAL_FILE)
        self._snap_path = os.path.join(directory, SNAPSHOT_FILE)
        self._wal = open(self._wal_path, "a", encoding="utf-8")
        # ownership token for HA fencing: the WAL inode this journal opened.
        # A takeover rotates the WAL through a new inode (compact below), so
        # "path's inode != mine" means this journal is DEPOSED — every
        # by-path file operation (compact's snapshot/WAL swaps, torn-write
        # truncation) must check this first or it would clobber the new
        # active's files.
        self._wal_inode = os.fstat(self._wal.fileno()).st_ino
        self._fenced = False
        self._wal_records = 0

        self._cv = threading.Condition()
        self._queue: "list[Tuple[str, str, Any]]" = []
        self._enqueued = 0
        self._processed = 0     # records drained (written or failed)
        self._failed = 0        # records lost to write errors
        self._closed = False
        self._writer = threading.Thread(target=self._writer_loop,
                                        name="tpusched-journal", daemon=True)
        self._writer.start()

    # sink signature: op in {"put", "delete"} — called under the store lock;
    # must stay allocation-cheap and syscall-free.
    def __call__(self, op: str, kind: str, obj: Any) -> None:
        if kind not in KIND_CLASSES:
            return
        with self._cv:
            if self._closed:
                return
            self._queue.append((op, kind, obj))
            self._enqueued += 1
            self._cv.notify()

    def _writer_loop(self) -> None:
        while True:
            with self._cv:
                while not self._queue and not self._closed:
                    self._cv.wait(0.5)
                batch, self._queue = self._queue, []
                closing = self._closed
            if batch:
                lost = 0
                try:
                    self._write_batch(batch)
                except Exception as e:  # durability is best-effort: never
                    klog.error_s(e, "journal write failed")  # take down the plane
                    lost = len(batch)
                with self._cv:
                    self._processed += len(batch)
                    self._failed += lost
                    self._cv.notify_all()
            if closing and not batch:
                return

    def _is_deposed_locked(self) -> bool:
        """True when another journal has taken over the directory (the WAL
        path no longer points at our inode). Called under ``_file_lock``."""
        if self._fenced:
            return True
        try:
            if os.stat(self._wal_path).st_ino != self._wal_inode:
                self._fenced = True
        except OSError:
            self._fenced = True   # WAL gone: someone else owns the dir
        if self._fenced:
            klog.error_s(None, "journal fenced: state dir taken over; "
                         "dropping all further writes")
        return self._fenced

    def _write_batch(self, batch) -> None:
        with self._file_lock:
            if self._is_deposed_locked():
                # deliberate data drop: a deposed active's writes must die,
                # not interleave with the new active's WAL
                raise RuntimeError("journal fenced (state dir taken over)")
            # a mid-batch write failure (disk full) can leave a torn partial
            # line; replay stops at the first undecodable line, so appending
            # after a tear would silently shadow every later record. On
            # failure, discard the Python-level buffer and truncate the file
            # back to the last known-good on-disk offset. The buffer is
            # always clean at entry (every exit path flushes or reopens), so
            # fstat's size IS the logical append position.
            good = os.fstat(self._wal.fileno()).st_size
            try:
                for op, kind, obj in batch:
                    rec = {"op": op, "kind": kind, "obj": encode_object(obj)}
                    self._wal.write(json.dumps(rec, separators=(",", ":")) + "\n")
                self._wal.flush()
            except Exception:
                self._reopen_discarding_buffer_locked(good)
                raise
            if self.fsync:
                os.fsync(self._wal.fileno())
            self._wal_records += len(batch)
            needs_compact = self._wal_records >= self.compact_every
        if needs_compact:
            self.compact()

    def _reopen_discarding_buffer_locked(self, good: int) -> None:
        """Recover from a torn batch: drop any bytes stuck in the text
        wrapper's buffer (close may fail re-flushing them — the fd closes
        regardless) and os.ftruncate the WAL back to ``good``. The caller
        holds ``_file_lock`` (the *_locked contract).

        Fencing: the truncate-and-reopen is BY PATH, so if the directory
        was taken over between our last write and this failure, doing it
        would corrupt the new active's WAL (truncating to OUR old offset
        can NUL-pad or discard THEIR records). A deposed journal just
        closes and stays fenced."""
        try:
            self._wal.close()
        except OSError:
            pass
        if self._is_deposed_locked():
            return
        try:
            fd = os.open(self._wal_path, os.O_RDWR)
            try:
                os.ftruncate(fd, good)
            finally:
                os.close(fd)
        except OSError as e:
            klog.error_s(e, "journal truncate after torn write failed",
                         offset=good)
        self._wal = open(self._wal_path, "a", encoding="utf-8")
        self._wal_inode = os.fstat(self._wal.fileno()).st_ino

    def compact(self) -> None:
        """Write a full snapshot and truncate the WAL (atomic via rename).
        Runs on the writer thread (or at attach time); takes the store lock
        only for the duration of dump_for_snapshot's dict copies."""
        dump, rv = self.api.dump_for_snapshot(KIND_CLASSES.keys())
        snap = {"rv": rv,
                "kinds": {k: [encode_object(o) for o in objs]
                          for k, objs in dump.items()}}
        tmp = self._snap_path + ".tmp"
        with self._file_lock:
            if self._is_deposed_locked():
                # by-path snapshot/WAL swaps from a deposed journal would
                # overwrite the new active's files with stale state
                return
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(snap, f, separators=(",", ":"))
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self._snap_path)
            self._wal.close()
            # rotate the WAL through a NEW inode (empty tmp + rename), not
            # an in-place truncate: attach() compacts at startup, so an HA
            # takeover re-inodes the WAL here — a deposed active that still
            # holds the old fd keeps appending to the orphaned inode, where
            # its un-fenced writes vanish instead of interleaving with ours
            wal_tmp = self._wal_path + ".tmp"
            open(wal_tmp, "w", encoding="utf-8").close()
            os.replace(wal_tmp, self._wal_path)
            self._wal = open(self._wal_path, "a", encoding="utf-8")
            self._wal_inode = os.fstat(self._wal.fileno()).st_ino
            self._wal_records = 0
        # a successful snapshot contains every live object, so records lost
        # to earlier write errors are durable again — clear the failure flag
        with self._cv:
            self._failed = 0
            self._cv.notify_all()

    def flush(self, timeout: float = 10.0) -> bool:
        """Block until every record enqueued so far has been processed.
        Returns False on timeout OR if any record was lost to a write error —
        callers must not treat state as durable then."""
        deadline = time.monotonic() + timeout
        with self._cv:
            target = self._enqueued
            while self._processed < target:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cv.wait(remaining)
            return self._failed == 0

    def close(self) -> None:
        """Drain the queue, stop the writer, close the WAL."""
        with self._cv:
            if self._closed:
                return
            self._closed = True
            self._cv.notify_all()
        self._writer.join(timeout=10)
        with self._file_lock:
            self._wal.close()


# -- recovery + attachment ----------------------------------------------------

def load_into(api: srv.APIServer, directory: str) -> int:
    """Replay snapshot + WAL from ``directory`` into ``api``. Returns the
    number of live objects restored. Must run before any watchers register
    (restore does not dispatch events — informers replay on add_watch)."""
    by_kind: Dict[str, Dict[str, Any]] = {k: {} for k in KIND_CLASSES}
    max_rv = 0

    snap_path = os.path.join(directory, SNAPSHOT_FILE)
    if os.path.exists(snap_path):
        with open(snap_path, encoding="utf-8") as f:
            snap = json.load(f)
        max_rv = snap.get("rv", 0)
        for kind, objs in snap.get("kinds", {}).items():
            cls = KIND_CLASSES.get(kind)
            if cls is None:
                continue
            for data in objs:
                obj = decode_object(cls, data)
                by_kind[kind][obj.meta.key] = obj

    wal_path = os.path.join(directory, WAL_FILE)
    if os.path.exists(wal_path):
        with open(wal_path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    # torn tail write (crash mid-append): stop replay here,
                    # everything before the tear is consistent
                    klog.error_s(None, "journal tail truncated; stopping replay")
                    break
                kind, cls = rec.get("kind"), KIND_CLASSES.get(rec.get("kind"))
                if cls is None:
                    continue
                obj = decode_object(cls, rec["obj"])
                # every record — including deletes and superseded puts —
                # advances the rv floor, so post-restart writes can never
                # re-mint a resource_version watchers already observed
                if obj.meta.resource_version > max_rv:
                    max_rv = obj.meta.resource_version
                if rec["op"] == "delete":
                    by_kind[kind].pop(obj.meta.key, None)
                else:
                    by_kind[kind][obj.meta.key] = obj

    count = 0
    uids: List[str] = []
    for kind, objs in by_kind.items():
        if objs:
            api.restore(kind, objs.values())
            count += len(objs)
            for o in objs.values():
                max_rv = max(max_rv, o.meta.resource_version)
                uids.append(o.meta.uid)
    api.restore_resource_version(max_rv)
    metalib.bump_uid_counter(uids)
    return count


def attach(api: srv.APIServer, directory: str, fsync: bool = False,
           compact_every: int = 50_000) -> Journal:
    """Recover state from ``directory`` (if any) into ``api``, then install a
    Journal as its persistence sink. Call before starting schedulers or
    controllers."""
    restored = load_into(api, directory)
    if restored:
        klog.info_s("recovered state from journal", directory=directory,
                    objects=restored)
    journal = Journal(api, directory, fsync=fsync, compact_every=compact_every)
    # fold recovered state into a fresh snapshot so old WAL entries are
    # dropped and recovery stays O(live objects), not O(history)
    journal.compact()
    api.set_persistence_sink(journal)
    return journal
