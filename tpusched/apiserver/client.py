"""Typed clientset facade over the APIServer.

Analog of the generated clientset in /root/reference/pkg/generated
(versioned.NewForConfig) plus the core kube client: typed CRUD per kind, with
the Bind subresource on pods. QPS/burst throttling is supported to mirror the
controller's --qps/--burst API budget
(/root/reference/cmd/controller/app/options.go:43-44).
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional

from ..api.core import Binding
from ..util import tracectx
from . import server as srv


class _TokenBucket:
    def __init__(self, qps: float, burst: int, clock=time.monotonic):
        self.qps, self.burst, self._clock = qps, burst, clock
        self._tokens = float(burst)
        self._last = clock()
        self._lock = threading.Lock()

    def wait(self):
        if self.qps <= 0:
            return
        while True:
            with self._lock:
                now = self._clock()
                self._tokens = min(self.burst, self._tokens + (now - self._last) * self.qps)
                self._last = now
                if self._tokens >= 1:
                    self._tokens -= 1
                    return
                need = (1 - self._tokens) / self.qps
            time.sleep(need)


class _KindClient:
    def __init__(self, api: srv.APIServer, kind: str, bucket: Optional[_TokenBucket]):
        self._api, self._kind, self._bucket = api, kind, bucket

    def _throttle(self):
        if self._bucket:
            self._bucket.wait()

    def create(self, obj):
        self._throttle()
        return self._api.create(self._kind, obj)

    def get(self, key: str):
        self._throttle()
        return self._api.get(self._kind, key)

    def try_get(self, key: str):
        self._throttle()
        return self._api.try_get(self._kind, key)

    def list(self, namespace=None, selector: Optional[Dict[str, str]] = None):
        self._throttle()
        return self._api.list(self._kind, namespace, selector)

    def update(self, obj):
        self._throttle()
        return self._api.update(self._kind, obj)

    def patch(self, key: str, mutate: Callable):
        self._throttle()
        return self._api.patch(self._kind, key, mutate)

    def delete(self, key: str):
        self._throttle()
        return self._api.delete(self._kind, key)


class _PodClient(_KindClient):
    def bind(self, binding: Binding):
        self._throttle()
        return self._api.bind(binding)


class Clientset:
    def __init__(self, api: srv.APIServer, qps: float = 0.0, burst: int = 0):
        bucket = _TokenBucket(qps, burst) if qps > 0 else None
        self.api = api
        self.pods = _PodClient(api, srv.PODS, bucket)
        self.nodes = _KindClient(api, srv.NODES, bucket)
        self.podgroups = _KindClient(api, srv.POD_GROUPS, bucket)
        self.elasticquotas = _KindClient(api, srv.ELASTIC_QUOTAS, bucket)
        self.priorityclasses = _KindClient(api, srv.PRIORITY_CLASSES, bucket)
        self.pdbs = _KindClient(api, srv.PDBS, bucket)
        self.tputopologies = _KindClient(api, srv.TPU_TOPOLOGIES, bucket)

    def record_event(self, object_key: str, kind: str, etype: str, reason: str,
                     message: str = "") -> None:
        # flight-recorder correlation: an Event recorded inside a traced
        # cycle carries the cycle's trace id, so an operator can jump from
        # `kubectl describe`-style output to /debug/flightrecorder
        tid = tracectx.get()
        if tid:
            message = f"{message} [trace={tid}]" if message \
                else f"[trace={tid}]"
        self.api.record_event(object_key, kind, etype, reason, message)
