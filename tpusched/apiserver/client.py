"""Typed clientset facade over the APIServer.

Analog of the generated clientset in /root/reference/pkg/generated
(versioned.NewForConfig) plus the core kube client: typed CRUD per kind, with
the Bind subresource on pods. QPS/burst throttling is supported to mirror the
controller's --qps/--burst API budget
(/root/reference/cmd/controller/app/options.go:43-44).

Resilience layer (the retry contract every consumer gets for free):

- every verb classifies failures through ``errors.is_retriable`` and retries
  transient ones under capped exponential backoff with jitter, bounded by
  BOTH an attempt budget and a per-call wall deadline — the client-go
  rate-limited-workqueue + RetryOnConflict discipline, collapsed to the one
  place all API traffic passes through;
- ``patch`` retries Conflict: the server re-reads the live object under its
  lock on every attempt, so the retry IS the conflict-aware
  re-read-and-retry loop;
- ``bind`` heals the lost-response case: a retried bind that Conflicts
  re-reads the pod, and "already bound to MY node" is success (the first
  attempt's write landed; failing the cycle would roll back a healthy gang);
- retries annotate the active flight-recorder trace (an ``api-retry`` span
  per sleep) and bump ``tpusched_api_retries_total`` /
  ``tpusched_api_retry_exhausted_total``; exhaustions also feed the
  caller's ``on_retry_exhausted`` hook (the scheduler's degraded-mode trip
  counter), successes feed ``on_success`` (its reset).
"""
from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, Optional

from ..api.core import Binding
from ..util import klog, tracectx
from ..util.metrics import (api_retries, api_retry_exhausted, events_dropped,
                            goodput_reports_dropped)
from . import server as srv
from .errors import Conflict, Throttled, is_retriable


@dataclass
class RetryPolicy:
    """Capped exponential backoff with jitter + a per-call wall deadline.

    Defaults are tuned for a control loop: fail a single call within
    ~5 s worst-case so the scheduler's own failure path (requeue with pod
    backoff, degraded mode) takes over instead of one cycle hanging."""
    max_attempts: int = 4           # total tries, including the first
    initial_backoff_s: float = 0.02
    max_backoff_s: float = 0.5
    jitter: float = 0.25            # ± fraction of the backoff
    deadline_s: float = 5.0         # wall budget incl. throttle wait + sleeps


DEFAULT_RETRY_POLICY = RetryPolicy()

# Private jitter source: retry sleeps must not consume (or be perturbed by)
# the GLOBAL random stream — seeded tests and the chaos soak's injector own
# their own deterministic streams, and client jitter drawing from the
# shared module RNG would silently desynchronize them.
_RNG = random.Random()


class _TokenBucket:
    def __init__(self, qps: float, burst: int, clock=time.monotonic):
        self.qps, self.burst, self._clock = qps, burst, clock
        self._tokens = float(burst)
        self._last = clock()
        self._lock = threading.Lock()

    def wait(self, deadline: Optional[float] = None) -> None:
        """Block until a token is available. ``deadline`` (in this bucket's
        clock domain) bounds the wait: a token that cannot be minted in time
        raises ``Throttled`` — terminal, never an unbounded sleep — so a
        tiny qps cannot wedge a binding thread forever."""
        if self.qps <= 0:
            return
        while True:
            with self._lock:
                now = self._clock()
                self._tokens = min(self.burst, self._tokens + (now - self._last) * self.qps)
                self._last = now
                if self._tokens >= 1:
                    self._tokens -= 1
                    return
                need = (1 - self._tokens) / self.qps
            if deadline is not None and now + need > deadline:
                raise Throttled(
                    f"qps budget exhausted: next token in {need:.3f}s, "
                    f"deadline in {max(0.0, deadline - now):.3f}s")
            time.sleep(need)


class _KindClient:
    def __init__(self, api: srv.APIServer, kind: str,
                 bucket: Optional[_TokenBucket],
                 policy: Optional[RetryPolicy] = DEFAULT_RETRY_POLICY,
                 hooks: Optional["_Hooks"] = None):
        self._api, self._kind, self._bucket = api, kind, bucket
        self._policy = policy
        self._hooks = hooks or _NO_HOOKS

    def _invoke(self, verb: str, key: str, fn, heal=None):
        """The retry core every verb funnels through. ``heal(exc, attempt)``
        optionally resolves a retriable error without another server round
        trip (returns a 1-tuple result to adopt, or None to keep going)."""
        pol = self._policy
        if pol is None:                       # retries disabled (tests)
            if self._bucket:
                self._bucket.wait()
            return fn()
        deadline = time.monotonic() + pol.deadline_s
        backoff = pol.initial_backoff_s
        attempt = 1
        while True:
            try:
                if self._bucket:
                    self._bucket.wait(deadline)
                out = fn()
            except Exception as e:  # noqa: BLE001 — classified below
                # heal first: it can resolve errors the taxonomy calls
                # terminal (a retried bind Conflicting against its own
                # landed write), so a genuine failure that heal declines
                # raises immediately — no wasted sleeps, no spurious
                # retry-exhausted feed into degraded mode
                healed = heal(e, attempt) if heal is not None else None
                if healed is not None:
                    self._hooks.on_success()
                    return healed[0]
                if not is_retriable(verb, e):
                    raise
                delay = backoff * (1 + pol.jitter * (2 * _RNG.random() - 1))
                if (attempt >= pol.max_attempts
                        or time.monotonic() + delay > deadline):
                    api_retry_exhausted.with_labels(verb).inc()
                    self._hooks.on_retry_exhausted(verb, self._kind, e)
                    klog.V(3).info_s("api retry budget exhausted",
                                     verb=verb, kind=self._kind, key=key,
                                     attempts=attempt, err=str(e))
                    raise
                api_retries.with_labels(verb).inc()
                self._annotate_retry(verb, key, attempt, delay, e)
                time.sleep(delay)
                backoff = min(backoff * 2, pol.max_backoff_s)
                attempt += 1
                continue
            self._hooks.on_success()
            return out

    @staticmethod
    def _annotate_retry(verb: str, key: str, attempt: int, delay: float,
                        exc: Exception) -> None:
        # an api-retry is invisible latency inside whatever extension point
        # is running: put a span on the active cycle trace so a slow cycle
        # under apiserver degradation is attributable from the dump alone
        from .. import trace
        tr = trace.current()
        if tr is not None:
            tr.add_event("api-retry", time.perf_counter(), delay,
                         {"verb": verb, "key": key, "attempt": attempt,
                          "err": str(exc)[:120]})

    def create(self, obj):
        return self._invoke("create", obj.meta.key,
                            lambda: self._api.create(self._kind, obj))

    def get(self, key: str):
        return self._invoke("get", key, lambda: self._api.get(self._kind, key))

    def try_get(self, key: str):
        return self._invoke("try_get", key,
                            lambda: self._api.try_get(self._kind, key))

    def list(self, namespace=None, selector: Optional[Dict[str, str]] = None):
        return self._invoke("list", "",
                            lambda: self._api.list(self._kind, namespace,
                                                   selector))

    def update(self, obj):
        return self._invoke("update", obj.meta.key,
                            lambda: self._api.update(self._kind, obj))

    def patch(self, key: str, mutate: Callable):
        return self._invoke("patch", key,
                            lambda: self._api.patch(self._kind, key, mutate))

    def delete(self, key: str, uid: Optional[str] = None):
        """``uid``: precondition the delete on the observed object instance
        (DeleteOptions.Preconditions.UID) — a stale sweep must not kill a
        same-name replacement. Conflict on mismatch, terminal by taxonomy."""
        return self._invoke("delete", key,
                            lambda: self._api.delete(self._kind, key,
                                                     uid=uid))


class _PodClient(_KindClient):
    def bind(self, binding: Binding):
        def heal(exc: Exception, attempt: int):
            """Lost-response bind healing: a Conflict on a RETRIED bind
            means either a genuine double-bind or our own first attempt
            landing without its response. Re-read and compare: bound to
            our node ⇒ the write was ours, the call succeeded.
            First-attempt Conflicts stay terminal (a real already-bound
            pod must fail the cycle)."""
            if attempt < 2 or not isinstance(exc, Conflict):
                return None
            # bounded re-read retry: a single transient blip here must not
            # convert an actually-successful bind into a terminal Conflict
            # (and, for gangs, a spurious whole-gang rollback). Raw store
            # read on purpose — a throttle/deadline wait inside heal would
            # charge the verification read against the budget the bind
            # already spent.
            pod = None
            for i in range(3):
                try:
                    pod = self._api.try_get(self._kind, binding.pod_key)
                    break
                except Exception as e:  # noqa: BLE001 — best-effort,
                    # but the swallowed read failure must stay diagnosable
                    klog.V(3).info_s("bind heal verification read failed",
                                     pod=binding.pod_key, attempt=i,
                                     error=str(e))
                    if i < 2:
                        time.sleep(0.01)
            if pod is not None and pod.spec.node_name == binding.node_name:
                klog.V(3).info_s("bind healed after lost response",
                                 pod=binding.pod_key, node=binding.node_name)
                return (None,)
            return None
        return self._invoke("bind", binding.pod_key,
                            lambda: self._api.bind(binding), heal=heal)


class _NodeClient(_KindClient):
    def heartbeat(self, name: str, now: Optional[float] = None,
                  reports: Optional[list] = None):
        """The kubelet heartbeat (Lease-renewal analog): stamp
        ``status.last_heartbeat_time``. Goes through the normal retry
        layer — a node agent keeps heartbeating through transient apiserver
        blips; the lifecycle controller's grace period absorbs the rest.
        Both Ready transitions (condition + taint) stay with the lifecycle
        controller, so exactly one component owns the node-health edges.

        ``reports``: in-band ``GangMemberStatus`` progress reports from the
        gang members running on this node, piggybacked so runtime goodput
        telemetry costs zero extra API calls. Delivery is best-effort AFTER
        the heartbeat lands (the liveness signal is the load-bearing half):
        a failed fan-out is swallowed and counted, never retried — the next
        heartbeat carries fresher numbers anyway."""
        # tpulint: disable=monotonic-clock — heartbeat stamps are
        # wall-clock by contract: the lifecycle controller compares
        # them against its own injected wall clock; tests pass now=
        ts = time.time() if now is None else now

        def mutate(node):
            node.status.last_heartbeat_time = ts
        out = self.patch(f"/{name}" if "/" not in name else name, mutate)
        if reports:
            _fan_out_reports(self._api, reports, node=name)
        return out


def _fan_out_reports(api, reports: list, **ctx) -> None:
    """In-band status-report fan-out, advisory by contract: a failure is
    swallowed and counted, never retried — the next batch carries fresher
    numbers anyway."""
    try:
        api.report_status(reports)
    except Exception as e:  # noqa: BLE001 — advisory by contract
        goodput_reports_dropped.inc(len(reports))
        klog.V(4).info_s("goodput report fan-out dropped",
                         reports=len(reports), err=str(e), **ctx)


class _Hooks:
    """Caller-observable retry outcomes (degraded-mode feed). on_success is
    called on EVERY successful API call — keep implementations O(1)."""

    def __init__(self, on_retry_exhausted=None, on_success=None):
        self.on_retry_exhausted = on_retry_exhausted or (lambda *a: None)
        self.on_success = on_success or (lambda: None)


_NO_HOOKS = _Hooks()


class Clientset:
    def __init__(self, api: srv.APIServer, qps: float = 0.0, burst: int = 0,
                 retry: Optional[RetryPolicy] = DEFAULT_RETRY_POLICY,
                 on_retry_exhausted=None, on_success=None):
        bucket = _TokenBucket(qps, burst) if qps > 0 else None
        hooks = (_Hooks(on_retry_exhausted, on_success)
                 if (on_retry_exhausted or on_success) else _NO_HOOKS)
        self.api = api
        self.pods = _PodClient(api, srv.PODS, bucket, retry, hooks)
        self.nodes = _NodeClient(api, srv.NODES, bucket, retry, hooks)
        self.podgroups = _KindClient(api, srv.POD_GROUPS, bucket, retry, hooks)
        self.elasticquotas = _KindClient(api, srv.ELASTIC_QUOTAS, bucket,
                                         retry, hooks)
        self.priorityclasses = _KindClient(api, srv.PRIORITY_CLASSES, bucket,
                                           retry, hooks)
        self.pdbs = _KindClient(api, srv.PDBS, bucket, retry, hooks)
        self.tputopologies = _KindClient(api, srv.TPU_TOPOLOGIES, bucket,
                                         retry, hooks)

    def report_status(self, reports: list) -> None:
        """Direct (non-heartbeat) in-band status report path for emitters
        without a node identity (a serving frontend, a test pump). Same
        best-effort contract as ``record_event``: advisory telemetry must
        never raise into the caller, and is never retried."""
        _fan_out_reports(self.api, reports)

    def record_event(self, object_key: str, kind: str, etype: str, reason: str,
                     message: str = "") -> None:
        """Best-effort by contract: an Event is advisory telemetry and must
        NEVER raise into a scheduling/binding cycle — a failed emission is
        swallowed and counted (tpusched_events_dropped_total), not retried
        (retrying advisory writes under an outage amplifies the outage)."""
        # flight-recorder correlation: an Event recorded inside a traced
        # cycle carries the cycle's trace id, so an operator can jump from
        # `kubectl describe`-style output to /debug/flightrecorder
        tid = tracectx.get()
        if tid:
            message = f"{message} [trace={tid}]" if message \
                else f"[trace={tid}]"
        try:
            self.api.record_event(object_key, kind, etype, reason, message)
        except Exception as e:  # noqa: BLE001 — best-effort by contract
            events_dropped.inc()
            klog.V(4).info_s("event emission dropped", object=object_key,
                             reason=reason, err=str(e))

    def record_event_deferred(self, object_key: str, kind: str, etype: str,
                              reason: str,
                              message_fn: Callable[[], str]) -> None:
        """``record_event`` for hot paths: the message is built lazily on
        the apiserver's fan-out flusher when batching is armed (synchronous
        fallback otherwise). The trace id is thread-local, so it is
        captured HERE on the calling thread and spliced in at format time —
        deferral must not lose the flight-recorder correlation."""
        tid = tracectx.get()

        def build() -> str:
            message = message_fn()
            if tid:
                message = f"{message} [trace={tid}]" if message \
                    else f"[trace={tid}]"
            return message

        try:
            self.api.record_event_deferred(object_key, kind, etype, reason,
                                           build)
        except Exception as e:  # noqa: BLE001 — best-effort by contract
            events_dropped.inc()
            klog.V(4).info_s("event emission dropped", object=object_key,
                             reason=reason, err=str(e))
