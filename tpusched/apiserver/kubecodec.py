"""Kubernetes JSON ↔ tpusched dataclass codec.

The hermetic control plane stores plain dataclasses; a real kube-apiserver
speaks the wire shapes published in ``manifests/crds/`` (camelCase fields,
quantity strings, RFC3339 timestamps). This module is the total mapping
between the two for every kind the framework consumes — the hand-written
equivalent of the reference's generated deepcopy/conversion functions
(/root/reference/apis/scheduling/v1alpha1/zz_generated.deepcopy.go) plus
client-go's serializers.

Lossiness discipline: decoding a real cluster's Pod drops fields this
framework does not model (volumes, env, probes...). Writers must therefore
never PUT a re-encoded Pod wholesale — ``kube.KubeAPIServer`` turns every
update into an RFC 7386 merge-patch computed between two *encoded* forms,
so untouched (including unmodeled) fields are never sent. ``merge_patch``
below is that diff.

resourceVersion: kube's is an opaque string; ours is an int. etcd mints
decimal uint64 strings, so ``int(rv)`` is faithful against any real
apiserver; a non-numeric RV (some aggregated API) decodes as 0 and relies
on server-side conflict checks alone.
"""
from __future__ import annotations

import datetime as _dt
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..api.core import (Binding, Container, NODE_READY, Node, NodeCondition,
                        NodeSpec, NodeStatus, Pod, PodCondition,
                        PodDisruptionBudget, PodSpec, PodStatus,
                        PriorityClass, Taint, Toleration)
from ..api.meta import ObjectMeta, OwnerReference
from ..api.resources import CPU, ResourceList, parse_quantity
from ..api.scheduling import (ElasticQuota, ElasticQuotaSpec,
                              ElasticQuotaStatus, PodGroup, PodGroupSpec,
                              PodGroupStatus)
from ..api.topology import TpuTopology, TpuTopologySpec
from . import server as srv

# -- quantities ---------------------------------------------------------------


def format_quantity(resource: str, value: int) -> str:
    """Canonical int units → kube quantity string (cpu millicores → '250m',
    everything else plain base-unit integers — valid quantities kube
    normalizes server-side)."""
    if resource == CPU:
        return f"{int(value)}m"
    return str(int(value))


def encode_resources(r: Optional[ResourceList]) -> Optional[Dict[str, str]]:
    if r is None:
        return None
    return {k: format_quantity(k, v) for k, v in r.items()}


def decode_resources(r: Optional[Dict[str, Any]]) -> ResourceList:
    if not r:
        return {}
    return {k: parse_quantity(v, k) for k, v in r.items()}


# -- timestamps ---------------------------------------------------------------

def encode_time(t: Optional[float], micro: bool = False) -> Optional[str]:
    if t is None or not t:
        return None
    dt = _dt.datetime.fromtimestamp(float(t), _dt.timezone.utc)
    if micro:
        return dt.strftime("%Y-%m-%dT%H:%M:%S.%f") + "Z"
    return dt.strftime("%Y-%m-%dT%H:%M:%SZ")


def decode_time(s: Optional[str]) -> Optional[float]:
    if not s:
        return None
    txt = s.rstrip("Z")
    for fmt in ("%Y-%m-%dT%H:%M:%S.%f", "%Y-%m-%dT%H:%M:%S"):
        try:
            return _dt.datetime.strptime(txt, fmt).replace(
                tzinfo=_dt.timezone.utc).timestamp()
        except ValueError:
            continue
    return None


def decode_rv(rv: Any) -> int:
    try:
        return int(rv)
    except (TypeError, ValueError):
        return 0


# -- metadata -----------------------------------------------------------------

def encode_meta(meta: ObjectMeta, namespaced: bool) -> Dict[str, Any]:
    out: Dict[str, Any] = {"name": meta.name}
    if namespaced:
        out["namespace"] = meta.namespace
    if meta.labels:
        out["labels"] = dict(meta.labels)
    if meta.annotations:
        out["annotations"] = dict(meta.annotations)
    if meta.resource_version:
        out["resourceVersion"] = str(meta.resource_version)
    ct = encode_time(meta.creation_timestamp)
    if ct:
        out["creationTimestamp"] = ct
    dt = encode_time(meta.deletion_timestamp)
    if dt:
        out["deletionTimestamp"] = dt
    if meta.owner_references:
        out["ownerReferences"] = [
            {"apiVersion": o.api_version, "kind": o.kind, "name": o.name,
             "uid": o.uid, "controller": o.controller}
            for o in meta.owner_references]
    return out


def decode_meta(m: Dict[str, Any], namespaced: bool) -> ObjectMeta:
    meta = ObjectMeta(
        name=m.get("name", ""),
        namespace=m.get("namespace", "default") if namespaced else "",
        labels=dict(m.get("labels") or {}),
        annotations=dict(m.get("annotations") or {}),
        resource_version=decode_rv(m.get("resourceVersion")),
        creation_timestamp=decode_time(m.get("creationTimestamp")) or 0.0,
        deletion_timestamp=decode_time(m.get("deletionTimestamp")),
        owner_references=[OwnerReference(
            api_version=o.get("apiVersion", ""), kind=o.get("kind", ""),
            name=o.get("name", ""), uid=str(o.get("uid", "")),
            controller=bool(o.get("controller", False)))
            for o in m.get("ownerReferences") or []])
    uid = m.get("uid")
    if uid:
        meta.uid = str(uid)
    return meta


# -- Pod ----------------------------------------------------------------------

def _encode_container(c: Container) -> Dict[str, Any]:
    out: Dict[str, Any] = {"name": c.name}
    if c.image:
        out["image"] = c.image
    res: Dict[str, Any] = {}
    if c.requests:
        res["requests"] = encode_resources(c.requests)
    if c.limits:
        res["limits"] = encode_resources(c.limits)
    if res:
        out["resources"] = res
    return out


def _decode_container(c: Dict[str, Any]) -> Container:
    res = c.get("resources") or {}
    return Container(name=c.get("name", "main"), image=c.get("image", ""),
                     requests=decode_resources(res.get("requests")),
                     limits=decode_resources(res.get("limits")))


def encode_pod(p: Pod) -> Dict[str, Any]:
    spec: Dict[str, Any] = {
        "containers": [_encode_container(c) for c in p.spec.containers],
        "schedulerName": p.spec.scheduler_name,
    }
    if p.spec.init_containers:
        spec["initContainers"] = [_encode_container(c)
                                  for c in p.spec.init_containers]
    if p.spec.node_name:
        spec["nodeName"] = p.spec.node_name
    if p.spec.node_selector:
        spec["nodeSelector"] = dict(p.spec.node_selector)
    if p.spec.priority:
        spec["priority"] = p.spec.priority
    if p.spec.priority_class_name:
        spec["priorityClassName"] = p.spec.priority_class_name
    if p.spec.tolerations:
        spec["tolerations"] = [
            {k: v for k, v in (("key", t.key), ("operator", t.operator),
                               ("value", t.value), ("effect", t.effect)) if v}
            for t in p.spec.tolerations]
    if p.spec.overhead:
        spec["overhead"] = encode_resources(p.spec.overhead)
    status: Dict[str, Any] = {"phase": p.status.phase}
    if p.status.nominated_node_name:
        status["nominatedNodeName"] = p.status.nominated_node_name
    if p.status.conditions:
        status["conditions"] = [
            {"type": c.type, "status": c.status, "reason": c.reason,
             "message": c.message,
             "lastTransitionTime": encode_time(c.last_transition_time)}
            for c in p.status.conditions]
    if p.status.start_time is not None:
        status["startTime"] = encode_time(p.status.start_time)
    return {"apiVersion": "v1", "kind": "Pod",
            "metadata": encode_meta(p.meta, True),
            "spec": spec, "status": status}


def decode_pod(d: Dict[str, Any]) -> Pod:
    s = d.get("spec") or {}
    st = d.get("status") or {}
    return Pod(
        meta=decode_meta(d.get("metadata") or {}, True),
        spec=PodSpec(
            containers=[_decode_container(c)
                        for c in s.get("containers") or []],
            init_containers=[_decode_container(c)
                             for c in s.get("initContainers") or []],
            node_name=s.get("nodeName", ""),
            node_selector=dict(s.get("nodeSelector") or {}),
            scheduler_name=s.get("schedulerName", "default-scheduler"),
            priority=int(s.get("priority") or 0),
            priority_class_name=s.get("priorityClassName", ""),
            tolerations=[Toleration(key=t.get("key", ""),
                                    operator=t.get("operator", "Equal"),
                                    value=t.get("value", ""),
                                    effect=t.get("effect", ""))
                         for t in s.get("tolerations") or []],
            overhead=decode_resources(s.get("overhead"))),
        status=PodStatus(
            phase=st.get("phase", "Pending"),
            nominated_node_name=st.get("nominatedNodeName", ""),
            conditions=[PodCondition(
                type=c.get("type", ""), status=c.get("status", "True"),
                reason=c.get("reason", ""), message=c.get("message", ""),
                last_transition_time=decode_time(
                    c.get("lastTransitionTime")) or 0.0)
                for c in st.get("conditions") or []],
            start_time=decode_time(st.get("startTime"))))


# -- Node ---------------------------------------------------------------------

def encode_node(n: Node) -> Dict[str, Any]:
    spec: Dict[str, Any] = {}
    if n.spec.unschedulable:
        spec["unschedulable"] = True
    if n.spec.taints:
        spec["taints"] = [{"key": t.key, "value": t.value, "effect": t.effect}
                          for t in n.spec.taints]
    status: Dict[str, Any] = {
        "capacity": encode_resources(n.status.capacity) or {},
        "allocatable": encode_resources(n.status.allocatable) or {}}
    # node health model: conditions round-trip as v1.NodeCondition; the
    # node-level heartbeat stamp rides the Ready condition's
    # lastHeartbeatTime (where the real kubelet keeps it)
    conditions: List[Dict[str, Any]] = []
    hb = encode_time(n.status.last_heartbeat_time, micro=True)
    for c in n.status.conditions:
        cd: Dict[str, Any] = {"type": c.type, "status": c.status}
        if c.reason:
            cd["reason"] = c.reason
        if c.message:
            cd["message"] = c.message
        lt = encode_time(c.last_transition_time, micro=True)
        if lt:
            cd["lastTransitionTime"] = lt
        if c.type == NODE_READY and hb:
            cd["lastHeartbeatTime"] = hb
        conditions.append(cd)
    if hb and not any(c.type == NODE_READY for c in n.status.conditions):
        # heartbeat-managed node with no Ready condition written yet:
        # synthesize the carrier so the stamp survives (decode treats a
        # Ready=True condition identically to an absent one)
        conditions.append({"type": NODE_READY, "status": "True",
                           "lastHeartbeatTime": hb})
    if conditions:
        status["conditions"] = conditions
    return {"apiVersion": "v1", "kind": "Node",
            "metadata": encode_meta(n.meta, False),
            "spec": spec,
            "status": status}


def decode_node(d: Dict[str, Any]) -> Node:
    s = d.get("spec") or {}
    st = d.get("status") or {}
    conditions: List[NodeCondition] = []
    hb: Optional[float] = None
    for cd in st.get("conditions") or []:
        conditions.append(NodeCondition(
            type=cd.get("type", ""),
            status=cd.get("status", "True"),
            reason=cd.get("reason", ""),
            message=cd.get("message", ""),
            last_transition_time=decode_time(
                cd.get("lastTransitionTime")) or 0.0))
        t = decode_time(cd.get("lastHeartbeatTime"))
        if t is not None and (hb is None or t > hb):
            hb = t
    return Node(
        meta=decode_meta(d.get("metadata") or {}, False),
        spec=NodeSpec(
            unschedulable=bool(s.get("unschedulable", False)),
            taints=[Taint(key=t.get("key", ""), value=t.get("value", ""),
                          effect=t.get("effect", "NoSchedule"))
                    for t in s.get("taints") or []]),
        status=NodeStatus(capacity=decode_resources(st.get("capacity")),
                          allocatable=decode_resources(st.get("allocatable")),
                          conditions=conditions,
                          last_heartbeat_time=hb))


# -- PodGroup -----------------------------------------------------------------

def encode_podgroup(pg: PodGroup) -> Dict[str, Any]:
    spec: Dict[str, Any] = {"minMember": pg.spec.min_member}
    if pg.spec.min_resources is not None:
        spec["minResources"] = encode_resources(pg.spec.min_resources)
    if pg.spec.schedule_timeout_seconds is not None:
        spec["scheduleTimeoutSeconds"] = pg.spec.schedule_timeout_seconds
    if pg.spec.tpu_slice_shape:
        spec["tpuSliceShape"] = pg.spec.tpu_slice_shape
    if pg.spec.tpu_accelerator:
        spec["tpuAccelerator"] = pg.spec.tpu_accelerator
    if pg.spec.multislice_set:
        spec["multisliceSet"] = pg.spec.multislice_set
        spec["multisliceIndex"] = pg.spec.multislice_index
    if pg.spec.multislice_set_size:
        spec["multisliceSetSize"] = pg.spec.multislice_set_size
    status: Dict[str, Any] = {
        "phase": pg.status.phase, "occupiedBy": pg.status.occupied_by,
        "scheduled": pg.status.scheduled, "running": pg.status.running,
        "succeeded": pg.status.succeeded, "failed": pg.status.failed}
    sst = encode_time(pg.status.schedule_start_time)
    if sst:
        status["scheduleStartTime"] = sst
    return {"apiVersion": "scheduling.tpu.dev/v1alpha1", "kind": "PodGroup",
            "metadata": encode_meta(pg.meta, True),
            "spec": spec, "status": status}


def decode_podgroup(d: Dict[str, Any]) -> PodGroup:
    s = d.get("spec") or {}
    st = d.get("status") or {}
    min_res = s.get("minResources")
    return PodGroup(
        meta=decode_meta(d.get("metadata") or {}, True),
        spec=PodGroupSpec(
            min_member=int(s.get("minMember") or 0),
            min_resources=(decode_resources(min_res)
                           if min_res is not None else None),
            schedule_timeout_seconds=s.get("scheduleTimeoutSeconds"),
            tpu_slice_shape=s.get("tpuSliceShape", ""),
            tpu_accelerator=s.get("tpuAccelerator", ""),
            multislice_set=s.get("multisliceSet", ""),
            multislice_index=int(s.get("multisliceIndex") or 0),
            multislice_set_size=int(s.get("multisliceSetSize") or 0)),
        status=PodGroupStatus(
            phase=st.get("phase", ""),
            occupied_by=st.get("occupiedBy", ""),
            scheduled=int(st.get("scheduled") or 0),
            running=int(st.get("running") or 0),
            succeeded=int(st.get("succeeded") or 0),
            failed=int(st.get("failed") or 0),
            schedule_start_time=decode_time(st.get("scheduleStartTime"))))


# -- ElasticQuota -------------------------------------------------------------

def encode_elasticquota(eq: ElasticQuota) -> Dict[str, Any]:
    return {"apiVersion": "scheduling.tpu.dev/v1alpha1",
            "kind": "ElasticQuota",
            "metadata": encode_meta(eq.meta, True),
            "spec": {"min": encode_resources(eq.spec.min) or {},
                     "max": encode_resources(eq.spec.max) or {}},
            "status": {"used": encode_resources(eq.status.used) or {}}}


def decode_elasticquota(d: Dict[str, Any]) -> ElasticQuota:
    s = d.get("spec") or {}
    st = d.get("status") or {}
    return ElasticQuota(
        meta=decode_meta(d.get("metadata") or {}, True),
        spec=ElasticQuotaSpec(min=decode_resources(s.get("min")),
                              max=decode_resources(s.get("max"))),
        status=ElasticQuotaStatus(used=decode_resources(st.get("used"))))


# -- PriorityClass ------------------------------------------------------------

def encode_priorityclass(pc: PriorityClass) -> Dict[str, Any]:
    return {"apiVersion": "scheduling.k8s.io/v1", "kind": "PriorityClass",
            "metadata": encode_meta(pc.meta, False),
            "value": pc.value, "preemptionPolicy": pc.preemption_policy}


def decode_priorityclass(d: Dict[str, Any]) -> PriorityClass:
    return PriorityClass(
        meta=decode_meta(d.get("metadata") or {}, False),
        value=int(d.get("value") or 0),
        preemption_policy=d.get("preemptionPolicy", "PreemptLowerPriority"))


# -- PodDisruptionBudget ------------------------------------------------------

def encode_pdb(pdb: PodDisruptionBudget) -> Dict[str, Any]:
    return {"apiVersion": "policy/v1", "kind": "PodDisruptionBudget",
            "metadata": encode_meta(pdb.meta, True),
            "spec": {"selector": {"matchLabels": dict(pdb.selector)}},
            "status": {"disruptionsAllowed": pdb.disruptions_allowed}}


def decode_pdb(d: Dict[str, Any]) -> PodDisruptionBudget:
    sel = ((d.get("spec") or {}).get("selector") or {})
    return PodDisruptionBudget(
        meta=decode_meta(d.get("metadata") or {}, True),
        selector=dict(sel.get("matchLabels") or {}),
        disruptions_allowed=int(
            (d.get("status") or {}).get("disruptionsAllowed") or 0))


# -- TpuTopology --------------------------------------------------------------

def encode_tputopology(t: TpuTopology) -> Dict[str, Any]:
    return {"apiVersion": "topology.tpu.dev/v1alpha1", "kind": "TpuTopology",
            "metadata": encode_meta(t.meta, False),
            "spec": {"pool": t.spec.pool,
                     "accelerator": t.spec.accelerator,
                     "dims": list(t.spec.dims),
                     "wrap": list(t.spec.wrap),
                     "hosts": {h: list(c) for h, c in t.spec.hosts.items()},
                     "chipsPerHost": t.spec.chips_per_host,
                     "dcnDomain": t.spec.dcn_domain}}


def decode_tputopology(d: Dict[str, Any]) -> TpuTopology:
    s = d.get("spec") or {}
    return TpuTopology(
        meta=decode_meta(d.get("metadata") or {}, False),
        spec=TpuTopologySpec(
            pool=s.get("pool", ""),
            accelerator=s.get("accelerator", "tpu-v5p"),
            dims=tuple(int(x) for x in s.get("dims") or ()),
            wrap=tuple(bool(x) for x in s.get("wrap") or ()),
            hosts={h: tuple(int(x) for x in c)
                   for h, c in (s.get("hosts") or {}).items()},
            chips_per_host=int(s.get("chipsPerHost") or 4),
            dcn_domain=s.get("dcnDomain", "")))


# -- Binding / Event payloads (request bodies, not stored kinds) --------------

def encode_binding(b: Binding) -> Dict[str, Any]:
    """The pods/binding POST body. Annotations ride the Binding's metadata —
    the apiserver merges them into the pod on bind, the contract the
    reference's FlexGPU Bind relies on
    (/root/reference/pkg/flexgpu/flex_gpu.go:230-242)."""
    ns, name = b.pod_key.split("/", 1)
    meta: Dict[str, Any] = {"name": name, "namespace": ns}
    if b.annotations:
        meta["annotations"] = dict(b.annotations)
    return {"apiVersion": "v1", "kind": "Binding", "metadata": meta,
            "target": {"apiVersion": "v1", "kind": "Node",
                       "name": b.node_name}}


# -- kind registry ------------------------------------------------------------

class KindInfo:
    def __init__(self, kind: str, api_version: str, k8s_kind: str,
                 plural: str, namespaced: bool,
                 encode: Callable[[Any], Dict[str, Any]],
                 decode: Callable[[Dict[str, Any]], Any],
                 status_sub: bool = False):
        self.kind = kind
        self.api_version = api_version
        self.k8s_kind = k8s_kind
        self.plural = plural
        self.namespaced = namespaced
        self.encode = encode
        self.decode = decode
        # the kind serves a /status subresource: a real apiserver IGNORES
        # status fields written to the main resource, so the client must
        # split writes (manifests/crds declare `subresources: status` for
        # the CRDs; pods/nodes/PDBs have it built in)
        self.status_sub = status_sub

    def collection_path(self, namespace: Optional[str] = None) -> str:
        base = ("/api/v1" if self.api_version == "v1"
                else f"/apis/{self.api_version}")
        if self.namespaced and namespace is not None:
            return f"{base}/namespaces/{namespace}/{self.plural}"
        return f"{base}/{self.plural}"

    def object_path(self, key: str) -> str:
        ns, _, name = key.partition("/")
        if self.namespaced:
            return f"{self.collection_path(ns or 'default')}/{name}"
        return f"{self.collection_path()}/{name or ns}"


KINDS: Dict[str, KindInfo] = {k.kind: k for k in (
    KindInfo(srv.PODS, "v1", "Pod", "pods", True, encode_pod, decode_pod,
             status_sub=True),
    KindInfo(srv.NODES, "v1", "Node", "nodes", False,
             encode_node, decode_node, status_sub=True),
    KindInfo(srv.POD_GROUPS, "scheduling.tpu.dev/v1alpha1", "PodGroup",
             "podgroups", True, encode_podgroup, decode_podgroup,
             status_sub=True),
    KindInfo(srv.ELASTIC_QUOTAS, "scheduling.tpu.dev/v1alpha1",
             "ElasticQuota", "elasticquotas", True,
             encode_elasticquota, decode_elasticquota, status_sub=True),
    KindInfo(srv.PRIORITY_CLASSES, "scheduling.k8s.io/v1", "PriorityClass",
             "priorityclasses", False,
             encode_priorityclass, decode_priorityclass),
    KindInfo(srv.PDBS, "policy/v1", "PodDisruptionBudget",
             "poddisruptionbudgets", True, encode_pdb, decode_pdb,
             status_sub=True),
    KindInfo(srv.TPU_TOPOLOGIES, "topology.tpu.dev/v1alpha1", "TpuTopology",
             "tputopologies", False,
             encode_tputopology, decode_tputopology),
)}


# -- merge patch --------------------------------------------------------------

def merge_patch(old: Dict[str, Any], new: Dict[str, Any]) -> Dict[str, Any]:
    """RFC 7386 merge patch turning ``old`` into ``new`` (both JSON
    objects). Empty result = nothing changed. Lists are replaced wholesale
    — merge-patch semantics, which matches how this framework writes
    list-valued fields (conditions, tolerations: full-value updates)."""
    patch: Dict[str, Any] = {}
    for k, v in new.items():
        if k not in old:
            patch[k] = v
        elif isinstance(old[k], dict) and isinstance(v, dict):
            sub = merge_patch(old[k], v)
            if sub:
                patch[k] = sub
        elif old[k] != v:
            patch[k] = v
    for k in old:
        if k not in new:
            patch[k] = None
    return patch


def apply_merge_patch(doc: Any, patch: Any) -> Any:
    """RFC 7386 apply (the server half; the fake apiserver uses it)."""
    if not isinstance(patch, dict):
        return patch
    if not isinstance(doc, dict):
        doc = {}
    out = dict(doc)
    for k, v in patch.items():
        if v is None:
            out.pop(k, None)
        else:
            out[k] = apply_merge_patch(out.get(k), v)
    return out
