"""Informer/lister layer over APIServer watches.

Analog of client-go SharedInformerFactory + the generated factory in
/root/reference/pkg/generated/informers. An Informer keeps its own local cache
(synced by watch events) and fans out to registered event handlers in watch
order; Listers read from that cache without touching the server.
"""
from __future__ import annotations

from collections import OrderedDict, deque
from typing import Any, Callable, Deque, Dict, List, Optional

from . import server as srv
from ..util.locking import GuardedLock, guarded_by


@guarded_by("_lock", "_cache", "_index_fns", "_indexes",
            "_on_add", "_on_update", "_on_delete", "_tombstones",
            "_pending")
class Informer:
    def __init__(self, api: srv.APIServer, kind: str):
        self._api = api
        self.kind = kind
        self._lock = GuardedLock("apiserver.Informer")
        self._cache: Dict[str, Any] = {}
        # client-go Indexers: index name → key_fn, and the materialized
        # index name → index value → {object key → object}
        self._index_fns: Dict[str, Callable[[Any], Optional[str]]] = {}
        self._indexes: Dict[str, Dict[str, Dict[str, Any]]] = {}
        self._on_add: List[Callable[[Any], None]] = []
        self._on_update: List[Callable[[Any, Any], None]] = []
        self._on_delete: List[Callable[[Any], None]] = []
        # Ordered delivery (ISSUE 13 root-cause fix): the APIServer fans
        # watch events out OUTSIDE its store lock, on the MUTATING
        # caller's thread — so two racing writers (a bind commit, a
        # delete) can deliver their events in the OPPOSITE of store
        # order.  Unordered, a late bind-confirm MODIFIED processed after
        # the pod's DELETED resurrects the object in this cache AND in
        # every downstream handler's state (the scheduler cache counted
        # such phantoms as permanent occupancy — wedged gangs under
        # storm churn).  Two defenses, both keyed on the store's globally
        # monotonic resourceVersion:
        #  - staleness rejection: an ADDED/MODIFIED at or below the rv we
        #    already saw for the key (live or tombstoned) is dropped; a
        #    DELETED carrying an instance older than the cached one is
        #    dropped (a recreate already superseded it);
        #  - serialized dispatch: cache mutation + event enqueue happen
        #    under the informer lock, handlers drain FIFO under a
        #    dedicated dispatch lock — per-informer handler order equals
        #    cache-update order, without ever running handlers under the
        #    informer lock (handlers may read listers).
        self._tombstones: "OrderedDict[str, int]" = OrderedDict()
        self._pending: Deque = deque()
        self._dispatch_lock = GuardedLock("apiserver.InformerDispatch")
        api.add_watch(kind, self._handle, replay=True)

    def _index_insert_locked(self, obj) -> None:
        for name, fn in self._index_fns.items():
            val = fn(obj)
            if val is not None:
                self._indexes[name].setdefault(val, {})[obj.meta.key] = obj

    def _index_remove_locked(self, obj) -> None:
        for name, fn in self._index_fns.items():
            val = fn(obj)
            if val is not None:
                bucket = self._indexes[name].get(val)
                if bucket is not None:
                    bucket.pop(obj.meta.key, None)
                    if not bucket:
                        del self._indexes[name][val]

    _TOMBSTONE_CAP = 4096

    def _handle(self, ev: srv.WatchEvent) -> None:
        key = ev.object.meta.key
        rv = ev.object.meta.resource_version
        with self._lock:
            if ev.type == srv.DELETED:
                # A DELETED for a key this informer never saw (replay race:
                # the object was created and deleted around add_watch's
                # replay snapshot, or a resync dropped it first) is
                # TOLERATED: indexes are keyed off the cached object, so an
                # absent entry means nothing to unindex — the event still
                # fans out to handlers (client-go's DeletedFinalStateUnknown
                # analog; handlers must be delete-idempotent).
                old = self._cache.get(key)
                if old is not None and old.meta.resource_version > rv:
                    # stale DELETED delivered late: the cached instance is
                    # NEWER (a recreate's ADDED overtook this delete in the
                    # unordered fan-out) — the delete belongs to a dead
                    # predecessor, not the live object
                    return
                if old is not None:
                    self._cache.pop(key)
                    self._index_remove_locked(old)
                self._tombstone_locked(key, rv)
            else:
                old = self._cache.get(key)
                last = old.meta.resource_version if old is not None \
                    else self._tombstones.get(key)
                if last is not None and rv <= last:
                    # stale reorder: we already saw this key at (or past)
                    # this rv — a late bind-confirm MODIFIED overtaken by
                    # the object's DELETED, or a replay ADDED overtaken by
                    # a live update.  Delivering it would resurrect dead
                    # state in every downstream cache.
                    return
                if old is not None:
                    self._index_remove_locked(old)
                self._cache[key] = ev.object
                self._index_insert_locked(ev.object)
            self._pending.append(ev)
        self._drain_pending()

    def _tombstone_locked(self, key: str, rv: int) -> None:
        """Remember the deleted instance's rv so late stale events for the
        key are rejected.  Re-deleted keys move to the fresh end of the
        bounded record: cap eviction must shed genuinely old tombstones,
        not the hottest (most recently re-deleted) keys."""
        tomb = self._tombstones
        tomb[key] = max(rv, tomb.pop(key, 0))
        while len(tomb) > self._TOMBSTONE_CAP:
            tomb.popitem(last=False)

    def _drain_pending(self) -> None:
        """FIFO handler dispatch under the dedicated dispatch lock: events
        enter ``_pending`` in cache-update order (informer lock), and
        whichever thread holds the dispatch lock drains them in that order
        — so handlers observe per-informer event order even though the
        APIServer fans out on each mutating caller's thread.  Handlers
        never run under the informer lock (they may read listers)."""
        with self._dispatch_lock:
            while True:
                with self._lock:
                    if not self._pending:
                        return
                    ev = self._pending.popleft()
                    if ev.type == srv.ADDED:
                        handlers = [(h, (ev.object,))
                                    for h in self._on_add]
                    elif ev.type == srv.MODIFIED:
                        handlers = [(h, (ev.old_object, ev.object))
                                    for h in self._on_update]
                    else:
                        handlers = [(h, (ev.object,))
                                    for h in self._on_delete]
                # per-handler isolation (client-go's processor gives each
                # listener its own delivery): one handler raising must not
                # starve the other handlers of the event, nor propagate
                # into the watch source
                for h, args in handlers:
                    self._dispatch(h, *args)

    def _dispatch(self, handler, *args) -> None:
        try:
            handler(*args)
        except Exception as e:
            from ..util import klog
            klog.error_s(e, "informer event handler panicked",
                         kind=self.kind)

    def add_event_handler(self, on_add=None, on_update=None, on_delete=None,
                          replay: bool = True):
        """client-go AddEventHandler: with replay, on_add fires for every
        object already in the cache. Snapshot+append happen under the informer
        lock so an object created in between is either in the replay set or
        delivered live (at-least-once; handlers must tolerate duplicate adds,
        as client-go's must). Returns a registration token for
        remove_event_handler (client-go's ResourceEventHandlerRegistration)."""
        with self._lock:
            existing = (list(self._cache.values())
                        if (replay and on_add) else [])
            if on_add:
                self._on_add.append(on_add)
            if on_update:
                self._on_update.append(on_update)
            if on_delete:
                self._on_delete.append(on_delete)
        for o in existing:
            self._dispatch(on_add, o)   # same isolation as live delivery
        return (on_add, on_update, on_delete)

    def remove_event_handler(self, registration) -> None:
        """Detach a registration returned by add_event_handler so a stopped
        component (e.g. the Trimaran assign handler) no longer receives
        events."""
        on_add, on_update, on_delete = registration
        with self._lock:
            if on_add in self._on_add:
                self._on_add.remove(on_add)
            if on_update in self._on_update:
                self._on_update.remove(on_update)
            if on_delete in self._on_delete:
                self._on_delete.remove(on_delete)

    # -- lister ---------------------------------------------------------------
    # Listers return SHARED references, exactly like client-go listers share
    # pointers out of the informer cache: callers must treat results as
    # read-only (deepcopy before mutating). This keeps the hot scheduling
    # paths (queue-sort comparisons, sibling listing) allocation-free.

    def add_index(self, name: str,
                  key_fn: Callable[[Any], Optional[str]]) -> None:
        """Register a named index (client-go cache.Indexers analog). key_fn
        maps an object to its index value, or None to leave it unindexed.
        Existing cache contents are indexed immediately; idempotent for the
        same name (shared informers register once per consumer)."""
        with self._lock:
            existing = self._index_fns.get(name)
            if existing is key_fn:
                return
            if existing is not None:
                raise ValueError(
                    f"index {name!r} already registered with a different "
                    f"key function")
            self._index_fns[name] = key_fn
            self._indexes[name] = {}
            for obj in self._cache.values():
                val = key_fn(obj)
                if val is not None:
                    self._indexes[name].setdefault(val, {})[obj.meta.key] = obj

    def by_index(self, name: str, value: str) -> List[Any]:
        """All cached objects whose index `name` maps to `value` — O(bucket)
        instead of an O(cache) items() scan. Shared references, read-only."""
        with self._lock:
            bucket = self._indexes[name].get(value)
            return list(bucket.values()) if bucket else []

    def index_values(self, name: str) -> List[str]:
        """The distinct values of index `name` currently holding objects —
        O(buckets). Lets a sweep visit only populated groups (e.g. the
        node-lifecycle orphan GC walks bound-to node names, not all pods)."""
        with self._lock:
            return list(self._indexes[name])

    def get(self, key: str):
        with self._lock:
            return self._cache.get(key)

    def items(self, namespace: Optional[str] = None,
              selector: Optional[Dict[str, str]] = None) -> List[Any]:
        with self._lock:
            objs = [o for o in self._cache.values()
                    if namespace is None or o.meta.namespace == namespace]
        if selector:
            objs = [o for o in objs
                    if all(o.meta.labels.get(k) == v for k, v in selector.items())]
        return objs

    def has_synced(self) -> bool:
        return True  # in-memory watches are synchronous

    def resync(self) -> None:
        """Relist-and-diff (client-go's reconnect/resync after missed watch
        events): pull the authoritative list from the API server, reconcile
        the local cache + indexes, and synthesize the handler deliveries a
        live watch would have made — Added for objects the cache never saw,
        Modified for resourceVersion drift, Deleted for objects the server
        no longer has. Handlers observe at-least-once semantics exactly as
        with live events. The in-memory watch fan-out cannot actually drop
        events, but HA fail-over and kube-backed deployments re-attach
        informers to servers whose history they missed — this is their
        catch-up path.

        The list AND the reconcile run under the informer lock: a live
        watch delivery racing the relist would otherwise be overwritten by
        the (already stale) listed copy, or a just-created object evicted
        with a spurious Deleted. A concurrent _handle blocks until the
        reconcile commits, then applies on top — its object is never older
        than the list (the server dispatches synchronously after commit).
        Lock order informer→store matches _handle's (the store lock is
        released before watch dispatch). Handler fan-out happens after the
        lock drops, exactly as _handle does."""
        added, updated, deleted = [], [], []
        with self._lock:
            live = {o.meta.key: o for o in self._api.list(self.kind)}
            for key, obj in live.items():
                old = self._cache.get(key)
                if old is None:
                    added.append(obj)
                elif old.meta.resource_version != obj.meta.resource_version:
                    updated.append((old, obj))
            for key, old in list(self._cache.items()):
                if key not in live:
                    deleted.append(old)
            for old, obj in updated:
                self._index_remove_locked(old)
            for old in deleted:
                self._index_remove_locked(old)
                del self._cache[old.meta.key]
                # same staleness protection as a live DELETED: without the
                # tombstone, a late reordered MODIFIED for the vanished key
                # would resurrect it — and the resync path is exactly where
                # missed/reordered history is most likely
                self._tombstone_locked(old.meta.key,
                                       old.meta.resource_version)
            for obj in added + [o for _, o in updated]:
                self._cache[obj.meta.key] = obj
                self._index_insert_locked(obj)
            # synthesized deliveries enter the SAME ordered pending queue
            # as live events (appended under the informer lock), so a
            # concurrent live delivery cannot interleave handlers out of
            # cache-update order
            for obj in added:
                self._pending.append(srv.WatchEvent(srv.ADDED, self.kind,
                                                    obj))
            for old, obj in updated:
                self._pending.append(srv.WatchEvent(srv.MODIFIED, self.kind,
                                                    obj, old))
            for old in deleted:
                self._pending.append(srv.WatchEvent(srv.DELETED, self.kind,
                                                    old))
        self._drain_pending()

    def close(self) -> None:
        """Detach from the API server's watch fan-out and drop handlers —
        after this the informer's cache is frozen and it receives nothing."""
        self._api.remove_watch(self.kind, self._handle)
        with self._lock:
            self._on_add.clear()
            self._on_update.clear()
            self._on_delete.clear()


@guarded_by("_lock", "_informers", "_closed")
class InformerFactory:
    """SharedInformerFactory analog: one shared Informer per kind."""

    def __init__(self, api: srv.APIServer):
        self._api = api
        self._lock = GuardedLock("apiserver.InformerFactory",
                                 reentrant=False)
        self._informers: Dict[str, Informer] = {}
        self._closed = False

    def informer(self, kind: str) -> Informer:
        with self._lock:
            if self._closed:
                # a lazily-created informer on a closed factory would
                # re-register a watch handler nobody will ever remove
                raise RuntimeError(
                    "InformerFactory is closed (owner stopped)")
            if kind not in self._informers:
                self._informers[kind] = Informer(self._api, kind)
            return self._informers[kind]

    # typed sugar
    def pods(self) -> Informer: return self.informer(srv.PODS)
    def nodes(self) -> Informer: return self.informer(srv.NODES)
    def podgroups(self) -> Informer: return self.informer(srv.POD_GROUPS)
    def elasticquotas(self) -> Informer: return self.informer(srv.ELASTIC_QUOTAS)
    def priorityclasses(self) -> Informer: return self.informer(srv.PRIORITY_CLASSES)
    def pdbs(self) -> Informer: return self.informer(srv.PDBS)
    def tputopologies(self) -> Informer: return self.informer(srv.TPU_TOPOLOGIES)

    def wait_for_cache_sync(self) -> None:
        return  # synchronous watches: always synced

    def close(self) -> None:
        """Close every shared informer and refuse new ones (factory
        Shutdown analog). Idempotent."""
        with self._lock:
            self._closed = True
            informers, self._informers = list(self._informers.values()), {}
        for inf in informers:
            inf.close()
