"""In-memory API server + client/informer layer.

The reference's only process boundary is the Kubernetes API server: plugins
read through informer caches and write via clientset (SURVEY §1, §5 —
"the API server (etcd) *is* the checkpoint"). This package rebuilds that
contract hermetically: a thread-safe object store with watch fan-out,
merge-patch, and the Bind subresource, so the real scheduler + controllers run
in-process against fabricated Nodes exactly like the reference's envtest
integration tier (/root/reference/test/integration/main_test.go:31-46).
"""
from .server import APIServer, WatchEvent
from .client import Clientset, RetryPolicy
from .errors import Conflict, NotFound, Throttled, Unavailable
from .faults import FaultInjector, FaultRule
from .informers import Informer, InformerFactory

__all__ = ["APIServer", "WatchEvent", "Clientset", "RetryPolicy",
           "Conflict", "NotFound", "Throttled", "Unavailable",
           "FaultInjector", "FaultRule", "Informer", "InformerFactory"]
