"""Deterministic, seedable fault injector wrapping an APIServer.

The reference's resilience story is exercised by the real world (flaky
apiservers, conflict storms under HA controllers); the hermetic rebuild
needs the adversary built in. ``FaultInjector`` interposes on the API
surface the clientset calls (the watch fan-out and informer paths pass
through untouched — faults model the REQUEST path, not the store), so the
same injector drives unit tests, the chaos soak (tests/test_chaos_soak.py,
``make chaos-smoke``) and ad-hoc debugging.

Determinism: every probabilistic decision draws from one ``random.Random``
seeded at construction, and rule evaluation order is the registration
order — a failing soak reproduces from its printed seed.

Fault shapes (``FaultRule``):

- ``error="unavailable"``: transient ``errors.Unavailable`` (the retriable
  blip). With ``after=True`` the operation APPLIES first and the error is
  raised afterwards — the lost-response case (e.g. a bind timeout whose
  write landed), which is what makes conflict-healing paths testable.
- ``error="conflict"`` / ``"not_found"``: semantic errors injected without
  touching the store (optimistic-concurrency races, informer-lag races).
- ``latency_s``: a deterministic stall before the verdict (slow apiserver);
  composable with any error or with ``error="none"`` for pure latency.
- ``max_injections`` bounds a rule (an outage of exactly N failures);
  ``probability`` makes it intermittent; ``key_substr`` scopes it to
  matching object keys (fail ONE gang member's bind, not the burst).
"""
from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from . import server as srv
from .errors import Conflict, NotFound, Unavailable

ALL = "*"

_ERRORS = {
    "unavailable": lambda msg: Unavailable(msg),
    "conflict": lambda msg: Conflict(msg),
    "not_found": lambda msg: NotFound(msg),
}


@dataclass
class FaultRule:
    """One injection rule. Matches (verb, kind, key); fires with
    ``probability`` until ``max_injections`` is spent."""
    verbs: tuple = (ALL,)
    kinds: tuple = (ALL,)
    error: str = "unavailable"      # unavailable | conflict | not_found | none
    probability: float = 1.0
    latency_s: float = 0.0
    after: bool = False             # apply the op, then fail (lost response)
    max_injections: Optional[int] = None
    key_substr: str = ""
    name: str = ""
    injected: int = field(default=0, compare=False)

    def matches(self, verb: str, kind: str, key: str) -> bool:
        if self.max_injections is not None and self.injected >= self.max_injections:
            return False
        if ALL not in self.verbs and verb not in self.verbs:
            return False
        if ALL not in self.kinds and kind not in self.kinds:
            return False
        if self.key_substr and self.key_substr not in (key or ""):
            return False
        return True


class FaultInjector:
    """APIServer-shaped wrapper injecting faults on the request path.

    Drop-in anywhere an ``APIServer`` is accepted (Scheduler, Clientset,
    TestCluster(api=...)): the CRUD/bind/record_event surface is
    intercepted; everything else (watches, peek, leases, persistence,
    restore) delegates to the wrapped server so informers and HA machinery
    see the store exactly as-is.
    """

    def __init__(self, api: srv.APIServer, seed: int = 0):
        self._api = api
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self._rules: List[FaultRule] = []
        self._enabled = True
        self._injections_total = 0

    # -- rule management ------------------------------------------------------

    def add_rule(self, rule: FaultRule) -> FaultRule:
        with self._lock:
            self._rules.append(rule)
        return rule

    def set_rules(self, rules: List[FaultRule]) -> None:
        with self._lock:
            self._rules = list(rules)

    def clear(self) -> None:
        with self._lock:
            self._rules = []

    def set_enabled(self, v: bool) -> None:
        with self._lock:
            self._enabled = bool(v)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "injections_total": self._injections_total,
                "rules": [{"name": r.name or f"rule{i}", "error": r.error,
                           "verbs": list(r.verbs), "kinds": list(r.kinds),
                           "injected": r.injected}
                          for i, r in enumerate(self._rules)],
            }

    # -- the interposition core ----------------------------------------------

    def _decide(self, verb: str, kind: str, key: str) -> Optional[FaultRule]:
        """Pick the first matching rule that fires (one RNG draw per
        matching probabilistic rule, in registration order)."""
        with self._lock:
            if not self._enabled:
                return None
            for r in self._rules:
                if not r.matches(verb, kind, key):
                    continue
                if r.probability < 1.0 and self._rng.random() >= r.probability:
                    continue
                r.injected += 1
                self._injections_total += 1
                return r
        return None

    def _call(self, verb: str, kind: str, key: str, fn):
        rule = self._decide(verb, kind, key)
        if rule is None:
            return fn()
        if rule.latency_s > 0:
            time.sleep(rule.latency_s)
        make = _ERRORS.get(rule.error)
        if make is None:            # pure latency / "none"
            return fn()
        msg = (f"injected {rule.error} [{rule.name or 'fault'}] "
               f"on {verb} {kind} {key}")
        if rule.after:
            fn()                    # the write LANDED; the response is lost
        raise make(msg)

    # -- intercepted surface --------------------------------------------------

    def create(self, kind: str, obj):
        return self._call("create", kind, obj.meta.key,
                          lambda: self._api.create(kind, obj))

    def get(self, kind: str, key: str):
        return self._call("get", kind, key, lambda: self._api.get(kind, key))

    def try_get(self, kind: str, key: str):
        # a not_found injection here models the informer-lag race (object
        # exists, the read misses it): surface None exactly like a miss
        try:
            return self._call("try_get", kind, key,
                              lambda: self._api.try_get(kind, key))
        except NotFound:
            return None

    def list(self, kind: str, namespace=None, selector=None):
        return self._call("list", kind, "",
                          lambda: self._api.list(kind, namespace, selector))

    def update(self, kind: str, obj):
        return self._call("update", kind, obj.meta.key,
                          lambda: self._api.update(kind, obj))

    def patch(self, kind: str, key: str, mutate):
        return self._call("patch", kind, key,
                          lambda: self._api.patch(kind, key, mutate))

    def delete(self, kind: str, key: str, uid=None) -> None:
        return self._call("delete", kind, key,
                          lambda: self._api.delete(kind, key, uid=uid))

    def bind(self, binding) -> None:
        return self._call("bind", srv.PODS, binding.pod_key,
                          lambda: self._api.bind(binding))

    def record_event(self, object_key: str, kind: str, etype: str,
                     reason: str, message: str) -> None:
        return self._call("record_event", kind, object_key,
                          lambda: self._api.record_event(
                              object_key, kind, etype, reason, message))

    # -- transparent delegation ----------------------------------------------

    def __getattr__(self, name: str):
        # watches, peek, events, leases, persistence, restore, cursors —
        # the store side of the contract is never faulted
        return getattr(self._api, name)
