"""Thread-safe in-memory object store with watch fan-out.

Semantics mirrored from the k8s API server as the reference uses it:
- objects are stored by kind + namespace/name key; every write bumps
  ``resource_version``;
- reads return deep copies (informer-cache isolation — callers may never
  mutate stored state in place, the discipline client-go enforces by
  convention);
- writers race via optimistic concurrency is *not* modeled; instead ``patch``
  takes a mutator applied atomically under the store lock, which is the
  behavioral equivalent of the reference's strategic-merge-patch loop
  (/root/reference/pkg/util/podgroup.go:33-50 + controller patch sites);
- the Bind subresource sets ``pod.spec.node_name`` and merges the Binding's
  annotations into the pod (contract of the reference's custom FlexGPU Bind,
  flex_gpu.go:230-242).
"""
from __future__ import annotations

import collections
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from ..api.core import Binding, Event, GangMemberStatus, Pod, PodCondition
from ..util import klog
from ..util.metrics import (fanout_batches_total, fanout_events_total,
                            fanout_flush_seconds)

# Canonical kind names.
PODS = "pods"
NODES = "nodes"
POD_GROUPS = "podgroups"
ELASTIC_QUOTAS = "elasticquotas"
PRIORITY_CLASSES = "priorityclasses"
PDBS = "poddisruptionbudgets"
TPU_TOPOLOGIES = "tputopologies"
LEASES = "leases"

ALL_KINDS = (PODS, NODES, POD_GROUPS, ELASTIC_QUOTAS, PRIORITY_CLASSES, PDBS,
             TPU_TOPOLOGIES, LEASES)

ADDED = "Added"
MODIFIED = "Modified"
DELETED = "Deleted"


@dataclass
class WatchEvent:
    type: str            # Added | Modified | Deleted
    kind: str
    object: Any          # deep copy of the object after (or before, if Deleted)
    old_object: Any = None  # deep copy before the change (Modified only)


class NotFound(KeyError):
    pass


class Conflict(RuntimeError):
    pass


@dataclass
class _Lease:
    """Coordination lease for leader election (reference analog: Endpoints
    lock "sched-plugins-controller" in kube-system,
    /root/reference/cmd/controller/app/server.go:84-123)."""
    meta: Any = None
    holder: str = ""
    renew_time: float = 0.0
    lease_duration: float = 15.0

    def deepcopy(self):
        return _Lease(meta=self.meta.deepcopy() if self.meta else None,
                      holder=self.holder, renew_time=self.renew_time,
                      lease_duration=self.lease_duration)


class _FanoutBatcher:
    """Coalesced watch fan-out (ISSUE 16 tentpole b).

    In synchronous mode (the default, flush window 0) every mutator runs
    the whole watch fan-out — every informer's cache update plus every
    downstream handler — on its own thread before its API call returns.
    Under storm load that makes the bind thread's critical path mostly
    OTHER components' bookkeeping.  With a flush window armed, mutators
    instead append their events to this queue IN COMMIT ORDER (under the
    store lock — a deque append, nothing else) and return; one named
    daemon thread wakes per window and delivers the accumulated batch.

    Ordering contract — strictly stronger than synchronous mode: events
    are enqueued under the store lock at commit time, so the flusher
    delivers them in TRUE store-commit order.  Synchronous fan-out runs
    on each mutating caller's thread and two racing writers can deliver
    in the opposite of commit order (the PR 12 reorder class, defended by
    the informers' per-key RV staleness rejection + tombstones).  Those
    informer-side defenses stay on and are still required for replays and
    mixed-mode operation; the batched path just stops generating the
    reorder in the first place.  Per-informer FIFO handler serialization
    (Informer._drain_pending) is untouched — the flusher is simply ONE
    more calling thread to it, and the dedicated dispatch lock already
    serializes handler execution.

    Deferred Events (``record_event_deferred``) ride the same queue:
    their message %-formatting and Event construction happen on the
    flusher, so a bind commit pays one tuple append for its audit trail.

    Shutdown: the thread is daemonic and dies with the process;
    ``flush()`` drains synchronously for tests and drain barriers.
    """

    def __init__(self, window_s: float, deliver_watch: Callable[..., None],
                 deliver_event: Callable[[Event], None]):
        self._window_s = window_s
        self._deliver_watch = deliver_watch
        self._deliver_event = deliver_event
        self._cv = threading.Condition(threading.Lock())
        self._queue: collections.deque = collections.deque()
        self._stopped = False
        self._batches = 0
        self._delivered = 0
        self._last_flush_s = 0.0
        self._health_sink: Optional[Callable[[Dict[str, Any]], None]] = None
        self._thread = threading.Thread(
            target=self._run, name="apiserver-fanout-flush", daemon=True)
        self._thread.start()

    def submit(self, item) -> None:
        """Append one WatchEvent/Event to the batch. Called under the
        APIServer store lock — commit order IS queue order."""
        with self._cv:
            self._queue.append(item)
            if len(self._queue) == 1:
                self._cv.notify()

    def flush(self) -> None:
        """Deliver everything queued so far on the CALLING thread (tests,
        drain barriers). Safe to race the flusher: the splice is atomic
        and delivery order is splice order."""
        self._flush_once()

    def stop(self) -> None:
        with self._cv:
            self._stopped = True
            self._cv.notify()
        self.flush()

    def set_health_sink(self, sink: Optional[Callable[[Dict[str, Any]], None]]
                        ) -> None:
        self._health_sink = sink
        if sink is not None:
            sink(self.health())

    def health(self) -> Dict[str, Any]:
        with self._cv:
            return {"mode": "batched",
                    "flush_window_ms": round(self._window_s * 1e3, 3),
                    "queue_depth": len(self._queue),
                    "batches": self._batches,
                    "events_delivered": self._delivered,
                    "last_flush_s": round(self._last_flush_s, 6)}

    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._queue and not self._stopped:
                    self._cv.wait()
                if self._stopped and not self._queue:
                    return
            # coalescing window: let racing mutators pile on before the
            # flush (duration-only sleep — no wall-clock deadline)
            if self._window_s > 0:
                time.sleep(self._window_s)
            self._flush_once()

    def _flush_once(self) -> None:
        with self._cv:
            if not self._queue:
                return
            batch = list(self._queue)
            self._queue.clear()
        t0 = time.monotonic()
        for item in batch:
            try:
                if isinstance(item, WatchEvent):
                    self._deliver_watch(item)
                else:
                    self._deliver_event(item() if callable(item) else item)
            except Exception as e:  # a handler/codec panic must not stall
                klog.error_s(e, "fanout flush delivery panicked")
        took = time.monotonic() - t0
        fanout_batches_total.inc()
        fanout_events_total.inc(len(batch))
        fanout_flush_seconds.observe(took)
        with self._cv:
            self._batches += 1
            self._delivered += len(batch)
            self._last_flush_s = took
        sink = self._health_sink
        if sink is not None:
            try:
                sink(self.health())
            # tpulint: disable=exception-taxonomy — advisory telemetry
            # mirror; a failing sink must not stall the fan-out flusher
            except Exception:  # noqa: BLE001
                pass


class APIServer:
    """The hermetic control plane. All access is via the public methods; the
    lock is never held while user callbacks run."""

    def __init__(self, clock=time.time, fanout_flush_window_s=None):
        self._clock = clock
        self._lock = threading.RLock()
        self._rv = 0
        # Coalesced watch fan-out (ISSUE 16 tentpole b). Window 0 (the
        # default) keeps the historical synchronous dispatch: every
        # existing test, replay, and race-smoke run is byte-identical.
        # A positive window arms the batcher; TPUSCHED_FANOUT_FLUSH_MS
        # is the ops knob when the constructor isn't reachable (bench
        # arms, canary rollout).
        if fanout_flush_window_s is None:
            try:
                fanout_flush_window_s = float(
                    os.environ.get("TPUSCHED_FANOUT_FLUSH_MS", "0")) / 1e3
            except ValueError:
                fanout_flush_window_s = 0.0
        self._fanout: Optional[_FanoutBatcher] = None
        if fanout_flush_window_s > 0:
            self._fanout = _FanoutBatcher(
                fanout_flush_window_s, self._dispatch, self._append_event)
        self._stores: Dict[str, Dict[str, Any]] = {k: {} for k in ALL_KINDS}
        self._handlers: Dict[str, List[Callable[[WatchEvent], None]]] = {k: [] for k in ALL_KINDS}
        # k8s Events (recorder sink). Bounded ring: real Events are TTL'd in
        # etcd (1h default); an always-on control plane must not grow
        # per-retry FailedScheduling records without bound.
        self._events: "collections.deque[Event]" = collections.deque(
            maxlen=10_000)
        self._stopped = False
        # Optional persistence sink (apiserver.persistence.Journal): called
        # under the store lock, before the watch event fires — the etcd
        # happens-before. Signature: sink(op: "put"|"delete", kind, stored).
        self._persist: Optional[Callable[[str, str, Any], None]] = None
        # In-band gang runtime status sinks (goodput aggregator, fleet
        # trace capture).  Reports are ADVISORY: sinks run outside the
        # store lock, must be bounded/shedding, and a panicking sink is
        # swallowed — runtime telemetry never breaks the control plane.
        self._status_sinks: List[Callable[[List[GangMemberStatus]], Any]] = []

    # -- plumbing -------------------------------------------------------------

    def _bump(self, obj) -> None:
        self._rv += 1
        obj.meta.resource_version = self._rv

    def set_persistence_sink(self, sink: Optional[Callable[[str, str, Any], None]]) -> None:
        with self._lock:
            self._persist = sink

    def restore(self, kind: str, objects) -> None:
        """Load recovered objects without dispatching watch events (informers
        replay on add_watch). Only valid before watchers register."""
        with self._lock:
            for o in objects:
                self._stores[kind][o.meta.key] = o
                if o.meta.resource_version > self._rv:
                    self._rv = o.meta.resource_version

    def restore_resource_version(self, rv: int) -> None:
        with self._lock:
            if rv > self._rv:
                self._rv = rv

    def current_resource_version(self) -> int:
        """The store's latest resourceVersion — a cheap change cursor for
        callers memoizing work against cluster state (defrag trial cache)."""
        with self._lock:
            return self._rv

    def dump_for_snapshot(self, kinds) -> "tuple[Dict[str, List[Any]], int]":
        """Consistent point-in-time view of the stores for compaction. The
        returned objects are the live stored ones — callers must only read
        (the persistence codec does)."""
        with self._lock:
            return ({k: list(self._stores[k].values()) for k in kinds},
                    self._rv)

    def _dispatch(self, ev: WatchEvent) -> None:
        for h in list(self._handlers[ev.kind]):
            try:
                h(ev)
            except Exception as e:  # handlers must not kill the server
                klog.error_s(e, "watch handler panicked", kind=ev.kind)

    def _fanout_submit_locked(self, ev: WatchEvent) -> bool:
        """Queue ``ev`` on the batcher if one is armed. MUST be called under
        the store lock — that is what makes queue order commit order. Lock
        order is store→batcher only (the flusher delivers without touching
        the store lock), so this nesting cannot deadlock."""
        b = self._fanout
        if b is None:
            return False
        b.submit(ev)
        return True

    def _append_event(self, ev: Event) -> None:
        with self._lock:
            self._events.append(ev)

    def fanout_flush(self) -> None:
        """Synchronously deliver all queued fan-out (no-op when the batcher
        is off). Test/drain barrier: after this returns, every write that
        HAPPENED-BEFORE the call has reached every informer."""
        if self._fanout is not None:
            self._fanout.flush()

    def fanout_health(self) -> Dict[str, Any]:
        if self._fanout is None:
            return {"mode": "synchronous", "flush_window_ms": 0.0}
        return self._fanout.health()

    def set_fanout_health_sink(
            self, sink: Optional[Callable[[Dict[str, Any]], None]]) -> None:
        """Wire a health publisher (the scheduler points this at the flight
        recorder's ``health.fanout`` slot). Advisory-only; sink panics are
        swallowed by the batcher."""
        if self._fanout is not None:
            self._fanout.set_health_sink(sink)
        elif sink is not None:
            sink(self.fanout_health())

    def add_watch(self, kind: str, handler: Callable[[WatchEvent], None],
                  replay: bool = True) -> None:
        """Register a watch handler. With replay=True (client-go semantics),
        the handler first receives synthetic Added events for every existing
        object."""
        with self._lock:
            existing = list(self._stores[kind].values())  # shared, read-only
            self._handlers[kind].append(handler)
        if replay:
            for o in existing:
                handler(WatchEvent(ADDED, kind, o))

    def remove_watch(self, kind: str, handler: Callable[[WatchEvent], None]) -> None:
        """Deregister a watch handler (client-go watch Stop analog): a
        stopped component must not keep receiving events — without this a
        long-lived process restarting schedulers (HA fail-over, the what-if
        planner's stop/restore/restart barrier) accumulates dead handlers
        that are invoked on every write forever."""
        with self._lock:
            try:
                self._handlers[kind].remove(handler)
            except ValueError:
                pass

    # -- CRUD -----------------------------------------------------------------

    # Write-path sharing discipline: stored objects are never mutated in
    # place after publication (every write replaces them wholesale), so watch
    # events carry the stored object itself — exactly client-go's shared
    # informer-cache contract. Consumers MUST treat watched/listed objects as
    # read-only; get()/list() still return private deep copies.

    def create(self, kind: str, obj) -> Any:
        with self._lock:
            key = obj.meta.key
            if key in self._stores[kind]:
                raise Conflict(f"{kind} {key} already exists")
            stored = obj.deepcopy()
            if not stored.meta.creation_timestamp:
                stored.meta.creation_timestamp = self._clock()
            self._bump(stored)
            self._stores[kind][key] = stored
            if self._persist:
                self._persist("put", kind, stored)
            ev = WatchEvent(ADDED, kind, stored)
            deferred = self._fanout_submit_locked(ev)
        if not deferred:
            self._dispatch(ev)
        return stored.deepcopy()  # callers own (and may mutate) returns

    def get(self, kind: str, key: str):
        with self._lock:
            obj = self._stores[kind].get(key)
            if obj is None:
                raise NotFound(f"{kind} {key} not found")
            return obj.deepcopy()

    def try_get(self, kind: str, key: str):
        try:
            return self.get(kind, key)
        except NotFound:
            return None

    def list(self, kind: str, namespace: Optional[str] = None,
             selector: Optional[Dict[str, str]] = None) -> List[Any]:
        with self._lock:
            # select before copying — only matches pay the per-object copy
            objs = [o.deepcopy() for o in self._stores[kind].values()
                    if (namespace is None or o.meta.namespace == namespace)
                    and (not selector
                         or all(o.meta.labels.get(k) == v
                                for k, v in selector.items()))]
        return objs

    def update(self, kind: str, obj) -> Any:
        """PUT. Optimistic concurrency (the kube-apiserver contract the
        reference's controllers retry against): a non-zero
        ``metadata.resourceVersion`` that does not match the stored object
        is rejected with Conflict — the caller's copy is stale and must be
        re-read. Divergence, documented in doc/develop.md: RV 0 (an object
        never read from this store) is accepted as "no precondition",
        where the real apiserver rejects empty-RV PUTs for built-ins."""
        with self._lock:
            key = obj.meta.key
            old = self._stores[kind].get(key)
            if old is None:
                raise NotFound(f"{kind} {key} not found")
            if (obj.meta.resource_version
                    and obj.meta.resource_version != old.meta.resource_version):
                raise Conflict(
                    f"{kind} {key}: stale resourceVersion "
                    f"{obj.meta.resource_version} != {old.meta.resource_version}")
            stored = obj.deepcopy()
            stored.meta.creation_timestamp = old.meta.creation_timestamp
            stored.meta.uid = old.meta.uid
            self._bump(stored)
            self._stores[kind][key] = stored
            if self._persist:
                self._persist("put", kind, stored)
            ev = WatchEvent(MODIFIED, kind, stored, old)
            deferred = self._fanout_submit_locked(ev)
        if not deferred:
            self._dispatch(ev)
        return stored.deepcopy()

    def patch(self, kind: str, key: str, mutate: Callable[[Any], None]) -> Any:
        """Atomic read-modify-write (merge-patch analog). `mutate` runs under
        the store lock against a private copy of the live object; keep it
        pure and fast."""
        with self._lock:
            old = self._stores[kind].get(key)
            if old is None:
                raise NotFound(f"{kind} {key} not found")
            stored = old.deepcopy()
            mutate(stored)
            self._bump(stored)
            self._stores[kind][key] = stored
            if self._persist:
                self._persist("put", kind, stored)
            ev = WatchEvent(MODIFIED, kind, stored, old)
            deferred = self._fanout_submit_locked(ev)
        if not deferred:
            self._dispatch(ev)
        return stored.deepcopy()

    def delete(self, kind: str, key: str, uid: Optional[str] = None) -> None:
        """``uid`` is the DeleteOptions.Preconditions.UID analog: the delete
        applies only to the exact object instance the caller observed. A
        controller deleting from a point-in-time sweep (node lifecycle
        orphan GC) MUST pass it — without the precondition, a stale delete
        races the gang repair controller's recreation of the same pod name
        and silently kills the replacement."""
        with self._lock:
            obj = self._stores[kind].get(key)
            if obj is None:
                raise NotFound(f"{kind} {key} not found")
            if uid is not None and obj.meta.uid != uid:
                raise Conflict(
                    f"{kind} {key}: uid precondition failed "
                    f"({uid} != live {obj.meta.uid})")
            self._stores[kind].pop(key, None)
            # a delete IS a write: etcd bumps its revision for deletions
            # too, and current_resource_version() consumers (the defrag
            # negative-trial cache) must see freed capacity as a change
            self._rv += 1
            if self._persist:
                self._persist("delete", kind, obj)
            ev = WatchEvent(DELETED, kind, obj)
            deferred = self._fanout_submit_locked(ev)
        if not deferred:
            self._dispatch(ev)

    def peek(self, kind: str, key: str):
        """Zero-copy read of the live stored object (or None). Callers MUST
        treat the result as read-only — this is the hot-poll path (e.g. the
        integration harness's podScheduled loop) where a full deepcopy per
        probe would contend the store lock against binds."""
        with self._lock:
            return self._stores[kind].get(key)

    # -- subresources ---------------------------------------------------------

    def bind(self, binding: Binding) -> None:
        """POST pods/<p>/binding. Fails if the pod is already bound (the API
        server's real behavior, which the scheduler cache relies on) or if
        the target node no longer exists. The node check is a DELIBERATE
        divergence from the real apiserver (which admits binds to any node
        name and lets the kubelet reject the pod): this hermetic control
        plane has no kubelet, so the terminal NotFound is what lets a bind
        racing a node deletion trigger the gang-atomic rollback instead of
        silently parking pods on vanished hardware. Kube-backed deployments
        take the slower path for this window — the bind lands, and the node
        lifecycle controller's orphan GC + gang repair recover the gang."""
        now = self._clock()

        def mutate(pod: Pod):
            # already-bound check FIRST: a lost-response bind retried after
            # the target node died must surface the Conflict the client's
            # heal path recognizes ("bound to my node" ⇒ success), not a
            # terminal NotFound that would roll back a gang whose bind
            # actually committed
            if pod.spec.node_name:
                raise Conflict(f"pod {binding.pod_key} already bound to {pod.spec.node_name}")
            # inside patch's store lock: atomic with the commit, so a node
            # deletion can never interleave between the check and the write
            if "/" + binding.node_name not in self._stores[NODES]:
                raise NotFound(f"node {binding.node_name} not found")
            pod.spec.node_name = binding.node_name
            pod.meta.annotations.update(binding.annotations)
            pod.status.conditions.append(PodCondition(
                type="PodScheduled", status="True", last_transition_time=now))
        self.patch(PODS, binding.pod_key, mutate)

    def record_event(self, object_key: str, kind: str, etype: str, reason: str,
                     message: str) -> None:
        ev = Event(object_key=object_key, kind=kind, type=etype, reason=reason,
                   message=message, timestamp=self._clock())
        with self._lock:
            self._events.append(ev)

    def record_event_deferred(self, object_key: str, kind: str, etype: str,
                              reason: str,
                              message_fn: Callable[[], str]) -> None:
        """record_event with the message formatting (and the events-ring
        lock acquisition) pushed onto the fan-out flusher. The bind hot
        path pays one timestamp read + queue append; the timestamp is
        taken NOW so deferral never skews event time. Falls back to the
        synchronous path when the batcher is off."""
        if self._fanout is None:
            self.record_event(object_key, kind, etype, reason, message_fn())
            return
        ts = self._clock()

        def build() -> Event:
            return Event(object_key=object_key, kind=kind, type=etype,
                         reason=reason, message=message_fn(), timestamp=ts)

        self._fanout.submit(build)

    def events(self) -> List[Event]:
        with self._lock:
            return list(self._events)

    # -- gang runtime status reports (heartbeat-piggybacked) -------------------

    def add_status_sink(self, sink: Callable[[List[GangMemberStatus]], Any]
                        ) -> None:
        """Register a runtime-status consumer. Idempotent per sink object —
        a re-armed capture must not double-deliver every report."""
        with self._lock:
            if sink not in self._status_sinks:
                self._status_sinks.append(sink)

    def remove_status_sink(self, sink) -> None:
        with self._lock:
            try:
                self._status_sinks.remove(sink)
            except ValueError:
                pass

    def report_status(self, reports: List[GangMemberStatus]) -> None:
        """In-band gang member progress reports, normally piggybacked on
        the node heartbeat (``clientset.nodes.heartbeat``). Stamps unstamped
        reports and fans them out to every registered sink OUTSIDE the
        store lock — sinks own their bounding/shedding; a panicking sink is
        contained like a watch handler."""
        if not reports:
            return
        now = self._clock()
        for r in reports:
            if not r.timestamp:
                r.timestamp = now
        with self._lock:
            sinks = list(self._status_sinks)
        for sink in sinks:
            try:
                sink(reports)
            except Exception as e:  # sinks must not kill the server
                klog.error_s(e, "status sink panicked")

    # -- coordination (leases for leader election) ---------------------------

    def acquire_or_renew_lease(self, name: str, holder: str,
                               lease_duration: float = 15.0) -> bool:
        """Atomically acquire/renew a named lease. Returns True if `holder`
        is (now) the leader."""
        now = self._clock()
        with self._lock:
            lease = self._stores[LEASES].get("/" + name)
            if lease is None or lease.holder == holder or \
                    now - lease.renew_time > lease.lease_duration:
                from ..api.meta import ObjectMeta
                new = _Lease(meta=ObjectMeta(name=name, namespace=""),
                             holder=holder, renew_time=now,
                             lease_duration=lease_duration)
                self._rv += 1
                new.meta.resource_version = self._rv
                self._stores[LEASES]["/" + name] = new
                return True
            return False

    def lease_holder(self, name: str) -> str:
        with self._lock:
            lease = self._stores[LEASES].get("/" + name)
            return lease.holder if lease else ""
