"""Scheduling framework: extension points, cycle state, node snapshots, runtime.

Rebuild of the contract the reference plugs into (vendored
k8s.io/kubernetes/pkg/scheduler/framework; SURVEY §1 "Hosting runtime"):
QueueSort → PreFilter → Filter → PostFilter → PreScore → Score →
Reserve → Permit → PreBind → Bind → PostBind, with CycleState carrying
per-cycle plugin data and a waitingPods map as the in-process gang barrier.
"""
from .status import (Status, Code, SUCCESS, ERROR, UNSCHEDULABLE,
                     UNSCHEDULABLE_AND_UNRESOLVABLE, WAIT, SKIP)
from .cycle_state import CycleState
from .nodeinfo import NodeInfo, Snapshot, MAX_NODE_SCORE, MIN_NODE_SCORE
from .interfaces import (Plugin, QueueSortPlugin, PreFilterPlugin, FilterPlugin,
                         PostFilterPlugin, PreScorePlugin, ScorePlugin,
                         ReservePlugin, PermitPlugin, PreBindPlugin, BindPlugin,
                         PostBindPlugin, PreFilterExtensions, EnqueueExtensions,
                         ClusterEvent, PostFilterResult, NodeScore,
                         EVENT_ADD, EVENT_UPDATE, EVENT_DELETE,
                         RESOURCE_POD, RESOURCE_NODE, RESOURCE_POD_GROUP,
                         RESOURCE_ELASTIC_QUOTA, RESOURCE_TPU_TOPOLOGY,
                         WILDCARD_EVENT)
from .runtime import (Framework, Registry, Handle, PluginProfile,
                      PODS_TO_ACTIVATE_KEY, GANG_ROLLBACK_STATE_KEY,
                      QUOTA_GUARD_STATE_KEY, PodsToActivate)

__all__ = [n for n in dir() if not n.startswith("_")]
