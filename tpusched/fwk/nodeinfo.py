"""NodeInfo + cluster Snapshot (framework's SharedLister contract).

A Snapshot is taken once per scheduling cycle and is the only cluster view
plugins may use in Filter/Score (hot path; SURVEY §3.2). NodeInfo supports
add_pod/remove_pod so preemption dry-runs can simulate victim removal
(/root/reference/pkg/capacityscheduling/capacity_scheduling.go:489-506).
"""
from __future__ import annotations

import itertools
from typing import Dict, Iterable, List, Optional

from ..api.core import Node, Pod
from ..api.resources import ResourceList
from ..util.podutil import pod_request_with_defaults

MAX_NODE_SCORE = 100
MIN_NODE_SCORE = 0

# Process-global monotonic generation (upstream nodeinfo.nextGeneration):
# every NodeInfo mutation takes a FRESH value, so a node deleted and re-added
# can never collide with its predecessor's generation in the incremental
# snapshot (sched/cache.py). CPython's count.__next__ is atomic.
_generation = itertools.count(1)


def next_generation() -> int:
    return next(_generation)


def quorum_count_with_inflight(snapshot, pg_name: str,
                               namespace: str) -> int:
    """Gang members assigned INCLUDING the caller's own in-flight pod.

    Upstream counts ``assigned + 1`` because the in-flight pod is never in
    a frozen-at-cycle-start snapshot (core.go:209-215).  The cache's
    persistent snapshots (PooledSnapshot) carry the LIVE gang-quorum
    index instead — the cycle's own assume is already counted by Permit
    time — so adding 1 there would release the barrier one member early.
    This helper is the one place that knows which convention a lister
    uses; every quorum comparison goes through it."""
    n = snapshot.assigned_count(pg_name, namespace)
    return n if getattr(snapshot, "live_pg_assigned", False) else n + 1


def minmax_normalize(raw: Dict[str, int], scores) -> None:
    """Min-max normalize NodeScore list in place from a raw per-node dict
    (the shared pattern of allocatable.go:141-166 / pod_state.go:72-95);
    all-equal raw values map to MAX_NODE_SCORE."""
    values = [raw.get(s.name, 0) for s in scores]
    lo, hi = (min(values), max(values)) if values else (0, 0)
    for s in scores:
        v = raw.get(s.name, 0)
        s.score = MAX_NODE_SCORE if hi == lo else \
            int((v - lo) * MAX_NODE_SCORE // (hi - lo))


class NodeInfo:
    __slots__ = ("node", "pods", "requested", "non_zero_requested",
                 "generation", "derived_cache")

    def __init__(self, node: Optional[Node] = None, pods: Iterable[Pod] = ()):
        self.node = node
        self.pods: List[Pod] = []
        self.requested: ResourceList = {}
        self.non_zero_requested: ResourceList = {}
        self.generation = next_generation()
        # (generation, value) memo for derived per-node models (e.g. the
        # TpuSlice ChipNode); any add/remove/update invalidates by bumping
        # the generation
        self.derived_cache: Dict[str, tuple] = {}
        for p in pods:
            self.add_pod(p)

    def derived(self, key: str, build):
        """Generation-keyed memo: returns build(self), cached until this
        NodeInfo changes. Only for values derived purely from (node, pods)."""
        ent = self.derived_cache.get(key)
        if ent is not None and ent[0] == self.generation:
            return ent[1]
        value = build(self)
        self.derived_cache[key] = (self.generation, value)
        return value

    @property
    def allocatable(self) -> ResourceList:
        return self.node.status.allocatable if self.node else {}

    def set_node(self, node: Node) -> None:
        self.node = node
        self.generation = next_generation()

    def add_pod(self, pod: Pod) -> None:
        self.pods.append(pod)
        for k, v in pod_request_with_defaults(pod).items():
            self.requested[k] = self.requested.get(k, 0) + v
        for k, v in pod_request_with_defaults(pod, non_zero=True).items():
            self.non_zero_requested[k] = self.non_zero_requested.get(k, 0) + v
        self.generation = next_generation()

    def remove_pod(self, pod: Pod) -> bool:
        for i, p in enumerate(self.pods):
            if p.meta.uid == pod.meta.uid or p.key == pod.key:
                self.pods.pop(i)
                for k, v in pod_request_with_defaults(p).items():
                    self.requested[k] = self.requested.get(k, 0) - v
                for k, v in pod_request_with_defaults(p, non_zero=True).items():
                    self.non_zero_requested[k] = self.non_zero_requested.get(k, 0) - v
                self.generation = next_generation()
                return True
        return False

    def clone(self) -> "NodeInfo":
        out = NodeInfo()
        out.node = self.node  # nodes are treated as immutable snapshots
        out.pods = list(self.pods)
        out.requested = dict(self.requested)
        out.non_zero_requested = dict(self.non_zero_requested)
        out.generation = self.generation
        out.derived_cache = dict(self.derived_cache)  # values are derived-pure
        return out


class Snapshot:
    """Immutable-by-convention per-cycle cluster view; also the fake shared
    lister used by unit tests (/root/reference/test/util/fake.go:32-101)."""

    # True when assigned_count serves the cache's LIVE gang-quorum index
    # (set by PooledSnapshot): the caller's own in-cycle assume is already
    # counted, so quorum checks must NOT add the upstream "+1 for the
    # in-flight pod" (core.go:209-215) on top — see
    # quorum_count_with_inflight.
    live_pg_assigned = False

    def __init__(self, nodes: Iterable[Node] = (), pods: Iterable[Pod] = ()):
        self._infos: Dict[str, NodeInfo] = {}
        self._pg_assigned: Optional[Dict[str, int]] = None  # lazy gang index
        self._pg_live: Optional[Dict[str, int]] = None      # sans terminating
        # per-pool mutation cursors this snapshot was captured at (set by
        # sched.cache at build time; {} on hand-built test snapshots).  The
        # torus window index's cursor-consistency rule compares a plane's
        # version against THIS — equality proves the plane and the
        # snapshot describe the same occupancy epoch for that pool.
        self.pool_cursors: Dict[str, int] = {}
        for n in nodes:
            self._infos[n.name] = NodeInfo(n)
        for p in pods:
            if p.spec.node_name and p.spec.node_name in self._infos:
                self._infos[p.spec.node_name].add_pod(p)

    @classmethod
    def from_infos(cls, infos: Dict[str, "NodeInfo"],
                   pg_assigned: Optional[Dict[str, int]] = None) -> "Snapshot":
        """pg_assigned: a precomputed gang→assigned-members index (the
        scheduler cache maintains one incrementally); when absent the index
        is derived lazily from the infos on first assigned_count query."""
        out = cls()
        out._infos = infos
        out._pg_assigned = pg_assigned
        return out

    @staticmethod
    def _node_pg_counts(info: "NodeInfo") -> Dict[str, int]:
        from ..api.scheduling import POD_GROUP_LABEL
        counts: Dict[str, int] = {}
        for p in info.pods:
            name = p.meta.labels.get(POD_GROUP_LABEL)
            if name and p.spec.node_name:
                key = f"{p.meta.namespace}/{name}"
                counts[key] = counts.get(key, 0) + 1
        return counts

    @staticmethod
    def _node_pg_live_counts(info: "NodeInfo") -> Dict[str, int]:
        from ..api.scheduling import POD_GROUP_LABEL
        counts: Dict[str, int] = {}
        for p in info.pods:
            name = p.meta.labels.get(POD_GROUP_LABEL)
            if (name and p.spec.node_name
                    and p.meta.deletion_timestamp is None):
                key = f"{p.meta.namespace}/{name}"
                counts[key] = counts.get(key, 0) + 1
        return counts

    def assigned_live_count(self, pg_name: str, namespace: str) -> int:
        """Like assigned_count but excluding TERMINATING members (deletion
        timestamp set): the disruption-floor input. A member evicted by an
        earlier cycle that is still draining must not count as a quorum
        survivor, or back-to-back preemptions on different hosts would
        each think the gang can spare one more. Lazy per-snapshot index
        (cold preemption path only), per-node generation-memoized."""
        if self._pg_live is None:
            idx: Dict[str, int] = {}
            for info in self._infos.values():
                for key, c in info.derived(
                        "Snapshot/pg-live",
                        self._node_pg_live_counts).items():
                    idx[key] = idx.get(key, 0) + c
            self._pg_live = idx
        return self._pg_live.get(f"{namespace}/{pg_name}", 0)

    def assigned_count(self, pg_name: str, namespace: str) -> int:
        """Members of a gang with a node assigned (assumed or bound) — the
        quorum input (core.go:301-318). Indexed lazily once per snapshot so
        per-Permit cost is O(1) instead of O(pods); the per-node counts are
        generation-memoized (derived()), so the snapshot index rebuild is
        O(nodes) — only nodes that changed since the last cycle re-walk
        their pods."""
        if self._pg_assigned is None:
            idx: Dict[str, int] = {}
            for info in self._infos.values():
                for key, c in info.derived(
                        "Snapshot/pg-assigned", self._node_pg_counts).items():
                    idx[key] = idx.get(key, 0) + c
            self._pg_assigned = idx
        return self._pg_assigned.get(f"{namespace}/{pg_name}", 0)

    # SharedLister / NodeInfoLister ------------------------------------------
    def list(self) -> List[NodeInfo]:
        return list(self._infos.values())

    def get(self, node_name: str) -> Optional[NodeInfo]:
        return self._infos.get(node_name)

    def node_names(self) -> List[str]:
        return list(self._infos)

    def num_nodes(self) -> int:
        return len(self._infos)

    def clone(self) -> "Snapshot":
        return Snapshot.from_infos(
            {name: info.clone() for name, info in self._infos.items()})


class PoolChain:
    """Lazy pool-ordered candidate SEQUENCE over per-pool NodeInfo lists:
    len/iter/random-access without flattening.  Built O(pools) per
    snapshot epoch; the per-pool lists are cached against the pool's
    sub-map by the cache, so an epoch where one pool mutated re-lists one
    pool and chains the rest by reference — the last per-cycle O(hosts)
    term (the flat candidate materialization) becomes O(pools).  Random
    access (the Filter sweep's rotating start index) is a bisect over
    prefix lengths — O(log pools), pools are double-digit."""

    __slots__ = ("_lists", "_offsets", "_len")

    def __init__(self, lists: List[List["NodeInfo"]]):
        self._lists = lists
        self._offsets = []
        n = 0
        for lst in lists:
            self._offsets.append(n)
            n += len(lst)
        self._len = n

    def __len__(self) -> int:
        return self._len

    def __iter__(self):
        for lst in self._lists:
            yield from lst

    def __getitem__(self, i: int) -> "NodeInfo":
        if i < 0:
            i += self._len
        if not 0 <= i < self._len:
            raise IndexError(i)
        import bisect
        j = bisect.bisect_right(self._offsets, i) - 1
        return self._lists[j][i - self._offsets[j]]


class PooledSnapshot(Snapshot):
    """Persistent/versioned cluster view composed of PER-POOL sub-maps
    (sched/cache.py's O(Δ) cycle core): each pool's ``{node: NodeInfo}``
    dict is built once at that pool's cursor and SHARED STRUCTURALLY by
    every snapshot that includes the pool until the pool mutates again —
    a cycle over a quiet fleet composes its view from existing sub-maps
    in O(pools) instead of rebuilding an O(hosts) dict, and a single
    informer event re-clones one pool, not the fleet.

    Immutability contract (stronger than the base class's by-convention):
    the sub-map dicts are shared between the cache and EVERY live
    snapshot, so they are never mutated in place — a pool rebuild swaps
    in a fresh dict.  ``list()`` therefore returns ONE cached flat list
    per snapshot epoch (pool-ordered: the lazy candidate sequence the
    scheduler sweeps), and callers must treat it as read-only — exactly
    the read-only contract snapshot NodeInfos already carry."""

    def __init__(self, pools: Dict[str, Dict[str, "NodeInfo"]],
                 pool_cursors: Dict[str, int],
                 pg_assigned: Optional[Dict[str, int]] = None,
                 pool_lists: Optional[Dict[str, List["NodeInfo"]]] = None):
        self._pools = pools
        self._infos = None          # base-class attr unused; see overrides
        self.pool_cursors = pool_cursors
        self._pg_assigned = pg_assigned
        self.live_pg_assigned = pg_assigned is not None
        self._pg_live = None
        self._num = sum(len(m) for m in pools.values())
        self._flat: Optional[List[NodeInfo]] = None   # lazy, cached
        self._cursor_tuple = None                     # lazy, cached
        # per-pool value lists shared from the cache's persistent entries
        # (a pool re-lists only when its sub-map was rebuilt); the chain
        # over them is this snapshot's candidate sequence
        self._pool_lists = pool_lists
        self._chain: Optional[PoolChain] = None       # lazy, cached

    def candidate_seq(self):
        """Pool-ordered candidate sequence (len/iter/index) WITHOUT
        flattening — the scheduler's sweep input.  Falls back to the
        cached flat list when per-pool lists were not provided."""
        if self._pool_lists is None:
            return self.list()
        chain = self._chain
        if chain is None:
            chain = self._chain = PoolChain(
                [self._pool_lists[p] for p in self._pools])
        return chain

    def pool_segments(self):
        """[(pool, per-pool NodeInfo list)] in candidate-sequence order —
        the native dispatch packer keys its per-(pool, cursor) candidate
        blocks off these shared lists (sched/nativedispatch.py), reusing a
        pool's packed matrix until the pool's cursor moves.  None when the
        snapshot was built without per-pool lists (plain test snapshots)."""
        if self._pool_lists is None:
            return None
        return [(p, self._pool_lists[p]) for p in self._pools]

    def cursor_tuple(self):
        """Canonical sorted ((pool, cursor), ...) — the equivalence-cache
        validity witness, memoized per snapshot epoch (the per-cycle sort
        of the cursor dict was one of the last O(pools)-per-cycle terms)."""
        if self._cursor_tuple is None:
            self._cursor_tuple = tuple(sorted(self.pool_cursors.items()))
        return self._cursor_tuple

    # SharedLister overrides over the pooled layout -------------------------
    def list(self) -> List[NodeInfo]:
        flat = self._flat
        if flat is None:
            flat = [info for pool in self._pools.values()
                    for info in pool.values()]
            self._flat = flat
        return flat

    def get(self, node_name: str) -> Optional[NodeInfo]:
        # O(#pools) dict probes (single-digit per shard partition, ≤ fleet
        # pool count globally) — cheaper than maintaining a merged name
        # index that would have to be rebuilt O(hosts) per epoch
        for pool in self._pools.values():
            info = pool.get(node_name)
            if info is not None:
                return info
        return None

    def node_names(self) -> List[str]:
        return [name for pool in self._pools.values() for name in pool]

    def num_nodes(self) -> int:
        return self._num

    def _iter_infos(self):
        for pool in self._pools.values():
            yield from pool.values()

    def assigned_live_count(self, pg_name: str, namespace: str) -> int:
        if self._pg_live is None:
            idx: Dict[str, int] = {}
            for info in self._iter_infos():
                for key, c in info.derived(
                        "Snapshot/pg-live",
                        self._node_pg_live_counts).items():
                    idx[key] = idx.get(key, 0) + c
            self._pg_live = idx
        return self._pg_live.get(f"{namespace}/{pg_name}", 0)

    def assigned_count(self, pg_name: str, namespace: str) -> int:
        if self._pg_assigned is None:
            idx: Dict[str, int] = {}
            for info in self._iter_infos():
                for key, c in info.derived(
                        "Snapshot/pg-assigned", self._node_pg_counts).items():
                    idx[key] = idx.get(key, 0) + c
            self._pg_assigned = idx
        return self._pg_assigned.get(f"{namespace}/{pg_name}", 0)

    def clone(self) -> "Snapshot":
        # forks (what-if planner, defrag trials) get a plain mutable
        # Snapshot: they exist to mutate their copy
        return Snapshot.from_infos(
            {name: info.clone() for pool in self._pools.values()
             for name, info in pool.items()})
