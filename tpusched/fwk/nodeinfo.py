"""NodeInfo + cluster Snapshot (framework's SharedLister contract).

A Snapshot is taken once per scheduling cycle and is the only cluster view
plugins may use in Filter/Score (hot path; SURVEY §3.2). NodeInfo supports
add_pod/remove_pod so preemption dry-runs can simulate victim removal
(/root/reference/pkg/capacityscheduling/capacity_scheduling.go:489-506).
"""
from __future__ import annotations

import itertools
from typing import Dict, Iterable, List, Optional

from ..api.core import Node, Pod
from ..api.resources import ResourceList
from ..util.podutil import pod_request_with_defaults

MAX_NODE_SCORE = 100
MIN_NODE_SCORE = 0

# Process-global monotonic generation (upstream nodeinfo.nextGeneration):
# every NodeInfo mutation takes a FRESH value, so a node deleted and re-added
# can never collide with its predecessor's generation in the incremental
# snapshot (sched/cache.py). CPython's count.__next__ is atomic.
_generation = itertools.count(1)


def next_generation() -> int:
    return next(_generation)


def minmax_normalize(raw: Dict[str, int], scores) -> None:
    """Min-max normalize NodeScore list in place from a raw per-node dict
    (the shared pattern of allocatable.go:141-166 / pod_state.go:72-95);
    all-equal raw values map to MAX_NODE_SCORE."""
    values = [raw.get(s.name, 0) for s in scores]
    lo, hi = (min(values), max(values)) if values else (0, 0)
    for s in scores:
        v = raw.get(s.name, 0)
        s.score = MAX_NODE_SCORE if hi == lo else \
            int((v - lo) * MAX_NODE_SCORE // (hi - lo))


class NodeInfo:
    __slots__ = ("node", "pods", "requested", "non_zero_requested",
                 "generation", "derived_cache")

    def __init__(self, node: Optional[Node] = None, pods: Iterable[Pod] = ()):
        self.node = node
        self.pods: List[Pod] = []
        self.requested: ResourceList = {}
        self.non_zero_requested: ResourceList = {}
        self.generation = next_generation()
        # (generation, value) memo for derived per-node models (e.g. the
        # TpuSlice ChipNode); any add/remove/update invalidates by bumping
        # the generation
        self.derived_cache: Dict[str, tuple] = {}
        for p in pods:
            self.add_pod(p)

    def derived(self, key: str, build):
        """Generation-keyed memo: returns build(self), cached until this
        NodeInfo changes. Only for values derived purely from (node, pods)."""
        ent = self.derived_cache.get(key)
        if ent is not None and ent[0] == self.generation:
            return ent[1]
        value = build(self)
        self.derived_cache[key] = (self.generation, value)
        return value

    @property
    def allocatable(self) -> ResourceList:
        return self.node.status.allocatable if self.node else {}

    def set_node(self, node: Node) -> None:
        self.node = node
        self.generation = next_generation()

    def add_pod(self, pod: Pod) -> None:
        self.pods.append(pod)
        for k, v in pod_request_with_defaults(pod).items():
            self.requested[k] = self.requested.get(k, 0) + v
        for k, v in pod_request_with_defaults(pod, non_zero=True).items():
            self.non_zero_requested[k] = self.non_zero_requested.get(k, 0) + v
        self.generation = next_generation()

    def remove_pod(self, pod: Pod) -> bool:
        for i, p in enumerate(self.pods):
            if p.meta.uid == pod.meta.uid or p.key == pod.key:
                self.pods.pop(i)
                for k, v in pod_request_with_defaults(p).items():
                    self.requested[k] = self.requested.get(k, 0) - v
                for k, v in pod_request_with_defaults(p, non_zero=True).items():
                    self.non_zero_requested[k] = self.non_zero_requested.get(k, 0) - v
                self.generation = next_generation()
                return True
        return False

    def clone(self) -> "NodeInfo":
        out = NodeInfo()
        out.node = self.node  # nodes are treated as immutable snapshots
        out.pods = list(self.pods)
        out.requested = dict(self.requested)
        out.non_zero_requested = dict(self.non_zero_requested)
        out.generation = self.generation
        out.derived_cache = dict(self.derived_cache)  # values are derived-pure
        return out


class Snapshot:
    """Immutable-by-convention per-cycle cluster view; also the fake shared
    lister used by unit tests (/root/reference/test/util/fake.go:32-101)."""

    def __init__(self, nodes: Iterable[Node] = (), pods: Iterable[Pod] = ()):
        self._infos: Dict[str, NodeInfo] = {}
        self._pg_assigned: Optional[Dict[str, int]] = None  # lazy gang index
        self._pg_live: Optional[Dict[str, int]] = None      # sans terminating
        # per-pool mutation cursors this snapshot was captured at (set by
        # sched.cache at build time; {} on hand-built test snapshots).  The
        # torus window index's cursor-consistency rule compares a plane's
        # version against THIS — equality proves the plane and the
        # snapshot describe the same occupancy epoch for that pool.
        self.pool_cursors: Dict[str, int] = {}
        for n in nodes:
            self._infos[n.name] = NodeInfo(n)
        for p in pods:
            if p.spec.node_name and p.spec.node_name in self._infos:
                self._infos[p.spec.node_name].add_pod(p)

    @classmethod
    def from_infos(cls, infos: Dict[str, "NodeInfo"],
                   pg_assigned: Optional[Dict[str, int]] = None) -> "Snapshot":
        """pg_assigned: a precomputed gang→assigned-members index (the
        scheduler cache maintains one incrementally); when absent the index
        is derived lazily from the infos on first assigned_count query."""
        out = cls()
        out._infos = infos
        out._pg_assigned = pg_assigned
        return out

    @staticmethod
    def _node_pg_counts(info: "NodeInfo") -> Dict[str, int]:
        from ..api.scheduling import POD_GROUP_LABEL
        counts: Dict[str, int] = {}
        for p in info.pods:
            name = p.meta.labels.get(POD_GROUP_LABEL)
            if name and p.spec.node_name:
                key = f"{p.meta.namespace}/{name}"
                counts[key] = counts.get(key, 0) + 1
        return counts

    @staticmethod
    def _node_pg_live_counts(info: "NodeInfo") -> Dict[str, int]:
        from ..api.scheduling import POD_GROUP_LABEL
        counts: Dict[str, int] = {}
        for p in info.pods:
            name = p.meta.labels.get(POD_GROUP_LABEL)
            if (name and p.spec.node_name
                    and p.meta.deletion_timestamp is None):
                key = f"{p.meta.namespace}/{name}"
                counts[key] = counts.get(key, 0) + 1
        return counts

    def assigned_live_count(self, pg_name: str, namespace: str) -> int:
        """Like assigned_count but excluding TERMINATING members (deletion
        timestamp set): the disruption-floor input. A member evicted by an
        earlier cycle that is still draining must not count as a quorum
        survivor, or back-to-back preemptions on different hosts would
        each think the gang can spare one more. Lazy per-snapshot index
        (cold preemption path only), per-node generation-memoized."""
        if self._pg_live is None:
            idx: Dict[str, int] = {}
            for info in self._infos.values():
                for key, c in info.derived(
                        "Snapshot/pg-live",
                        self._node_pg_live_counts).items():
                    idx[key] = idx.get(key, 0) + c
            self._pg_live = idx
        return self._pg_live.get(f"{namespace}/{pg_name}", 0)

    def assigned_count(self, pg_name: str, namespace: str) -> int:
        """Members of a gang with a node assigned (assumed or bound) — the
        quorum input (core.go:301-318). Indexed lazily once per snapshot so
        per-Permit cost is O(1) instead of O(pods); the per-node counts are
        generation-memoized (derived()), so the snapshot index rebuild is
        O(nodes) — only nodes that changed since the last cycle re-walk
        their pods."""
        if self._pg_assigned is None:
            idx: Dict[str, int] = {}
            for info in self._infos.values():
                for key, c in info.derived(
                        "Snapshot/pg-assigned", self._node_pg_counts).items():
                    idx[key] = idx.get(key, 0) + c
            self._pg_assigned = idx
        return self._pg_assigned.get(f"{namespace}/{pg_name}", 0)

    # SharedLister / NodeInfoLister ------------------------------------------
    def list(self) -> List[NodeInfo]:
        return list(self._infos.values())

    def get(self, node_name: str) -> Optional[NodeInfo]:
        return self._infos.get(node_name)

    def node_names(self) -> List[str]:
        return list(self._infos)

    def num_nodes(self) -> int:
        return len(self._infos)

    def clone(self) -> "Snapshot":
        return Snapshot.from_infos(
            {name: info.clone() for name, info in self._infos.items()})
