"""Status codes for plugin results (framework *Status semantics)."""
from __future__ import annotations

from enum import IntEnum
from typing import List, Optional


class Code(IntEnum):
    SUCCESS = 0
    ERROR = 1
    UNSCHEDULABLE = 2
    # Unresolvable: preemption will not help; skip PostFilter for this pod.
    UNSCHEDULABLE_AND_UNRESOLVABLE = 3
    WAIT = 4   # Permit only
    SKIP = 5


SUCCESS = Code.SUCCESS
ERROR = Code.ERROR
UNSCHEDULABLE = Code.UNSCHEDULABLE
UNSCHEDULABLE_AND_UNRESOLVABLE = Code.UNSCHEDULABLE_AND_UNRESOLVABLE
WAIT = Code.WAIT
SKIP = Code.SKIP


class Status:
    __slots__ = ("code", "reasons", "plugin", "retry_after_s")

    def __init__(self, code: Code = SUCCESS, reasons: Optional[List[str]] = None,
                 plugin: str = ""):
        self.code = code
        self.reasons = reasons or []
        self.plugin = plugin
        # Time-bounded rejection hint: the pod was rejected by a denial
        # WINDOW (denied-PG / denied-multislice-set TTL), so retrying is
        # pointless before — and correct after — this many seconds. The
        # scheduler parks such pods in backoffQ with this expiry instead of
        # unschedulableQ: no cluster event will ever fire when a TTL lapses,
        # so event-driven requeue would leave them to the periodic flush.
        self.retry_after_s: Optional[float] = None

    # Constructors -----------------------------------------------------------
    @staticmethod
    def success() -> "Status":
        # Shared immutable instance: the success status is by far the hottest
        # allocation (every plugin × every node per cycle); with_plugin()
        # copies-on-write so the singleton can never be mutated.
        return _SUCCESS

    @staticmethod
    def error(msg: str) -> "Status":
        return Status(ERROR, [msg])

    @staticmethod
    def unschedulable(*reasons: str) -> "Status":
        return Status(UNSCHEDULABLE, list(reasons))

    @staticmethod
    def unresolvable(*reasons: str) -> "Status":
        return Status(UNSCHEDULABLE_AND_UNRESOLVABLE, list(reasons))

    @staticmethod
    def wait() -> "Status":
        return Status(WAIT)

    @staticmethod
    def skip() -> "Status":
        return Status(SKIP)

    # Predicates -------------------------------------------------------------
    def is_success(self) -> bool:
        return self.code == SUCCESS

    def is_wait(self) -> bool:
        return self.code == WAIT

    def is_skip(self) -> bool:
        return self.code == SKIP

    def is_unschedulable(self) -> bool:
        return self.code in (UNSCHEDULABLE, UNSCHEDULABLE_AND_UNRESOLVABLE)

    def is_error(self) -> bool:
        return self.code == ERROR

    def message(self) -> str:
        return "; ".join(self.reasons)

    def with_plugin(self, name: str) -> "Status":
        # uniformly copy-on-write: plugins may return shared/cached Status
        # instances (the success singleton is one), and run_filter_plugins
        # calls this per node — in-place mutation would corrupt them across
        # nodes. Use the result, not the receiver.
        if self.plugin == name:
            return self
        out = Status(self.code, list(self.reasons), name)
        out.retry_after_s = self.retry_after_s
        return out

    def with_retry_after(self, seconds: float) -> "Status":
        """Attach the time-bounded-rejection hint (see retry_after_s).
        Mutates in place — callers construct a fresh Status for rejection
        paths; never call on the success singleton."""
        self.retry_after_s = seconds
        return self

    def __repr__(self) -> str:
        return f"Status({self.code.name}, {self.reasons!r}, plugin={self.plugin!r})"


_SUCCESS = Status(SUCCESS)


def merge_statuses(statuses: List[Status]) -> Status:
    """PluginToStatus.Merge: error > unresolvable > unschedulable > success."""
    code, plugin = SUCCESS, ""
    reasons: List[str] = []
    for s in statuses:
        if s.is_success():
            continue
        reasons.extend(s.reasons)
        if s.code == ERROR:
            code, plugin = ERROR, s.plugin
        elif s.code == UNSCHEDULABLE_AND_UNRESOLVABLE and code != ERROR:
            code, plugin = UNSCHEDULABLE_AND_UNRESOLVABLE, s.plugin
        elif s.code == UNSCHEDULABLE and code not in (ERROR, UNSCHEDULABLE_AND_UNRESOLVABLE):
            code, plugin = UNSCHEDULABLE, s.plugin
    if code == SUCCESS:
        return Status.success()
    return Status(code, reasons, plugin)
