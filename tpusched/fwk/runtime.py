"""Framework runtime: plugin registry, profiles, extension-point dispatch,
waitingPods barrier, pod nominator.

Rebuild of framework.NewFramework + frameworkImpl (vendored upstream in the
reference). The waitingPods map is the in-process gang barrier coscheduling
relies on (SURVEY §5 "Distributed communication backend").
"""
from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

from ..api.core import Node, Pod
from .. import trace
from ..util import klog, tracectx
from ..util.metrics import plugin_execution_seconds
from .cycle_state import CycleState
from .interfaces import (BatchFilterPlugin, BindPlugin, ClusterEvent,
                         EnqueueExtensions, FilterPlugin, NodeScore,
                         PermitPlugin, Plugin, PostBindPlugin,
                         PostFilterPlugin, PostFilterResult, PreBindPlugin,
                         PreFilterPlugin, PreScorePlugin, QueueSortPlugin,
                         ReservePlugin, ScorePlugin, WILDCARD_EVENT)
from .nodeinfo import MAX_NODE_SCORE, NodeInfo, Snapshot
from .status import SKIP, Status, merge_statuses

# CycleState key through which plugins ask the scheduler to move specific
# pods back into the active queue (framework.PodsToActivateKey; used by gang
# sibling activation, /root/reference/pkg/coscheduling/core/core.go:111-143).
PODS_TO_ACTIVATE_KEY = "tpusched/pods-to-activate"

# CycleState key the scheduler sets before running Unreserve on a
# gang-bind-rollback failure path (sched/scheduler): the cycle failed
# because of an API-side bind outage, NOT because the gang cannot fit.
# Coscheduling's Unreserve reads it to skip the denied-PodGroup window —
# the rollback's whole point is re-admitting the gang through pod backoff
# as soon as the faults clear, and a denial TTL on top would stall that.
GANG_ROLLBACK_STATE_KEY = "tpusched/gang-bind-rollback"

# CycleState key CapacityScheduling's PreFilter writes when ElasticQuotas
# exist: the cache quota EPOCH its admission inputs were read at.  The
# scheduler's sharded commit passes it into Cache.assume_pod_guarded as
# the compare-and-reserve key (ISSUE 14) — a framework-level name so the
# scheduler never imports plugin modules.
QUOTA_GUARD_STATE_KEY = "tpusched/quota-commit-guard"


class PodsToActivate:
    def __init__(self):
        self.lock = threading.Lock()
        self.map: Dict[str, Pod] = {}

    def clone(self):
        return self  # shared across cloned cycle states on purpose


@dataclass
class PluginProfile:
    """A scheduler profile: which plugins run at which extension points.

    Analog of the KubeSchedulerConfiguration profile the reference wires via
    YAML (manifests/*/scheduler-config.yaml; e.g. coscheduling enables
    queueSort/preFilter/postFilter/permit/reserve/postBind,
    manifests/coscheduling/scheduler-config.yaml:10-34)."""
    scheduler_name: str = "tpusched"
    queue_sort: str = "PrioritySort"
    pre_filter: List[str] = field(default_factory=list)
    filter: List[str] = field(default_factory=list)
    post_filter: List[str] = field(default_factory=list)
    pre_score: List[str] = field(default_factory=list)
    score: List[Tuple[str, int]] = field(default_factory=list)  # (name, weight)
    reserve: List[str] = field(default_factory=list)
    permit: List[str] = field(default_factory=list)
    pre_bind: List[str] = field(default_factory=list)
    bind: List[str] = field(default_factory=list)  # first Success/non-Skip wins
    post_bind: List[str] = field(default_factory=list)
    plugin_args: Dict[str, Any] = field(default_factory=dict)
    # upstream percentageOfNodesToScore: 0 = adaptive (50 - nodes/125,
    # floor 5%, only above 100 nodes); 100 = always scan every node
    percentage_of_nodes_to_score: int = 0
    # upstream KubeSchedulerConfiguration.parallelism (default 16): worker
    # threads for the per-node Filter/Score sweeps; 0 = min(16, cpu count),
    # 1 = fully serial (deterministic single-threaded scan)
    parallelism: int = 0
    # upstream podInitialBackoffSeconds / podMaxBackoffSeconds (scheduler
    # defaults 1s / 10s): the retry backoff a failed pod serves before it
    # may be popped again. None = use the defaults; an explicit 0 means
    # retry immediately (upstream allows it, so it must not be conflated
    # with "unset")
    pod_initial_backoff_s: Optional[float] = None
    pod_max_backoff_s: Optional[float] = None
    # unschedulableQ periodic flush (upstream flushUnschedulablePodsLeftover,
    # default 30 s): a wall-clock SAFETY NET behind the event-logical move
    # drains — None = default, explicit 0 disables it (purely event-driven
    # retries; deterministic replay uses 0 so a wall flush can never land
    # on a run-dependent event boundary).
    unschedulable_flush_s: Optional[float] = None
    # gang-aware equivalence-class scheduling cache (sched/equivcache.py):
    # memoized PreFilter/Filter outcomes reused across equivalent pods
    # (gang siblings). equiv_cache_differential additionally re-runs the
    # FULL path on every cache hit and asserts the identical placement —
    # the oracle check bench scenarios and tests run with; never enable it
    # in production wiring (it spends the cycle the cache saved).
    equiv_cache: bool = True
    equiv_cache_differential: bool = False
    # API-degradation circuit breaker (sched/scheduler._DegradedMode):
    # after `degraded_threshold` CONSECUTIVE retry-exhausted API calls the
    # scheduler pauses pop-dispatch for an exponentially growing window
    # (initial→max) instead of hot-looping failures against a dead
    # apiserver; any successful API call resets the trip counter and ends
    # the episode at the next window lapse. 0 threshold disables.
    degraded_threshold: int = 3
    degraded_initial_pause_s: float = 1.0
    degraded_max_pause_s: float = 30.0
    # Stuck-gang watchdog (sched/scheduler._StuckGangWatchdog): a gang with
    # pending/waiting members whose progress signature (bound+assumed count,
    # pending count, barrier population) has not moved for
    # `stuck_gang_after_s` is declared stuck — pinned `gang_stuck` anomaly,
    # `tpusched_gang_stuck_total`, a /debug/flightrecorder health entry, and
    # a forced reactivation of its parked members. The watchdog also
    # enforces permit-barrier deadlines missed by the event sweeper
    # (belt-and-braces: a wedged sweeper must not wedge gangs with it).
    # 0 disables.
    stuck_gang_after_s: float = 30.0
    stuck_gang_sweep_interval_s: float = 1.0
    # Scheduling SLO objectives (tpusched/obs/slo.py): latency targets for
    # pod first-enqueue→bound and PodGroup-to-Bound.  Breaches feed the
    # tpusched_slo_* burn metrics and the bench SLO summary; 0 disables an
    # objective.  Config YAML: `slo: {podE2ESeconds, gangBoundSeconds}`.
    slo_pod_e2e_s: float = 2.0
    slo_gang_bound_s: float = 2.0
    # Sharded dispatch (sched/shards.py, ROADMAP item 1): number of
    # per-pool dispatch lanes running scheduling cycles concurrently, each
    # over its pool partition with optimistic conflict resolution on the
    # cache's per-pool cursors; a serialized global lane handles pods whose
    # feasible pools span shards (multislice sets, explicit cross-shard
    # constraints, cross-quota borrowers).  1 (default) = the classic
    # single dispatch loop, byte-identical behavior to pre-sharding.
    # 0 = auto (min(4, cpu count)).  Config YAML: `dispatchShards`.
    dispatch_shards: int = 1
    # Shard escalation TTL override (sched/shards.ESCALATION_TTL_S default
    # 30 s): how long an escalated unit stays routed to the global lane
    # before returning to its home shard.  None = default.  Deterministic
    # replay pins it to the whole run (a wall-clock TTL lapsing mid-replay
    # re-routes a unit at a run-dependent event boundary).
    escalation_ttl_s: Optional[float] = None
    # LEGACY quota serialization (pre-ISSUE-14 behavior): route EVERY pod
    # through the global lane whenever any ElasticQuota exists, instead of
    # the quota-aware optimistic commit protocol (cache quota epoch
    # compare-and-reserve).  Kept as the A/B baseline arm for
    # bench.py --storm-quota and as an operational escape hatch
    # (doc/ops.md).  Config YAML: `quotaSerializeDispatch`.
    quota_serialize_dispatch: bool = False
    # _BindingPool worker count. 0 = auto, sized relative to the dispatch
    # shard count (2 workers per lane, floor 4, cap 32) so bind submission
    # from N concurrent lanes does not become the new serialization point.
    # Config YAML: `bindPoolWorkers`.
    bind_pool_workers: int = 0
    # Incremental torus window index (topology/windowindex.py, ISSUE 13):
    # per-(pool, shape) occupancy planes + window survivor/membership
    # tables maintained O(Δcells) from cache transitions, serving
    # TopologyMatch's PreFilter sweep, the capacity collector and the
    # defrag pre-gate as table lookups.  False (or the
    # TPUSCHED_NO_WINDOW_INDEX=1 env) keeps the classic per-cycle Python
    # recompute as the only path.
    torus_window_index: bool = True
    # Native batched dispatch inner loop (sched/nativedispatch.py, ISSUE
    # 16): evaluate covered cycles' whole Filter→Score sweep in one
    # GIL-released C++ call (native/torus_engine.cc), re-entering Python
    # only for PreScore/argmax and the guarded commit.  False (or
    # TPUSCHED_NO_NATIVE=1 / TPUSCHED_NATIVE_DISPATCH=0) keeps the
    # pure-Python sweep as the only path.  Config YAML: `nativeDispatch`.
    native_dispatch: bool = True
    # Sampled in-cycle differential oracle: every Nth native cycle per
    # lane ALSO runs the pure-Python sweep and asserts the identical
    # placement (mismatches count
    # tpusched_native_dispatch_differential_mismatches_total and the
    # oracle's answer wins).  0 disables; the TPUSCHED_NATIVE_DIFFERENTIAL
    # env overrides.  Config YAML: `nativeDispatchDifferentialPeriod`.
    native_dispatch_differential_period: int = 0

    def effective_dispatch_shards(self) -> int:
        """Resolve the auto (0) setting; always >= 1."""
        if self.dispatch_shards > 0:
            return self.dispatch_shards
        import os
        return max(1, min(4, os.cpu_count() or 1))

    def all_plugin_names(self) -> List[str]:
        names: List[str] = [self.queue_sort]
        for lst in (self.pre_filter, self.filter, self.post_filter,
                    self.pre_score, self.reserve, self.permit, self.pre_bind,
                    self.bind, self.post_bind):
            names.extend(lst)
        names.extend(n for n, _ in self.score)
        seen, out = set(), []
        for n in names:
            if n and n not in seen:
                seen.add(n)
                out.append(n)
        return out


class Registry(Dict[str, Callable[[Any, "Handle"], Plugin]]):
    """name → factory(args, handle). Mirrors app.WithPlugin registration
    (/root/reference/cmd/scheduler/main.go:34-47)."""

    def register(self, name: str, factory) -> None:
        if name in self:
            raise ValueError(f"plugin {name} already registered")
        self[name] = factory


class _WaitingPod:
    """A pod parked at Permit. Per-plugin deadlines; any rejection or any
    plugin's timeout rejects the pod; all allowed ⇒ proceed to bind.

    ``clock`` is the now-read the deadlines live on (the framework passes
    its handle clock's): under virtual-time replay the permit window is a
    real armed deadline the driver jumps to, not a wall wait."""

    def __init__(self, pod: Pod, plugin_timeouts: Dict[str, float],
                 clock=None):
        self.pod = pod
        self._cond = threading.Condition()
        self._clock = clock or time.monotonic
        now = self._clock()
        self._pending: Dict[str, float] = {p: now + t for p, t in plugin_timeouts.items()}
        self._status: Optional[Status] = None
        self._callbacks: List = []

    def get_pending_plugins(self) -> List[str]:
        with self._cond:
            return list(self._pending)

    def _take_callbacks_locked(self) -> List:
        cbs, self._callbacks = self._callbacks, []
        return cbs

    @staticmethod
    def _fire(cbs: List, status: Status) -> None:
        for cb in cbs:
            cb(status)

    def add_done_callback(self, fn) -> None:
        """fn(status) exactly once when the barrier resolves (allow-all,
        rejection, or deadline) — immediately if it already has. The
        callback runs on whichever thread resolves the pod; keep it cheap
        (the scheduler's hands the bind off to its worker pool)."""
        with self._cond:
            if self._status is None:
                self._callbacks.append(fn)
                return
            status = self._status
        fn(status)

    def allow(self, plugin: str) -> None:
        fire: List = []
        with self._cond:
            self._pending.pop(plugin, None)
            if not self._pending and self._status is None:
                self._status = Status.success()
            if self._status is not None:
                fire = self._take_callbacks_locked()
            self._cond.notify_all()
        self._fire(fire, self._status)

    def reject(self, plugin: str, msg: str) -> None:
        with self._cond:
            if self._status is None:
                self._status = Status.unschedulable(msg).with_plugin(plugin)
            fire = self._take_callbacks_locked()
            self._cond.notify_all()
        self._fire(fire, self._status)

    def deadline(self) -> Optional[float]:
        """Earliest permit deadline (monotonic), None once resolved."""
        with self._cond:
            if self._status is not None or not self._pending:
                return None
            return min(self._pending.values())

    def expire_if_due(self, now: float) -> None:
        fire: List = []
        with self._cond:
            if self._status is None and self._pending \
                    and min(self._pending.values()) <= now:
                plugin = min(self._pending, key=self._pending.get)
                self._status = Status.unschedulable(
                    f"pod {self.pod.key} rejected: permit wait timeout"
                ).with_plugin(plugin)
                fire = self._take_callbacks_locked()
                self._cond.notify_all()
        self._fire(fire, self._status)

    def wait(self) -> Status:
        """Blocking wait (direct framework users only; the scheduler's
        binding path is callback-driven).  Under a VIRTUAL clock the
        remaining window is virtual seconds — the condition wait below
        still bounds real blocking, but deadline enforcement then comes
        from ``expire_if_due`` (driver/watchdog), not from this wait."""
        with self._cond:
            while self._status is None:
                if not self._pending:
                    self._status = Status.success()
                    break
                deadline = min(self._pending.values())
                remaining = deadline - self._clock()
                if remaining <= 0:
                    plugin = min(self._pending, key=self._pending.get)
                    self._status = Status.unschedulable(
                        f"pod {self.pod.key} rejected: permit wait timeout").with_plugin(plugin)
                    break
                self._cond.wait(timeout=remaining)
            return self._status


class PodNominator:
    """Tracks preemptor pods nominated to nodes (upstream PodNominator;
    the reference's tests carry a copied one, test/util/fake.go:103-247)."""

    def __init__(self):
        self._lock = threading.RLock()
        self._by_node: Dict[str, Dict[str, Pod]] = {}
        # bumped on every effective add/remove/update — the equivalence
        # cache's witness that NO nomination changed between a cached
        # entry's arming and its reuse (an empty map at both ends is not
        # enough: a nominate→un-nominate round trip in between ran
        # preemption machinery the entry never saw)
        self._generation = 0

    @property
    def generation(self) -> int:
        return self._generation

    def add_nominated_pod(self, pod: Pod, node_name: str) -> None:
        node = node_name or pod.status.nominated_node_name
        if not node:
            return
        with self._lock:
            self.delete_nominated_pod_if_exists(pod)
            self._by_node.setdefault(node, {})[pod.key] = pod
            self._generation += 1

    def delete_nominated_pod_if_exists(self, pod: Pod) -> None:
        with self._lock:
            for node, pods in list(self._by_node.items()):
                if pod.key in pods:
                    del pods[pod.key]
                    if not pods:
                        del self._by_node[node]
                    self._generation += 1

    def update_nominated_pod(self, old: Pod, new: Pod) -> None:
        with self._lock:
            self.delete_nominated_pod_if_exists(old)
            if new.status.nominated_node_name:
                self.add_nominated_pod(new, new.status.nominated_node_name)

    def nominated_pods_for_node(self, node_name: str) -> List[Pod]:
        with self._lock:
            return list(self._by_node.get(node_name, {}).values())

    def empty(self) -> bool:
        # Lock-free peek: callers use this only as a fast-path hint, and a
        # stale False merely takes the slow path.
        return not self._by_node


class Handle:
    """framework.Handle analog passed to plugin factories: cluster views,
    clients, the waitingPods map, and helper runs (SURVEY §3.1 init
    boundary)."""

    # cache quota-ledger accessor (sched.Cache.quota_view), attached by
    # the scheduler after cache construction: CapacityScheduling's
    # PreFilter reads its admission inputs (per-quota min/max/used)
    # through it so the sharded commit's semantic compare-and-reserve
    # judges the same arithmetic on live state.  None = no ledger
    # (standalone plugin construction in unit tests; the plugin falls
    # back to its own informer mirror).
    quota_view = None
    # companion accessor (sched.Cache.quota_bounds_signature): the
    # equivalence cache's quota fingerprint input under guarded commits
    quota_bounds_signature = None
    # True when EVERY commit in this scheduler passes through the guarded
    # assume (sharded dispatch): the precondition for keeping the
    # equivalence cache warm under ElasticQuotas — a stale memoized quota
    # admission is then caught at the commit's semantic re-check instead
    # of slipping into an unguarded assume_pod.
    quota_guarded_commits = False

    def __init__(self, clientset, informer_factory, framework_getter,
                 clock=time.time, clock_handle=None):
        from ..util.clock import as_clock
        self.clientset = clientset
        self.informer_factory = informer_factory
        self._framework_getter = framework_getter
        self.clock = clock
        # the full Clock object (util/clock): wall/mono reads PLUS the
        # deadline registry.  Plugins and the framework route their gate
        # clocks (denial windows, permit deadlines, flush windows)
        # through this so a VirtualClock replay sees every lapse as an
        # armed deadline instead of a wall wait.
        self.clock_handle = clock_handle if clock_handle is not None \
            else as_clock(clock)
        self.pod_nominator = PodNominator()
        self._snapshot: Snapshot = Snapshot()
        # Per-thread snapshot slot for concurrent dispatch lanes (sharded
        # scheduling runs cycles on several threads at once, each against
        # its own epoch view); the shared slot above stays as the fallback
        # for threads that never set one — binding-pool workers and
        # informer callbacks running Unreserve read the most recent cycle's
        # view there, exactly as they did pre-sharding.
        self._snapshot_tls = threading.local()

    # Snapshot (updated by the scheduler at cycle start) ----------------------
    def snapshot_shared_lister(self) -> Snapshot:
        snap = getattr(self._snapshot_tls, "snap", None)
        return snap if snap is not None else self._snapshot

    # Dispatch scope: '' = fleet-wide candidates (single loop / global
    # lane), 'partition' = a shard lane's pool-restricted view.  Plugins
    # whose verdicts are cached process-globally (Coscheduling's
    # denied-PodGroup window) consult this so a partition-scoped shortfall
    # is never promoted into a fleet-wide denial — the escalated retry on
    # the global lane must not be poisoned by its own shard's miss.
    def dispatch_scope(self) -> str:
        return getattr(self._snapshot_tls, "scope", "")

    def set_dispatch_scope(self, scope: str) -> None:
        self._snapshot_tls.scope = scope

    def set_snapshot(self, snap: Snapshot, shared: bool = True) -> None:
        """``shared=False`` installs the snapshot for THIS thread only —
        shard lanes use it for their partition-restricted views, which
        must never become the fallback other threads read (a bind worker
        resolving another lane's pod would see a world without its
        node)."""
        if shared:
            self._snapshot = snap
        self._snapshot_tls.snap = snap

    # Framework passthroughs --------------------------------------------------
    @property
    def framework(self) -> "Framework":
        return self._framework_getter()

    def iterate_over_waiting_pods(self, fn: Callable[[_WaitingPod], None]) -> None:
        self.framework.iterate_over_waiting_pods(fn)

    def get_waiting_pod(self, uid: str) -> Optional[_WaitingPod]:
        return self.framework.get_waiting_pod(uid)

    def reject_waiting_pod(self, uid: str, plugin: str = "", msg: str = "") -> bool:
        return self.framework.reject_waiting_pod(uid, plugin, msg)

    def run_filter_plugins_with_nominated_pods(self, state: CycleState, pod: Pod,
                                               node_info: NodeInfo) -> Status:
        return self.framework.run_filter_plugins_with_nominated_pods(state, pod, node_info)

    def record_event(self, obj_key: str, kind: str, etype: str, reason: str,
                     message: str = "") -> None:
        self.clientset.record_event(obj_key, kind, etype, reason, message)



def _timed_plugin(point: str, plugin_name: str, fn, *args):
    """plugin_execution_duration_seconds{plugin,extension_point} recorder
    (upstream parity) + the per-plugin child span of the active cycle trace
    (it nests under the extension-point span the scheduler opened, and
    reuses the metric's perf_counter reads — tracing adds one tuple append,
    no attrs dict: the parent span IS the extension point). Wired only at
    the once-per-cycle extension points — the per-node Filter/Score sweeps
    stay unrecorded per plugin on purpose (an observation per plugin per
    node per pod would cost more than the plugin bodies; the whole-sweep
    number lives in framework_extension_point_duration_seconds instead)."""
    hist = plugin_execution_seconds.with_labels(plugin_name, point)
    # profiler attribution (obs/profiler): one thread-local list store each
    # way — the sampler reads it cross-thread, so a sample taken inside the
    # plugin body lands as "point/plugin", not just a Python frame
    prev_plugin = tracectx.set_plugin(plugin_name)
    t0 = time.perf_counter()
    try:
        return fn(*args)
    finally:
        dur = time.perf_counter() - t0
        tracectx.set_plugin(prev_plugin)
        hist.observe(dur)
        tr = trace.current()
        if tr is not None:
            # inlined CycleTrace.add_event — this is the hottest trace
            # write and the method-call overhead is measurable here
            ev = tr._events
            if len(ev) < trace.MAX_SPANS_PER_TRACE:
                ev.append((plugin_name, t0 - tr.perf_start, dur, None))
            else:
                tr.truncated += 1


class Framework:
    """One profile's compiled plugin set."""

    def __init__(self, registry: Registry, profile: PluginProfile, handle: Handle):
        from ..util.clock import WALL
        self.profile = profile
        self.handle = handle
        # gate clock for the permit barrier (handles built before the
        # clock_handle attr existed fall back to the wall singleton)
        self._clock_handle = getattr(handle, "clock_handle", None) or WALL
        self._now = self._clock_handle.now
        self._waiting: Dict[str, _WaitingPod] = {}
        self._waiting_lock = threading.RLock()
        # deadline sweeper for the event-driven permit barrier: started
        # lazily on the first waiting pod; woken on registration and close
        self._waiting_cv = threading.Condition(self._waiting_lock)
        self._sweeper: Optional[threading.Thread] = None
        # earliest outstanding permit deadline the sweeper is sleeping
        # toward (monotonic); None = no horizon. Inserters notify ONLY when
        # they shrink it, so a gang of same-timeout waiters (deadlines
        # strictly increasing) wakes the sweeper exactly once — without
        # this, every arrival woke an O(n) rescan: O(n^2) per gang.
        self._permit_horizon: Optional[float] = None
        self._closed = False

        plugins: Dict[str, Plugin] = {}
        for name in profile.all_plugin_names():
            if name not in registry:
                raise ValueError(f"plugin {name!r} not in registry")
            plugins[name] = registry[name](profile.plugin_args.get(name), handle)
        self.plugins = plugins

        def _bucket(names: Iterable[str], cls) -> List[Plugin]:
            out = []
            for n in names:
                p = plugins[n]
                if not isinstance(p, cls):
                    raise TypeError(f"plugin {n} does not implement {cls.__name__}")
                out.append(p)
            return out

        self.queue_sort_plugin: QueueSortPlugin = _bucket([profile.queue_sort], QueueSortPlugin)[0]
        self.pre_filter_plugins = _bucket(profile.pre_filter, PreFilterPlugin)
        self.filter_plugins = _bucket(profile.filter, FilterPlugin)
        # Hot-loop dispatch table: (name, bound filter method) resolved once —
        # filter runs plugins×nodes times per cycle and name()/attr lookups
        # dominate the Python-side overhead otherwise.
        self._filter_dispatch = [(p.name(), p.filter) for p in self.filter_plugins]
        # Plugins with a vectorized whole-fleet path (BatchFilterPlugin): the
        # scheduler runs these once over all candidate nodes, then excludes
        # them from the per-node sweep (sched/scheduler.py).
        self.batch_filter_plugins = [
            p for p in self.filter_plugins if isinstance(p, BatchFilterPlugin)]
        # Equivalence-cache fast path (sched/equivcache.py): the subset of
        # filters whose verdict can change between cycles of equivalent pods
        # while only same-class assumes moved the mutation cursor (resource/
        # chip fit). A cache hit re-runs ONLY these over the cached feasible
        # set; EQUIV_DYNAMIC=False plugins were already decided by the entry.
        # Batch-capable dynamics keep their vectorized path on hits too
        # (the scheduler runs filter_batch over the cached set first).
        self.dynamic_batch_filter_plugins = [
            p for p in self.batch_filter_plugins
            if getattr(type(p), "EQUIV_DYNAMIC", True)]
        batch_names = {p.name() for p in self.dynamic_batch_filter_plugins}
        self._dynamic_filter_dispatch = [
            (p.name(), p.filter) for p in self.filter_plugins
            if getattr(type(p), "EQUIV_DYNAMIC", True)
            and p.name() not in batch_names]
        # PreFilter/Filter plugins carrying cache-invisible state: their
        # fingerprints gate entry creation and revalidate every lookup.
        from .interfaces import EquivalenceAware
        seen_eq: Dict[str, Plugin] = {}
        for p in list(self.pre_filter_plugins) + list(self.filter_plugins):
            if isinstance(p, EquivalenceAware) and p.name() not in seen_eq:
                seen_eq[p.name()] = p
        self.equiv_aware_plugins = list(seen_eq.values())
        # Optional per-node parallelism for score (scheduler injects the
        # shared pool; None = serial, the default for bare Frameworks/tests)
        self.parallelizer = None
        self.post_filter_plugins = _bucket(profile.post_filter, PostFilterPlugin)
        self.pre_score_plugins = _bucket(profile.pre_score, PreScorePlugin)
        self.score_plugins: List[Tuple[ScorePlugin, int]] = [
            (p, w) for (p, w) in zip(_bucket([n for n, _ in profile.score], ScorePlugin),
                                     [w for _, w in profile.score])]
        self.reserve_plugins = _bucket(profile.reserve, ReservePlugin)
        self.permit_plugins = _bucket(profile.permit, PermitPlugin)
        self.pre_bind_plugins = _bucket(profile.pre_bind, PreBindPlugin)
        self.bind_plugins = _bucket(profile.bind, BindPlugin)
        self.post_bind_plugins = _bucket(profile.post_bind, PostBindPlugin)

    # -- queue sort ----------------------------------------------------------
    def less(self, pi1, pi2) -> bool:
        return self.queue_sort_plugin.less(pi1, pi2)

    # -- prefilter -----------------------------------------------------------
    def run_pre_filter_plugins(self, state: CycleState, pod: Pod) -> Status:
        for p in self.pre_filter_plugins:
            s = _timed_plugin("PreFilter", p.name(), p.pre_filter, state, pod)
            if s.is_skip():
                state.skip_filter_plugins.add(p.name())
                continue
            if not s.is_success():
                return s.with_plugin(p.name())
        return Status.success()

    def run_pre_filter_extension_add_pod(self, state: CycleState, pod: Pod,
                                         pod_to_add: Pod, node_info: NodeInfo) -> Status:
        for p in self.pre_filter_plugins:
            ext = p.pre_filter_extensions()
            if ext is None:
                continue
            s = ext.add_pod(state, pod, pod_to_add, node_info)
            if not s.is_success():
                return s.with_plugin(p.name())
        return Status.success()

    def run_pre_filter_extension_remove_pod(self, state: CycleState, pod: Pod,
                                            pod_to_remove: Pod, node_info: NodeInfo) -> Status:
        for p in self.pre_filter_plugins:
            ext = p.pre_filter_extensions()
            if ext is None:
                continue
            s = ext.remove_pod(state, pod, pod_to_remove, node_info)
            if not s.is_success():
                return s.with_plugin(p.name())
        return Status.success()

    # -- filter --------------------------------------------------------------
    def run_filter_plugins(self, state: CycleState, pod: Pod,
                           node_info: NodeInfo,
                           exclude: frozenset = frozenset()) -> Status:
        """``exclude`` skips plugins the caller already evaluated for this
        node via their batch path (scheduler's vectorized pre-pass)."""
        skip = state.skip_filter_plugins
        for name, filter_fn in self._filter_dispatch:
            if name in skip or name in exclude:
                continue
            s = filter_fn(state, pod, node_info)
            if not s.is_success():
                return s.with_plugin(name)
        return Status.success()

    def run_dynamic_filter_plugins(self, state: CycleState, pod: Pod,
                                   node_info: NodeInfo) -> Status:
        """Equivalence-cache hit path: only the capacity-consuming filters
        re-run over a cached feasible node (static verdicts are byte-stable
        while the entry is armed — see FilterPlugin.EQUIV_DYNAMIC), and
        batch-capable dynamics are excluded here (the scheduler already ran
        their filter_batch over the whole cached set). The caller guarantees
        no nominated pods exist (hits are impossible otherwise)."""
        skip = state.skip_filter_plugins
        for name, filter_fn in self._dynamic_filter_dispatch:
            if name in skip:
                continue
            s = filter_fn(state, pod, node_info)
            if not s.is_success():
                return s.with_plugin(name)
        return Status.success()

    def run_filter_plugins_with_nominated_pods(self, state: CycleState, pod: Pod,
                                               node_info: NodeInfo,
                                               exclude: frozenset = frozenset()) -> Status:
        """Upstream semantics: evaluate twice when higher-priority nominated
        pods exist on the node — once assuming they are running, once not.
        ``exclude`` only applies on the no-nominated-pods fast path: a
        nominated dry-run mutates node state, so every plugin must re-run."""
        if self.handle.pod_nominator.empty():
            return self.run_filter_plugins(state, pod, node_info, exclude)
        nominated = [p for p in self.handle.pod_nominator.nominated_pods_for_node(
            node_info.node.name) if p.priority >= pod.priority and p.key != pod.key]
        for add_nominated in ([True, False] if nominated else [False]):
            state_to_use, info_to_use = state, node_info
            if add_nominated:
                state_to_use = state.clone()
                info_to_use = node_info.clone()
                for np in nominated:
                    info_to_use.add_pod(np)
                    s = self.run_pre_filter_extension_add_pod(state_to_use, pod, np, info_to_use)
                    if not s.is_success():
                        return s
            s = self.run_filter_plugins(state_to_use, pod, info_to_use)
            if not s.is_success():
                return s
        return Status.success()

    # -- postfilter ----------------------------------------------------------
    def run_post_filter_plugins(self, state: CycleState, pod: Pod,
                                filtered_node_status_map) -> Tuple[Optional[PostFilterResult], Status]:
        statuses: List[Status] = []
        for p in self.post_filter_plugins:
            result, s = _timed_plugin("PostFilter", p.name(), p.post_filter,
                                      state, pod, filtered_node_status_map)
            s = s.with_plugin(p.name())
            if s.is_success():
                return result, s
            if not s.is_unschedulable():
                return None, s
            statuses.append(s)
        return None, merge_statuses(statuses) if statuses else Status.unschedulable("no postfilter plugins")

    # -- score ---------------------------------------------------------------
    def run_pre_score_plugins(self, state: CycleState, pod: Pod,
                              nodes: List[Node]) -> Status:
        for p in self.pre_score_plugins:
            s = _timed_plugin("PreScore", p.name(), p.pre_score, state, pod,
                              nodes)
            if s.is_skip():
                state.skip_score_plugins.add(p.name())
                continue
            if not s.is_success():
                return s.with_plugin(p.name())
        return Status.success()

    def run_score_plugins(self, state: CycleState, pod: Pod,
                          nodes: List[Node]) -> Tuple[Dict[str, int], Status]:
        """Returns total weighted score per node name."""
        totals: Dict[str, int] = {n.name: 0 for n in nodes}
        par = self.parallelizer
        for plugin, weight in self.score_plugins:
            if plugin.name() in state.skip_score_plugins:
                continue
            if par is not None and len(nodes) >= 64:
                # upstream prioritizeNodes parallelism
                # (generic_scheduler.go:426): score nodes concurrently; a
                # score() must already be safe under the parallel Filter
                # contract (read-only on shared state / idempotent memos).
                # Pool workers carry no cycle context, so the CALLING
                # cycle's snapshot is installed into each worker's thread-
                # local slot — without this a score() reading the shared
                # lister on a worker thread would see whatever fallback
                # snapshot happens to be installed (under sharded dispatch
                # possibly none at all), not this cycle's epoch view.
                snap = self.handle.snapshot_shared_lister()

                def score_at(i, _snap=snap, _plugin=plugin):
                    self.handle.set_snapshot(_snap, shared=False)
                    return _plugin.score(state, pod, nodes[i].name)
                results = par.map(score_at, len(nodes))
                scores = []
                for n, (val, s) in zip(nodes, results):
                    if not s.is_success():
                        return {}, s.with_plugin(plugin.name())
                    scores.append(NodeScore(n.name, val))
            else:
                scores = []
                for n in nodes:
                    val, s = plugin.score(state, pod, n.name)
                    if not s.is_success():
                        return {}, s.with_plugin(plugin.name())
                    scores.append(NodeScore(n.name, val))
            ns = plugin.normalize_score(state, pod, scores)
            if ns is not None and not ns.is_success():
                return {}, ns.with_plugin(plugin.name())
            for sc in scores:
                if not (0 <= sc.score <= MAX_NODE_SCORE):
                    return {}, Status.error(
                        f"plugin {plugin.name()} returned invalid score {sc.score} for node {sc.name}")
                totals[sc.name] += sc.score * weight
        return totals, Status.success()

    # -- reserve -------------------------------------------------------------
    def run_reserve_plugins_reserve(self, state: CycleState, pod: Pod,
                                    node_name: str) -> Status:
        for i, p in enumerate(self.reserve_plugins):
            s = _timed_plugin("Reserve", p.name(), p.reserve, state, pod,
                              node_name)
            if not s.is_success():
                for q in reversed(self.reserve_plugins[:i]):
                    _timed_plugin("Unreserve", q.name(), q.unreserve, state,
                                  pod, node_name)
                return s.with_plugin(p.name())
        return Status.success()

    def run_reserve_plugins_unreserve(self, state: CycleState, pod: Pod,
                                      node_name: str) -> None:
        for p in reversed(self.reserve_plugins):
            _timed_plugin("Unreserve", p.name(), p.unreserve, state, pod,
                          node_name)

    # -- permit --------------------------------------------------------------
    def run_permit_plugins(self, state: CycleState, pod: Pod,
                           node_name: str) -> Status:
        plugin_timeouts: Dict[str, float] = {}
        status_code = Status.success()
        for p in self.permit_plugins:
            s, timeout = _timed_plugin("Permit", p.name(), p.permit, state,
                                       pod, node_name)
            if s.is_success():
                continue
            if s.is_wait():
                plugin_timeouts[p.name()] = timeout
                continue
            return s.with_plugin(p.name())
        if plugin_timeouts:
            with self._waiting_cv:
                if self._closed:
                    # closing framework: nothing will ever resolve or expire
                    # this barrier — fail the pod now instead of leaking its
                    # reserved state
                    return Status.unschedulable(
                        f"pod {pod.key} rejected: framework is closing")
                wp = _WaitingPod(pod, plugin_timeouts, clock=self._now)
                self._waiting[pod.meta.uid] = wp
                if self._sweeper is None:
                    self._sweeper = threading.Thread(
                        target=self._sweep_permit_deadlines,
                        name="tpusched-permit-sweeper", daemon=True)
                    self._sweeper.start()
                d = wp.deadline()
                if d is not None:
                    # every permit deadline is an armed gate: a virtual-
                    # time replay driver jumps to it and expires the
                    # barrier via expire_due_permits (a stale fire after
                    # early resolution is harmless — expire_if_due is
                    # idempotent on resolved pods)
                    self._clock_handle.arm("permit", d)
                if d is not None and (self._permit_horizon is None
                                      or d < self._permit_horizon):
                    self._permit_horizon = d
                    self._waiting_cv.notify_all()
            # post-registration hooks, OUTSIDE the waiting lock (a hook's
            # own serialization may be held by a thread that is sweeping
            # the waiting map — calling under the lock would invert the
            # order and deadlock): each wait-requesting plugin gets one
            # chance to re-check conditions a sweep could have changed
            # while this pod was between permit() and registration.
            # Guarded per plugin: the pod is already parked (committed) —
            # a raising hook must degrade to "hook never ran" (the barrier
            # timeout still bounds the pod), not abort a cycle whose
            # waiting-map entry would then leak unresolved forever.
            for p in self.permit_plugins:
                if p.name() in plugin_timeouts:
                    try:
                        p.on_pod_waiting(wp)
                    except Exception as e:  # noqa: BLE001
                        klog.error_s(e, "on_pod_waiting hook failed",
                                     plugin=p.name(), pod=pod.key)
            return Status.wait()
        return status_code

    def wait_on_permit(self, pod: Pod) -> Status:
        """Blocking WaitOnPermit (upstream scheduler.go:557 shape). The
        scheduler's binding path uses notify_on_permit instead — one parked
        OS thread per gang member doesn't survive contact with 256-pod
        gangs; this stays for API parity and direct framework users."""
        with self._waiting_lock:
            wp = self._waiting.get(pod.meta.uid)
        if wp is None:
            return Status.success()
        try:
            return wp.wait()
        finally:
            with self._waiting_lock:
                self._waiting.pop(pod.meta.uid, None)

    def notify_on_permit(self, pod: Pod, fn) -> None:
        """Event-driven WaitOnPermit: fn(status) fires exactly once when the
        pod's permit barrier resolves (immediately if the pod is not
        waiting). The waitingPods entry is removed before fn runs."""
        with self._waiting_lock:
            wp = self._waiting.get(pod.meta.uid)
        if wp is None:
            fn(Status.success())
            return

        def done(status: Status) -> None:
            with self._waiting_lock:
                self._waiting.pop(pod.meta.uid, None)
            fn(status)

        wp.add_done_callback(done)

    def _sweep_permit_deadlines(self) -> None:
        """Enforce permit timeouts for callback-mode waiters: sleeps until
        the earliest outstanding deadline, then expires due pods. wait()
        callers enforce their own deadline; expire_if_due is a no-op on
        already-resolved pods, so the two paths compose."""
        while True:
            with self._waiting_cv:
                if self._closed:
                    return
                nxt = None
                for wp in self._waiting.values():
                    d = wp.deadline()
                    if d is not None and (nxt is None or d < nxt):
                        nxt = d
                self._permit_horizon = nxt
                # under a VIRTUAL clock the horizon is virtual seconds
                # away — a real-time wait toward it would either spin or
                # oversleep.  The sweeper goes purely event-driven there;
                # deadline enforcement comes from the replay driver
                # (expire_due_permits after each clock advance) and the
                # watchdog's belt-and-braces expire_if_due.
                timeout = None if (nxt is None
                                   or self._clock_handle.virtual) \
                    else max(0.01, nxt - self._now())
                self._waiting_cv.wait(timeout=timeout)
                if self._closed:
                    return
                # a wake before the horizon means an inserter SHRANK it
                # (inserters only notify then): nothing can be due yet,
                # recompute the horizon without sweeping the waiters
                now = self._now()
                horizon = self._permit_horizon
                if horizon is None or now < horizon:
                    continue
                due = [wp for wp in self._waiting.values()
                       if (d := wp.deadline()) is not None and d <= now]
            for wp in due:  # fires callbacks — never under the lock
                wp.expire_if_due(now)

    def expire_due_permits(self, now: Optional[float] = None) -> int:
        """Enforce every lapsed permit deadline NOW (idempotent on
        resolved pods).  The virtual-time replay driver calls this after
        each clock advance — the real-time sweeper thread cannot pace
        itself against a clock that only moves when driven.  Returns how
        many barriers actually expired: their resolution callbacks hand
        work to the bind pool ASYNCHRONOUSLY, so the driver must settle
        whenever this is nonzero (a queue-side probe alone can miss the
        in-flight hand-off)."""
        if now is None:
            now = self._now()
        with self._waiting_lock:
            pods = list(self._waiting.values())
        expired = 0
        for wp in pods:             # fires callbacks — never under the lock
            # single read: a concurrent resolution between two deadline()
            # calls would turn the second into None mid-comparison
            d = wp.deadline()
            if d is not None and d <= now:
                expired += 1
            wp.expire_if_due(now)
        return expired

    def iterate_over_waiting_pods(self, fn) -> None:
        with self._waiting_lock:
            pods = list(self._waiting.values())
        for wp in pods:
            fn(wp)

    def get_waiting_pod(self, uid: str) -> Optional[_WaitingPod]:
        with self._waiting_lock:
            return self._waiting.get(uid)

    def reject_waiting_pod(self, uid: str, plugin: str = "", msg: str = "") -> bool:
        with self._waiting_lock:
            wp = self._waiting.get(uid)
        if wp is None:
            return False
        wp.reject(plugin, msg)
        return True

    # -- bind ----------------------------------------------------------------
    def run_pre_bind_plugins(self, state: CycleState, pod: Pod,
                             node_name: str) -> Status:
        for p in self.pre_bind_plugins:
            s = _timed_plugin("PreBind", p.name(), p.pre_bind, state, pod,
                              node_name)
            if not s.is_success():
                return s.with_plugin(p.name())
        return Status.success()

    def run_bind_plugins(self, state: CycleState, pod: Pod,
                         node_name: str) -> Status:
        if not self.bind_plugins:
            return Status.error("no bind plugin configured")
        for p in self.bind_plugins:
            s = _timed_plugin("Bind", p.name(), p.bind, state, pod, node_name)
            if s.is_skip():
                continue
            return s.with_plugin(p.name()) if not s.is_success() else s
        return Status.error("all bind plugins skipped")

    def run_post_bind_plugins(self, state: CycleState, pod: Pod,
                              node_name: str) -> None:
        for p in self.post_bind_plugins:
            _timed_plugin("PostBind", p.name(), p.post_bind, state, pod,
                          node_name)

    # -- lifecycle -----------------------------------------------------------
    def close(self) -> None:
        """Release plugin background resources (collector threads etc.).
        Any pod still at the permit barrier is rejected first — once the
        sweeper dies nothing would ever resolve it, and its callback is what
        runs the unreserve/forget failure path."""
        with self._waiting_cv:
            self._closed = True
            stragglers = list(self._waiting.values())
            self._waiting_cv.notify_all()
        for wp in stragglers:
            wp.reject("", "framework closing")
        if self._sweeper is not None:
            self._sweeper.join(timeout=5)
            self._sweeper = None
        for p in self.plugins.values():
            closer = getattr(p, "close", None)
            if callable(closer):
                try:
                    closer()
                except Exception as e:
                    klog.error_s(e, "plugin close failed", plugin=p.name())

    # -- enqueue hints -------------------------------------------------------
    def events_to_register(self) -> List[ClusterEvent]:
        events: List[ClusterEvent] = []
        for p in self.plugins.values():
            if isinstance(p, EnqueueExtensions):
                events.extend(p.events_to_register())
        return events or [WILDCARD_EVENT]
