"""Plugin interfaces — the framework's typed extension points.

Each plugin implements the subset it needs and the framework dispatches by
isinstance (analog of `var _ framework.FilterPlugin = &FlexGPU{}` assertions,
/root/reference/pkg/flexgpu/flex_gpu.go:27-30).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Tuple

from ..api.core import Node, Pod
from .cycle_state import CycleState
from .nodeinfo import NodeInfo
from .status import Status

# Cluster-event resources/actions for requeue hints (EnqueueExtensions,
# /root/reference/pkg/coscheduling/coscheduling.go:93-101).
RESOURCE_POD = "Pod"
RESOURCE_NODE = "Node"
RESOURCE_POD_GROUP = "PodGroup"
RESOURCE_ELASTIC_QUOTA = "ElasticQuota"
RESOURCE_TPU_TOPOLOGY = "TpuTopology"

EVENT_ADD = 1
EVENT_UPDATE = 2
EVENT_DELETE = 4
EVENT_ALL = EVENT_ADD | EVENT_UPDATE | EVENT_DELETE


@dataclass(frozen=True)
class ClusterEvent:
    resource: str
    action_type: int

    def matches(self, resource: str, action: int) -> bool:
        return (self.resource in (resource, "*")) and bool(self.action_type & action)


WILDCARD_EVENT = ClusterEvent("*", EVENT_ALL)


@dataclass
class NodeScore:
    name: str
    score: int


@dataclass
class PostFilterResult:
    nominated_node_name: str = ""


class Plugin:
    NAME = "Plugin"

    def name(self) -> str:
        return self.NAME


class QueueSortPlugin(Plugin):
    def less(self, pod_info1, pod_info2) -> bool:
        raise NotImplementedError


class PreFilterExtensions:
    """Keeps PreFilter-computed state consistent while preemption dry-runs
    add/remove pods (capacity_scheduling.go:283-318)."""

    def add_pod(self, state: CycleState, pod_to_schedule: Pod,
                pod_to_add: Pod, node_info: NodeInfo) -> Status:
        return Status.success()

    def remove_pod(self, state: CycleState, pod_to_schedule: Pod,
                   pod_to_remove: Pod, node_info: NodeInfo) -> Status:
        return Status.success()


class PreFilterPlugin(Plugin):
    def pre_filter(self, state: CycleState, pod: Pod) -> Status:
        raise NotImplementedError

    def pre_filter_extensions(self) -> Optional[PreFilterExtensions]:
        return None


class EquivalenceAware:
    """Optional mixin for PreFilter/Filter plugins whose verdicts read state
    the equivalence cache's mutation cursor cannot see (PodGroup/topology CR
    specs, TTL'd denial windows, freed-window claims, sibling counts,
    quota mirrors).

    ``equiv_fingerprint`` returns hashable key material covering exactly
    those inputs; the scheduler stores it at entry creation and recomputes
    it at every lookup — any difference invalidates the entry. Returning
    ``None`` VETOES the fast path for this pod (the plugin cannot prove its
    PreFilter output is reusable, e.g. TopologyMatch with multiple surviving
    placement windows, CapacityScheduling while quotas exist).

    ``state`` is the just-completed cycle's CycleState at entry creation and
    ``None`` at lookup revalidation. The two computations are compared for
    equality, so by default the returned material must NOT depend on
    ``state`` — consult it only for the veto decision. The one sanctioned
    exception is *predicting the post-Reserve value* of a field this
    cycle's own Reserve is about to write: TopologyMatch normalizes its
    pool pin this way (an unpinned arming cycle with exactly one surviving
    window fingerprints the pool Reserve will pin, so the next sibling's
    pinned lookup still matches). Use that pattern only when the creation
    cycle can prove what the lookup-time value will be — and note a failed
    Reserve that never writes the field just costs a safe miss."""

    def equiv_fingerprint(self, pod: Pod, state: Optional[CycleState]):
        return None


class FilterPlugin(Plugin):
    # Equivalence-cache classification (sched/equivcache.py). True (the
    # conservative default) means this plugin's verdict can change between
    # two cycles of EQUIVALENT pods even while the cache mutation cursor
    # only advanced by the scheduler's own same-class assumes — i.e. it
    # reads consumable capacity (resource fit, chip fit) — so the cached
    # fast path must re-run it over the cached feasible set. False is a
    # plugin's promise that its verdict depends only on (node object,
    # pod-equivalence fields, PreFilter-cached cycle state): those inputs
    # are byte-identical while an entry is armed (any node/pod change
    # invalidates), so re-running it would be pure waste.
    EQUIV_DYNAMIC = True

    def filter(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Status:
        raise NotImplementedError


class BatchFilterPlugin(FilterPlugin):
    """Optional vectorized fast path over the whole candidate node list.

    ``filter_batch`` must be semantically identical to calling ``filter``
    per node on the SAME node_infos: entry i is None when node i passes,
    else the failure Status. The scheduler uses it as a pre-pass when no
    nominated pods are in play (a nominated-pod dry-run mutates per-node
    state the batch pass cannot see, so those nodes take the per-node
    path). Upstream has no analog — its per-node parallelism is goroutines
    (generic_scheduler.go:266); here the TPU-first equivalent is
    vectorizing the fleet-wide checks with numpy, which also sidesteps the
    GIL entirely for the heavy part.
    """

    def filter_batch(self, state: CycleState, pod: Pod,
                     node_infos) -> List[Optional[Status]]:
        raise NotImplementedError


class PostFilterPlugin(Plugin):
    def post_filter(self, state: CycleState, pod: Pod,
                    filtered_node_status_map) -> Tuple[Optional[PostFilterResult], Status]:
        raise NotImplementedError


class PreScorePlugin(Plugin):
    def pre_score(self, state: CycleState, pod: Pod, nodes: List[Node]) -> Status:
        raise NotImplementedError


class ScorePlugin(Plugin):
    def score(self, state: CycleState, pod: Pod, node_name: str) -> Tuple[int, Status]:
        raise NotImplementedError

    def normalize_score(self, state: CycleState, pod: Pod,
                        scores: List[NodeScore]) -> Optional[Status]:
        return None  # None ⇒ no score extension


class ReservePlugin(Plugin):
    def reserve(self, state: CycleState, pod: Pod, node_name: str) -> Status:
        raise NotImplementedError

    def unreserve(self, state: CycleState, pod: Pod, node_name: str) -> None:
        pass


class PermitPlugin(Plugin):
    def permit(self, state: CycleState, pod: Pod,
               node_name: str) -> Tuple[Status, float]:
        """Returns (status, timeout_seconds). Wait status parks the pod in
        waitingPods until Allow/Reject/timeout."""
        raise NotImplementedError

    def on_pod_waiting(self, waiting_pod) -> None:
        """Called once, without framework locks held, right AFTER a pod this
        plugin asked to Wait was registered in the waitingPods map. A mass
        rejection that ran between permit() returning Wait and the
        registration iterates a map the pod was not yet in — this hook is
        where a plugin re-checks such a condition and resolves the pod
        (``waiting_pod.reject`` is idempotent) instead of stranding it at
        the barrier until its timeout. Default: nothing."""


class PreBindPlugin(Plugin):
    def pre_bind(self, state: CycleState, pod: Pod, node_name: str) -> Status:
        raise NotImplementedError


class BindPlugin(Plugin):
    def bind(self, state: CycleState, pod: Pod, node_name: str) -> Status:
        raise NotImplementedError


class PostBindPlugin(Plugin):
    def post_bind(self, state: CycleState, pod: Pod, node_name: str) -> None:
        pass


class EnqueueExtensions:
    """Optional mixin: plugins declare which cluster events can make pods they
    rejected schedulable again."""

    def events_to_register(self) -> List[ClusterEvent]:
        return [WILDCARD_EVENT]
