"""CycleState: per-scheduling-cycle key/value store for plugin data.

Plugins snapshot-clone state into CycleState at PreFilter and read/mutate it
through the cycle — the race-freedom discipline the reference relies on
(/root/reference/pkg/capacityscheduling/capacity_scheduling.go:83-93 clones
the ElasticQuota snapshot per cycle)."""
from __future__ import annotations

import threading
from typing import Any, Dict


class StateKeyNotFound(KeyError):
    pass


class CycleState:
    def __init__(self):
        self._lock = threading.RLock()
        self._data: Dict[str, Any] = {}
        # Set by the scheduler when preemption might still make the pod
        # schedulable (mirrors framework's recordPluginMetrics/skip flags).
        self.skip_score_plugins: set = set()
        self.skip_filter_plugins: set = set()
        # Upstream PreFilterResult.NodeNames: a PreFilter that already knows
        # the only viable hosts narrows the cycle to them; multiple calls
        # intersect. None = all nodes. The scheduler slices the candidate
        # list BEFORE the per-node Filter sweep — at fleet scale this is
        # the difference between sweeping 1024 hosts and the ~64 a slice
        # placement can actually use.
        self.restricted_node_names = None  # Optional[set]

    def restrict_nodes(self, names) -> None:
        s = names if isinstance(names, set) else set(names)
        with self._lock:
            self.restricted_node_names = (
                s if self.restricted_node_names is None
                else self.restricted_node_names & s)

    def write(self, key: str, value: Any) -> None:
        with self._lock:
            self._data[key] = value

    def read(self, key: str) -> Any:
        with self._lock:
            if key not in self._data:
                raise StateKeyNotFound(key)
            return self._data[key]

    def try_read(self, key: str) -> Any:
        with self._lock:
            return self._data.get(key)

    def delete(self, key: str) -> None:
        with self._lock:
            self._data.pop(key, None)

    def read_or_init(self, key: str, factory) -> Any:
        """Atomic get-or-create: under parallel Filter/Score, the lazy
        'try_read → write on miss' memo pattern loses entries (two threads
        both miss and install DIFFERENT containers); this makes the install
        atomic so every thread shares one."""
        with self._lock:
            v = self._data.get(key)
            if v is None:
                v = factory()
                self._data[key] = v
            return v

    def export(self, exclude=frozenset()) -> Dict[str, Any]:
        """Snapshot the data map for the equivalence cache (minus per-cycle
        scheduler keys). Values are shared by reference — install() applies
        the StateData.Clone discipline when they re-enter a cycle."""
        with self._lock:
            return {k: v for k, v in self._data.items() if k not in exclude}

    def install(self, data: Dict[str, Any]) -> None:
        """Replay an exported data map into this cycle, cloning values that
        implement .clone() (same contract as clone()) so a plugin mutating
        its cycle state cannot corrupt the cached original."""
        with self._lock:
            for k, v in data.items():
                self._data[k] = v.clone() if hasattr(v, "clone") else v

    def adopt(self, other: "CycleState") -> None:
        """Merge ``other``'s data map by REFERENCE — no re-clone. Only for
        a throwaway donor that is discarded right after the call (the
        equivalence-cache hit path committing its scratch state): cloning
        again here would clone values install() already cloned."""
        with self._lock:
            self._data.update(other._data)

    def clone(self) -> "CycleState":
        """Shallow clone; values implementing .clone() are cloned too
        (StateData.Clone contract)."""
        out = CycleState()
        with self._lock:
            for k, v in self._data.items():
                out._data[k] = v.clone() if hasattr(v, "clone") else v
            if self.restricted_node_names is not None:
                out.restricted_node_names = set(self.restricted_node_names)
        return out
