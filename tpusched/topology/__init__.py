"""ICI-torus topology engine (TPU-native successor of the reference's NUMA
bitmask fitting, /root/reference/pkg/noderesourcetopology/filter.go:84-150)."""
from .torus import (HostGrid, enumerate_placements, host_block_shape,
                    validate_slice_shape)

__all__ = ["HostGrid", "enumerate_placements", "host_block_shape",
           "validate_slice_shape"]
