"""ICI-torus topology engine (TPU-native successor of the reference's NUMA
bitmask fitting, /root/reference/pkg/noderesourcetopology/filter.go:84-150)."""
from .torus import (HostGrid, candidate_host_blocks, enumerate_placements,
                    host_block_shape, validate_slice_shape)

__all__ = ["HostGrid", "candidate_host_blocks", "enumerate_placements",
           "host_block_shape", "validate_slice_shape"]
