"""Incrementally-maintained torus window index (ISSUE 13, ROADMAP item 2).

Today every TopologyMatch PreFilter of a slice pod pays an O(pool-hosts)
occupancy snapshot scan plus an O(placements × words) feasibility sweep, and
the capacity collector independently re-derives the largest-placeable window
by existence-probing the placement generator.  This module replaces those
per-cycle recomputations with ONE index maintained O(Δcells) from the
scheduler cache's existing transition points:

- per-pool OCCUPANCY PLANES (free / capacity-free bitsets, per-gang cell
  masks, chip totals) derived from per-node facts fed by ``sched/cache.py``
  at assume/confirm/forget/add/remove/health-flip time, inside the cache's
  own critical sections;
- per-(pool, chip-shape) WINDOW INDEXES: the placement-mask set, cell→
  placement CSR posting lists, live per-placement blocked counts, survivor
  count, and per-cell membership — a plane delta re-evaluates only the
  placements whose masks intersect the touched cells
  (native ``tpusched_index_apply``; pure-Python twin below);
- one READ SURFACE shared by TopologyMatch (PreFilter/Filter/Score inputs,
  PostFilter's window search), the capacity collector
  (``pool_largest_placeable_chips`` / fragmentation) and the defrag
  advisor's pre-gate.

Consistency rule (the cursor-consistency contract, doc/performance.md):
every plane stores the per-pool mutation cursor it was updated at, written
ATOMICALLY with the data delta while the cache lock is held.  A reader may
consume an answer only when the plane's version equals the pool cursor its
OWN snapshot was captured at (``Snapshot.pool_cursors`` — since ISSUE 14
the cache's persistent ``PooledSnapshot`` carries these as the same
per-pool cursors its sub-maps were composed at, so the index's planes and
the snapshot's pool sub-maps are versioned by ONE clock); any mismatch —
the index ran ahead of the snapshot, a topology CR changed, a node's pool
label disagrees with the CR — falls back to the Python full-recompute
path, which stays the differential oracle (sampled in-cycle via
``TopologyMatchArgs.index_differential_period``) and the graceful-degrade
path when the index is disabled (``TPUSCHED_NO_WINDOW_INDEX=1``).  With
``TPUSCHED_NO_NATIVE=1`` the index still runs, on its pure-Python kernels.
"""
from __future__ import annotations

import ctypes
from typing import Dict, FrozenSet, List, Optional, Tuple

from .. import native
from ..api.core import Node, Pod, node_health_error
from ..api.resources import TPU
from ..api.scheduling import POD_GROUP_LABEL
from ..api.topology import LABEL_POOL
from ..util import tracectx
from ..util.locking import GuardedLock, guarded_by
from ..util.metrics import (torus_index_cells_touched_total,
                            torus_index_rebuilds_total,
                            torus_index_updates_total)
from .engine import MaskGrid, PlacementSet, enumerate_placement_masks
from .torus import HostGrid

GangKey = Tuple[str, Optional[str]]          # (namespace, pod-group label)


def gang_key_of(pod: Pod) -> GangKey:
    return (pod.meta.namespace, pod.meta.labels.get(POD_GROUP_LABEL))


def _pod_usage(pod: Pod) -> Tuple[int, bool]:
    """(whole chips, counts-as-TPU-pod) — the same accounting the plugin's
    ``_node_pg_usage`` (chip sums for window math) and the capacity
    collector's ``_node_chip_usage`` (chips-or-memory presence for the
    capacity plane) apply."""
    from ..plugins.tpuslice.chip_node import pod_tpu_limits
    chips, chips_set, _, mem_set = pod_tpu_limits(pod)
    return chips, (chips_set or mem_set)


class WindowQuery:
    """One pool's PreFilter answer served from the index: identical to
    ``feasible_membership`` over ``_occupancy`` on a same-cursor snapshot.
    ``membership`` is a SHARED memoized dict — read-only by contract."""

    __slots__ = ("survivors", "membership", "assigned", "pool_util")

    def __init__(self, survivors: int, membership: Dict[str, int],
                 assigned: FrozenSet, pool_util: float):
        self.survivors = survivors
        self.membership = membership
        self.assigned = assigned
        self.pool_util = pool_util


class _NodeFact:
    """Per-node occupancy facts, grid-independent (keyed by node name so a
    TpuTopology re-layout only re-materializes planes, never re-derives
    usage)."""

    __slots__ = ("pool", "alloc", "used", "tpu_pods", "owners", "healthy")

    def __init__(self) -> None:
        self.pool = ""
        self.alloc = 0
        self.used = 0                     # whole chips over every pod
        self.tpu_pods = 0                 # pods with any TPU chip/mem ask
        # (namespace, pg-label-or-None) → [chips, pod count]; every pod
        # contributes an entry (the plugin's has_sibling test counts any
        # resident pod of the gang, TPU or not)
        self.owners: Dict[GangKey, List[int]] = {}
        self.healthy = True


def _to_words(mask: int, words: int) -> ctypes.Array:
    return (ctypes.c_uint64 * words).from_buffer_copy(
        mask.to_bytes(words * 8, "little"))


class _ShapeIndex:
    """Window index for one (pool, chip shape): placement masks, CSR
    posting lists, live blocked counts / survivor count / membership."""

    __slots__ = ("shape", "pset", "n", "words", "ncells", "offsets", "pids",
                 "blocked", "membership", "covered", "survivors", "memo",
                 "dirty")

    def __init__(self, shape: Tuple[int, ...], pset: PlacementSet):
        self.shape = shape
        self.pset = pset
        self.n = len(pset.masks)
        self.words = pset.mgrid.words
        self.ncells = pset.mgrid.ncells
        ncells = self.ncells
        self.offsets = (ctypes.c_int64 * (ncells + 1))()
        lib = native.load()
        if lib is not None and self.n:
            counts = (ctypes.c_int64 * ncells)()
            prev = tracectx.set_plugin("native:torus_index")
            try:
                lib.tpusched_postings_count(pset.packed(), self.n,
                                            self.words, counts)
                total = 0
                for c in range(ncells):
                    self.offsets[c] = total
                    total += counts[c]
                self.offsets[ncells] = total
                self.pids = (ctypes.c_int64 * max(1, total))()
                ctypes.memset(counts, 0, ctypes.sizeof(counts))
                lib.tpusched_postings_fill(pset.packed(), self.n, self.words,
                                           self.offsets, counts, self.pids)
            finally:
                tracectx.set_plugin(prev)
        else:
            counts = [0] * ncells
            for m in pset.masks:
                b = m
                while b:
                    low = b & -b
                    counts[low.bit_length() - 1] += 1
                    b ^= low
            total = 0
            for c in range(ncells):
                self.offsets[c] = total
                total += counts[c]
            self.offsets[ncells] = total
            self.pids = (ctypes.c_int64 * max(1, total))()
            fill = [0] * ncells
            for p, m in enumerate(pset.masks):
                b = m
                while b:
                    low = b & -b
                    cell = low.bit_length() - 1
                    self.pids[self.offsets[cell] + fill[cell]] = p
                    fill[cell] += 1
                    b ^= low
        self.blocked = (ctypes.c_int32 * max(1, self.n))()
        self.membership = (ctypes.c_int64 * max(1, ncells))()
        self.covered = (ctypes.c_uint64 * max(1, self.words))()
        self.survivors = 0
        # need → [version, alloc_gen, survivors, membership dict,
        # dirty-mark]: gang siblings' PreFilters between plane deltas are
        # pure memo hits, and after a delta the NEXT sweep patches only
        # the dirty cells (appended by apply()) instead of re-walking the
        # whole covered plane — the O(Δ) guarantee end to end.  Served
        # dicts are never mutated in place (readers hold them outside the
        # lock); a patch copies, fixes the dirty cells, and re-memoizes.
        self.memo: Dict[int, list] = {}
        # cells whose membership/eligibility may have moved since the
        # oldest memo entry (append-only; reset with the memo)
        self.dirty: List[int] = []

    def rebuild(self, free_mask: int) -> None:
        ctypes.memset(self.blocked, 0, ctypes.sizeof(self.blocked))
        ctypes.memset(self.membership, 0, ctypes.sizeof(self.membership))
        ctypes.memset(self.covered, 0, ctypes.sizeof(self.covered))
        self.memo.clear()
        self.dirty.clear()
        if not self.n:
            self.survivors = 0
            return
        lib = native.load()
        if lib is not None:
            prev = tracectx.set_plugin("native:torus_index")
            try:
                self.survivors = lib.tpusched_index_build(
                    self.pset.packed(), self.n, self.words,
                    _to_words(free_mask, self.words), self.blocked,
                    self.membership, self.covered)
            finally:
                tracectx.set_plugin(prev)
            return
        survivors = 0
        for p, m in enumerate(self.pset.masks):
            blk = (m & ~free_mask).bit_count()
            self.blocked[p] = blk
            if blk:
                continue
            survivors += 1
            b = m
            while b:
                low = b & -b
                cell = low.bit_length() - 1
                self.membership[cell] += 1
                if self.membership[cell] == 1:
                    self.covered[cell >> 6] |= 1 << (cell & 63)
                b ^= low
        self.survivors = survivors

    def apply(self, changed: List[Tuple[int, int]]) -> None:
        """``changed``: (cell, dir) with dir=+1 freed / -1 un-freed."""
        if not self.n or not changed:
            return
        self._mark_dirty(changed)
        lib = native.load()
        k = len(changed)
        if lib is not None:
            cells = (ctypes.c_int64 * k)(*(c for c, _ in changed))
            dirs = (ctypes.c_int8 * k)(*(d for _, d in changed))
            prev = tracectx.set_plugin("native:torus_index")
            try:
                self.survivors += lib.tpusched_index_apply(
                    self.pset.packed(), self.n, self.words, self.offsets,
                    self.pids, cells, dirs, k, self.blocked, self.membership,
                    self.covered)
            finally:
                tracectx.set_plugin(prev)
            return
        for cell, direction in changed:
            for i in range(self.offsets[cell], self.offsets[cell + 1]):
                p = self.pids[i]
                before = self.blocked[p]
                self.blocked[p] = before - direction
                if direction > 0 and before == 1:
                    flip = 1
                elif direction < 0 and before == 0:
                    flip = -1
                else:
                    continue
                self.survivors += flip
                b = self.pset.masks[p]
                while b:
                    low = b & -b
                    c = low.bit_length() - 1
                    self.membership[c] += flip
                    if self.membership[c] == 0:
                        self.covered[c >> 6] &= ~(1 << (c & 63))
                    elif flip > 0 and self.membership[c] == 1:
                        self.covered[c >> 6] |= 1 << (c & 63)
                    b ^= low

    def _mark_dirty(self, changed: List[Tuple[int, int]]) -> None:
        """Record every cell whose membership or eligibility MAY move under
        this delta: the changed cells themselves plus every cell of every
        placement posted on them (a conservative superset of the placements
        that actually flip — the native kernel does not report flips)."""
        if not self.memo:
            self.dirty.clear()            # nothing to patch: stay empty
            return
        if len(self.dirty) > 4 * self.ncells:
            # pathological churn: a full rebuild of the memo is cheaper
            # than an ever-growing patch log
            self.memo.clear()
            self.dirty.clear()
            return
        dirty = self.dirty
        masks = self.pset.masks
        for cell, _ in changed:
            dirty.append(cell)
            for i in range(self.offsets[cell], self.offsets[cell + 1]):
                b = masks[self.pids[i]]
                while b:
                    low = b & -b
                    dirty.append(low.bit_length() - 1)
                    b ^= low

    def covered_int(self) -> int:
        return int.from_bytes(bytes(self.covered), "little")


class _PoolPlane:
    """One pool's materialized occupancy planes over its MaskGrid."""

    __slots__ = ("pool", "topo_key", "topo_rv", "grid", "mgrid", "version",
                 "mixed", "free_mask", "cap_mask", "gang_cells", "cell_keys",
                 "cell_state", "total_alloc", "total_used", "free_chips",
                 "alloc_gen", "alloc_ge", "shapes", "largest_memo")

    def __init__(self, pool: str, topo_key: str, topo_rv: int,
                 grid: HostGrid, mgrid: MaskGrid):
        self.pool = pool
        self.topo_key = topo_key
        self.topo_rv = topo_rv
        self.grid = grid
        self.mgrid = mgrid
        self.version = -1                 # pool cursor of the last update
        self.mixed = False                # node label pool ≠ CR pool: refuse
        self.free_mask = 0                # present & healthy & zero chips
        self.cap_mask = 0                 # + zero TPU usage & alloc > 0
        self.gang_cells: Dict[GangKey, int] = {}
        self.cell_keys: Dict[int, FrozenSet[GangKey]] = {}
        # cell → (alloc, used) contributions currently inside the totals
        self.cell_state: Dict[int, Tuple[int, int]] = {}
        self.total_alloc = 0
        self.total_used = 0
        self.free_chips = 0               # Σ max(0, alloc - used)
        self.alloc_gen = 0
        self.alloc_ge: Dict[int, Tuple[int, int]] = {}  # need → (gen, mask)
        self.shapes: Dict[Tuple[int, ...], _ShapeIndex] = {}
        self.largest_memo: Optional[Tuple[int, int]] = None  # (version, chips)

    def pool_util(self) -> float:
        return (self.total_used / self.total_alloc
                if self.total_alloc else 1.0)

    def alloc_ge_mask(self, need: int,
                      facts: Dict[str, "_NodeFact"]) -> int:
        ent = self.alloc_ge.get(need)
        if ent is not None and ent[0] == self.alloc_gen:
            return ent[1]
        m = 0
        for node, coord in self.grid.coord_of.items():
            fact = facts.get(node)
            if fact is not None and fact.alloc >= need:
                m |= 1 << self.mgrid.cell(coord)
        if len(self.alloc_ge) > 16:
            self.alloc_ge.clear()
        self.alloc_ge[need] = (self.alloc_gen, m)
        return m


@guarded_by("_lock", "_facts", "_planes", "_node_planes", "_grids",
            "_stale", "_updates", "_rebuilds", "_cells_touched",
            "_pset_cache")
class TorusWindowIndex:
    """The index.  Writers are the scheduler cache's mutators: they hold
    the cache lock and call the ``cache_*`` hooks, which take this lock
    inside — lock order Cache → WindowIndex, never the reverse (readers
    never touch the cache).  Readers are dispatch-lane PreFilters, the
    /metrics capacity collector and the defrag advisor's pre-gate."""

    def __init__(self, publish: bool = True):
        self._lock = GuardedLock("topology.WindowIndex")
        self._publish = publish           # False for shadow schedulers
        self._facts: Dict[str, _NodeFact] = {}
        self._planes: Dict[str, _PoolPlane] = {}
        self._node_planes: Dict[str, List[str]] = {}
        # pool → (topo key, rv, HostGrid, MaskGrid) awaiting (re)build
        self._grids: Dict[str, Tuple[str, int, HostGrid, MaskGrid]] = {}
        self._stale: Dict[str, None] = {}
        self._updates = 0
        self._rebuilds = 0
        self._cells_touched = 0
        # bounded placement-set cache for read surfaces outside live planes
        # (PostFilter sweeps, the capacity ladder)
        self._pset_cache: Dict[Tuple, PlacementSet] = {}

    # -- topology CR intake (informer thread) ---------------------------------

    def observe_topology(self, topo) -> bool:
        """Record/refresh a pool's grid geometry and mark its plane stale.
        The caller must follow up with ``Cache.sync_window_index()`` so the
        plane is rebuilt atomically with its pool cursor.  Returns True when
        a rebuild is pending."""
        grid = HostGrid.from_spec(topo.spec)
        with self._lock:
            if grid is None:
                self._drop_pool_locked(topo.spec.pool)
                return False
            pool = grid.pool
            known = self._grids.get(pool)
            if (known is not None and known[0] == topo.key
                    and known[1] == topo.meta.resource_version
                    and pool in self._planes):
                return False              # same geometry already live
            self._grids[pool] = (topo.key, topo.meta.resource_version,
                                 grid, MaskGrid(grid))
            self._stale[pool] = None
            return True

    def forget_topology(self, pool: str) -> None:
        with self._lock:
            self._drop_pool_locked(pool)

    def _drop_pool_locked(self, pool: str) -> None:
        self._grids.pop(pool, None)
        self._stale.pop(pool, None)
        plane = self._planes.pop(pool, None)
        if plane is not None:
            for node in plane.grid.coord_of:
                pools = self._node_planes.get(node)
                if pools and pool in pools:
                    pools.remove(pool)

    def mark_stale(self, pool: str) -> None:
        """Quarantine one pool (differential-mismatch self-heal): queries
        miss until ``Cache.sync_window_index()`` rebuilds the plane."""
        with self._lock:
            if pool in self._grids:
                self._stale[pool] = None
                plane = self._planes.get(pool)
                if plane is not None:
                    plane.version = -1

    def stale_pools(self) -> List[str]:
        with self._lock:
            return list(self._stale)

    # -- cache-side hooks (ALL called with the cache lock held) ---------------

    def cache_reset(self) -> None:
        with self._lock:
            self._facts.clear()
            self._planes.clear()
            self._node_planes.clear()
            for pool in self._grids:
                self._stale[pool] = None

    def cache_seed_node(self, node: Node, pods) -> None:
        """Attach-time seeding: facts only; planes follow via
        ``rebuild_stale``."""
        with self._lock:
            self._set_fact_locked(node, pods)

    def rebuild_stale(self, cursor_of) -> None:
        """Build every stale pool's plane from current facts, stamping it
        with ``cursor_of(pool)`` — the caller holds the cache lock, so the
        facts/cursor pair is a consistent epoch."""
        with self._lock:
            for pool in list(self._stale):
                ent = self._grids.get(pool)
                self._stale.pop(pool, None)
                if ent is None:
                    continue
                self._build_plane_locked(pool, ent, cursor_of(pool))

    def cache_note(self, pool: str, cursor: int) -> None:
        """A structural mutation with no occupancy-visible delta still
        advances the pool's cursor; track it or every later query misses."""
        with self._lock:
            plane = self._planes.get(pool)
            if plane is not None:
                plane.version = cursor

    def cache_pod_delta(self, node_name: str, pod: Pod, delta: int,
                        stamps) -> None:
        with self._lock:
            fact = self._facts.get(node_name)
            if fact is not None:
                chips, is_tpu = _pod_usage(pod)
                fact.used += delta * chips
                if is_tpu:
                    fact.tpu_pods += delta
                key = gang_key_of(pod)
                ent = fact.owners.get(key)
                if ent is None:
                    ent = fact.owners[key] = [0, 0]
                ent[0] += delta * chips
                ent[1] += delta
                if ent[1] <= 0:
                    fact.owners.pop(key, None)
                self._apply_node_locked(node_name)
            self._stamp_locked(stamps)

    def cache_node_upsert(self, node: Node, pods, stamps) -> None:
        """``pods``: the node's full resident pod list (add/replace paths),
        or None to keep the existing pod-derived facts (an in-place
        health/alloc/label update)."""
        with self._lock:
            self._set_fact_locked(node, pods)
            self._apply_node_locked(node.name)
            self._stamp_locked(stamps)

    def cache_node_removed(self, name: str, stamps) -> None:
        with self._lock:
            self._facts.pop(name, None)
            self._apply_node_locked(name)
            self._stamp_locked(stamps)

    def _set_fact_locked(self, node: Node, pods) -> None:
        fact = self._facts.get(node.name)
        if fact is None:
            fact = self._facts[node.name] = _NodeFact()
            if pods is None:
                pods = ()
        fact.pool = node.meta.labels.get(LABEL_POOL, "")
        fact.alloc = node.status.allocatable.get(TPU, 0)
        fact.healthy = node_health_error(node) is None
        if pods is not None:
            fact.used = 0
            fact.tpu_pods = 0
            fact.owners = {}
            for p in pods:
                chips, is_tpu = _pod_usage(p)
                fact.used += chips
                if is_tpu:
                    fact.tpu_pods += 1
                key = gang_key_of(p)
                ent = fact.owners.get(key)
                if ent is None:
                    ent = fact.owners[key] = [0, 0]
                ent[0] += chips
                ent[1] += 1

    def _stamp_locked(self, stamps) -> None:
        for pool, cursor in stamps:
            plane = self._planes.get(pool)
            if plane is not None:
                plane.version = cursor
        self._updates += 1
        if self._publish:
            torus_index_updates_total.inc()

    def _apply_node_locked(self, name: str) -> None:
        for pool in self._node_planes.get(name, ()):
            plane = self._planes.get(pool)
            if plane is not None:
                self._apply_cell_locked(plane, name)

    def _apply_cell_locked(self, plane: _PoolPlane, name: str,
                           count: bool = True) -> None:
        coord = plane.grid.coord_of.get(name)
        if coord is None:
            return
        cell = plane.mgrid.cell(coord)
        bit = 1 << cell
        fact = self._facts.get(name)
        present = fact is not None
        if present and fact.pool != plane.pool:
            # CR pool and node label disagree: version semantics can no
            # longer be trusted for this plane — refuse to serve it until
            # a rebuild observes a consistent world
            plane.mixed = True
        # totals
        prev = plane.cell_state.get(cell)
        alloc = fact.alloc if present else 0
        used = fact.used if present else 0
        if present:
            if prev is None or prev[0] != alloc:
                plane.alloc_gen += 1
            plane.cell_state[cell] = (alloc, used)
        else:
            if prev is not None:
                plane.alloc_gen += 1
            plane.cell_state.pop(cell, None)
        pa, pu = prev if prev is not None else (0, 0)
        plane.total_alloc += alloc - pa
        plane.total_used += used - pu
        plane.free_chips += max(0, alloc - used) - max(0, pa - pu)
        # gang cells
        new_keys = frozenset(fact.owners) if present else frozenset()
        old_keys = plane.cell_keys.get(cell, frozenset())
        if new_keys != old_keys:
            for k in old_keys - new_keys:
                m = plane.gang_cells.get(k, 0) & ~bit
                if m:
                    plane.gang_cells[k] = m
                else:
                    plane.gang_cells.pop(k, None)
            for k in new_keys - old_keys:
                plane.gang_cells[k] = plane.gang_cells.get(k, 0) | bit
            if new_keys:
                plane.cell_keys[cell] = new_keys
            else:
                plane.cell_keys.pop(cell, None)
        # planes
        free = present and fact.healthy and used == 0
        cap = (present and fact.healthy and fact.tpu_pods == 0
               and alloc > 0)
        if cap != bool(plane.cap_mask & bit):
            plane.cap_mask ^= bit
        if free != bool(plane.free_mask & bit):
            plane.free_mask ^= bit
            if count:
                self._cells_touched += 1
                if self._publish:
                    torus_index_cells_touched_total.inc()
            changed = [(cell, 1 if free else -1)]
            for sidx in plane.shapes.values():
                sidx.apply(changed)

    def _build_plane_locked(self, pool: str, ent, cursor: int) -> None:
        topo_key, rv, grid, mgrid = ent
        old = self._planes.get(pool)
        plane = _PoolPlane(pool, topo_key, rv, grid, mgrid)
        for node in grid.coord_of:
            pools = self._node_planes.setdefault(node, [])
            if pool not in pools:
                pools.append(pool)
            self._apply_cell_locked(plane, node, count=False)
        # a full rebuild observes the whole world at once: clear any
        # mixed verdict derived from it only if it still holds
        plane.mixed = any(
            self._facts[n].pool != pool
            for n in grid.coord_of if n in self._facts)
        self._planes[pool] = plane
        # keep previously-hot shapes warm across the rebuild: placement
        # sets depend only on (dims, wrap, accelerator), so a same-geometry
        # rebuild (host relabels, rv bumps) reuses them and pays only the
        # cheap blocked-count rebuild.  Changed geometry drops the shapes;
        # the next query re-enumerates OUTSIDE the locks (_shape_ready) —
        # enumeration must never run under the cache lock.
        if old is not None and old.grid.dims == grid.dims \
                and old.grid.wrap == grid.wrap and old.grid.acc is grid.acc:
            for shape, old_sidx in old.shapes.items():
                old_sidx.rebuild(plane.free_mask)
                plane.shapes[shape] = old_sidx
        plane.version = cursor
        self._rebuilds += 1
        if self._publish:
            torus_index_rebuilds_total.inc()

    def _ensure_shape_locked(self, plane: _PoolPlane,
                             shape: Tuple[int, ...]) -> Optional[_ShapeIndex]:
        sidx = plane.shapes.get(shape)
        if sidx is None:
            pset = enumerate_placement_masks(plane.mgrid, shape)
            sidx = _ShapeIndex(shape, pset)
            sidx.rebuild(plane.free_mask)
            plane.shapes[shape] = sidx
        return sidx

    def _shape_ready(self, pool: str, topo_key: str, topo_rv: int,
                     shape: Tuple[int, ...]) -> bool:
        """Ensure the (pool, shape) window index exists, with the
        placement enumeration + posting-list build running OUTSIDE the
        index lock: cache mutators block on that lock from inside their
        own critical sections, and first-touch enumeration of a big pool
        is the most expensive operation in this module — holding the lock
        through it would stall every dispatch lane behind one probe."""
        with self._lock:
            plane = self._serving_plane_locked(pool, topo_key, topo_rv,
                                               None)
            if plane is None:
                return False
            if shape in plane.shapes:
                return True
            mgrid = plane.mgrid
        pset = enumerate_placement_masks(mgrid, shape)
        sidx = _ShapeIndex(shape, pset)
        with self._lock:
            plane = self._planes.get(pool)
            if (plane is None or plane.topo_key != topo_key
                    or plane.topo_rv != topo_rv
                    or plane.mgrid is not mgrid):
                return False          # geometry moved underneath the build
            if shape not in plane.shapes:
                sidx.rebuild(plane.free_mask)
                plane.shapes[shape] = sidx
            return True

    # -- read surface ---------------------------------------------------------

    def pool_version(self, pool: str) -> int:
        with self._lock:
            plane = self._planes.get(pool)
            return plane.version if plane is not None else -1

    def _serving_plane_locked(self, pool: str, topo_key: str, topo_rv: int,
                              expected_cursor: Optional[int]
                              ) -> Optional[_PoolPlane]:
        plane = self._planes.get(pool)
        if (plane is None or plane.mixed or pool in self._stale
                or plane.topo_key != topo_key or plane.topo_rv != topo_rv):
            return None
        if expected_cursor is not None and plane.version != expected_cursor:
            return None
        return plane

    def query(self, topo, shape: Tuple[int, ...], gang_key: GangKey,
              chips_needed: int,
              expected_cursor: Optional[int]) -> Optional[WindowQuery]:
        """The PreFilter sweep for one pool, as a table lookup.  Returns
        None whenever the index cannot PROVE it answers for the caller's
        snapshot epoch — the caller falls back to the full recompute."""
        if expected_cursor is None:
            return None
        shape = tuple(shape)
        if not self._shape_ready(topo.spec.pool, topo.key,
                                 topo.meta.resource_version, shape):
            return None
        with self._lock:
            plane = self._serving_plane_locked(
                topo.spec.pool, topo.key, topo.meta.resource_version,
                expected_cursor)
            if plane is None:
                return None
            sidx = plane.shapes.get(shape)
            if sidx is None:
                return None
            assigned_mask = plane.gang_cells.get(gang_key, 0)
            util = plane.pool_util()
            if assigned_mask == 0:
                membership = self._gangfree_membership_locked(
                    plane, sidx, chips_needed)
                return WindowQuery(sidx.survivors, membership, frozenset(),
                                   util)
            # sibling path: placements must contain every assigned cell —
            # candidates come from ONE assigned cell's posting list
            free = plane.free_mask & ~assigned_mask
            eligible = (free
                        & plane.alloc_ge_mask(chips_needed, self._facts)) \
                | self._sibling_eligible_locked(plane, gang_key,
                                                assigned_mask, chips_needed)
            first = (assigned_mask & -assigned_mask).bit_length() - 1
            survivors = 0
            counts: Dict[int, int] = {}
            masks = sidx.pset.masks
            for i in range(sidx.offsets[first], sidx.offsets[first + 1]):
                m = masks[sidx.pids[i]]
                if (m & assigned_mask) != assigned_mask:
                    continue
                if (m & ~assigned_mask) & ~free:
                    continue
                survivors += 1
                b = m & eligible
                while b:
                    low = b & -b
                    cell = low.bit_length() - 1
                    counts[cell] = counts.get(cell, 0) + 1
                    b ^= low
            membership = {}
            node_of_cell = plane.mgrid.node_of_cell
            for cell, c in counts.items():
                node = node_of_cell[cell]
                if node is not None:
                    membership[node] = c
            assigned = frozenset(
                plane.grid.coord_of[n]
                for n in self._gang_nodes_locked(plane, assigned_mask))
            return WindowQuery(survivors, membership, assigned, util)

    def _gangfree_membership_locked(self, plane: _PoolPlane,
                                    sidx: _ShapeIndex,
                                    need: int) -> Dict[str, int]:
        """The gang-free sweep's node→membership table: memo hit when the
        plane is unchanged, O(Δ) patch of a copied dict after a delta,
        full O(covered) walk only on first touch / alloc changes."""
        node_of_cell = plane.mgrid.node_of_cell
        ent = sidx.memo.get(need)
        if ent is not None and ent[0] == plane.version:
            return ent[3]
        eligible = plane.free_mask & plane.alloc_ge_mask(need, self._facts)
        if ent is not None and ent[1] == plane.alloc_gen:
            d = dict(ent[3])              # never patch a served dict
            for cell in set(sidx.dirty[ent[4]:]):
                node = node_of_cell[cell]
                if node is None:
                    continue
                m = sidx.membership[cell]
                if m and (eligible >> cell) & 1:
                    d[node] = m
                else:
                    d.pop(node, None)
            sidx.memo[need] = [plane.version, plane.alloc_gen,
                               sidx.survivors, d, len(sidx.dirty)]
            return d
        membership: Dict[str, int] = {}
        bits = sidx.covered_int() & eligible
        while bits:
            low = bits & -bits
            cell = low.bit_length() - 1
            node = node_of_cell[cell]
            if node is not None:
                membership[node] = sidx.membership[cell]
            bits ^= low
        sidx.memo[need] = [plane.version, plane.alloc_gen, sidx.survivors,
                           membership, len(sidx.dirty)]
        return membership

    def _gang_nodes_locked(self, plane: _PoolPlane, mask: int):
        node_of_cell = plane.mgrid.node_of_cell
        out = []
        while mask:
            low = mask & -mask
            node = node_of_cell[low.bit_length() - 1]
            if node is not None:
                out.append(node)
            mask ^= low
        return out

    def _sibling_eligible_locked(self, plane: _PoolPlane, gang_key: GangKey,
                                 assigned_mask: int, need: int) -> int:
        """Cells the gang already sits on that can still take THIS pod:
        healthy, zero foreign chips, and enough chips left after
        siblings — the sub-host packing case of ``_occupancy``."""
        out = 0
        m = assigned_mask
        node_of_cell = plane.mgrid.node_of_cell
        while m:
            low = m & -m
            cell = low.bit_length() - 1
            m ^= low
            node = node_of_cell[cell]
            fact = self._facts.get(node) if node is not None else None
            if fact is None or not fact.healthy:
                continue
            ent = fact.owners.get(gang_key)
            sib = ent[0] if ent else 0
            if fact.used - sib:
                continue                  # foreign chips on the host
            if fact.alloc - sib >= need:
                out |= low
        return out

    def assigned_view(self, topo, gang_key: GangKey,
                      expected_cursor: Optional[int]
                      ) -> Optional[FrozenSet]:
        """PostFilter's pinning input: the gang's already-assigned host
        coords in this pool, or None when the index cannot serve."""
        if expected_cursor is None:
            return None
        with self._lock:
            plane = self._serving_plane_locked(
                topo.spec.pool, topo.key, topo.meta.resource_version,
                expected_cursor)
            if plane is None:
                return None
            mask = plane.gang_cells.get(gang_key, 0)
            return frozenset(
                plane.grid.coord_of[n]
                for n in self._gang_nodes_locked(plane, mask))

    def placement_set(self, topo, mgrid: MaskGrid,
                      shape: Tuple[int, ...]) -> PlacementSet:
        """Shared placement enumeration (PostFilter's window sweep, the
        capacity ladder): served from the live plane's shape index when
        the geometry matches, else from a small bounded cache."""
        shape = tuple(shape)
        if self._shape_ready(topo.spec.pool, topo.key,
                             topo.meta.resource_version, shape):
            with self._lock:
                plane = self._planes.get(topo.spec.pool)
                if (plane is not None and plane.topo_key == topo.key
                        and plane.topo_rv == topo.meta.resource_version):
                    sidx = plane.shapes.get(shape)
                    if sidx is not None:
                        return sidx.pset
        key = (topo.key, topo.meta.resource_version, shape)
        with self._lock:
            got = self._pset_cache.get(key)
        if got is None:
            got = enumerate_placement_masks(mgrid, shape)   # outside lock
            with self._lock:
                got = self._pset_cache.setdefault(key, got)
                while len(self._pset_cache) > 64:
                    self._pset_cache.pop(next(iter(self._pset_cache)))
        return got

    # -- capacity / defrag surface -------------------------------------------

    def capacity_view(self, topo) -> Optional[Tuple[FrozenSet, int, int, int]]:
        """(window-eligible free host coords, free chips, capacity chips,
        version) for the /metrics collector — the maintained twin of
        ``obs.capacity.pool_occupancy`` (no snapshot walk).  Staleness is
        tolerated by that surface's contract, so no cursor is required;
        geometry must still match."""
        with self._lock:
            plane = self._serving_plane_locked(
                topo.spec.pool, topo.key, topo.meta.resource_version, None)
            if plane is None or plane.version < 0:
                return None
            coords = []
            m = plane.cap_mask
            node_of_cell = plane.mgrid.node_of_cell
            coord_of = plane.grid.coord_of
            while m:
                low = m & -m
                node = node_of_cell[low.bit_length() - 1]
                if node is not None:
                    coords.append(coord_of[node])
                m ^= low
            return (frozenset(coords), plane.free_chips, plane.total_alloc,
                    plane.version)

    def largest_placeable(self, topo) -> Optional[Tuple[int, int, int, int]]:
        """(largest placeable chips, free chips, capacity, version) —
        memoized on the plane version, so an idle pool answers for free
        and an active one recomputes only after a real occupancy delta."""
        view = self.capacity_view(topo)
        if view is None:
            return None
        coords, free_chips, capacity, version = view
        with self._lock:
            plane = self._planes.get(topo.spec.pool)
            if plane is None:
                return None
            memo = plane.largest_memo
            if memo is not None and memo[0] == version:
                return (memo[1], free_chips, capacity, version)
            grid = plane.grid
        # the ladder search runs OUTSIDE the index lock: it is bounded but
        # not O(1), and cache mutators block on this lock
        from ..obs.capacity import largest_window_chips  # lazy: import cycle
        largest = largest_window_chips(grid, coords) if coords else 0
        with self._lock:
            plane = self._planes.get(topo.spec.pool)
            if plane is not None and plane.version == version:
                plane.largest_memo = (version, largest)
        return (largest, free_chips, capacity, version)

    def window_exists_with(self, topo, shape: Tuple[int, ...],
                           extra_free_nodes=()) -> Optional[bool]:
        """Defrag pre-gate: could any placement of ``shape`` land on the
        pool's currently-free hosts PLUS ``extra_free_nodes`` (a candidate
        migration's vacated hosts)?  None when the index cannot answer."""
        shape = tuple(shape)
        if not self._shape_ready(topo.spec.pool, topo.key,
                                 topo.meta.resource_version, shape):
            return None
        with self._lock:
            plane = self._serving_plane_locked(
                topo.spec.pool, topo.key, topo.meta.resource_version, None)
            if plane is None or plane.version < 0:
                return None
            sidx = plane.shapes.get(shape)
            if sidx is None:
                return None
            extra = 0
            for n in extra_free_nodes:
                coord = plane.grid.coord_of.get(n)
                if coord is not None:
                    extra |= 1 << plane.mgrid.cell(coord)
            if not extra:
                return sidx.survivors > 0
            free = plane.free_mask | extra
            for m in sidx.pset.masks:
                if not m & ~free:
                    return True
            return False

    # -- observability --------------------------------------------------------

    def health(self, cursor_of=None) -> Dict[str, object]:
        """/debug/flightrecorder ``health.torus_index`` payload: per-pool
        version + staleness (vs the live pool cursor when ``cursor_of`` is
        given), shape count, and the cumulative maintenance counters."""
        with self._lock:
            pools = {}
            for pool, plane in self._planes.items():
                row = {"version": plane.version,
                       "shapes": len(plane.shapes),
                       "survivor_counts": {
                           "x".join(map(str, s)): plane.shapes[s].survivors
                           for s in plane.shapes},
                       "mixed": plane.mixed,
                       "stale": pool in self._stale}
                pools[pool] = row
            out = {"pools": pools,
                   "updates_total": self._updates,
                   "rebuilds_total": self._rebuilds,
                   "cells_touched_total": self._cells_touched}
        if cursor_of is not None:
            for pool, row in out["pools"].items():
                try:
                    row["cursor_lag"] = cursor_of(pool) - row["version"]
                except Exception as e:  # noqa: BLE001 — advisory surface
                    row["cursor_lag_error"] = str(e)
        return out

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"updates": self._updates, "rebuilds": self._rebuilds,
                    "cells_touched": self._cells_touched,
                    "pools": len(self._planes)}

    # -- test/debug surface ---------------------------------------------------

    def debug_plane(self, pool: str) -> Optional[Dict[str, object]]:
        """Internal plane state for the property tests' incremental-vs-
        scratch comparison."""
        with self._lock:
            plane = self._planes.get(pool)
            if plane is None:
                return None
            return {
                "version": plane.version,
                "free_mask": plane.free_mask,
                "cap_mask": plane.cap_mask,
                "gang_cells": dict(plane.gang_cells),
                "total_alloc": plane.total_alloc,
                "total_used": plane.total_used,
                "free_chips": plane.free_chips,
                "shapes": {
                    s: {"survivors": sidx.survivors,
                        "blocked": list(sidx.blocked[:sidx.n]),
                        "membership": list(
                            sidx.membership[:sidx.ncells]),
                        "covered": sidx.covered_int()}
                    for s, sidx in plane.shapes.items()},
            }

    def ensure_shape(self, pool: str, shape: Tuple[int, ...]) -> bool:
        with self._lock:
            plane = self._planes.get(pool)
            if plane is None:
                return False
            self._ensure_shape_locked(plane, tuple(shape))
            return True
