"""Slice-shape fitting on ICI tori.

The reference's NUMA filter ANDs per-resource feasibility bitmasks over ≤8
zones in one dimension (/root/reference/pkg/noderesourcetopology/filter.go:
35-37,84-150). The TPU generalization (SURVEY §5, §7.5): a node pool is a 2-D
(v5e) or 3-D (v5p) torus of chips; hosts own fixed sub-blocks (2x2 on v5e,
2x2x1 on v5p — 4 chips); a job requests a chip-shape like 4x4x4 which must
map onto a *contiguous free block* of the torus, modulo axis permutation,
with wraparound only on axes the pool wraps.

Everything here works in HOST units: chip shapes are converted via the
accelerator's host extent, placements are host-coordinate sets.
"""
from __future__ import annotations

import itertools
from dataclasses import dataclass
from typing import Dict, FrozenSet, List, Optional, Sequence, Tuple

from ..api.topology import ACCELERATORS, TpuAccelerator, TpuTopologySpec
from ..util import klog

# Host extents: how a host's chips are laid out in the torus.
HOST_EXTENT = {
    "tpu-v4": (2, 2, 1),    # 4 chips as a 2x2x1 block of the 3-D torus
    "tpu-v5e": (2, 2),      # 4 chips as a 2x2 tile of the 2-D torus
    "tpu-v5p": (2, 2, 1),   # 4 chips as a 2x2x1 block of the 3-D torus
    "tpu-v6e": (4, 2),      # 8 chips as a 4x2 tile of the 2-D mesh
}

Coord = Tuple[int, ...]
Placement = FrozenSet[Coord]   # set of host coords (host units)


# Memo caches for the two pure shape functions below. Every PreFilter of a
# slice pod evaluates them once per pool (a 1024-host/16-pool fleet pays
# ~32 calls per pod per cycle); the result depends only on (shape,
# accelerator, dims) — a handful of distinct keys fleet-wide. Bounded by
# FIFO eviction of the OLDEST entry at the cap (dicts iterate in insertion
# order): an adversarial stream of unique shapes can only cycle the cold
# tail, it can never wipe the hot keys the live fleet re-reads every cycle
# the way the old wholesale clear() did.
_CACHE_CAP = 4096
_blocks_cache: dict = {}
_validate_cache: dict = {}
_MISS = object()


def _evict_oldest(cache: dict) -> None:
    while len(cache) >= _CACHE_CAP:
        cache.pop(next(iter(cache)))


def candidate_host_blocks(chip_shape: Coord, acc: TpuAccelerator,
                          host_dims: Coord) -> "Sequence[Coord]":
    """All host-block shapes realizable by rotating `chip_shape` onto the
    torus (an immutable, memoized sequence). Rotation happens on the CHIP
    shape FIRST; each rotated axis must then divide the (anisotropic) host
    extent on the torus axis it lands on — permuting after division is
    wrong on v5p's (2,2,1) extent (it both misses feasible rotations and
    fabricates non-rotations)."""
    key = (chip_shape, acc.name, host_dims)
    hit = _blocks_cache.get(key, _MISS)
    if hit is not _MISS:
        return hit
    extent = HOST_EXTENT[acc.name]
    blocks: List[Coord] = []
    for perm in dict.fromkeys(itertools.permutations(chip_shape)):
        if any(perm[i] % extent[i] for i in range(len(extent))):
            continue
        hb = tuple(perm[i] // extent[i] for i in range(len(extent)))
        if all(hb[i] <= host_dims[i] for i in range(len(hb))):
            blocks.append(hb)
    # cache a TUPLE: the memo hands the same object to every caller, and
    # a mutable cached list would let one caller's sort/append poison
    # feasibility answers fleet-wide
    out = tuple(dict.fromkeys(blocks))
    _evict_oldest(_blocks_cache)
    _blocks_cache[key] = out
    return out


def validate_slice_shape(shape: Coord, acc: TpuAccelerator,
                         pool_dims: Coord) -> Optional[str]:
    """Returns an error string or None. Shape and pool dims are in chips."""
    key = (shape, acc.name, pool_dims)
    hit = _validate_cache.get(key, _MISS)
    if hit is not _MISS:
        return hit
    extent = HOST_EXTENT[acc.name]
    if len(shape) != acc.ici_dims:
        err = (f"slice shape {shape} has {len(shape)} axes; "
               f"{acc.name} torus has {acc.ici_dims}")
    elif len(pool_dims) != acc.ici_dims:
        err = f"pool dims {pool_dims} do not match {acc.name} torus rank"
    elif any(s <= 0 for s in shape):
        err = f"slice shape {shape} axes must be positive"
    else:
        host_dims = tuple(d // e for d, e in zip(pool_dims, extent))
        if not candidate_host_blocks(shape, acc, host_dims):
            err = (f"slice shape {shape} cannot map onto pool dims "
                   f"{pool_dims} (host extent {extent}) under any rotation")
        else:
            err = None
    _evict_oldest(_validate_cache)
    _validate_cache[key] = err
    return err


def host_block_shape(chip_shape: Coord, acc: TpuAccelerator) -> Coord:
    """Identity-orientation chip shape → host-block shape (v5p 4x4x4 chips →
    2x2x4 hosts). Placement enumeration uses candidate_host_blocks, which
    handles rotations."""
    extent = HOST_EXTENT[acc.name]
    return tuple(s // e for s, e in zip(chip_shape, extent))


@dataclass
class HostGrid:
    """A pool's torus reduced to host units."""
    pool: str
    acc: TpuAccelerator
    dims: Coord                       # host-unit dims per axis
    wrap: Tuple[bool, ...]
    node_of: Dict[Coord, str]         # host coord → node name
    coord_of: Dict[str, Coord]        # node name → host coord

    @classmethod
    def from_spec(cls, spec: TpuTopologySpec) -> Optional["HostGrid"]:
        acc = ACCELERATORS.get(spec.accelerator)
        if acc is None or not spec.dims:
            return None
        extent = HOST_EXTENT[acc.name]
        if len(spec.dims) != len(extent):
            return None
        dims = tuple(d // e for d, e in zip(spec.dims, extent))
        wrap = tuple(spec.wrap) if spec.wrap else tuple(False for _ in dims)
        node_of: Dict[Coord, str] = {}
        coord_of: Dict[str, Coord] = {}
        for node, chip_coord in spec.hosts.items():
            if len(chip_coord) != len(dims):
                klog.warning_s("host coord rank mismatch; dropping host",
                               pool=spec.pool, node=node, coord=chip_coord)
                continue
            hc = tuple(c // e for c, e in zip(chip_coord, extent))
            if any(not (0 <= hc[i] < dims[i]) for i in range(len(dims))):
                # out-of-torus coords from a malformed CR must not alias a
                # real cell in the mask engine — drop the host instead
                klog.warning_s("host coord outside pool torus; dropping host",
                               pool=spec.pool, node=node, coord=chip_coord)
                continue
            node_of[hc] = node
            coord_of[node] = hc
        return cls(spec.pool, acc, dims, wrap, node_of, coord_of)


def iter_placements(grid: HostGrid, chip_shape: Coord):
    """Lazily yield every host-set where `chip_shape` (chips; any
    rotation) can sit on the grid — wraparound anchors only on wrapped
    axes; a block spanning the full axis uses a single anchor.  May yield
    the same set more than once across rotations (enumerate_placements
    dedups); the generator form exists so existence probes (the
    fragmentation gauge's largest-window search) can stop at the first
    fit without materializing the full placement list — and so the gauge
    and the scheduler share ONE implementation of the placement rules."""
    rank = len(grid.dims)
    for shape in candidate_host_blocks(chip_shape, grid.acc, grid.dims):
        anchor_ranges = []
        for i in range(rank):
            if shape[i] == grid.dims[i]:
                anchor_ranges.append(range(1))
            elif grid.wrap[i]:
                anchor_ranges.append(range(grid.dims[i]))
            else:
                anchor_ranges.append(range(grid.dims[i] - shape[i] + 1))
        offsets = list(itertools.product(*(range(s) for s in shape)))
        for anchor in itertools.product(*anchor_ranges):
            yield frozenset(
                tuple((anchor[i] + off[i]) % grid.dims[i]
                      for i in range(rank))
                for off in offsets)


def enumerate_placements(grid: HostGrid, chip_shape: Coord) -> List[Placement]:
    """All DISTINCT host-sets where `chip_shape` can sit on the grid."""
    out: List[Placement] = []
    seen = set()
    for hosts in iter_placements(grid, chip_shape):
        if hosts not in seen:
            seen.add(hosts)
            out.append(hosts)
    return out


def feasible_placements(placements: Sequence[Placement],
                        assigned: FrozenSet[Coord],
                        free: FrozenSet[Coord]) -> List[Placement]:
    """Placements that contain every already-assigned gang host and whose
    remaining hosts are all free — the incremental all-or-nothing constraint
    each Filter call enforces."""
    out = []
    for p in placements:
        if assigned <= p and (p - assigned) <= free:
            out.append(p)
    return out
