"""Mask-based placement engine over a HostGrid — native C++ fast path with a
pure-Python fallback.

Placements (torus.py represents them as frozensets of host coords) become
bitmasks over row-major host cells. Enumeration and the per-cycle
feasibility + membership pass run either in the native engine
(tpusched/native/torus_engine.cc) or in the Python implementations here;
both are differential-tested against torus.py's reference semantics
(tests/test_native_engine.py).

The per-cycle contract (matches torus.feasible_placements plus the
membership counting the TopologyMatch PreFilter does on top):
- a placement p survives iff assigned ⊆ p and (p \\ assigned) ⊆ free;
- for each surviving p, every host of p ∩ eligible gets membership += 1
  (the corner-packing score input: how many surviving slices a host sits in).
"""
from __future__ import annotations

import ctypes
from typing import Dict, FrozenSet, Iterable, List, Optional, Tuple

from .. import native
from ..util import tracectx
from .torus import (Coord, HostGrid, candidate_host_blocks,
                    enumerate_placements)


class MaskGrid:
    """Row-major cell indexing for a HostGrid (host units)."""

    def __init__(self, grid: HostGrid):
        self.grid = grid
        self.rank = len(grid.dims)
        self.dims = grid.dims
        strides = [0] * self.rank
        ncells = 1
        for i in range(self.rank - 1, -1, -1):
            strides[i] = ncells
            ncells *= grid.dims[i]
        self.strides = tuple(strides)
        self.ncells = ncells
        self.words = (ncells + 63) // 64
        self.node_of_cell: List[Optional[str]] = [None] * ncells
        for coord, node in grid.node_of.items():
            self.node_of_cell[self.cell(coord)] = node

    def cell(self, coord: Coord) -> int:
        return sum(c * s for c, s in zip(coord, self.strides))

    def mask_of(self, coords: Iterable[Coord]) -> int:
        m = 0
        for c in coords:
            m |= 1 << self.cell(c)
        return m

    def coords_of(self, mask: int) -> FrozenSet[Coord]:
        out = []
        while mask:
            low = mask & -mask
            cell = low.bit_length() - 1
            coord = []
            for s in self.strides:
                coord.append(cell // s)
                cell %= s
            out.append(tuple(coord))
            mask ^= low
        return frozenset(out)


class PlacementSet:
    """All distinct placements of one chip shape on one grid, as int masks;
    the packed uint64 buffer for the native engine is built once and reused
    every cycle."""

    def __init__(self, mgrid: MaskGrid, masks: List[int]):
        self.mgrid = mgrid
        self.masks = masks
        self._packed: Optional[ctypes.Array] = None

    def __len__(self) -> int:
        return len(self.masks)

    def packed(self) -> ctypes.Array:
        if self._packed is None:
            words = self.mgrid.words
            nbytes = words * 8
            # bulk conversion: int.to_bytes emits the little-endian word
            # layout the native ABI expects directly, so one bytearray
            # splice per placement replaces the word-by-word Python loop
            # (this build also feeds the window index's posting lists)
            raw = bytearray(len(self.masks) * nbytes)
            for i, m in enumerate(self.masks):
                raw[i * nbytes:(i + 1) * nbytes] = m.to_bytes(nbytes,
                                                              "little")
            self._packed = (ctypes.c_uint64 * (
                len(self.masks) * words)).from_buffer_copy(raw)
        return self._packed


def _to_words(mask: int, words: int) -> ctypes.Array:
    buf = (ctypes.c_uint64 * words)()
    for w in range(words):
        buf[w] = (mask >> (64 * w)) & 0xFFFFFFFFFFFFFFFF
    return buf


def enumerate_placement_masks(mgrid: MaskGrid,
                              chip_shape: Coord) -> PlacementSet:
    """All distinct host-cell masks where chip_shape (any rotation) fits —
    mask analog of torus.enumerate_placements."""
    grid = mgrid.grid
    blocks = candidate_host_blocks(chip_shape, grid.acc, grid.dims)
    if not blocks:
        return PlacementSet(mgrid, [])
    lib = native.load()
    if lib is not None:
        rank = mgrid.rank
        dims = (ctypes.c_int64 * rank)(*grid.dims)
        wrap = (ctypes.c_uint8 * rank)(*(1 if w else 0 for w in grid.wrap))
        flat = (ctypes.c_int64 * (len(blocks) * rank))(
            *(x for b in blocks for x in b))
        cap = 256
        while True:
            out = (ctypes.c_uint64 * (cap * mgrid.words))()
            n = lib.tpusched_enumerate_placements(
                dims, wrap, rank, flat, len(blocks), out, cap)
            if n >= 0:
                break
            cap *= 4  # buffer too small; grow and retry
        masks = []
        words = mgrid.words
        for i in range(n):
            m = 0
            for w in range(words):
                m |= out[i * words + w] << (64 * w)
            masks.append(m)
        return PlacementSet(mgrid, masks)
    # Fallback reuses the reference enumeration rather than duplicating the
    # trickiest logic (full-axis single anchor, wrap-only anchors, rotation
    # dedup); mask conversion is cheap next to the enumeration itself.
    return PlacementSet(
        mgrid, [mgrid.mask_of(p) for p in enumerate_placements(grid,
                                                               chip_shape)])


def feasible_membership(
        pset: PlacementSet, assigned: int, free: int,
        eligible: int) -> Tuple[int, Dict[str, int]]:
    """One pass over the placement set: how many placements survive this
    cycle's occupancy, and for each eligible host, in how many survivors it
    appears. Returns (survivor count, node name → membership)."""
    mgrid = pset.mgrid
    lib = native.load()
    if lib is not None and pset.masks:
        words = mgrid.words
        membership = (ctypes.c_int64 * mgrid.ncells)()
        # profiler attribution: native sweep time shows up as its own
        # /debug/profile plugin row instead of melting into TopologyMatch
        prev = tracectx.set_plugin("native:torus_engine")
        try:
            survivors = lib.tpusched_feasible_membership(
                pset.packed(), len(pset.masks), words,
                _to_words(assigned, words), _to_words(free, words),
                _to_words(eligible, words), membership, None)
        finally:
            tracectx.set_plugin(prev)
        counts: Dict[str, int] = {}
        for cell in range(mgrid.ncells):
            if membership[cell]:
                node = mgrid.node_of_cell[cell]
                if node is not None:
                    counts[node] = membership[cell]
        return survivors, counts
    survivors = 0
    cell_counts: Dict[int, int] = {}
    for m in pset.masks:
        if assigned & ~m:
            continue                      # assigned ⊄ placement
        if (m & ~assigned) & ~free:
            continue                      # claims a non-free host
        survivors += 1
        bits = m & eligible
        while bits:
            low = bits & -bits
            cell = low.bit_length() - 1
            cell_counts[cell] = cell_counts.get(cell, 0) + 1
            bits ^= low
    counts = {}
    for cell, n in cell_counts.items():
        node = mgrid.node_of_cell[cell]
        if node is not None:
            counts[node] = n
    return survivors, counts
