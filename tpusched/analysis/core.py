"""tpulint framework: file contexts, the rule registry, suppressions,
the runner, and output rendering.

Design notes:

- One ``FileContext`` per file, shared by every rule: the AST is parsed
  once, suppression comments are extracted once, and rules are pure
  functions of the context — this is what keeps the full-tree run inside
  the tier-1 latency budget (< 15 s, enforced by tests/test_analysis.py).
- Suppressions are per-line and per-rule, and the justification is part of
  the syntax: ``# tpulint: disable=RULE[,RULE2] — reason``.  A suppression
  with no reason, an unknown rule name, or one that never matches a finding
  is itself a finding (``suppression-hygiene``) — the suppression table
  must stay an honest ledger of known, justified exceptions.
- Rules see every file; scoping (``plugins/`` only, ``testing/`` exempt,
  ...) lives INSIDE each rule next to the invariant it checks, so reading
  one rule file tells the whole story.
"""
from __future__ import annotations

import ast
import dataclasses
import json
import re
import time
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Tuple, Type

# -- findings -----------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str          # repo-relative posix path
    line: int
    message: str
    col: int = 0

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule}: " \
               f"{self.message}"

    def to_dict(self) -> Dict:
        return dataclasses.asdict(self)


# -- suppressions -------------------------------------------------------------

# Directive shape: "tpulint: disable=<rule>[,<rule2>] <sep> <justification>"
# in a comment.  The reason separator accepts an em dash, a double hyphen,
# or a colon; the reason itself is mandatory (suppression-hygiene flags
# empty ones).
_SUPPRESS_RE = re.compile(
    r"#\s*tpulint:\s*disable=([A-Za-z0-9_,\- ]+?)"
    r"(?:\s*(?:—|--|:)\s*(.*))?$")


@dataclasses.dataclass
class Suppression:
    line: int                  # line the comment sits on
    rules: Tuple[str, ...]
    reason: str
    target: int                # line the suppression applies to: its own
    #                            for trailing comments, the next
    #                            non-comment line for standalone ones (a
    #                            justification may wrap over several
    #                            comment lines)
    used: bool = False

    def matches(self, finding: Finding) -> bool:
        return finding.rule in self.rules and finding.line == self.target


@dataclasses.dataclass(frozen=True)
class CallSite:
    """One ``self.<callee>(...)`` call inside a method, with the lexical
    lock context the interprocedural rules need: every ``with self.<g>:``
    / ``with self.<g>():`` guard name active at the site."""
    cls: str
    caller: str
    callee: str
    node: ast.Call
    guards: Tuple[str, ...]
    is_with_context: bool      # the call IS a with-statement's context expr


class _SelfCallCollector(ast.NodeVisitor):
    """Collects every ``self.<m>(...)`` site in one method, tracking the
    lexical ``with self.<g>[()]:`` guard stack.  Nested defs are traversed
    transparently (a closure built under the lock keeps the lexical
    context — same policy as the lock-discipline rule; the runtime
    recorder owns call-time truth)."""

    def __init__(self, cls_name: str, method_name: str):
        self.cls = cls_name
        self.caller = method_name
        self.guards: List[str] = []
        self.sites: List[CallSite] = []
        self._with_ctx: set = set()      # id() of Calls used as with items

    @staticmethod
    def _guard_name(expr: ast.AST) -> Optional[str]:
        if isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Name) \
                and expr.value.id == "self":
            return expr.attr
        if isinstance(expr, ast.Call) \
                and isinstance(expr.func, ast.Attribute) \
                and isinstance(expr.func.value, ast.Name) \
                and expr.func.value.id == "self":
            return expr.func.attr
        return None

    def visit_With(self, node: ast.With) -> None:
        added = 0
        for item in node.items:
            g = self._guard_name(item.context_expr)
            if g is not None:
                self.guards.append(g)
                added += 1
            if isinstance(item.context_expr, ast.Call):
                self._with_ctx.add(id(item.context_expr))
        self.generic_visit(node)
        for _ in range(added):
            self.guards.pop()

    visit_AsyncWith = visit_With

    def visit_Call(self, node: ast.Call) -> None:
        f = node.func
        if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name) \
                and f.value.id == "self":
            self.sites.append(CallSite(
                cls=self.cls, caller=self.caller, callee=f.attr, node=node,
                guards=tuple(self.guards),
                is_with_context=id(node) in self._with_ctx))
        self.generic_visit(node)


class FileContext:
    """Everything a rule needs about one file: source, AST, suppressions.

    The AST is walked ONCE here into ``nodes`` (+ a parent map); rules
    iterate that flat list instead of re-walking the tree — this is the
    difference between the full-tree pass taking seconds and taking ten.

    ``self_call_graph`` (lazy) adds the one-pass per-module call graph the
    interprocedural rules (locked-callgraph) consume: every
    ``self.<m>(...)`` call site per (class, method), annotated with its
    lexical lock context.  Built on first access only — ``--changed-only``
    runs never pay for call-graph construction on modules no rule asks
    about, and unchanged modules are never parsed at all.
    """

    def __init__(self, root: Path, path: Path):
        self.root = root
        self.path = path
        self.relpath = path.relative_to(root).as_posix()
        self.source = path.read_text(encoding="utf-8")
        self.lines = self.source.splitlines()
        self.tree: Optional[ast.AST] = None
        self.parse_error: Optional[str] = None
        try:
            self.tree = ast.parse(self.source, filename=str(path))
        except SyntaxError as e:
            self.parse_error = f"{e.msg} (line {e.lineno})"
        self.nodes: List[ast.AST] = []
        self._parent: Dict[int, ast.AST] = {}
        if self.tree is not None:
            stack = [self.tree]
            while stack:
                n = stack.pop()
                self.nodes.append(n)
                for c in ast.iter_child_nodes(n):
                    self._parent[id(c)] = n
                    stack.append(c)
        self._self_call_graph: Optional[List["CallSite"]] = None
        self.suppressions: List[Suppression] = []
        # lines strictly inside a multi-line string literal (docstrings):
        # a '# tpulint:' there is documentation, not a directive
        in_string: set = set()
        for n in self.nodes:
            if isinstance(n, ast.Constant) and isinstance(n.value, str) \
                    and getattr(n, "end_lineno", n.lineno) > n.lineno:
                in_string.update(range(n.lineno + 1, n.end_lineno))
        for i, line in enumerate(self.lines, start=1):
            m = _SUPPRESS_RE.search(line)
            if not m or i in in_string:
                continue
            rules = tuple(r.strip() for r in m.group(1).split(",")
                          if r.strip())
            reason = (m.group(2) or "").strip()
            target = i
            if line.lstrip().startswith("#"):
                # standalone comment: applies to the next non-comment
                # line, so a long justification can wrap
                target = i + 1
                while target <= len(self.lines) \
                        and self.lines[target - 1].lstrip().startswith("#"):
                    target += 1
            self.suppressions.append(Suppression(
                line=i, rules=rules, reason=reason,
                target=target))

    # convenience for rules ---------------------------------------------------

    def segment(self, node: ast.AST) -> str:
        """Source text of a node (best effort)."""
        try:
            return ast.get_source_segment(self.source, node) or ""
        except (ValueError, TypeError, IndexError):
            return ""

    def in_dir(self, *prefixes: str) -> bool:
        return any(self.relpath.startswith(p) for p in prefixes)

    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self._parent.get(id(node))

    def enclosing_function(self, node: ast.AST
                           ) -> Optional[ast.FunctionDef]:
        """Innermost function/method containing ``node`` (None at module
        level) — O(depth) via the parent map."""
        cur = self._parent.get(id(node))
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return cur
            cur = self._parent.get(id(cur))
        return None

    def has_identifier(self, idents: Sequence[str]) -> bool:
        """Does the FILE mention any of these identifiers?"""
        wanted = set(idents)
        for n in self.nodes:
            if isinstance(n, ast.Name) and n.id in wanted:
                return True
            if isinstance(n, ast.Attribute) and n.attr in wanted:
                return True
        return False

    @property
    def self_call_graph(self) -> List["CallSite"]:
        """Per-module call graph of ``self.<m>(...)`` sites, one pass over
        each class body, built lazily and cached.  ``guards`` carries the
        attribute names of every enclosing ``with self.<g>:`` /
        ``with self.<g>():`` item, which is how callers prove "the lock is
        lexically held here" to the locked-callgraph rule."""
        if self._self_call_graph is None:
            sites: List[CallSite] = []
            if self.tree is not None:
                for cls in ast.walk(self.tree):
                    if isinstance(cls, ast.ClassDef):
                        for m in cls.body:
                            if isinstance(m, (ast.FunctionDef,
                                              ast.AsyncFunctionDef)):
                                v = _SelfCallCollector(cls.name, m.name)
                                for stmt in m.body:
                                    v.visit(stmt)
                                sites.extend(v.sites)
            self._self_call_graph = sites
        return self._self_call_graph

    def import_aliases(self, module: str, attr: str) -> List[str]:
        """Every dotted spelling under which ``module.attr`` is reachable
        in this file: 'time.time' itself, 'alias.time' for
        ``import time as alias``, and bare names for
        ``from time import time [as t]``."""
        out = [f"{module}.{attr}"]
        for n in self.nodes:
            if isinstance(n, ast.Import):
                for a in n.names:
                    if a.name == module and a.asname:
                        out.append(f"{a.asname}.{attr}")
            elif isinstance(n, ast.ImportFrom) and n.module == module:
                for a in n.names:
                    if a.name == attr:
                        out.append(a.asname or a.name)
        return out


# -- rule registry ------------------------------------------------------------


class Rule:
    """Base class: one invariant.  ``check`` runs per file; ``finish`` runs
    once after every file (cross-file state like duplicate detection)."""

    name = ""
    summary = ""

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        return ()

    def finish(self) -> Iterable[Finding]:
        return ()

    # helper used by most rules
    def finding(self, ctx: FileContext, node: ast.AST, message: str
                ) -> Finding:
        return Finding(rule=self.name, path=ctx.relpath,
                       line=getattr(node, "lineno", 0),
                       col=getattr(node, "col_offset", 0), message=message)


RULES: Dict[str, Type[Rule]] = {}

SUPPRESSION_HYGIENE = "suppression-hygiene"


def register(cls: Type[Rule]) -> Type[Rule]:
    assert cls.name, "rule classes must set a name"
    assert cls.name not in RULES, f"duplicate rule {cls.name}"
    RULES[cls.name] = cls
    return cls


def rule_names() -> List[str]:
    return sorted(RULES) + [SUPPRESSION_HYGIENE]


# -- AST helpers shared by rules ---------------------------------------------


def dotted_name(node: ast.AST) -> str:
    """'a.b.c' for Name/Attribute chains, '' for anything else."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def iter_functions(tree: ast.AST):
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def references_identifier(node: ast.AST, idents: Sequence[str]) -> bool:
    """Does the subtree mention any of these identifiers (as a Name or an
    attribute component)?"""
    wanted = set(idents)
    for n in ast.walk(node):
        if isinstance(n, ast.Name) and n.id in wanted:
            return True
        if isinstance(n, ast.Attribute) and n.attr in wanted:
            return True
    return False


# -- runner -------------------------------------------------------------------


@dataclasses.dataclass
class Report:
    findings: List[Finding]
    suppressed: List[Tuple[Finding, Suppression]]
    files: int
    rules: List[str]
    duration_s: float
    errors: List[str]

    @property
    def clean(self) -> bool:
        return not self.findings and not self.errors

    def render_text(self) -> str:
        out = [f.render() for f in self.findings]
        out += [f"ERROR: {e}" for e in self.errors]
        verdict = "clean" if self.clean else \
            f"{len(self.findings)} finding(s)"
        out.append(f"tpulint: {self.files} file(s), {len(self.rules)} "
                   f"rule(s), {len(self.suppressed)} suppressed, "
                   f"{self.duration_s:.2f}s — {verdict}")
        return "\n".join(out)

    def to_json(self) -> str:
        return json.dumps({
            "version": 1,
            "files": self.files,
            "rules": self.rules,
            "findings": [f.to_dict() for f in self.findings],
            "suppressed": [
                {**f.to_dict(), "reason": s.reason,
                 "suppressed_at": s.line}
                for f, s in self.suppressed],
            "errors": self.errors,
            "duration_s": round(self.duration_s, 3),
        }, indent=None, sort_keys=True)

    def to_sarif(self) -> str:
        """SARIF 2.1.0 — the interchange format CI annotators consume, so
        findings land as inline review comments instead of a log to grep.
        One run, one result per finding; suppressed findings are emitted
        with a suppression record (SARIF's own model for them); tool
        errors become toolExecutionNotifications."""
        def rule_meta(name: str) -> Dict:
            if name == SUPPRESSION_HYGIENE:
                desc = ("suppressions must be justified, known and "
                        "actually used")
            else:
                cls = RULES.get(name)
                desc = cls.summary if cls is not None else ""
            return {"id": name, "shortDescription": {"text": desc}}

        def location(f: Finding) -> Dict:
            return {"physicalLocation": {
                "artifactLocation": {"uri": f.path,
                                     "uriBaseId": "SRCROOT"},
                "region": {"startLine": max(1, f.line),
                           "startColumn": max(1, f.col + 1)}}}

        def result(f: Finding, suppression: Optional[Suppression] = None
                   ) -> Dict:
            out = {"ruleId": f.rule, "level": "error",
                   "message": {"text": f.message},
                   "locations": [location(f)]}
            if suppression is not None:
                out["suppressions"] = [{
                    "kind": "inSource",
                    "justification": suppression.reason}]
            return out

        run = {
            "tool": {"driver": {
                "name": "tpulint",
                "informationUri":
                    "https://github.com/tpusched/tpusched",
                "rules": [rule_meta(n) for n in self.rules]}},
            "results": [result(f) for f in self.findings]
            + [result(f, s) for f, s in self.suppressed],
            "invocations": [{
                "executionSuccessful": not self.errors,
                "toolExecutionNotifications": [
                    {"level": "error", "message": {"text": e}}
                    for e in self.errors]}],
        }
        return json.dumps({
            "$schema": "https://json.schemastore.org/sarif-2.1.0.json",
            "version": "2.1.0",
            "runs": [run],
        }, indent=None, sort_keys=True)


class Runner:
    def __init__(self, root: Path, rule_names_filter:
                 Optional[Sequence[str]] = None):
        self.root = Path(root)
        all_names = rule_names()
        if rule_names_filter:
            unknown = sorted(set(rule_names_filter) - set(all_names))
            if unknown:
                raise ValueError(f"unknown rule(s): {', '.join(unknown)} "
                                 f"(known: {', '.join(all_names)})")
            self.active = list(dict.fromkeys(rule_names_filter))
        else:
            self.active = all_names
        self._rules: List[Rule] = [RULES[n]() for n in self.active
                                   if n in RULES]
        self._hygiene = SUPPRESSION_HYGIENE in self.active

    def run(self, paths: Sequence[Path]) -> Report:
        t0 = time.monotonic()
        files = self._collect(paths)
        errors: List[str] = []
        raw: List[Finding] = []
        contexts: List[FileContext] = []
        for path in files:
            try:
                ctx = FileContext(self.root, path)
            except OSError as e:
                errors.append(f"{path}: unreadable: {e}")
                continue
            if ctx.parse_error is not None:
                errors.append(f"{ctx.relpath}: syntax error: "
                              f"{ctx.parse_error}")
                continue
            contexts.append(ctx)
            for rule in self._rules:
                try:
                    raw.extend(rule.check(ctx))
                except Exception as e:
                    errors.append(f"{ctx.relpath}: rule {rule.name} "
                                  f"crashed: {e!r}")
        for rule in self._rules:
            try:
                raw.extend(rule.finish())
            except Exception as e:
                errors.append(f"rule {rule.name} finish crashed: {e!r}")

        findings, suppressed = self._apply_suppressions(contexts, raw)
        if self._hygiene:
            findings.extend(self._hygiene_findings(contexts))
        findings.sort(key=lambda f: (f.path, f.line, f.rule))
        return Report(findings=findings, suppressed=suppressed,
                      files=len(contexts), rules=self.active,
                      duration_s=time.monotonic() - t0, errors=errors)

    # -- internals ------------------------------------------------------------

    def _collect(self, paths: Sequence[Path]) -> List[Path]:
        out: List[Path] = []
        for p in paths:
            p = Path(p)
            if not p.is_absolute():
                p = self.root / p
            if p.is_dir():
                out.extend(sorted(f for f in p.rglob("*.py")
                                  if "__pycache__" not in f.parts))
            elif p.suffix == ".py" and p.exists():
                out.append(p)
        # stable + deduped
        seen, uniq = set(), []
        for f in out:
            if f not in seen:
                seen.add(f)
                uniq.append(f)
        return uniq

    def _apply_suppressions(self, contexts: List[FileContext],
                            raw: List[Finding]):
        by_path = {c.relpath: c for c in contexts}
        findings: List[Finding] = []
        suppressed: List[Tuple[Finding, Suppression]] = []
        for f in raw:
            ctx = by_path.get(f.path)
            hit = None
            if ctx is not None:
                for s in ctx.suppressions:
                    if s.matches(f):
                        hit = s
                        break
            if hit is not None:
                hit.used = True
                suppressed.append((f, hit))
            else:
                findings.append(f)
        return findings, suppressed

    def _hygiene_findings(self, contexts: List[FileContext]
                          ) -> List[Finding]:
        known = set(rule_names())
        active = set(self.active)
        out: List[Finding] = []
        for ctx in contexts:
            for s in ctx.suppressions:
                mk = lambda msg, s=s, ctx=ctx: Finding(  # noqa: E731
                    rule=SUPPRESSION_HYGIENE, path=ctx.relpath,
                    line=s.line, message=msg)
                if not s.reason:
                    out.append(mk(
                        "suppression carries no justification — write "
                        "tpulint: disable=<rule> — <why this is safe>"))
                bad = sorted(set(s.rules) - known)
                if bad:
                    out.append(mk(f"suppression names unknown rule(s): "
                                  f"{', '.join(bad)}"))
                # 'unused' is only decidable for rules that actually ran
                # this pass (the per-rule hack/ wrappers run subsets)
                if (not s.used and s.reason
                        and not bad and set(s.rules) <= active):
                    out.append(mk(
                        f"suppression for {','.join(s.rules)} matched no "
                        f"finding — stale; delete it"))
        return out
