"""tpulint: AST-based invariant analysis for the tpusched tree.

The repo's correctness conventions — all API traffic through the retrying
clientset, every Filter consults node health, Prometheus naming, structured
logging, the retriable-vs-terminal exception taxonomy, shadow-scheduler
telemetry isolation, monotonic clocks in duration math, thread and lock
discipline — started life as grep lints and review habit.  This package
turns them into real AST passes with one shared framework:

- a rule registry (``analysis.core.RULES``; add a rule by subclassing
  ``Rule`` and decorating with ``@register``),
- per-line suppressions that MUST carry a written justification
  (``# tpulint: disable=RULE — reason``), verified non-empty and actually
  used by the ``suppression-hygiene`` meta-rule,
- text and JSON output, stable exit codes (0 clean / 1 findings /
  2 usage-or-internal error),
- one interpreter pass over the tree: every rule shares each file's parsed
  AST, so ``make verify`` costs one parse per file, not one grep per rule.

Run it: ``python -m tpusched.cmd.lint`` (see that module for flags, incl.
``--changed-only`` for the pre-commit loop).  The runtime complement —
debug-mode instrumented locks that build the acquisition-order graph and
assert guarded-state mutations hold their declared lock — lives in
``tpusched/util/locking.py`` and is exercised by the chaos soaks.
"""
from __future__ import annotations

from .core import (Finding, Report, Rule, Runner, RULES, register,
                   rule_names)
from . import rules as _rules  # noqa: F401 — importing registers the rules

__all__ = ["Finding", "Report", "Rule", "Runner", "RULES", "register",
           "rule_names"]
