"""tpulint rules.  Importing this package registers every rule with
``analysis.core.RULES``; each module holds one invariant family so the
scoping and the rationale live next to the check."""
from __future__ import annotations

from . import api_calls        # noqa: F401
from . import callgraph        # noqa: F401
from . import clocks           # noqa: F401
from . import exceptions       # noqa: F401
from . import flow             # noqa: F401
from . import locks            # noqa: F401
from . import logging_discipline  # noqa: F401
from . import metrics_names    # noqa: F401
from . import node_health      # noqa: F401
from . import shadow           # noqa: F401
from . import threads          # noqa: F401
