"""shadow-isolation: ``telemetry=False`` code paths must not reach the
process-global observability surfaces.

Shadow schedulers (what-if planner, defrag trials — ``tpusched/sim/``)
schedule FORKED state holding the SAME pod/gang keys as the live fleet.
A trial that touches a global surface corrupts production telemetry: a
trial bind evicts the real pod's why-pending diagnosis, a trial's capacity
collector publishes hypothetical pool gauges as real, its SLO observations
dilute the production burn rate, and its cycle traces overwrite the live
gang's stitched trace (ROADMAP PR 5 closed exactly these leaks).  The
global surfaces are reached through a small, known accessor set, which is
what makes the invariant statically checkable:

    trace.default_recorder / install_recorder
    obs.default_engine / install_engine / default_slo / install_slo
    obs.default_profiler / install_profiler / ensure_profiler
    REGISTRY.gauge_func / REGISTRY.register_collector

The profiler/throughput additions (ISSUE 7) extend the same contract: a
shadow scheduler gets a private (or nil) profiler and an inert
``ThroughputTelemetry(publish=False)`` — a trial run must never publish
live hot-path samples or binds/sec.  The fleet-trace additions (ISSUE 9)
extend it again: a replay driver or shadow scheduler must never reach the
process-global fleet recorder (``default_fleetrecorder``/
``ensure_fleetrace``) — a replay's simulated binds journaled into the
live trace directory would forge fleet history.  The goodput additions
(ISSUE 10) extend it once more: the runtime-telemetry aggregator
(``default_goodput``/``install_goodput``/``ensure_goodput``) is a live
surface — a shadow publishing synthetic member reports would fabricate
fleet goodput, straggler anomalies and throughput-matrix cells; shadows
hold a private ``GoodputAggregator(publish=False)`` instead.  (The pure
data types — ``GoodputMatrix``, ``workload_fingerprint_of`` — are NOT
accessors: sim/ consumes matrices by value on purpose.)  The incident
plane (ISSUE 20) extends it once more: the health timeline, anomaly
sentinel and incident-bundle manager (``default_timeline``/
``default_sentinel``/``default_incidents``/``ensure_incidents`` and
their installers) are live surfaces — a shadow ticking the global
timeline would fold trial bind rates into the fleet health history and
a shadow firing the global sentinel would write trial incidents into
the operator's black box; shadows hold private ``publish=False``
instances with an in-memory bundle ring.

Checks:

1. ``tpusched/sim/`` may not reference any accessor (or ``REGISTRY`` at
   all), and every ``Scheduler(...)`` it constructs must pass
   ``telemetry=False`` explicitly;
2. everywhere else, a function that calls an accessor must visibly branch
   on the shadow marker — reference ``telemetry``/``_telemetry`` (the
   Scheduler flag) or ``publish``/``_publish`` (the SLO tracker's) in the
   same function — and module-level accessor calls are findings outright.

Exempt: the modules that DEFINE the accessors (``trace/__init__.py``,
``obs/__init__.py``), ``cmd/`` (process entry points wire the live
surfaces by contract), and ``testing/`` (harnesses swap recorders on
purpose, restoring them in ``finally``).
"""
from __future__ import annotations

import ast
from typing import Iterable, Optional

from ..core import (Finding, FileContext, Rule, dotted_name,
                    references_identifier, register)

_ACCESSORS = frozenset((
    "default_recorder", "install_recorder", "default_engine",
    "install_engine", "default_slo", "install_slo",
    "default_profiler", "install_profiler", "ensure_profiler",
    "default_fleetrecorder", "install_fleetrecorder", "ensure_fleetrace",
    "default_goodput", "install_goodput", "ensure_goodput",
    "default_timeline", "install_timeline",
    "default_sentinel", "install_sentinel",
    "default_incidents", "install_incidents", "ensure_incidents"))
_REGISTRY_METHODS = frozenset(("gauge_func", "register_collector"))
_GUARDS = ("telemetry", "_telemetry", "publish", "_publish")
_DEFINING = frozenset(("tpusched/trace/__init__.py",
                       "tpusched/obs/__init__.py"))


def _accessor_call(node: ast.Call) -> Optional[str]:
    name = dotted_name(node.func)
    if not name:
        return None
    leaf = name.rsplit(".", 1)[-1]
    if leaf in _ACCESSORS:
        return name
    if leaf in _REGISTRY_METHODS and "REGISTRY" in name.split("."):
        return name
    return None


@register
class ShadowIsolation(Rule):
    name = "shadow-isolation"
    summary = ("telemetry=False paths must not reach global metric "
               "registries, the live flight recorder, or SLO trackers")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        if not ctx.relpath.startswith("tpusched/"):
            return
        if ctx.in_dir("tpusched/sim/"):
            yield from self._check_shadow_module(ctx)
            return
        if ctx.relpath in _DEFINING \
                or ctx.in_dir("tpusched/cmd/", "tpusched/testing/"):
            return
        for node in ctx.nodes:
            if not isinstance(node, ast.Call):
                continue
            name = _accessor_call(node)
            if name is None:
                continue
            fn = ctx.enclosing_function(node)
            if fn is None:
                yield self.finding(
                    ctx, node,
                    f"{name}() at module level reaches the process-global "
                    f"telemetry surface unconditionally — shadow "
                    f"schedulers import this module too")
            elif not references_identifier(fn, _GUARDS):
                yield self.finding(
                    ctx, node,
                    f"{name}() without a telemetry/publish guard in "
                    f"{fn.name}(): a telemetry=False shadow reaching this "
                    f"path would corrupt live telemetry — branch on the "
                    f"shadow marker or suppress with justification")

    def _check_shadow_module(self, ctx: FileContext) -> Iterable[Finding]:
        for node in ctx.nodes:
            if isinstance(node, (ast.Import, ast.ImportFrom)):
                for alias in node.names:
                    leaf = alias.name.rsplit(".", 1)[-1]
                    if leaf in _ACCESSORS or leaf == "REGISTRY":
                        yield self.finding(
                            ctx, node,
                            f"shadow module imports global telemetry "
                            f"surface {leaf!r} — shadows get private "
                            f"instances (Scheduler(telemetry=False) "
                            f"builds them)")
            if isinstance(node, (ast.Name, ast.Attribute)):
                ident = node.attr if isinstance(node, ast.Attribute) \
                    else node.id
                if ident in _ACCESSORS or ident == "REGISTRY":
                    yield self.finding(
                        ctx, node,
                        f"shadow module references global telemetry "
                        f"surface {ident!r} — shadows get private "
                        f"instances (Scheduler(telemetry=False) builds "
                        f"them)")
            if isinstance(node, ast.Call):
                callee = dotted_name(node.func)
                if callee.rsplit(".", 1)[-1] == "Scheduler":
                    tkw = [k for k in node.keywords
                           if k.arg == "telemetry"]
                    if not tkw or not (
                            isinstance(tkw[0].value, ast.Constant)
                            and tkw[0].value.value is False):
                        yield self.finding(
                            ctx, node,
                            "Scheduler constructed in a shadow module "
                            "must pass telemetry=False explicitly — the "
                            "default wires the live flight recorder, "
                            "diagnosis engine and SLO tracker")
        return
