"""thread-hygiene: every ``threading.Thread`` is named and daemon-explicit.

An unnamed thread is anonymous in stack dumps, ``/debug/threads``, the
lock-order recorder's witness lines and py-spy profiles — exactly the
places you look when a fleet wedges.  An implicit ``daemon`` flag is a
shutdown-semantics decision made by omission: non-daemon threads pin the
interpreter on exit (the _BindingPool docstring documents a real instance
of that bite).  Both are one keyword each at construction time; the rule
makes them mandatory.
"""
from __future__ import annotations

import ast
from typing import Iterable

from ..core import Finding, FileContext, Rule, dotted_name, register


@register
class ThreadHygiene(Rule):
    name = "thread-hygiene"
    summary = "threading.Thread(...) must pass name= and daemon= explicitly"

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        if not ctx.relpath.startswith("tpusched/"):
            return
        for node in ctx.nodes:
            if not isinstance(node, ast.Call):
                continue
            callee = dotted_name(node.func)
            if callee not in ("threading.Thread", "Thread"):
                continue
            kwargs = {k.arg for k in node.keywords}
            missing = [k for k in ("name", "daemon") if k not in kwargs]
            if missing:
                yield self.finding(
                    ctx, node,
                    f"threading.Thread without explicit "
                    f"{'/'.join(missing)}= — unnamed threads are "
                    f"anonymous in stack dumps and lock-order reports, "
                    f"and implicit daemon-ness decides shutdown "
                    f"semantics by omission")
