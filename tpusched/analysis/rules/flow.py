"""Flow-sensitive concurrency rules: atomicity-violation and
snapshot-discipline.

These see what tpulint's per-statement rules cannot: a read-modify-write
whose read and write each sit under the lock but with a RELEASE in
between (the check-then-act window a concurrent writer slips through),
and snapshot objects escaping the read-only, function-local contract that
keeps the capacity collector honest against the equivalence cache's
arming guard (sched/cache.peek_snapshot's docstring is the spec).  They
are the static companions of the interleaving explorer (tpusched/verify):
the lint pins the pattern, the explorer pins the schedules.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from ..core import FileContext, Finding, Rule, dotted_name, register
from .locks import _guarded_decl, _is_self_attr, _self_field, _MUTATORS


class _AtomicityChecker(ast.NodeVisitor):
    """Walks one method tracking lock REGIONS (maximal ``with self.<lock>``
    spans): records locals bound from guarded-field reads inside region R,
    and flags guarded-field writes in a later region R' != R whose
    statement references such a local — the value crossed a lock release.

    Locals re-bound from anything that is not a guarded read drop out of
    the tracking (the stale value is gone).  Nested defs are transparent,
    same policy as lock-discipline."""

    def __init__(self, lock_attr: str, fields: Set[str]):
        self.lock_attr = lock_attr
        self.fields = fields
        self.region: Optional[int] = None
        self._next_region = 0
        # local name → (region, guarded field it was read from, lineno)
        self.reads: Dict[str, Tuple[int, str, int]] = {}
        # (node, local, read_field, read_line, written_field)
        self.hits: List[Tuple[ast.AST, str, str, int, str]] = []

    # -- regions ---------------------------------------------------------------

    def visit_With(self, node: ast.With) -> None:
        locked = any(_is_self_attr(item.context_expr, self.lock_attr)
                     for item in node.items)
        if locked and self.region is None:
            self._next_region += 1
            self.region = self._next_region
            self.generic_visit(node)
            self.region = None
        else:
            self.generic_visit(node)

    visit_AsyncWith = visit_With

    # -- guarded reads ---------------------------------------------------------

    def _guarded_read_fields(self, expr: ast.AST) -> List[str]:
        out = []
        for n in ast.walk(expr):
            f = _self_field(n, self.fields)
            if f is not None:
                out.append(f)
        return out

    def _visit_binding(self, targets, value: Optional[ast.AST],
                       node: ast.AST) -> None:
        self._check_write_targets(targets, node)
        read_fields = (self._guarded_read_fields(value)
                       if self.region is not None and value is not None
                       else [])
        for tgt in targets:
            elts = tgt.elts if isinstance(tgt, (ast.Tuple, ast.List)) \
                else [tgt]
            for n in elts:
                if isinstance(n, ast.Name):
                    if read_fields:
                        self.reads[n.id] = (self.region, read_fields[0],
                                            node.lineno)
                    else:
                        # re-bound from something else: stale value gone
                        self.reads.pop(n.id, None)
        self.generic_visit(node)

    def visit_Assign(self, node: ast.Assign) -> None:
        self._visit_binding(node.targets, node.value, node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:       # a bare annotation binds nothing
            self._visit_binding([node.target], node.value, node)
        else:
            self.generic_visit(node)

    # -- guarded writes --------------------------------------------------------

    def _written_field(self, tgt: ast.AST) -> Optional[str]:
        f = _self_field(tgt, self.fields)
        if f is not None:
            return f
        if isinstance(tgt, ast.Subscript):
            return _self_field(tgt.value, self.fields)
        return None

    def _check_write_targets(self, targets, stmt: ast.AST) -> None:
        if self.region is None:
            return                    # unlocked writes are lock-discipline's
        for tgt in targets:
            elts = tgt.elts if isinstance(tgt, (ast.Tuple, ast.List)) \
                else [tgt]
            for t in elts:
                f = self._written_field(t)
                if f is not None:
                    self._flag_stale_operands(stmt, f)

    def _flag_stale_operands(self, stmt: ast.AST, written: str) -> None:
        for n in ast.walk(stmt):
            if isinstance(n, ast.Name) and n.id in self.reads:
                r, read_field, line = self.reads[n.id]
                if r != self.region:
                    self.hits.append((stmt, n.id, read_field, line, written))
                    return

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._check_write_targets([node.target], node)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        if self.region is not None \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr in _MUTATORS:
            f = _self_field(node.func.value, self.fields)
            if f is not None:
                self._flag_stale_operands(node, f)
        self.generic_visit(node)


@register
class AtomicityViolation(Rule):
    name = "atomicity-violation"
    summary = ("a guarded read must not flow into a guarded write across "
               "a lock release (check-then-act)")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        if not ctx.relpath.startswith("tpusched/"):
            return
        for cls in ctx.nodes:
            if not isinstance(cls, ast.ClassDef):
                continue
            decl = _guarded_decl(cls)
            if decl is None:
                continue
            lock_attr, fields = decl
            for method in cls.body:
                if not isinstance(method, (ast.FunctionDef,
                                           ast.AsyncFunctionDef)):
                    continue
                if method.name.endswith("_locked"):
                    continue          # one region by contract
                chk = _AtomicityChecker(lock_attr, set(fields))
                chk.visit(method)
                for node, local, read_field, line, written in chk.hits:
                    yield self.finding(
                        ctx, node,
                        f"{cls.name}.{method.name}: writes guarded "
                        f"self.{written} using {local!r} read from "
                        f"guarded self.{read_field} at line {line} in an "
                        f"EARLIER critical section — the lock was "
                        f"released in between, so the value may be stale "
                        f"(check-then-act); merge the critical sections "
                        f"or re-read under the lock")


_SNAPSHOT_ALLOWED = ("tpusched/sched/", "tpusched/verify/")
_SNAP_ESCAPE_MUTATORS = _MUTATORS
# foreign-thread snapshot readers under the read-only/function-local
# contract: peek_snapshot (last loop-built view, may be stale) and
# shared_snapshot (persistent composed view, always fresh — ISSUE 14)
_SNAP_READERS = ("peek_snapshot", "shared_snapshot")


@register
class SnapshotDiscipline(Rule):
    name = "snapshot-discipline"
    summary = ("peek_snapshot() results stay read-only and function-"
               "local; cache.snapshot() only from dispatch-owned code")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        if not ctx.relpath.startswith("tpusched/"):
            return
        yield from self._check_snapshot_callers(ctx)
        yield from self._check_peek_usage(ctx)

    # -- snapshot(): dispatch-owned only --------------------------------------

    def _check_snapshot_callers(self, ctx: FileContext) -> Iterable[Finding]:
        if ctx.in_dir(*_SNAPSHOT_ALLOWED):
            return
        for n in ctx.nodes:
            if not (isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    and n.func.attr == "snapshot"):
                continue
            recv = dotted_name(n.func.value)
            last = recv.rsplit(".", 1)[-1].lower()
            if "cache" not in last:
                continue              # some other object's snapshot()
            yield self.finding(
                ctx, n,
                f"cache.snapshot() called from {ctx.relpath} — a rebuild "
                f"from outside the scheduling loop advances the snapshot "
                f"cursor mid-cycle and launders foreign mutations past "
                f"the equivalence cache's arming guard; foreign threads "
                f"read cache.peek_snapshot() instead (see "
                f"sched/cache.py)")

    # -- peek_snapshot(): read-only, function-local ---------------------------

    @staticmethod
    def _binding_targets(stmt) -> List[ast.AST]:
        if isinstance(stmt, ast.Assign):
            return list(stmt.targets)
        if isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            return [stmt.target]
        return []

    def _check_peek_usage(self, ctx: FileContext) -> Iterable[Finding]:
        """Sweep each function in source order, tracking which locals
        CURRENTLY hold a peek_snapshot() result: a name bound from
        peek_snapshot() enters the set, a later re-bind from anything
        else leaves it (the stale value is gone — without this, a plain
        list mutated before the name is reused for a snapshot would be
        flagged).  Lexical order stands in for execution order, same
        posture as the rest of the suite."""
        for fn in ctx.nodes:
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            has_peek = any(isinstance(n, ast.Attribute)
                           and n.attr in _SNAP_READERS
                           for n in ast.walk(fn))
            if not has_peek:
                continue
            nodes = sorted(
                (n for n in ast.walk(fn) if hasattr(n, "lineno")),
                key=lambda n: (n.lineno, n.col_offset))
            snaps: Set[str] = set()
            for n in nodes:
                finding = self._peek_violation(ctx, n, snaps)
                if finding is not None:
                    yield finding
                for tgt in self._binding_targets(n):
                    elts = tgt.elts if isinstance(tgt, (ast.Tuple,
                                                        ast.List)) \
                        else [tgt]
                    v = n.value
                    from_peek = (isinstance(v, ast.Call)
                                 and isinstance(v.func, ast.Attribute)
                                 and v.func.attr in _SNAP_READERS
                                 and len(elts) == 1)
                    for name_tgt in elts:
                        if not isinstance(name_tgt, ast.Name):
                            continue
                        if from_peek:
                            snaps.add(name_tgt.id)
                        else:
                            snaps.discard(name_tgt.id)

    def _peek_violation(self, ctx: FileContext, n: ast.AST,
                        snaps: Set[str]) -> Optional[Finding]:
        def is_snap(x) -> bool:
            return isinstance(x, ast.Name) and x.id in snaps

        if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute) \
                and n.func.attr in _SNAP_ESCAPE_MUTATORS:
            if is_snap(n.func.value):
                return self.finding(
                    ctx, n, f"mutates a peek_snapshot() result "
                            f"(.{n.func.attr}()) — snapshots are shared "
                            f"read-only state; clone before mutating")
            if isinstance(n.func.value, ast.Attribute) \
                    and _is_self_attr(n.func.value, n.func.value.attr) \
                    and any(is_snap(a) for a in n.args):
                return self.finding(
                    ctx, n, f"stores a peek_snapshot() result into "
                            f"self.{n.func.value.attr} "
                            f"(.{n.func.attr}()) — a snapshot must not "
                            f"outlive the function without an epoch pin")
        if isinstance(n, ast.Return) and n.value is not None \
                and is_snap(n.value):
            return self.finding(
                ctx, n, "returns a peek_snapshot() result — the snapshot "
                        "escapes the function and can outlive its epoch "
                        "in the caller's hands; read what you need here "
                        "and return that (or the cursor)")
        if isinstance(n, (ast.Assign, ast.AnnAssign)):
            targets = n.targets if isinstance(n, ast.Assign) \
                else [n.target]
            for tgt in targets:
                if isinstance(tgt, ast.Attribute) and is_snap(tgt.value):
                    return self.finding(
                        ctx, n, "writes an attribute on a peek_snapshot() "
                                "result — snapshots are read-only")
                if isinstance(tgt, ast.Subscript) and is_snap(tgt.value):
                    return self.finding(
                        ctx, n, "item-writes into a peek_snapshot() "
                                "result — snapshots are read-only")
                if isinstance(tgt, ast.Subscript) \
                        and isinstance(tgt.value, ast.Attribute) \
                        and _is_self_attr(tgt.value, tgt.value.attr) \
                        and n.value is not None \
                        and any(is_snap(v) for v in ast.walk(n.value)):
                    return self.finding(
                        ctx, n, "stores a peek_snapshot() result into a "
                                "container on self — a snapshot must not "
                                "outlive the function without an epoch "
                                "pin")
                if isinstance(tgt, ast.Attribute) \
                        and _is_self_attr(tgt, tgt.attr) \
                        and n.value is not None \
                        and any(is_snap(v) for v in ast.walk(n.value)):
                    return self.finding(
                        ctx, n, "stores a peek_snapshot() result on self — "
                                "a snapshot must not outlive the function "
                                "without an epoch pin; keep the cursor "
                                "(cache.snapshot_cursor()), not the object")
        return None
