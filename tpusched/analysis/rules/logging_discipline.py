"""structured-logging: library code logs through ``tpusched.util.klog``
(``info_s``/``error_s``/``warning_s`` with key=value pairs), never bare
``print()``.

Exemptions mirror the original grep lint: ``tpusched/cmd/`` binaries print
JSON/prose to stdout by contract, and ``tpusched/testing/`` is harness
output.  Everything else that prints is invisible to the trace-id
correlation klog provides (util/tracectx.py) and unparseable for fleet log
pipelines.
"""
from __future__ import annotations

import ast
from typing import Iterable

from ..core import Finding, FileContext, Rule, register


@register
class StructuredLogging(Rule):
    name = "structured-logging"
    summary = "no bare print() in library code — use tpusched.util.klog"

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        if not ctx.relpath.startswith("tpusched/") \
                or ctx.in_dir("tpusched/cmd/", "tpusched/testing/"):
            return
        for node in ctx.nodes:
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Name) \
                    and node.func.id == "print":
                yield self.finding(
                    ctx, node,
                    "bare print() in library code — use tpusched.util.klog "
                    "(info_s/warning_s/error_s) so the line carries the "
                    "cycle trace id and stays machine-parseable")
