"""monotonic-clock: no ``time.time()`` in library code, and no ad-hoc
wall reads at scheduler GATE sites.

Wall clocks jump — NTP slew, VM suspend, leap smearing — and a latency or
duration computed from two ``time.time()`` reads can come out negative or
wildly large, which then feeds SLO burn rates, backoff deadlines and trace
spans.  The discipline:

- durations/deadlines come from ``time.monotonic()``;
- schedulable timestamps come from the component's injected ``clock=``
  (every long-lived object here takes one — that is also what makes the
  soaks and unit tests deterministic);
- the few wall-time-by-design sites (heartbeat stamps compared against
  other wall stamps, log line prefixes) carry a
  ``# tpulint: disable=monotonic-clock — reason`` suppression, which is
  exactly the documentation a reviewer needs.

The rule flags ``time.time()`` CALLS only.  ``clock=time.time`` default
parameters and ``default_factory=time.time`` are references, not calls —
the injected-clock idiom stays free.

GATE-SITE discipline (ISSUE 15): the modules that own scheduler time
gates — backoff/flush queues, permit barriers, denial windows,
escalation TTLs, watchdogs — must route their clocks through the
injected handle clock (``util/clock.Clock``), because virtual-time
replay depends on every gate reading (and ARMING its deadlines on) the
one substrate.  In those modules this rule additionally flags:

- direct ``time.monotonic()`` CALLS — a gate deadline computed from a
  raw wall read is invisible to ``VirtualClock`` and silently breaks
  trace compression.  Legitimate live-surface sites (bounds on REAL
  thread blocking: pop() wait deadlines, shutdown joins, health-publish
  pacing) carry a justified suppression;
- ``clock=time.monotonic`` DEFAULT parameters — gate components default
  to ``clock=None`` and resolve the fallback in the body
  (``clock or time.monotonic``), so a constructor wired without the
  handle clock is a visible choice, not an invisible default.
"""
from __future__ import annotations

import ast
from typing import Iterable

from ..core import Finding, FileContext, Rule, dotted_name, register

# The scheduler-owned gate modules (relpath prefixes): everything here
# holds at least one time gate the virtual-time replay driver must be
# able to see.  util/clock.py itself is the substrate — exempt.
_GATE_MODULES = (
    "tpusched/sched/queue.py",
    "tpusched/sched/scheduler.py",
    "tpusched/sched/shards.py",
    "tpusched/fwk/runtime.py",
    "tpusched/util/ttlcache.py",
    "tpusched/plugins/coscheduling/",
)


@register
class MonotonicClock(Rule):
    name = "monotonic-clock"
    summary = ("no time.time() calls — monotonic for durations, injected "
               "clock= for timestamps; gate sites route through the "
               "handle clock")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        if not ctx.relpath.startswith("tpusched/"):
            return
        if ctx.relpath == "tpusched/util/clock.py":
            return      # the substrate itself wraps the raw reads
        # resolve `import time as _time` / `from time import time` so an
        # alias cannot smuggle a wall-clock read past the rule
        spellings = set(ctx.import_aliases("time", "time"))
        gate = any(ctx.relpath.startswith(p) for p in _GATE_MODULES)
        mono_spellings = set(ctx.import_aliases("time", "monotonic")) \
            if gate else set()
        for node in ctx.nodes:
            if isinstance(node, ast.Call):
                name = dotted_name(node.func)
                if name in spellings:
                    yield self.finding(
                        ctx, node,
                        "time.time() call: use time.monotonic() for "
                        "durations/deadlines, the injected clock= for "
                        "timestamps; wall-time-by-design sites must be "
                        "suppressed with a justification")
                elif gate and name in mono_spellings:
                    yield self.finding(
                        ctx, node,
                        "raw time.monotonic() in a scheduler gate "
                        "module: route through the injected handle "
                        "clock (util/clock) so virtual-time replay sees "
                        "the gate; live-surface sites (real thread-wait "
                        "bounds, shutdown joins, publish pacing) need a "
                        "justified suppression")
            elif gate and isinstance(node, ast.FunctionDef):
                for arg, default in self._defaults(node):
                    if arg == "clock" \
                            and dotted_name(default) in mono_spellings:
                        yield self.finding(
                            ctx, default,
                            "clock=time.monotonic default parameter in "
                            "a gate module: default to clock=None and "
                            "resolve `clock or time.monotonic` in the "
                            "body — wiring a gate without the handle "
                            "clock must be a visible choice")

    @staticmethod
    def _defaults(fn: ast.FunctionDef):
        """(arg name, default node) pairs, positional + kw-only."""
        args = fn.args
        pos = args.posonlyargs + args.args
        for arg, default in zip(pos[len(pos) - len(args.defaults):],
                                args.defaults):
            yield arg.arg, default
        for arg, default in zip(args.kwonlyargs, args.kw_defaults):
            if default is not None:
                yield arg.arg, default
