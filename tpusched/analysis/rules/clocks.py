"""monotonic-clock: no ``time.time()`` in library code.

Wall clocks jump — NTP slew, VM suspend, leap smearing — and a latency or
duration computed from two ``time.time()`` reads can come out negative or
wildly large, which then feeds SLO burn rates, backoff deadlines and trace
spans.  The discipline:

- durations/deadlines come from ``time.monotonic()``;
- schedulable timestamps come from the component's injected ``clock=``
  (every long-lived object here takes one — that is also what makes the
  soaks and unit tests deterministic);
- the few wall-time-by-design sites (heartbeat stamps compared against
  other wall stamps, log line prefixes) carry a
  ``# tpulint: disable=monotonic-clock — reason`` suppression, which is
  exactly the documentation a reviewer needs.

The rule flags ``time.time()`` CALLS only.  ``clock=time.time`` default
parameters and ``default_factory=time.time`` are references, not calls —
the injected-clock idiom stays free.
"""
from __future__ import annotations

import ast
from typing import Iterable

from ..core import Finding, FileContext, Rule, dotted_name, register


@register
class MonotonicClock(Rule):
    name = "monotonic-clock"
    summary = ("no time.time() calls — monotonic for durations, injected "
               "clock= for timestamps")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        if not ctx.relpath.startswith("tpusched/"):
            return
        # resolve `import time as _time` / `from time import time` so an
        # alias cannot smuggle a wall-clock read past the rule
        spellings = set(ctx.import_aliases("time", "time"))
        for node in ctx.nodes:
            if isinstance(node, ast.Call) \
                    and dotted_name(node.func) in spellings:
                yield self.finding(
                    ctx, node,
                    "time.time() call: use time.monotonic() for "
                    "durations/deadlines, the injected clock= for "
                    "timestamps; wall-time-by-design sites must be "
                    "suppressed with a justification")
