"""locked-callgraph: a ``*_locked`` method may only be invoked with its
lock lexically held, or from a caller that is itself ``*_locked``.

The ``*_locked`` suffix is the repo's caller-holds-the-lock contract
(``_flush_locked``, ``_pg_adjust_locked``, ...).  The lock-discipline rule
verifies such methods may MUTATE guarded state; this rule closes the other
half interprocedurally: nobody may CALL one without the lock.  It consumes
the one-pass per-module call graph ``FileContext.self_call_graph`` builds
lazily (so ``--changed-only`` runs never construct graphs for unchanged
modules).

A call site is judged guarded when any lexically enclosing
``with self.<g>[()]:`` names

- the class's declared ``@guarded_by`` lock, or
- a lock-shaped attribute (contains "lock", or the conventional ``_mu`` /
  ``_cond`` / ``_cv`` condition-variable names — a Condition over a
  GuardedLock IS the guard, as in sched/queue.py), or
- a ``*_locked()`` acquiring helper (``with self._locked():`` in
  sched/ha.py's file lease).

Exemptions: callers named ``*_locked`` (the contract propagates), the
call being itself a with-statement's context expression (that IS the
acquire), and ``__init__`` (construction happens-before publication).

Lexical by design, like lock-discipline: a caller that truly holds the
lock non-lexically should be renamed ``*_locked``; a wrong rename is
exactly what the runtime recorder and the interleaving explorer
(tpusched/verify) exist to catch.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable

from ..core import FileContext, Finding, Rule, register
from .locks import _guarded_decl

_CV_NAMES = frozenset(("_mu", "_cond", "_cv", "mu", "cond", "cv"))


def _lockish(guard: str) -> bool:
    return "lock" in guard or guard in _CV_NAMES


@register
class LockedCallgraph(Rule):
    name = "locked-callgraph"
    summary = ("*_locked methods are only called under their lock or from "
               "*_locked callers")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        if not ctx.relpath.startswith("tpusched/"):
            return
        declared: Dict[str, str] = {}
        for cls in ctx.nodes:
            if isinstance(cls, ast.ClassDef):
                decl = _guarded_decl(cls)
                if decl is not None:
                    declared[cls.name] = decl[0]
        for site in ctx.self_call_graph:
            if not site.callee.endswith("_locked"):
                continue
            if site.is_with_context:
                continue              # `with self._locked():` — the acquire
            if site.caller.endswith("_locked") or site.caller == "__init__":
                continue
            decl_lock = declared.get(site.cls)
            if any(g == decl_lock or _lockish(g) for g in site.guards):
                continue
            yield self.finding(
                ctx, site.node,
                f"{site.cls}.{site.caller}: calls self.{site.callee}() "
                f"without the lock lexically held — *_locked means the "
                f"CALLER holds the lock; wrap the call in 'with "
                f"self.{decl_lock or '_lock'}:' or rename the caller "
                f"*_locked")
