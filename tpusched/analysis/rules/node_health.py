"""node-health-filters: every placement-producing plugin path must consult
node readiness.

``api.core.node_health_error`` is the single shared judgement (unschedulable
spec, Ready=False condition, not-ready taint) — a Filter that skips it can
admit a NotReady node, and a gang retrying after a node failure would land
right back on the dead hardware the lifecycle controller just drained
(PR 4).  Two checks:

1. every file under ``tpusched/plugins/`` that defines a ``filter(self, ...)``
   extension point must reference ``node_health_error`` somewhere in the
   file (directly or via a helper defined there — candidate-set builders
   like TopologyMatch._occupancy are covered by the file-level check);
2. the helper itself (``tpusched/api/core.py``) must keep covering all
   three health facts — a refactor that drops one silently weakens every
   filter at once.
"""
from __future__ import annotations

import ast
from typing import Iterable

from ..core import Finding, FileContext, Rule, register

_FACTS = ("spec.unschedulable", "node_ready", "TAINT_NODE_NOT_READY")


@register
class NodeHealthFilters(Rule):
    name = "node-health-filters"
    summary = ("every plugin Filter must consult api.core.node_health_error")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        if ctx.relpath == "tpusched/api/core.py":
            yield from self._check_helper(ctx)
            return
        if not ctx.in_dir("tpusched/plugins/"):
            return
        filters = [
            n for n in ctx.nodes
            if isinstance(n, ast.FunctionDef) and n.name == "filter"
            and n.args.args and n.args.args[0].arg == "self"]
        if not filters:
            return
        if ctx.has_identifier(("node_health_error",)):
            return
        for fn in filters:
            yield self.finding(
                ctx, fn,
                "defines a Filter but the file never consults "
                "node_health_error — import it from tpusched.api.core and "
                "reject unhealthy nodes before any placement arithmetic")

    def _check_helper(self, ctx: FileContext) -> Iterable[Finding]:
        helper = None
        for n in ctx.nodes:
            if isinstance(n, ast.FunctionDef) \
                    and n.name == "node_health_error":
                helper = n
                break
        if helper is None:
            yield Finding(rule=self.name, path=ctx.relpath, line=1,
                          message="api/core.py no longer defines "
                                  "node_health_error — every Filter "
                                  "depends on it")
            return
        body = ctx.segment(helper)
        for fact in _FACTS:
            if fact not in body:
                yield self.finding(
                    ctx, helper,
                    f"node_health_error no longer checks {fact} — a "
                    f"refactor that drops one health fact silently weakens "
                    f"every filter at once")
