"""exception-taxonomy: no bare/overbroad ``except`` that swallows the
retriable-vs-terminal distinction.

``apiserver/errors.py`` is the repo's failure contract: ``Unavailable`` is
worth retrying, ``Throttled``/``NotFound``/non-patch ``Conflict`` are
terminal, and every resilience path (retry loops, gang rollback, degraded
mode) branches on that distinction.  A ``except:`` or an
``except Exception: pass`` upstream of those branches erases it — a
terminal error silently becomes "nothing happened" and the failure paths
PRs 3–4 built never fire.

The rule:

- bare ``except:`` is always a finding (it also catches KeyboardInterrupt/
  SystemExit);
- ``except Exception`` / ``except BaseException`` (alone or in a tuple) is
  a finding UNLESS the handler visibly deals with what it caught: it binds
  the exception and references it (logs/wraps/classifies it), or it
  re-raises.  A broad catch that inspects or re-raises preserves the
  taxonomy; one that silently drops the error does not.

Deliberate best-effort swallows (telemetry refresh, teardown) must carry a
``# tpulint: disable=exception-taxonomy — reason`` suppression; the reason
is the documentation reviewers get.
"""
from __future__ import annotations

import ast
from typing import Iterable

from ..core import Finding, FileContext, Rule, register

_BROAD = frozenset(("Exception", "BaseException"))


def _broad_names(type_node: ast.AST):
    """The broad names matched by an except clause's type expression."""
    nodes = type_node.elts if isinstance(type_node, ast.Tuple) \
        else [type_node]
    out = []
    for n in nodes:
        if isinstance(n, ast.Name) and n.id in _BROAD:
            out.append(n.id)
    return out


@register
class ExceptionTaxonomy(Rule):
    name = "exception-taxonomy"
    summary = ("no bare/overbroad except that silently swallows the "
               "retriable-vs-terminal error taxonomy")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        if not ctx.relpath.startswith("tpusched/"):
            return
        for node in ctx.nodes:
            if not isinstance(node, ast.ExceptHandler):
                continue
            if node.type is None:
                yield self.finding(
                    ctx, node,
                    "bare except: swallows every error (incl. "
                    "KeyboardInterrupt) and the retriable-vs-terminal "
                    "taxonomy with it — catch the specific "
                    "apiserver.errors classes, or Exception with "
                    "handling")
                continue
            broad = _broad_names(node.type)
            if not broad:
                continue
            if self._handles(node):
                continue
            yield self.finding(
                ctx, node,
                f"except {broad[0]} silently drops the error — bind it "
                f"and log/classify it (klog.error_s, "
                f"apiserver.errors.is_retriable), re-raise, or suppress "
                f"with a written justification")

    @staticmethod
    def _handles(handler: ast.ExceptHandler) -> bool:
        for n in handler.body:
            for sub in ast.walk(n):
                if isinstance(sub, ast.Raise):
                    return True
                if handler.name and isinstance(sub, ast.Name) \
                        and sub.id == handler.name:
                    return True
        return False
