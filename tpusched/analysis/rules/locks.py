"""lock-discipline: guarded fields are only mutated with the declared lock
held.

``@util.locking.guarded_by("_lock", "_pods", ...)`` declares which lock
guards which fields (sched/cache.py, sched/queue.py, trace/recorder.py,
obs/diagnosis.py, apiserver/informers.py carry the annotations).  This
rule reads the declaration and verifies, lexically, that every mutation of
a guarded field happens either

- inside a ``with self.<lock>:`` block (any enclosing depth within the
  method), or
- in a method whose name ends ``_locked`` — the repo's long-standing
  caller-holds-the-lock convention (``_flush_locked``,
  ``_trim_locked``, ...), or
- in ``__init__`` (construction happens-before publication).

Mutations recognized: attribute (re)binds and aug-assigns, subscript
stores/deletes (``self._pods[k] = v``), and calls of known mutator methods
on the field (``self._ring.append(...)``).  Reads are not checked — the
runtime half (debug-mode ``GuardedLock`` + the chaos soaks) covers what
lexical analysis cannot see, e.g. a ``*_locked`` helper actually called
without the lock.

The rule is lexical by design: it cannot prove a ``_locked`` method's
callers hold the lock, and a mutation threaded through an alias
(``d = self._pods; d[k] = v``) escapes it.  Those are exactly the cases
the runtime recorder catches; the two halves are one check.
"""
from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Set, Tuple

from ..core import Finding, FileContext, Rule, dotted_name, register

_MUTATORS = frozenset((
    "append", "appendleft", "add", "insert", "extend", "extendleft",
    "pop", "popitem", "popleft", "remove", "discard", "clear", "update",
    "setdefault", "move_to_end", "rotate", "sort", "reverse",
    "difference_update", "intersection_update",
    "symmetric_difference_update", "push", "set_fn"))


def _guarded_decl(cls: ast.ClassDef) -> Optional[Tuple[str, Tuple[str, ...]]]:
    """(lock_attr, fields) from a @guarded_by('...', ...) decorator, if
    present with constant-string args."""
    for dec in cls.decorator_list:
        if not isinstance(dec, ast.Call):
            continue
        if dotted_name(dec.func).rsplit(".", 1)[-1] != "guarded_by":
            continue
        consts = [a.value for a in dec.args
                  if isinstance(a, ast.Constant)
                  and isinstance(a.value, str)]
        if len(consts) >= 2:
            return consts[0], tuple(consts[1:])
    return None


def _is_self_attr(node: ast.AST, attr: str) -> bool:
    return (isinstance(node, ast.Attribute) and node.attr == attr
            and isinstance(node.value, ast.Name)
            and node.value.id == "self")


def _self_field(node: ast.AST, fields: Set[str]) -> Optional[str]:
    """The guarded field name if ``node`` is ``self.<field>``."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self" and node.attr in fields):
        return node.attr
    return None


class _MethodChecker(ast.NodeVisitor):
    """Walks one method tracking whether the current position is inside a
    ``with self.<lock>`` block; records unguarded mutations."""

    def __init__(self, lock_attr: str, fields: Set[str]):
        self.lock_attr = lock_attr
        self.fields = fields
        self.depth = 0
        self.hits: List[Tuple[ast.AST, str, str]] = []  # node, field, op

    def visit_With(self, node: ast.With) -> None:
        locked = any(_is_self_attr(item.context_expr, self.lock_attr)
                     for item in node.items)
        if locked:
            self.depth += 1
        self.generic_visit(node)
        if locked:
            self.depth -= 1

    # nested defs keep the lexical context: a closure defined inside
    # `with self._lock:` does NOT inherit the guard at call time, but
    # flagging it would false-positive the common "build callback under
    # lock" idiom; the runtime recorder owns that case.
    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self.generic_visit(node)

    def _record(self, node: ast.AST, field: str, op: str) -> None:
        if self.depth == 0:
            self.hits.append((node, field, op))

    def visit_Assign(self, node: ast.Assign) -> None:
        for tgt in node.targets:
            self._check_target(tgt)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._check_target(node.target)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self._check_target(node.target)
        self.generic_visit(node)

    def visit_Delete(self, node: ast.Delete) -> None:
        for tgt in node.targets:
            self._check_target(tgt)
        self.generic_visit(node)

    def _check_target(self, tgt: ast.AST) -> None:
        if isinstance(tgt, (ast.Tuple, ast.List)):
            for elt in tgt.elts:
                self._check_target(elt)
            return
        if isinstance(tgt, ast.Starred):
            self._check_target(tgt.value)
            return
        field = _self_field(tgt, self.fields)
        if field is not None:
            self._record(tgt, field, "rebind")
            return
        if isinstance(tgt, ast.Subscript):
            field = _self_field(tgt.value, self.fields)
            if field is not None:
                self._record(tgt, field, "item-write")

    def visit_Call(self, node: ast.Call) -> None:
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr in _MUTATORS:
            field = _self_field(node.func.value, self.fields)
            if field is not None:
                self._record(node, field, node.func.attr)
        self.generic_visit(node)


@register
class LockDiscipline(Rule):
    name = "lock-discipline"
    summary = ("@guarded_by fields are mutated only under their declared "
               "lock (or in *_locked methods)")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        if not ctx.relpath.startswith("tpusched/"):
            return
        for cls in ctx.nodes:
            if not isinstance(cls, ast.ClassDef):
                continue
            decl = _guarded_decl(cls)
            if decl is None:
                continue
            lock_attr, fields = decl
            fieldset = set(fields)
            for method in cls.body:
                if not isinstance(method, (ast.FunctionDef,
                                           ast.AsyncFunctionDef)):
                    continue
                if method.name == "__init__" \
                        or method.name.endswith("_locked"):
                    continue
                chk = _MethodChecker(lock_attr, fieldset)
                chk.visit(method)
                for node, field, op in chk.hits:
                    yield self.finding(
                        ctx, node,
                        f"{cls.name}.{method.name}: mutates guarded "
                        f"field self.{field} ({op}) outside 'with "
                        f"self.{lock_attr}:' — hold the declared lock, "
                        f"or rename the method *_locked if the caller "
                        f"holds it")
