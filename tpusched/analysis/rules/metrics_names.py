"""metrics-names: the Prometheus naming contract.

Every metric registered in ``tpusched/`` must follow the conventions this
repo standardizes on — a name that breaks them ships a dashboard/alert
footgun that can never be renamed cheaply once scraped:

1. ``tpusched_`` prefix (one namespace for the whole control plane);
2. counters end ``_total``; histograms end ``_seconds`` (the unit suffix —
   every histogram here is a duration); gauges never end ``_total``;
3. no duplicate registrations of one name from multiple sites
   (``gauge_func`` is exempt: per-scheduler re-registration under fresh
   label sets is its designed lifecycle).

Duplicate detection is cross-file state, so it reports from ``finish()``
— which means a ``--changed-only`` run only sees duplicates within the
changed subset; the full ``make verify`` pass is authoritative.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Tuple

from ..core import Finding, FileContext, Rule, dotted_name, register

_KINDS = frozenset(("counter", "counter_vec", "gauge", "gauge_vec",
                    "gauge_func", "histogram", "histogram_vec"))


@register
class MetricsNames(Rule):
    name = "metrics-names"
    summary = "Prometheus naming contract for REGISTRY registrations"

    def __init__(self):
        self._seen: Dict[str, Tuple[str, str]] = {}   # name → (site, kind)
        self._dups: List[Finding] = []

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        if not ctx.relpath.startswith("tpusched/"):
            return
        for node in ctx.nodes:
            if not isinstance(node, ast.Call) \
                    or not isinstance(node.func, ast.Attribute):
                continue
            kind = node.func.attr
            if kind not in _KINDS \
                    or not dotted_name(node.func).endswith("REGISTRY."
                                                           + kind):
                continue
            if not (node.args and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                continue     # dynamic names are the registry's problem
            name = node.args[0].value
            site = f"{ctx.relpath}:{node.lineno}"
            if not name.startswith("tpusched_"):
                yield self.finding(ctx, node,
                                   f"{name}: missing tpusched_ prefix")
            if kind in ("counter", "counter_vec") \
                    and not name.endswith("_total"):
                yield self.finding(ctx, node,
                                   f"{name}: counters must end _total")
            if kind in ("histogram", "histogram_vec") \
                    and not name.endswith("_seconds"):
                yield self.finding(ctx, node,
                                   f"{name}: histograms must end _seconds "
                                   f"(every histogram here is a duration)")
            if kind in ("gauge", "gauge_vec", "gauge_func") \
                    and name.endswith("_total"):
                yield self.finding(ctx, node,
                                   f"{name}: gauges must not end _total")
            prev = self._seen.get(name)
            if prev is not None and not (kind == "gauge_func"
                                         and prev[1] == "gauge_func"):
                self._dups.append(self.finding(
                    ctx, node, f"{name}: duplicate registration "
                               f"(also at {prev[0]})"))
            self._seen.setdefault(name, (site, kind))

    def finish(self) -> Iterable[Finding]:
        return self._dups
