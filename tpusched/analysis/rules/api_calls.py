"""naked-api-calls: all scheduler-side API traffic must flow through the
retrying Clientset (tpusched/apiserver/client.py).

Its error taxonomy, capped-backoff retries, per-call deadlines and
degraded-mode hooks are the resilience contract (PR 3); a direct store call
silently opts out of all of it.  Two patterns fail:

1. ``self._api.<anything>`` outside ``tpusched/apiserver/`` — the raw store
   handle is an apiserver-package implementation detail;
2. direct CRUD/bind/record_event on a bare ``self.api`` inside the
   scheduling core (``sched/``, ``fwk/``, ``plugins/``) — the scheduler
   owns a clientset precisely so its read/write/failure paths keep the
   retry layer (reads go through informer caches, writes through the
   client).

``testing/`` is exempt: harness plumbing talks to the raw store on purpose
(fixtures and watch monitors must not be attacked by the fault injector).
Informer wiring (add_watch/peek/current_resource_version) and controller
store bootstrap are out of scope — pattern 2 only names the mutating verbs.
"""
from __future__ import annotations

import ast
from typing import Iterable

from ..core import Finding, FileContext, Rule, register

_CORE_DIRS = ("tpusched/sched/", "tpusched/fwk/", "tpusched/plugins/")
_VERBS = frozenset(("create", "get", "try_get", "list", "update", "patch",
                    "delete", "bind", "record_event"))


@register
class NakedApiCalls(Rule):
    name = "naked-api-calls"
    summary = ("API calls must go through the retrying Clientset, not the "
               "raw store handle")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        if not ctx.relpath.startswith("tpusched/"):
            return
        exempt_raw = ctx.in_dir("tpusched/apiserver/", "tpusched/testing/")
        in_core = ctx.in_dir(*_CORE_DIRS)
        if exempt_raw and not in_core:
            return
        call_funcs = {id(n.func) for n in ctx.nodes
                      if isinstance(n, ast.Call)}
        for node in ctx.nodes:
            if not isinstance(node, ast.Attribute):
                continue
            base = node.value
            if not (isinstance(base, ast.Attribute)
                    and isinstance(base.value, ast.Name)
                    and base.value.id == "self"):
                continue
            if base.attr == "_api" and not exempt_raw:
                yield self.finding(
                    ctx, node,
                    f"self._api.{node.attr}: raw store access outside "
                    f"tpusched/apiserver/ — route through the Clientset "
                    f"(apiserver/client.py) or an informer lister")
            elif (base.attr == "api" and in_core and node.attr in _VERBS
                    and id(node) in call_funcs):
                yield self.finding(
                    ctx, node,
                    f"self.api.{node.attr}(...): direct store verb in the "
                    f"scheduling core bypasses the retry layer — use "
                    f"self.clientset / handle.client_set()")
