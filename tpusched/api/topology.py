"""TPU topology CRs — the TPU-native successor of the NodeResourceTopology CRD.

The reference's NUMA plugin consumes an external NodeResourceTopology CRD
listing per-NUMA-zone resources ("node-%d",
/root/reference/pkg/noderesourcetopology/pluginhelpers.go:69-89) and fits pods
with a 1-D bitmask (filter.go:84-150). The TPU generalization (SURVEY §5, §7.5):
a node pool publishes a ``TpuTopology`` CR describing its ICI torus — axes,
wraparound, host coordinates — and the topologymatch plugin fits *slice shapes*
(2x2x1 … 4x4x8) as sub-blocks of the torus.

Group: topology.tpu.dev.
"""
from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Dict, Tuple

from .meta import ObjectMeta

TOPOLOGY_GROUP = "topology.tpu.dev"

# Node labels published by the (emulated) TPU device plugin / node pool.
LABEL_POOL = "tpu.dev/pool"               # node-pool (slice) name
LABEL_ACCELERATOR = "tpu.dev/accelerator"  # e.g. "tpu-v5p"
LABEL_COORD = "tpu.dev/coord"              # host coordinate "x-y-z" in the pool torus
LABEL_DCN_DOMAIN = "tpu.dev/dcn-domain"    # DCN proximity domain (multislice scoring)


@dataclass(frozen=True)
class TpuAccelerator:
    """Static accelerator catalog entry (hardware model, not a CR)."""
    name: str
    ici_dims: int          # 2 for v5e (2-D torus/mesh), 3 for v5p (3-D torus)
    chips_per_host: int
    hbm_mb_per_chip: int
    max_dims: Tuple[int, ...]   # largest supported slice per axis (chips)


# Public topology facts (cloud.google.com/tpu docs):
# - v4: 3-D torus, 4 chips/host, 32 GB HBM, slices 2x2x1 … 16x16x16
# - v5e: 2-D mesh, hosts carry 1/4/8 chips (we model 4), 16 GB HBM, up to 16x16
# - v5p: 3-D torus, 4 chips/host, 95 GB HBM, up to 16x20x28
# - v6e (Trillium): 2-D mesh, 8 chips/host (ct6e-standard-8t), 32 GB HBM,
#   up to 16x16
V4 = TpuAccelerator("tpu-v4", ici_dims=3, chips_per_host=4,
                    hbm_mb_per_chip=32 * 1024, max_dims=(16, 16, 16))
V5E = TpuAccelerator("tpu-v5e", ici_dims=2, chips_per_host=4,
                     hbm_mb_per_chip=16 * 1024, max_dims=(16, 16))
V5P = TpuAccelerator("tpu-v5p", ici_dims=3, chips_per_host=4,
                     hbm_mb_per_chip=95 * 1024, max_dims=(16, 20, 28))
V6E = TpuAccelerator("tpu-v6e", ici_dims=2, chips_per_host=8,
                     hbm_mb_per_chip=32 * 1024, max_dims=(16, 16))

ACCELERATORS: Dict[str, TpuAccelerator] = {a.name: a
                                           for a in (V4, V5E, V5P, V6E)}


def parse_shape(s: str) -> Tuple[int, ...]:
    """'4x4x4' → (4,4,4). Raises ValueError on malformed shapes."""
    dims = tuple(int(p) for p in s.lower().split("x"))
    if not dims or any(d <= 0 for d in dims):
        raise ValueError(f"invalid slice shape {s!r}")
    return dims


def format_coord(c: Tuple[int, ...]) -> str:
    return "-".join(str(x) for x in c)


def parse_coord(s: str) -> Tuple[int, ...]:
    return tuple(int(p) for p in s.split("-"))


@dataclass
class TpuTopologySpec:
    pool: str = ""                       # node-pool name
    accelerator: str = "tpu-v5p"
    # Torus dims in CHIP units per axis, e.g. (8, 8, 4) for a v5p-256 pool.
    dims: Tuple[int, ...] = ()
    # Per-axis wraparound. Real slices get wraparound links only on full-size
    # axes; emulated pools set this explicitly.
    wrap: Tuple[bool, ...] = ()
    # Host coordinates in CHIP units (hosts own `chips_per_host` chips laid
    # out contiguously along the last axis): node name → base chip coordinate.
    hosts: Dict[str, Tuple[int, ...]] = field(default_factory=dict)
    chips_per_host: int = 4
    dcn_domain: str = ""


@dataclass
class TpuTopology:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    spec: TpuTopologySpec = field(default_factory=TpuTopologySpec)

    def __post_init__(self):
        self.meta.namespace = ""  # cluster-scoped, like NodeResourceTopology

    @property
    def key(self) -> str:
        return self.meta.key

    def deepcopy(self) -> "TpuTopology":
        spec = replace(self.spec)
        spec.hosts = dict(self.spec.hosts)  # coords are immutable tuples
        return TpuTopology(meta=self.meta.deepcopy(), spec=spec)
