"""Object metadata — the apimachinery slice the framework needs.

Replaces k8s.io/apimachinery ObjectMeta for the rebuilt control plane
(reference uses metav1.ObjectMeta throughout, e.g.
/root/reference/apis/scheduling/v1alpha1/types.go:30).
"""
from __future__ import annotations

import itertools
import threading
import time
from dataclasses import dataclass, field, replace
from typing import Dict, List, Optional

_uid_counter = itertools.count(1)
_uid_lock = threading.Lock()


def new_uid() -> str:
    with _uid_lock:
        return f"uid-{next(_uid_counter):08d}"


def bump_uid_counter(uids) -> None:
    """Advance the process-local uid counter past every recovered uid so a
    restarted process can never mint a colliding uid (recovery path,
    apiserver.persistence.load_into)."""
    global _uid_counter
    highest = 0
    for u in uids:
        if isinstance(u, str) and u.startswith("uid-"):
            try:
                highest = max(highest, int(u[4:]))
            except ValueError:
                continue
    with _uid_lock:
        nxt = next(_uid_counter)
        _uid_counter = itertools.count(max(nxt, highest + 1))


@dataclass
class OwnerReference:
    api_version: str = ""
    kind: str = ""
    name: str = ""
    uid: str = ""
    controller: bool = False


@dataclass
class ObjectMeta:
    name: str = ""
    namespace: str = "default"
    uid: str = field(default_factory=new_uid)
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    creation_timestamp: float = field(default_factory=time.time)
    deletion_timestamp: Optional[float] = None
    resource_version: int = 0
    owner_references: List[OwnerReference] = field(default_factory=list)

    @property
    def key(self) -> str:
        """namespace/name key, the canonical cache key (client-go
        MetaNamespaceKeyFunc). Lazily cached: name/namespace are immutable
        once an object is in play (k8s semantics; cluster-scoped kinds blank
        the namespace in their own __post_init__, before any access). The
        cache lives outside the dataclass fields so eq/repr/codec ignore it."""
        k = self.__dict__.get("_key")
        if k is None:
            k = f"{self.namespace}/{self.name}"
            self.__dict__["_key"] = k
        return k

    def deepcopy(self) -> "ObjectMeta":
        # Hand-rolled: all leaves are scalars, so shallow container copies
        # give full isolation at a fraction of copy.deepcopy's cost (the
        # API-server store copies every object on read/write — hot path).
        return ObjectMeta(
            name=self.name, namespace=self.namespace, uid=self.uid,
            labels=dict(self.labels), annotations=dict(self.annotations),
            creation_timestamp=self.creation_timestamp,
            deletion_timestamp=self.deletion_timestamp,
            resource_version=self.resource_version,
            owner_references=[replace(o) for o in self.owner_references])
