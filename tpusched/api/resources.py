"""Resource quantities and lists.

A deliberately simple replacement for k8s resource.Quantity: quantities are
plain integers in canonical units (cpu: millicores, memory: bytes, extended
resources: integral counts). The reference's device model corrupted itself by
aliasing Quantity pointers (/root/reference/pkg/flexgpu/gpu_node.go:134-144,
:55,:73 — `assumed := u.usedMemory; assumed.Add(...)` mutates shared state);
value-typed ints make that class of bug impossible here.
"""
from __future__ import annotations

from typing import Dict, Mapping

# Canonical resource names (k8s v1.ResourceName analogs).
CPU = "cpu"                     # millicores
MEMORY = "memory"               # bytes
PODS = "pods"                   # count
EPHEMERAL_STORAGE = "ephemeral-storage"  # bytes

# TPU-native extended resources (north star: zero nvidia.com/* references;
# successor of nvidia.flex.com/gpu + nvidia.flex.com/memory,
# /root/reference/pkg/flexgpu/flex_gpu.go:31-34).
TPU = "google.com/tpu"              # whole chips
TPU_MEMORY = "google.com/tpu-memory"  # HBM megabytes, fractional-chip sharing

_SUFFIXES = {
    "k": 10**3, "M": 10**6, "G": 10**9, "T": 10**12, "P": 10**15,
    "Ki": 2**10, "Mi": 2**20, "Gi": 2**30, "Ti": 2**40, "Pi": 2**50,
}

ResourceList = Dict[str, int]


def parse_quantity(value, resource: str = "") -> int:
    """Parse '2', '500m', '1Gi', 1.5 → canonical int units.

    cpu values are returned in millicores; everything else in base units.
    """
    if isinstance(value, (int, float)):
        if resource == CPU:
            return int(round(float(value) * 1000))
        return int(value)
    s = str(value).strip()
    if s.endswith("m"):
        n = int(float(s[:-1]))
        return n if resource == CPU else n  # milli only meaningful for cpu
    for suf in sorted(_SUFFIXES, key=len, reverse=True):
        if s.endswith(suf):
            base = float(s[: -len(suf)]) * _SUFFIXES[suf]
            return int(round(base * 1000)) if resource == CPU else int(base)
    if resource == CPU:
        return int(round(float(s) * 1000))
    return int(float(s))


def make_resources(**kw) -> ResourceList:
    """Builder: make_resources(cpu='2', memory='4Gi', tpu=4) → canonical ResourceList.

    Mirrors the reference's test builder MakeResourceList().CPU().Mem().GPU()
    (/root/reference/test/integration/utils.go:59-160).
    """
    out: ResourceList = {}
    alias = {"cpu": CPU, "memory": MEMORY, "mem": MEMORY, "pods": PODS,
             "tpu": TPU, "tpu_memory": TPU_MEMORY}
    for k, v in kw.items():
        name = alias.get(k, k)
        out[name] = parse_quantity(v, name)
    return out


def add_resources(a: Mapping[str, int], b: Mapping[str, int]) -> ResourceList:
    out = dict(a)
    for k, v in b.items():
        out[k] = out.get(k, 0) + v
    return out


def sub_resources(a: Mapping[str, int], b: Mapping[str, int]) -> ResourceList:
    out = dict(a)
    for k, v in b.items():
        out[k] = out.get(k, 0) - v
    return out


def resources_fit(request: Mapping[str, int], free: Mapping[str, int]) -> bool:
    """True if every requested resource fits into `free` (missing free ⇒ 0)."""
    return all(v <= free.get(k, 0) for k, v in request.items() if v > 0)


def any_resource_positive(r: Mapping[str, int]) -> bool:
    return any(v > 0 for v in r.values())
