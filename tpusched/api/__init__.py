"""API object model: core objects (Pod/Node/...), scheduling CRDs, topology CRs."""
