"""Scheduling CRDs: PodGroup and ElasticQuota.

TPU-native rebuild of the reference's scheduling.sigs.k8s.io/v1alpha1 group
(/root/reference/apis/scheduling/v1alpha1/types.go:30-180). Both types are
kept accelerator-agnostic (north star in BASELINE.json): resource lists may
name any resource including google.com/tpu.

Group name: scheduling.tpu.dev. Gang membership label:
``pod-group.scheduling.tpu.dev`` (analog of PodGroupLabel, types.go:113).
"""
from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Optional

from .meta import ObjectMeta
from .resources import ResourceList

GROUP_NAME = "scheduling.tpu.dev"
POD_GROUP_LABEL = "pod-group." + GROUP_NAME
# Lightweight (CRD-less) gang admission, KEP-2: quorum declared on the pod
# itself. Only consulted when no PodGroup CR exists for the labeled name.
MIN_AVAILABLE_LABEL = POD_GROUP_LABEL + "/min-available"

# PodGroup phases (types.go:84-111). The lifecycle driven by the PodGroup
# controller is "" → Pending → PreScheduling → Scheduling/Scheduled → Running
# → Finished/Failed (/root/reference/pkg/controller/podgroup.go:185-273).
PG_PENDING = "Pending"
PG_PRE_SCHEDULING = "PreScheduling"
PG_SCHEDULING = "Scheduling"
PG_SCHEDULED = "Scheduled"
PG_RUNNING = "Running"
PG_UNKNOWN = "Unknown"
PG_FINISHED = "Finished"
PG_FAILED = "Failed"


@dataclass
class PodGroupSpec:
    # Minimal number of members to run the gang; fewer ⇒ nobody starts.
    min_member: int = 0
    # Minimal aggregate resources for the gang; used by the coscheduling
    # PreFilter cluster-capacity dry-run.
    min_resources: Optional[ResourceList] = None
    # Max seconds gang members wait in Permit before mass rejection.
    schedule_timeout_seconds: Optional[int] = None
    # --- TPU-native extensions (no reference analog; see SURVEY §7) ---
    # Requested ICI slice shape, e.g. "4x4x4" on a v5p torus. Consumed by the
    # topologymatch plugin for all-or-nothing slice placement.
    tpu_slice_shape: str = ""
    # Requested accelerator type, e.g. "tpu-v5p" / "tpu-v5e".
    tpu_accelerator: str = ""
    # For multi-slice jobs: name of the MultiSliceSet this gang belongs to and
    # its slice ordinal; consumed by the multislice DCN-aware scorer.
    multislice_set: str = ""
    multislice_index: int = 0
    # Declared number of slices in the set (minMember one level up). When
    # > 1, the MultiSlice plugin holds every member gang at the permit
    # barrier until ALL member gangs have quorum — set-level all-or-nothing
    # admission. 0 (default) keeps the pre-existing behavior: slices admit
    # independently, DCN proximity is a scoring preference only.
    multislice_set_size: int = 0


@dataclass
class PodGroupStatus:
    phase: str = ""
    occupied_by: str = ""
    scheduled: int = 0
    running: int = 0
    succeeded: int = 0
    failed: int = 0
    schedule_start_time: Optional[float] = None


@dataclass
class PodGroup:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodGroupSpec = field(default_factory=PodGroupSpec)
    status: PodGroupStatus = field(default_factory=PodGroupStatus)

    @property
    def key(self) -> str:
        return self.meta.key

    def deepcopy(self) -> "PodGroup":
        spec = replace(self.spec)
        if self.spec.min_resources is not None:
            spec.min_resources = dict(self.spec.min_resources)
        return PodGroup(meta=self.meta.deepcopy(), spec=spec,
                        status=replace(self.status))


@dataclass
class ElasticQuotaSpec:
    # Min: guaranteed resources; Max: ceiling (types.go:30-63). used ≤ max
    # always; used > min means this quota is borrowing from others.
    min: ResourceList = field(default_factory=dict)
    max: ResourceList = field(default_factory=dict)


@dataclass
class ElasticQuotaStatus:
    used: ResourceList = field(default_factory=dict)


@dataclass
class ElasticQuota:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    spec: ElasticQuotaSpec = field(default_factory=ElasticQuotaSpec)
    status: ElasticQuotaStatus = field(default_factory=ElasticQuotaStatus)

    @property
    def key(self) -> str:
        return self.meta.key

    def deepcopy(self) -> "ElasticQuota":
        return ElasticQuota(
            meta=self.meta.deepcopy(),
            spec=ElasticQuotaSpec(min=dict(self.spec.min),
                                  max=dict(self.spec.max)),
            status=ElasticQuotaStatus(used=dict(self.status.used)))


def pod_group_label(pod) -> str:
    """Gang name from the membership label (util/podgroup.go:53-60)."""
    return pod.meta.labels.get(POD_GROUP_LABEL, "")


def pod_group_full_name(pod) -> str:
    """namespace/pgName, or "" for non-gang pods (util/podgroup.go:63-69)."""
    name = pod_group_label(pod)
    if not name:
        return ""
    return f"{pod.meta.namespace}/{name}"


# Pod-informer index on gang membership (client-go cache.Indexers analog),
# keyed "namespace/pgName": sibling listing is O(gang), not O(all pods).
# Registered by every consumer (coscheduling manager, multislice scorer,
# PodGroup controller) — add_index is idempotent per name.
POD_GROUP_INDEX = "tpusched/pod-group"


def pod_group_index_key(pod) -> Optional[str]:
    return pod_group_full_name(pod) or None
