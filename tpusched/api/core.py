"""Core API objects: Pod, Node, PriorityClass, PodDisruptionBudget, Binding.

The minimal slice of k8s core/v1 the scheduling framework needs, rebuilt as
plain dataclasses. Semantics follow the reference's usage of client-go types
(pods with resource requests, nodes with allocatable, binding subresource at
/root/reference/pkg/flexgpu/flex_gpu.go:230-242).
"""
from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Dict, List, Optional

from .meta import ObjectMeta
from .resources import CPU, MEMORY, ResourceList

# -- Pod phases (v1.PodPhase) -------------------------------------------------
POD_PENDING = "Pending"
POD_RUNNING = "Running"
POD_SUCCEEDED = "Succeeded"
POD_FAILED = "Failed"

# -- QoS classes (k8s component-helpers qos, used by the qossort plugin,
#    /root/reference/pkg/qos/queue_sort.go:42-59) -----------------------------
QOS_GUARANTEED = "Guaranteed"
QOS_BURSTABLE = "Burstable"
QOS_BEST_EFFORT = "BestEffort"

DEFAULT_SCHEDULER_NAME = "tpusched"


@dataclass
class Container:
    name: str = "main"
    image: str = ""
    requests: ResourceList = field(default_factory=dict)
    limits: ResourceList = field(default_factory=dict)


@dataclass
class Toleration:
    key: str = ""
    operator: str = "Equal"   # Equal | Exists
    value: str = ""
    effect: str = ""          # "" matches all effects


@dataclass
class Taint:
    key: str = ""
    value: str = ""
    effect: str = "NoSchedule"  # NoSchedule | PreferNoSchedule | NoExecute


@dataclass
class PodSpec:
    containers: List[Container] = field(default_factory=list)
    init_containers: List[Container] = field(default_factory=list)
    node_name: str = ""
    node_selector: Dict[str, str] = field(default_factory=dict)
    scheduler_name: str = DEFAULT_SCHEDULER_NAME
    priority: int = 0
    priority_class_name: str = ""
    tolerations: List[Toleration] = field(default_factory=list)
    overhead: ResourceList = field(default_factory=dict)


@dataclass
class PodCondition:
    type: str = ""
    status: str = "True"
    reason: str = ""
    message: str = ""
    last_transition_time: float = 0.0


@dataclass
class PodStatus:
    phase: str = POD_PENDING
    nominated_node_name: str = ""
    conditions: List[PodCondition] = field(default_factory=list)
    start_time: Optional[float] = None


@dataclass
class Pod:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodSpec = field(default_factory=PodSpec)
    status: PodStatus = field(default_factory=PodStatus)

    @property
    def key(self) -> str:
        return self.meta.key

    @property
    def name(self) -> str:
        return self.meta.name

    @property
    def namespace(self) -> str:
        return self.meta.namespace

    @property
    def priority(self) -> int:
        return self.spec.priority

    def deepcopy(self) -> "Pod":
        # Hand-rolled copy (see ObjectMeta.deepcopy): every leaf is a scalar.
        spec = self.spec
        status = self.status
        return Pod(
            meta=self.meta.deepcopy(),
            spec=PodSpec(
                containers=[Container(c.name, c.image, dict(c.requests),
                                      dict(c.limits)) for c in spec.containers],
                init_containers=[Container(c.name, c.image, dict(c.requests),
                                           dict(c.limits))
                                 for c in spec.init_containers],
                node_name=spec.node_name,
                node_selector=dict(spec.node_selector),
                scheduler_name=spec.scheduler_name,
                priority=spec.priority,
                priority_class_name=spec.priority_class_name,
                tolerations=[replace(t) for t in spec.tolerations],
                overhead=dict(spec.overhead)),
            status=PodStatus(
                phase=status.phase,
                nominated_node_name=status.nominated_node_name,
                conditions=[replace(c) for c in status.conditions],
                start_time=status.start_time))

    def qos_class(self) -> str:
        """QoS per k8s component-helpers (reference qossort dependency)."""
        requests: ResourceList = {}
        limits: ResourceList = {}
        all_guaranteed = True
        for c in self.spec.containers + self.spec.init_containers:
            for k, v in c.requests.items():
                if v > 0:
                    requests[k] = requests.get(k, 0) + v
            for k, v in c.limits.items():
                if v > 0:
                    limits[k] = limits.get(k, 0) + v
            for res in (CPU, MEMORY):
                if c.limits.get(res, 0) == 0 or c.requests.get(res, c.limits.get(res, 0)) != c.limits.get(res, 0):
                    all_guaranteed = False
        if not requests and not limits:
            return QOS_BEST_EFFORT
        if all_guaranteed and set(requests) == set(limits) and limits:
            if all(requests.get(k, 0) == v for k, v in limits.items()):
                return QOS_GUARANTEED
        return QOS_BURSTABLE

    def is_terminating(self) -> bool:
        return self.meta.deletion_timestamp is not None


# -- Node conditions (v1.NodeCondition, the slice node-health needs) ----------
NODE_READY = "Ready"

# Taint the node lifecycle controller places on NotReady nodes (analog of
# k8s node.kubernetes.io/not-ready). Placement-producing Filters also consult
# the Ready condition directly (node_health_error), so the taint is the
# operator-visible artifact, not the only line of defense.
TAINT_NODE_NOT_READY = "node.tpu.dev/not-ready"


@dataclass
class NodeCondition:
    type: str = ""
    status: str = "True"
    reason: str = ""
    message: str = ""
    last_transition_time: float = 0.0


@dataclass
class NodeStatus:
    capacity: ResourceList = field(default_factory=dict)
    allocatable: ResourceList = field(default_factory=dict)
    conditions: List[NodeCondition] = field(default_factory=list)
    # Last kubelet heartbeat (epoch seconds). None = the node is not
    # heartbeat-managed (fixture/legacy nodes): the lifecycle controller
    # never marks such nodes NotReady, which keeps every pre-existing test
    # fleet implicitly healthy.
    last_heartbeat_time: Optional[float] = None


@dataclass
class NodeSpec:
    unschedulable: bool = False
    taints: List[Taint] = field(default_factory=list)


@dataclass
class Node:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    spec: NodeSpec = field(default_factory=NodeSpec)
    status: NodeStatus = field(default_factory=NodeStatus)

    def __post_init__(self):
        self.meta.namespace = ""  # nodes are cluster-scoped

    @property
    def name(self) -> str:
        return self.meta.name

    def deepcopy(self) -> "Node":
        return Node(
            meta=self.meta.deepcopy(),
            spec=NodeSpec(unschedulable=self.spec.unschedulable,
                          taints=[replace(t) for t in self.spec.taints]),
            status=NodeStatus(
                capacity=dict(self.status.capacity),
                allocatable=dict(self.status.allocatable),
                conditions=[replace(c) for c in self.status.conditions],
                last_heartbeat_time=self.status.last_heartbeat_time))

    def ready_condition(self) -> Optional[NodeCondition]:
        for c in self.status.conditions:
            if c.type == NODE_READY:
                return c
        return None

    def set_condition(self, ctype: str, status: str, reason: str = "",
                      message: str = "", now: float = 0.0) -> bool:
        """Upsert a condition; last_transition_time moves only on a status
        flip (k8s semantics). Returns True if the status actually changed."""
        for c in self.status.conditions:
            if c.type == ctype:
                changed = c.status != status
                if changed:
                    c.last_transition_time = now
                c.status, c.reason, c.message = status, reason, message
                return changed
        self.status.conditions.append(NodeCondition(
            type=ctype, status=status, reason=reason, message=message,
            last_transition_time=now))
        return True


def node_ready(node: Node) -> bool:
    """Ready unless an explicit Ready=False condition says otherwise — an
    absent condition means a legacy/fixture node that predates the health
    model, and those must keep scheduling."""
    c = node.ready_condition()
    return c is None or c.status == "True"


def node_health_error(node: Node) -> Optional[str]:
    """Why this node must not receive NEW placements, or None if healthy.
    The single helper every placement-producing Filter consults
    (hack/verify-node-health-filters.sh lints for it): unschedulable spec,
    a NotReady condition, or the lifecycle controller's not-ready taint."""
    if node.spec.unschedulable:
        return "node(s) were unschedulable"
    if not node_ready(node):
        return "node(s) were NotReady"
    for t in node.spec.taints:
        if t.key == TAINT_NODE_NOT_READY and t.effect in ("NoSchedule",
                                                          "NoExecute"):
            return "node(s) had the not-ready taint"
    return None


def heartbeat_only_update(old: Node, new: Node) -> bool:
    """True when the ONLY delta between two Node versions is the kubelet
    heartbeat stamp.  Nothing scheduling-relevant reads it, so both the
    scheduler's informer path (cache mutation cursor, parked-pod wakeups)
    and the fleet trace capture (event volume) drop such updates — the
    same reason Kubernetes moved heartbeats off the Node object onto
    Leases.  The one shared predicate keeps the two paths agreeing on
    what counts as a real node change."""
    return (old.status.last_heartbeat_time != new.status.last_heartbeat_time
            and old.spec == new.spec
            and old.meta.labels == new.meta.labels
            and old.status.capacity == new.status.capacity
            and old.status.allocatable == new.status.allocatable
            and old.status.conditions == new.status.conditions)


@dataclass
class PriorityClass:
    """scheduling.k8s.io/v1 PriorityClass; annotations drive preemption
    toleration policy (/root/reference/pkg/preemptiontoleration/
    preemption_toleration_policy.go:26-53)."""
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    value: int = 0
    preemption_policy: str = "PreemptLowerPriority"  # or "Never"

    def __post_init__(self):
        self.meta.namespace = ""

    def deepcopy(self) -> "PriorityClass":
        return PriorityClass(meta=self.meta.deepcopy(), value=self.value,
                             preemption_policy=self.preemption_policy)


@dataclass
class PodDisruptionBudget:
    """policy/v1 PDB — only what the preemption reprieve loop needs
    (/root/reference/pkg/capacityscheduling/capacity_scheduling.go:857-902)."""
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    selector: Dict[str, str] = field(default_factory=dict)   # matchLabels only
    disruptions_allowed: int = 0

    def deepcopy(self) -> "PodDisruptionBudget":
        return PodDisruptionBudget(meta=self.meta.deepcopy(),
                                   selector=dict(self.selector),
                                   disruptions_allowed=self.disruptions_allowed)

    def matches(self, pod: Pod) -> bool:
        if not self.selector or pod.namespace != self.meta.namespace:
            return False
        return all(pod.meta.labels.get(k) == v for k, v in self.selector.items())


@dataclass
class Binding:
    """The Bind subresource payload. The reference's custom FlexGPU Bind copies
    pod annotations into the Binding object so the on-node device plugin can
    read the chosen device index (/root/reference/pkg/flexgpu/flex_gpu.go:230-242);
    we preserve that contract."""
    pod_key: str = ""
    node_name: str = ""
    annotations: Dict[str, str] = field(default_factory=dict)


@dataclass
class Event:
    """A k8s Event record (controllers emit these,
    /root/reference/pkg/controller/elasticquota.go:208)."""
    object_key: str = ""
    kind: str = ""
    type: str = "Normal"
    reason: str = ""
    message: str = ""
    timestamp: float = 0.0


@dataclass
class GangMemberStatus:
    """One in-band runtime progress report from a RUNNING gang member — the
    payload that rides the node heartbeat (``clientset.nodes.heartbeat(...,
    reports=[...])``) so runtime goodput telemetry costs zero extra API
    round trips. Advisory by contract, like Events: the apiserver fans
    reports out to registered status sinks (the goodput aggregator, the
    fleet trace capture) best-effort, and every sink is bounded and sheds —
    a report is never load-bearing for scheduling correctness.

    ``throughput`` is items of ``unit`` per second ACROSS this member
    (tokens for training/serving, examples for input-bound pipelines,
    requests for serving frontends). ``step`` is the member's step index —
    the per-member step SKEW within a gang is the straggler signal.
    ``ttft_s`` carries the serving time-to-first-token over the member's
    reporting window (0 = not a serving member); ``stall_s`` accumulates
    checkpoint/restore stall seconds inside the window."""
    pod_key: str = ""
    gang: str = ""              # PodGroup full name ("" = solo workload)
    step: int = 0               # step index / serving tick at report time
    step_time_s: float = 0.0    # seconds per step over the window
    throughput: float = 0.0     # unit/s across the member
    unit: str = "tokens"        # tokens | examples | requests
    ttft_s: float = 0.0         # serving TTFT over the window (0 = n/a)
    stall_s: float = 0.0        # checkpoint/restore stall in the window
    timestamp: float = 0.0      # wall clock; 0 = stamped by the server


def tolerates(pod: Pod, taint: Taint) -> bool:
    for t in pod.spec.tolerations:
        if t.effect and t.effect != taint.effect:
            continue
        if t.operator == "Exists":
            if not t.key or t.key == taint.key:
                return True
        elif t.key == taint.key and t.value == taint.value:
            return True
    return False
