"""PreemptionToleration: DefaultPreemption with exemptable victims.

Rebuild of /root/reference/pkg/preemptiontoleration: a victim whose
PriorityClass carries the annotations

- ``preemption-toleration.scheduling.tpu.dev/minimum-preemptable-priority``
  (default: pc.value + 1)
- ``preemption-toleration.scheduling.tpu.dev/toleration-seconds``
  (default 0 = no toleration; negative = tolerate forever)

is exempt from preemption by preemptors below the minimum priority, within
the toleration window measured from the victim's PodScheduled condition
(preemption_toleration.go:125-175). Victim selection is otherwise the
default-preemption algorithm (:182-283): all lower-priority pods minus
exempted, remove-all feasibility check, PDB-aware reprieve from the highest
priority down.
"""
from __future__ import annotations

import time
from typing import List, Optional, Tuple

from ..api.core import Pod, PodDisruptionBudget, PriorityClass
from ..config.types import PreemptionTolerationArgs
from ..fwk import CycleState, Status
from ..fwk.interfaces import (PostFilterPlugin, PostFilterResult)
from ..fwk.nodeinfo import NodeInfo
from ..sched.preemption import (Evaluator, GangDisruptionFloor,
                                PreemptionInterface, dry_run_remove,
                                reprieve_victims)
from ..util import klog

ANNOTATION_PREFIX = "preemption-toleration.scheduling.tpu.dev/"
ANNOTATION_MIN_PREEMPTABLE = ANNOTATION_PREFIX + "minimum-preemptable-priority"
ANNOTATION_TOLERATION_SECONDS = ANNOTATION_PREFIX + "toleration-seconds"


class Policy:
    def __init__(self, minimum_preemptable_priority: int, toleration_seconds: int):
        self.minimum_preemptable_priority = minimum_preemptable_priority
        self.toleration_seconds = toleration_seconds


def parse_policy(pc: PriorityClass) -> Optional[Policy]:
    """Returns None on a malformed annotation (⇒ no toleration,
    preemption_toleration_policy.go:56-84)."""
    try:
        min_str = pc.meta.annotations.get(ANNOTATION_MIN_PREEMPTABLE)
        minimum = int(min_str) if min_str is not None else pc.value + 1
        tol_str = pc.meta.annotations.get(ANNOTATION_TOLERATION_SECONDS)
        toleration = int(tol_str) if tol_str is not None else 0
        return Policy(minimum, toleration)
    except ValueError:
        return None


def exempted_from_preemption(victim: Pod, preemptor: Pod, pc_getter,
                             now: Optional[float] = None) -> bool:
    """preemption_toleration.go:125-175 (public policy check)."""
    if not victim.spec.priority_class_name:
        return False
    pc = pc_getter(victim.spec.priority_class_name)
    if pc is None:
        return False
    policy = parse_policy(pc)
    if policy is None:
        return False
    if preemptor.priority >= policy.minimum_preemptable_priority:
        return False
    if policy.toleration_seconds < 0:
        return True
    scheduled_at = None
    for cond in victim.status.conditions:
        if cond.type == "PodScheduled" and cond.status == "True":
            scheduled_at = cond.last_transition_time
    if scheduled_at is None:
        return True  # not yet scheduled: tolerate (no effect on nominated pods)
    # tpulint: disable=monotonic-clock — fallback for direct helper
    # calls in tests; both production call sites pass the plugin
    # handle's injected clock, and the compared field
    # (PodCondition.last_transition_time) is wall-clock API data
    now = time.time() if now is None else now
    return scheduled_at + policy.toleration_seconds > now


class PreemptionToleration(PostFilterPlugin):
    NAME = "PreemptionToleration"

    def __init__(self, args: Optional[PreemptionTolerationArgs], handle):
        self.args = args or PreemptionTolerationArgs()
        self.handle = handle

    @classmethod
    def new(cls, args, handle) -> "PreemptionToleration":
        return cls(args, handle)

    def _pc(self, name: str) -> Optional[PriorityClass]:
        return self.handle.informer_factory.priorityclasses().get("/" + name)

    def post_filter(self, state: CycleState, pod: Pod,
                    filtered_node_status_map) -> Tuple[Optional[PostFilterResult], Status]:
        evaluator = Evaluator(self.NAME, self.handle, state,
                              _Interface(self.handle, self._pc))
        return evaluator.preempt(pod, filtered_node_status_map)


class _Interface(PreemptionInterface):
    def __init__(self, handle, pc_getter):
        self.handle = handle
        self.pc_getter = pc_getter

    def pod_eligible_to_preempt_others(self, pod: Pod,
                                       nominated_node_status) -> bool:
        pc = self.pc_getter(pod.spec.priority_class_name) \
            if pod.spec.priority_class_name else None
        if pc is not None and pc.preemption_policy == "Never":
            return False
        # default-preemption terminating-victim check on the nominated node
        nom = pod.status.nominated_node_name
        if nom:
            from ..fwk.status import UNSCHEDULABLE_AND_UNRESOLVABLE
            if (nominated_node_status is not None and
                    nominated_node_status.code == UNSCHEDULABLE_AND_UNRESOLVABLE):
                return True
            info = self.handle.snapshot_shared_lister().get(nom)
            if info is not None:
                for p in info.pods:
                    if p.is_terminating() and p.priority < pod.priority:
                        return False
        return True

    def select_victims_on_node(self, state: CycleState, pod: Pod,
                               node_info: NodeInfo,
                               pdbs: List[PodDisruptionBudget]
                               ) -> Tuple[List[Pod], int, Status]:
        now = self.handle.clock()
        potential: List[Pod] = []
        floor = GangDisruptionFloor(self.handle)
        for p in list(node_info.pods):
            if p.priority >= pod.priority:
                continue
            # the exemption filter — the plugin's whole point
            # (preemption_toleration.go:208-229). Checked BEFORE the gang
            # floor: an exempted pod can never be evicted, so it must not
            # consume the gang's disruption budget (that would wrongly
            # veto legal victims behind it)
            if exempted_from_preemption(p, pod, self.pc_getter, now):
                klog.V(5).info_s("victim candidate exempted", victim=p.key,
                                 preemptor=pod.key)
                continue
            if not floor.may_evict(p):
                klog.V(5).info_s("victim candidate protected by gang "
                                 "minMember floor", victim=p.key)
                continue
            potential.append(p)
            err = dry_run_remove(self.handle, state, pod, p, node_info)
            if err:
                return [], 0, err
        if not potential:
            return [], 0, Status.unresolvable(
                f"No preemption victims found on node {node_info.node.name}")
        s = self.handle.run_filter_plugins_with_nominated_pods(state, pod, node_info)
        if not s.is_success():
            return [], 0, s

        return reprieve_victims(self.handle, state, pod, node_info, potential,
                                pdbs)
