"""QOSSort sample plugin: queue ordering by priority, then QoS class.

Rebuild of /root/reference/pkg/qos/queue_sort.go:42-59: priority desc;
tie-break Guaranteed > Burstable > BestEffort; final tie by queue time.
"""
from __future__ import annotations

from ..api.core import QOS_BEST_EFFORT, QOS_BURSTABLE, QOS_GUARANTEED
from ..fwk.interfaces import QueueSortPlugin

_QOS_ORDER = {QOS_GUARANTEED: 0, QOS_BURSTABLE: 1, QOS_BEST_EFFORT: 2}


class QOSSort(QueueSortPlugin):
    NAME = "QOSSort"

    def less(self, pi1, pi2) -> bool:
        p1, p2 = pi1.pod.priority, pi2.pod.priority
        if p1 != p2:
            return p1 > p2
        q1 = _QOS_ORDER[pi1.pod.qos_class()]
        q2 = _QOS_ORDER[pi2.pod.qos_class()]
        if q1 != q2:
            return q1 < q2
        return pi1.timestamp < pi2.timestamp
