"""Plugin suite. ``default_registry()`` is the analog of the reference's
``app.NewSchedulerCommand(app.WithPlugin(...))`` registration
(/root/reference/cmd/scheduler/main.go:34-47) — every in-tree plugin is
registered; profiles choose what is enabled."""
from __future__ import annotations

from ..fwk import Registry


def default_registry() -> Registry:
    # Imports are local so plugin modules can import this package's helpers.
    from . import defaults
    from .tpuslice import TpuSlice
    r = Registry()
    r.register(defaults.PrioritySort.NAME, lambda args, h: defaults.PrioritySort())
    r.register(defaults.NodeResourcesFit.NAME, lambda args, h: defaults.NodeResourcesFit())
    r.register(defaults.NodeUnschedulable.NAME, lambda args, h: defaults.NodeUnschedulable())
    r.register(defaults.TaintToleration.NAME, lambda args, h: defaults.TaintToleration())
    r.register(defaults.NodeName.NAME, lambda args, h: defaults.NodeName())
    r.register(defaults.NodeSelector.NAME, lambda args, h: defaults.NodeSelector())
    r.register(defaults.DefaultBinder.NAME, lambda args, h: defaults.DefaultBinder(h))
    r.register(TpuSlice.NAME, TpuSlice.new)
    _register_optional(r)
    return r


def _register_optional(r: Registry) -> None:
    """Plugins added by later milestones register here as they land."""
    try:
        from .coscheduling import Coscheduling
        r.register(Coscheduling.NAME, Coscheduling.new)
    except ImportError:
        pass
    try:
        from .qossort import QOSSort
        r.register(QOSSort.NAME, lambda args, h: QOSSort())
    except ImportError:
        pass
    try:
        from .podstate import PodState
        r.register(PodState.NAME, PodState.new)
    except ImportError:
        pass
    try:
        from .topologymatch import TopologyMatch
        r.register(TopologyMatch.NAME, TopologyMatch.new)
    except ImportError:
        pass
    try:
        from .capacity import CapacityScheduling
        r.register(CapacityScheduling.NAME, CapacityScheduling.new)
    except ImportError:
        pass
    try:
        from .multislice import MultiSlice
        r.register(MultiSlice.NAME, MultiSlice.new)
    except ImportError:
        pass
    try:
        from .allocatable import NodeResourcesAllocatable
        r.register(NodeResourcesAllocatable.NAME, NodeResourcesAllocatable.new)
    except ImportError:
        pass
    try:
        from .resourcelimits import NodeResourceLimits
        r.register(NodeResourceLimits.NAME, NodeResourceLimits.new)
    except ImportError:
        pass
    try:
        from .trimaran import TargetLoadPacking, LoadVariationRiskBalancing
        r.register(TargetLoadPacking.NAME, TargetLoadPacking.new)
        r.register(LoadVariationRiskBalancing.NAME, LoadVariationRiskBalancing.new)
    except ImportError:
        pass
    try:
        from .preemptiontoleration import PreemptionToleration
        r.register(PreemptionToleration.NAME, PreemptionToleration.new)
    except ImportError:
        pass
    try:
        from .crossnodepreemption import CrossNodePreemption
        r.register(CrossNodePreemption.NAME, CrossNodePreemption.new)
    except ImportError:
        pass
