"""CrossNodePreemption: multi-node victim search.

The reference ships this sample plugin FULLY COMMENTED OUT
(/root/reference/pkg/crossnodepreemption/cross_node_preemption.go:19-224 —
every body is inside a block comment). Upstream behavior: a PostFilter that
brute-force DFSes over lower-priority pods ACROSS nodes to find a victim set
whose removal makes the preemptor schedulable — useful when a gang's
MinResources gate needs capacity freed on several nodes at once (dfs :171-180,
dryRunOnePass :184-207).

Here it is implemented and registered but, like the reference, enabled in no
default profile. The search is bounded: candidates are capped and subsets are
explored smallest-first.
"""
from __future__ import annotations

from itertools import combinations
from typing import List, Optional, Tuple

from ..api.core import Pod
from ..apiserver import server as srv
from ..fwk import CycleState, Status
from ..fwk.interfaces import PostFilterPlugin, PostFilterResult
from ..util import klog
from ..util.metrics import preemption_attempts

MAX_CANDIDATES = 10   # 2^10 subsets worst case, explored smallest-first
MAX_VICTIMS = 4


class CrossNodePreemption(PostFilterPlugin):
    NAME = "CrossNodePreemption"

    def __init__(self, args, handle):
        self.handle = handle

    @classmethod
    def new(cls, args, handle) -> "CrossNodePreemption":
        return cls(args, handle)

    def post_filter(self, state: CycleState, pod: Pod,
                    filtered_node_status_map) -> Tuple[Optional[PostFilterResult], Status]:
        preemption_attempts.inc()
        snapshot = self.handle.snapshot_shared_lister()
        candidates: List[Pod] = []
        for info in snapshot.list():
            for p in info.pods:
                if p.priority < pod.priority and not p.is_terminating():
                    candidates.append(p)
        candidates.sort(key=lambda p: p.priority)
        candidates = candidates[:MAX_CANDIDATES]
        if not candidates:
            return None, Status.unschedulable("no cross-node victim candidates")

        for size in range(1, min(MAX_VICTIMS, len(candidates)) + 1):
            for subset in combinations(candidates, size):
                node = self._dry_run(state, pod, subset)
                if node:
                    self._execute(pod, subset, node)
                    return (PostFilterResult(nominated_node_name=node),
                            Status.success())
        return None, Status.unschedulable(
            f"no victim set of ≤{MAX_VICTIMS} pods unblocks {pod.key}")

    def _dry_run(self, state: CycleState, pod: Pod, victims) -> Optional[str]:
        """Remove `victims` from a cloned cluster view; return a node the pod
        then fits on (dryRunOnePass analog)."""
        snapshot = self.handle.snapshot_shared_lister()
        state_copy = state.clone()
        infos = {}
        by_node = {}
        for v in victims:
            by_node.setdefault(v.spec.node_name, []).append(v)
        for node_name, vs in by_node.items():
            info = snapshot.get(node_name)
            if info is None:
                return None
            info = info.clone()
            infos[node_name] = info
            for v in vs:
                if not info.remove_pod(v):
                    return None
                s = self.handle.framework.run_pre_filter_extension_remove_pod(
                    state_copy, pod, v, info)
                if not s.is_success():
                    return None
        # Upstream's dryRunOnePass runs only the RemovePod PreFilter
        # extensions (done above) plus Filter — never a full PreFilter
        # re-run, which would leak side effects from stateful gates
        # (e.g. Coscheduling's denied-PG TTL cache) into a what-if pass.
        # Cluster-wide gates that read the live snapshot stay approximate
        # until the victims' deletions land.
        for info in snapshot.list():
            info_to_use = infos.get(info.node.name, info)
            fs = self.handle.run_filter_plugins_with_nominated_pods(
                state_copy, pod, info_to_use)
            if fs.is_success():
                return info.node.name
        return None

    def _execute(self, pod: Pod, victims, node: str) -> None:
        cs = self.handle.clientset
        for v in victims:
            if not self.handle.reject_waiting_pod(
                    v.meta.uid, self.NAME, f"preempted by {pod.key}"):
                try:
                    cs.pods.delete(v.key)
                except srv.NotFound:
                    pass
            cs.record_event(v.key, "Pod", "Normal", "Preempted",
                            f"Cross-node preempted by {pod.key}")
            klog.V(3).info_s("cross-node preempted victim", victim=v.key,
                             preemptor=pod.key, node=node)
