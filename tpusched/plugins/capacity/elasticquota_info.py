"""In-memory ElasticQuota accounting.

Rebuild of /root/reference/pkg/capacityscheduling/elasticquota.go: per-
namespace {Min, Max, Used, pods} (:55-61), reserve/unreserve (:74-88),
bound comparisons via cmp2 (:90-100,165-181), aggregate borrow check
(:40-51), idempotent add/delete by pod key (:127-159), deep clone (:102-125).

Comparison semantics (cmp2): only resources *named by the bound* are
compared — Max omitting a resource means unlimited, Min omitting one means
no guarantee.
"""
from __future__ import annotations

from typing import Dict, Iterable, Optional, Set

from ...api.core import Pod
from ...api.resources import ResourceList, add_resources
from ...util.podutil import pod_effective_request


def _over(used: ResourceList, delta: Optional[ResourceList],
          bound: ResourceList) -> bool:
    """any resource named in `bound` with used+delta > bound."""
    for k, b in bound.items():
        v = used.get(k, 0) + (delta.get(k, 0) if delta else 0)
        if v > b:
            return True
    return False


class ElasticQuotaInfo:
    __slots__ = ("namespace", "min", "max", "used", "pods")

    def __init__(self, namespace: str, min: Optional[ResourceList] = None,
                 max: Optional[ResourceList] = None,
                 used: Optional[ResourceList] = None,
                 pods: Optional[Set[str]] = None):
        self.namespace = namespace
        self.min: ResourceList = dict(min or {})
        self.max: ResourceList = dict(max or {})
        self.used: ResourceList = dict(used or {})
        self.pods: Set[str] = set(pods or ())

    # -- accounting -----------------------------------------------------------

    def reserve_resource(self, req: ResourceList) -> None:
        for k, v in req.items():
            self.used[k] = self.used.get(k, 0) + v

    def unreserve_resource(self, req: ResourceList) -> None:
        for k, v in req.items():
            self.used[k] = self.used.get(k, 0) - v

    def add_pod_if_not_present(self, pod: Pod) -> None:
        if pod.key in self.pods:
            return
        self.pods.add(pod.key)
        self.reserve_resource(pod_effective_request(pod))

    def delete_pod_if_present(self, pod: Pod) -> None:
        if pod.key not in self.pods:
            return
        self.pods.discard(pod.key)
        self.unreserve_resource(pod_effective_request(pod))

    # -- comparisons ----------------------------------------------------------

    def used_over_min_with(self, req: Optional[ResourceList] = None) -> bool:
        return _over(self.used, req, self.min)

    def used_over_max_with(self, req: Optional[ResourceList] = None) -> bool:
        return _over(self.used, req, self.max)

    def used_over_min(self) -> bool:
        return self.used_over_min_with(None)

    def clone(self) -> "ElasticQuotaInfo":
        return ElasticQuotaInfo(self.namespace, self.min, self.max, self.used,
                                self.pods)


class ElasticQuotaInfos(dict):
    """namespace → ElasticQuotaInfo (elasticquota.go:26)."""

    def aggregated_used_over_min_with(self, req: ResourceList) -> bool:
        """Σ used + req > Σ min for any resource named by some Min — the
        global borrow gate (elasticquota.go:40-51)."""
        total_used: ResourceList = {}
        total_min: ResourceList = {}
        for info in self.values():
            total_used = add_resources(total_used, info.used)
            total_min = add_resources(total_min, info.min)
        return _over(total_used, req, total_min)

    def clone(self) -> "ElasticQuotaInfos":
        out = ElasticQuotaInfos()
        for ns, info in self.items():
            out[ns] = info.clone()
        return out
