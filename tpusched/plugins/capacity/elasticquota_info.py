"""In-memory ElasticQuota accounting.

Rebuild of /root/reference/pkg/capacityscheduling/elasticquota.go: per-
namespace {Min, Max, Used, pods} (:55-61), reserve/unreserve (:74-88),
bound comparisons via cmp2 (:90-100,165-181), aggregate borrow check
(:40-51), idempotent add/delete by pod key (:127-159), deep clone (:102-125).

Comparison semantics (cmp2): only resources *named by the bound* are
compared — Max omitting a resource means unlimited, Min omitting one means
no guarantee.
"""
from __future__ import annotations

from typing import Dict, Iterable, Optional, Set

from ...api.core import Pod
from ...api.resources import ResourceList, add_resources
from ...util.podutil import pod_effective_request, resources_over_bound

# the ONE bound comparator, shared with the cache's commit-time
# compare-and-reserve (sched/cache.py) — admission and commit must
# evaluate the identical rule or the quota protocol is unsound
_over = resources_over_bound


class LazyPodKeys:
    """Deferred pod-key membership for a quota admission snapshot: the
    sets are consumed ONLY by preemption dry-run idempotency
    (add/delete_pod_if_present), so the common admission cycle must not
    pay an O(scheduled-quota-pods) copy per namespace per cycle
    (cache.quota_view hands out a loader instead; the copy happens on
    first dry-run touch).  Loaded after the view's critical section, so
    membership may lag ``used`` by the in-flight window — conservative
    for dry-run arithmetic (a just-released pod reads as still counted)
    and irrelevant to admission, which never reads membership."""

    __slots__ = ("_loader", "_set")

    def __init__(self, loader):
        self._loader = loader
        self._set = None

    def _materialized(self) -> set:
        if self._set is None:
            self._set = set(self._loader())
        return self._set

    def __contains__(self, key) -> bool:
        return key in self._materialized()

    def __iter__(self):
        return iter(self._materialized())

    def __len__(self) -> int:
        return len(self._materialized())

    def add(self, key) -> None:
        self._materialized().add(key)

    def discard(self, key) -> None:
        self._materialized().discard(key)


class ElasticQuotaInfo:
    __slots__ = ("namespace", "min", "max", "used", "pods")

    def __init__(self, namespace: str, min: Optional[ResourceList] = None,
                 max: Optional[ResourceList] = None,
                 used: Optional[ResourceList] = None,
                 pods: Optional[Set[str]] = None):
        self.namespace = namespace
        self.min: ResourceList = dict(min or {})
        self.max: ResourceList = dict(max or {})
        self.used: ResourceList = dict(used or {})
        self.pods: Set[str] = set(pods or ())

    # -- accounting -----------------------------------------------------------

    def reserve_resource(self, req: ResourceList) -> None:
        for k, v in req.items():
            self.used[k] = self.used.get(k, 0) + v

    def unreserve_resource(self, req: ResourceList) -> None:
        for k, v in req.items():
            self.used[k] = self.used.get(k, 0) - v

    def add_pod_if_not_present(self, pod: Pod) -> None:
        if pod.key in self.pods:
            return
        self.pods.add(pod.key)
        self.reserve_resource(pod_effective_request(pod))

    def delete_pod_if_present(self, pod: Pod) -> None:
        if pod.key not in self.pods:
            return
        self.pods.discard(pod.key)
        self.unreserve_resource(pod_effective_request(pod))

    # -- comparisons ----------------------------------------------------------

    def used_over_min_with(self, req: Optional[ResourceList] = None) -> bool:
        return _over(self.used, req, self.min)

    def used_over_max_with(self, req: Optional[ResourceList] = None) -> bool:
        return _over(self.used, req, self.max)

    def used_over_min(self) -> bool:
        return self.used_over_min_with(None)

    def clone(self) -> "ElasticQuotaInfo":
        return ElasticQuotaInfo(self.namespace, self.min, self.max, self.used,
                                self.pods)

    @classmethod
    def from_parts(cls, namespace: str, min: ResourceList, max: ResourceList,
                   used: ResourceList, pods: Set[str]) -> "ElasticQuotaInfo":
        """Adopt already-copied parts WITHOUT re-copying — the cache quota
        ledger's ``quota_view()`` hands out fresh dict/set copies per call
        (one consistent critical section), so the constructor's defensive
        copies would only double the per-cycle allocation."""
        info = cls.__new__(cls)
        info.namespace = namespace
        info.min = min
        info.max = max
        info.used = used
        info.pods = pods
        return info


class ElasticQuotaInfos(dict):
    """namespace → ElasticQuotaInfo (elasticquota.go:26)."""

    def aggregated_used_over_min_with(self, req: ResourceList) -> bool:
        """Σ used + req > Σ min for any resource named by some Min — the
        global borrow gate (elasticquota.go:40-51)."""
        total_used: ResourceList = {}
        total_min: ResourceList = {}
        for info in self.values():
            total_used = add_resources(total_used, info.used)
            total_min = add_resources(total_min, info.min)
        return _over(total_used, req, total_min)

    def clone(self) -> "ElasticQuotaInfos":
        out = ElasticQuotaInfos()
        for ns, info in self.items():
            out[ns] = info.clone()
        return out
