from .plugin import CapacityScheduling
from .elasticquota_info import ElasticQuotaInfo, ElasticQuotaInfos

__all__ = ["CapacityScheduling", "ElasticQuotaInfo", "ElasticQuotaInfos"]
