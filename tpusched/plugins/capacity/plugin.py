"""CapacityScheduling plugin: ElasticQuota min/max with borrowing and
quota-aware preemption.

Rebuild of /root/reference/pkg/capacityscheduling/capacity_scheduling.go:
- PreFilter snapshots all quota state into CycleState and rejects if
  used+pod > max, or the aggregate used would exceed Σmin, with
  nominated-pod accounting (:201-275);
- PreFilterExtensions Add/RemovePod keep the snapshot consistent during
  preemption dry-runs (:283-318);
- PostFilter runs the preemption Evaluator with quota-aware victim selection
  (:320-338, :465-644): borrowing semantics — if the preemptor's quota would
  stay within min, victims come from OTHER quotas that are over min
  (borrowers); otherwise from the SAME quota at lower priority;
- Reserve/Unreserve maintain live Used (:340-366);
- informer handlers mirror EQ CRs and assigned pods into memory (:646-751).
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

from ...api.core import Pod, PodDisruptionBudget
from ...api.resources import ResourceList
from ...api.scheduling import ElasticQuota
from ...fwk import CycleState, QUOTA_GUARD_STATE_KEY, Status
from ...fwk.interfaces import (ClusterEvent, EnqueueExtensions,
                               EquivalenceAware, EVENT_ADD,
                               EVENT_DELETE, EVENT_UPDATE, PostFilterPlugin,
                               PostFilterResult, PreFilterExtensions,
                               PreFilterPlugin, ReservePlugin,
                               RESOURCE_ELASTIC_QUOTA, RESOURCE_POD)
from ...fwk.nodeinfo import NodeInfo
from ...sched.preemption import (Evaluator, GangDisruptionFloor,
                                 PreemptionInterface, dry_run_remove,
                                 more_important_pod, reprieve_victims)
from ...util import klog
from ...util.podutil import assigned, is_pod_terminated, pod_effective_request
from .elasticquota_info import (ElasticQuotaInfo, ElasticQuotaInfos,
                                LazyPodKeys)

EQ_SNAPSHOT_KEY = "CapacityScheduling/elasticQuotaSnapshot"
PRE_FILTER_STATE_KEY = "CapacityScheduling/preFilterState"


class _EQSnapshot:
    def __init__(self, infos: ElasticQuotaInfos):
        self.infos = infos

    def clone(self):
        return _EQSnapshot(self.infos.clone())


class _PreFilterState:
    def __init__(self, pod_req: ResourceList,
                 nominated_in_eq_with_req: ResourceList,
                 nominated_with_req: ResourceList):
        self.pod_req = pod_req
        self.nominated_in_eq_with_req = nominated_in_eq_with_req
        self.nominated_with_req = nominated_with_req

    def clone(self):
        return self


class CapacityScheduling(PreFilterPlugin, PostFilterPlugin, ReservePlugin,
                         EnqueueExtensions, EquivalenceAware):
    NAME = "CapacityScheduling"

    def equiv_fingerprint(self, pod, state):
        """Under GUARDED commits (sharded dispatch, ISSUE 14) the cache
        stays warm through quotas: a memoized admission's staleness is
        caught by the commit's semantic re-check (used+in_eq vs max,
        Σused+total vs Σmin against the live ledger), so usage churn —
        including the same-class sibling assumes the cursor chain
        sanctions — needs no invalidation here.  The fingerprint is the
        BOUNDS signature only: a min/max or quota-set change alters which
        QuotaReserve a cycle should have built, so it must invalidate.

        Without guarded commits (single dispatch loop, the legacy
        serialize arm, standalone plugin use) the pre-14 veto stands:
        assume_pod is unguarded there, and a memoized snapshot could
        admit a pod the live quota arithmetic would reject."""
        if getattr(self.handle, "quota_guarded_commits", False):
            sig = getattr(self.handle, "quota_bounds_signature", None)
            if sig is not None:
                return sig()
        with self._lock:
            return None if self.eq_infos else ()

    def __init__(self, args, handle):
        self.handle = handle
        self._lock = threading.RLock()
        self.eq_infos = ElasticQuotaInfos()
        eq_informer = handle.informer_factory.elasticquotas()
        pod_informer = handle.informer_factory.pods()
        eq_informer.add_event_handler(on_add=self._eq_added,
                                      on_update=self._eq_updated,
                                      on_delete=self._eq_deleted)
        pod_informer.add_event_handler(on_add=self._pod_added,
                                       on_update=self._pod_updated,
                                       on_delete=self._pod_deleted)

    @classmethod
    def new(cls, args, handle) -> "CapacityScheduling":
        return cls(args, handle)

    def events_to_register(self) -> List[ClusterEvent]:
        return [ClusterEvent(RESOURCE_POD, EVENT_DELETE),
                ClusterEvent(RESOURCE_ELASTIC_QUOTA,
                             EVENT_ADD | EVENT_UPDATE | EVENT_DELETE),
                ClusterEvent("Node", EVENT_ADD | EVENT_UPDATE)]

    # -- informer mirror (capacity_scheduling.go:646-751) ---------------------

    def _eq_added(self, eq: ElasticQuota) -> None:
        with self._lock:
            info = self.eq_infos.get(eq.meta.namespace)
            if info is None:
                info = ElasticQuotaInfo(eq.meta.namespace)
                self.eq_infos[eq.meta.namespace] = info
            info.min = dict(eq.spec.min)
            info.max = dict(eq.spec.max)

    def _eq_updated(self, old: ElasticQuota, new: ElasticQuota) -> None:
        self._eq_added(new)

    def _eq_deleted(self, eq: ElasticQuota) -> None:
        with self._lock:
            self.eq_infos.pop(eq.meta.namespace, None)

    def _pod_added(self, pod: Pod) -> None:
        if not assigned(pod) or is_pod_terminated(pod):
            return
        with self._lock:
            info = self.eq_infos.get(pod.namespace)
            if info is not None:
                info.add_pod_if_not_present(pod)

    def _pod_updated(self, old: Pod, new: Pod) -> None:
        if assigned(new) and not is_pod_terminated(new):
            self._pod_added(new)
        else:
            self._pod_deleted(new)

    def _pod_deleted(self, pod: Pod) -> None:
        with self._lock:
            info = self.eq_infos.get(pod.namespace)
            if info is not None:
                info.delete_pod_if_present(pod)

    # -- PreFilter ------------------------------------------------------------

    def _snapshot_quotas(self, state: CycleState) -> "_EQSnapshot":
        """Quota admission inputs for this cycle.  Preferred source: the
        cache quota LEDGER through ``handle.quota_view`` — per-quota
        min/max/used captured in ONE cache critical section, so the
        commit's semantic re-check (``QuotaReserve``, written into
        CycleState at the end of pre_filter) judges the same arithmetic
        on live state.  Fallback: the plugin's own informer mirror
        (standalone construction in unit tests, no ledger attached) —
        correct for a single dispatch loop, which is the only way such a
        scheduler runs."""
        view = getattr(self.handle, "quota_view", None)
        if view is not None:
            raw, _epoch = view()
            infos = ElasticQuotaInfos()
            if raw:
                for ns, (mn, mx, used, pods_loader) in raw.items():
                    infos[ns] = ElasticQuotaInfo.from_parts(
                        ns, mn, mx, used, LazyPodKeys(pods_loader))
            return _EQSnapshot(infos)
        with self._lock:
            return _EQSnapshot(self.eq_infos.clone())

    def pre_filter(self, state: CycleState, pod: Pod) -> Status:
        # Reuse an existing snapshot when re-evaluated inside a preemption
        # dry-run (cloned CycleState): the dry-run's Add/RemovePod extensions
        # have adjusted it, and re-snapshotting the live infos would clobber
        # those adjustments (CrossNodePreemption re-runs PreFilter this way).
        snapshot = state.try_read(EQ_SNAPSHOT_KEY)
        if snapshot is None:
            snapshot = self._snapshot_quotas(state)
            state.write(EQ_SNAPSHOT_KEY, snapshot)
        pod_req = pod_effective_request(pod)

        eq = snapshot.infos.get(pod.namespace)
        if eq is None:
            state.write(PRE_FILTER_STATE_KEY,
                        _PreFilterState(pod_req, dict(pod_req), dict(pod_req)))
            return Status.success()

        # nominated-pod accounting (:218-257): reqs of nominated pods that
        # would consume this quota (same ns, ≥ priority) or global min spare
        # (other ns, quota not over min).  Guarded on the nominator's
        # lock-free empty() peek: the sweep below walks EVERY candidate
        # node per quota'd cycle, which with no nominated pods anywhere
        # (the overwhelmingly common case) was a pure O(nodes) tax on the
        # quota-storm hot path (ISSUE 14).
        in_eq: ResourceList = dict(pod_req)
        total: ResourceList = dict(pod_req)
        nominated_iter = () if self.handle.pod_nominator.empty() \
            else self.handle.snapshot_shared_lister().list()
        for info in nominated_iter:
            for np in self.handle.pod_nominator.nominated_pods_for_node(
                    info.node.name):
                if np.meta.uid == pod.meta.uid:
                    continue
                np_info = snapshot.infos.get(np.namespace)
                if np_info is None:
                    continue
                np_req = pod_effective_request(np)
                if np.namespace == pod.namespace and np.priority >= pod.priority:
                    for k, v in np_req.items():
                        in_eq[k] = in_eq.get(k, 0) + v
                        total[k] = total.get(k, 0) + v
                elif np.namespace != pod.namespace and not np_info.used_over_min():
                    for k, v in np_req.items():
                        total[k] = total.get(k, 0) + v

        state.write(PRE_FILTER_STATE_KEY, _PreFilterState(pod_req, in_eq, total))

        if eq.used_over_max_with(in_eq):
            from ... import trace
            if trace.current() is not None:   # kwargs stringify quota dicts
                trace.record_rejection(
                    self.NAME, "quota used would exceed Max",
                    quota_namespace=eq.namespace,
                    used=str(dict(eq.used)), max=str(dict(eq.max)),
                    request=str(dict(pod_req)))
            return Status.unschedulable(
                f"Pod {pod.key} is rejected in PreFilter because ElasticQuota "
                f"{eq.namespace} is more than Max")
        if (eq.used_over_min_with(in_eq) and self._dispatch_scope()
                == "partition"):
            # cross-quota BORROW on a shard lane (ISSUE 14): admitting this
            # pod spends spare min guaranteed to OTHER quotas, and borrower
            # preemption/nomination machinery is global-lane state — reject
            # here so the scheduler's standard escalation hop re-runs the
            # unit on the serialized global lane with fleet-wide admission.
            # Intra-min pods (the common multi-tenant case) stay on their
            # shard lanes: their commit is protected by the quota-epoch
            # compare-and-reserve.
            from ... import trace
            if trace.current() is not None:
                trace.record_rejection(
                    self.NAME, "over-min borrow needs fleet-wide admission "
                    "(escalating to the global lane)",
                    quota_namespace=eq.namespace,
                    used=str(dict(eq.used)), min=str(dict(eq.min)),
                    request=str(dict(pod_req)))
            return Status.unschedulable(
                f"Pod {pod.key} borrows beyond ElasticQuota {eq.namespace} "
                f"min: cross-quota admission runs on the global lane")
        if snapshot.infos.aggregated_used_over_min_with(total):
            from ... import trace
            if trace.current() is not None:
                trace.record_rejection(
                    self.NAME, "aggregate used would exceed sum of quota "
                    "mins (no spare capacity to borrow)",
                    quota_namespace=eq.namespace,
                    request=str(dict(pod_req)))
            return Status.unschedulable(
                f"Pod {pod.key} is rejected in PreFilter because total "
                f"ElasticQuota used is more than min")
        # admission passed: hand the commit the exact vectors it judged
        # (ISSUE 14).  The sharded commit re-evaluates used+in_eq vs max
        # and Σused+total vs Σmin against the LIVE cache ledger inside
        # assume_pod_guarded — the semantic compare-and-reserve that lets
        # quota'd pods dispatch on shard lanes without overshoot.
        from ...sched.cache import QuotaReserve
        state.write(QUOTA_GUARD_STATE_KEY,
                    QuotaReserve(eq.namespace, dict(in_eq), dict(total)))
        return Status.success()

    def _dispatch_scope(self) -> str:
        """'' (fleet-wide) or 'partition' (a shard lane's restricted
        cycle); tolerant of bare test handles without the accessor."""
        scope = getattr(self.handle, "dispatch_scope", None)
        return scope() if callable(scope) else ""

    def pre_filter_extensions(self) -> Optional[PreFilterExtensions]:
        return _Extensions()

    # -- PostFilter (preemption) ----------------------------------------------

    def post_filter(self, state: CycleState, pod: Pod,
                    filtered_node_status_map) -> Tuple[Optional[PostFilterResult], Status]:
        evaluator = Evaluator(self.NAME, self.handle, state,
                              _Preemptor(self.handle, state))
        result, status = evaluator.preempt(pod, filtered_node_status_map)
        if result is None:
            return None, status
        return result, status

    # -- Reserve --------------------------------------------------------------

    def reserve(self, state: CycleState, pod: Pod, node_name: str) -> Status:
        with self._lock:
            info = self.eq_infos.get(pod.namespace)
            if info is not None:
                info.add_pod_if_not_present(pod)
        return Status.success()

    def unreserve(self, state: CycleState, pod: Pod, node_name: str) -> None:
        with self._lock:
            info = self.eq_infos.get(pod.namespace)
            if info is not None:
                info.delete_pod_if_present(pod)


class _Extensions(PreFilterExtensions):
    """AddPod/RemovePod keep the per-cycle EQ snapshot consistent during
    preemption dry-runs (:283-318)."""

    def add_pod(self, state: CycleState, pod_to_schedule: Pod,
                pod_to_add: Pod, node_info: NodeInfo) -> Status:
        snap = state.try_read(EQ_SNAPSHOT_KEY)
        if snap is not None:
            info = snap.infos.get(pod_to_add.namespace)
            if info is not None:
                info.add_pod_if_not_present(pod_to_add)
        return Status.success()

    def remove_pod(self, state: CycleState, pod_to_schedule: Pod,
                   pod_to_remove: Pod, node_info: NodeInfo) -> Status:
        snap = state.try_read(EQ_SNAPSHOT_KEY)
        if snap is not None:
            info = snap.infos.get(pod_to_remove.namespace)
            if info is not None:
                info.delete_pod_if_present(pod_to_remove)
        return Status.success()


class _Preemptor(PreemptionInterface):
    """Quota-aware victim selection (:391-644)."""

    def __init__(self, handle, state: CycleState):
        self.handle = handle
        self.state = state

    def pod_eligible_to_preempt_others(self, pod: Pod,
                                       nominated_node_status: Optional[Status]) -> bool:
        # PreemptNever pods never preempt (:392-396)
        pc = None
        if pod.spec.priority_class_name:
            pc = self.handle.clientset.priorityclasses.try_get(
                "/" + pod.spec.priority_class_name)
        if pc is not None and pc.preemption_policy == "Never":
            return False
        nom = pod.status.nominated_node_name
        if not nom:
            return True
        from ...fwk.status import UNSCHEDULABLE_AND_UNRESOLVABLE
        if (nominated_node_status is not None
                and nominated_node_status.code == UNSCHEDULABLE_AND_UNRESOLVABLE):
            return True
        # terminating-victim check (:427-460): if a terminating pod on the
        # nominated node would release room the preemptor can claim, wait
        info = self.handle.snapshot_shared_lister().get(nom)
        if info is None:
            return True
        snap = self.state.try_read(EQ_SNAPSHOT_KEY)
        pfs = self.state.try_read(PRE_FILTER_STATE_KEY)
        eq = snap.infos.get(pod.namespace) if snap else None
        if eq is not None and pfs is not None:
            more_than_min = eq.used_over_min_with(pfs.nominated_in_eq_with_req)
            for p in info.pods:
                if not p.is_terminating():
                    continue
                p_eq = snap.infos.get(p.namespace) if snap else None
                if p_eq is None:
                    continue
                if p.namespace == pod.namespace and p.priority < pod.priority:
                    return False
                if (p.namespace != pod.namespace and not more_than_min
                        and p_eq.used_over_min()):
                    return False
        else:
            for p in info.pods:
                if snap and snap.infos.get(p.namespace) is not None:
                    continue
                if p.is_terminating() and p.priority < pod.priority:
                    return False
        return True

    def select_victims_on_node(self, state: CycleState, pod: Pod,
                               node_info: NodeInfo,
                               pdbs: List[PodDisruptionBudget]
                               ) -> Tuple[List[Pod], int, Status]:
        snap = state.try_read(EQ_SNAPSHOT_KEY)
        pfs = state.try_read(PRE_FILTER_STATE_KEY)
        if snap is None or pfs is None:
            return [], 0, Status.unschedulable("missing capacity cycle state")
        infos = snap.infos
        eq = infos.get(pod.namespace)

        potential: List[Pod] = []
        floor = GangDisruptionFloor(self.handle)

        def remove(v: Pod) -> Optional[Status]:
            return dry_run_remove(self.handle, state, pod, v, node_info)

        if eq is not None:
            more_than_min = eq.used_over_min_with(pfs.nominated_in_eq_with_req)
            for p in list(node_info.pods):
                p_eq = infos.get(p.namespace)
                if p_eq is None:
                    continue
                if more_than_min:
                    # preemptor exceeds its own min ⇒ reclaim only inside its
                    # quota, from lower-priority pods (:526-538)
                    if (p.namespace == pod.namespace
                            and p.priority < pod.priority
                            and floor.may_evict(p)):
                        potential.append(p)
                        err = remove(p)
                        if err:
                            return [], 0, err
                else:
                    # preemptor within min ⇒ its guarantee is borrowed; evict
                    # borrowers: other quotas currently over min (:539-553)
                    if (p.namespace != pod.namespace and p_eq.used_over_min()
                            and floor.may_evict(p)):
                        potential.append(p)
                        err = remove(p)
                        if err:
                            return [], 0, err
        else:
            for p in list(node_info.pods):
                if infos.get(p.namespace) is not None:
                    continue
                if p.priority < pod.priority and floor.may_evict(p):
                    potential.append(p)
                    err = remove(p)
                    if err:
                        return [], 0, err

        if not potential:
            return [], 0, Status.unresolvable(
                f"No victims found on node {node_info.node.name} "
                f"for preemptor pod {pod.name}")

        s = self.handle.run_filter_plugins_with_nominated_pods(state, pod, node_info)
        if not s.is_success():
            return [], 0, s

        if eq is not None:
            if (eq.used_over_max_with(pfs.pod_req)
                    or infos.aggregated_used_over_min_with(pfs.pod_req)):
                return [], 0, Status.unschedulable("global quota max exceeded")

        def quota_broken() -> bool:
            return eq is not None and (
                eq.used_over_max_with(pfs.nominated_in_eq_with_req)
                or infos.aggregated_used_over_min_with(pfs.nominated_with_req))

        return reprieve_victims(self.handle, state, pod, node_info, potential,
                                pdbs, extra_infeasible=quota_broken)
