"""PodState sample plugin: prefer nodes releasing capacity.

Rebuild of /root/reference/pkg/podstate/pod_state.go: score = count of
terminating pods − count of nominated pods per node (:57-69), min-max
normalized (:72-95).
"""
from __future__ import annotations

from typing import List, Optional, Tuple

from ..api.core import Pod
from ..fwk import CycleState, Status
from ..fwk.interfaces import NodeScore, ScorePlugin
from ..fwk.nodeinfo import minmax_normalize


class PodState(ScorePlugin):
    NAME = "PodState"

    def __init__(self, handle):
        self.handle = handle

    @classmethod
    def new(cls, args, handle) -> "PodState":
        return cls(handle)

    def score(self, state: CycleState, pod: Pod, node_name: str) -> Tuple[int, Status]:
        info = self.handle.snapshot_shared_lister().get(node_name)
        if info is None:
            return 0, Status.error(f"node {node_name} not in snapshot")
        terminating = sum(1 for p in info.pods if p.is_terminating())
        nominated = len(self.handle.pod_nominator.nominated_pods_for_node(node_name))
        # read_or_init: score runs across nodes in parallel
        raw = state.read_or_init("PodState/raw", dict)
        raw[node_name] = terminating - nominated
        return 0, Status.success()

    def normalize_score(self, state: CycleState, pod: Pod,
                        scores: List[NodeScore]) -> Optional[Status]:
        minmax_normalize(state.try_read("PodState/raw") or {}, scores)
        return Status.success()
