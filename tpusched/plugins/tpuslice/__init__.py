from .plugin import TpuSlice, CHIP_INDEX_ANNOTATION
from .chip_node import ChipNode, Chip

__all__ = ["TpuSlice", "ChipNode", "Chip", "CHIP_INDEX_ANNOTATION"]
