"""Per-node TPU chip model, rebuilt from pod annotations each cycle.

Successor of the reference's gpuNode (/root/reference/pkg/flexgpu/gpu_node.go).
Deliberate fixes over the reference (SURVEY §2 quirks, resolved not inherited):

- Value-typed integer accounting. The reference aliases resource.Quantity
  pointers (`assumed := u.usedMemory; assumed.Add(...)` mutates the chip,
  gpu_node.go:134-144; all devices share one memEachGPU pointer,
  gpu_node.go:55,73) so fit computations corrupt the model mid-cycle. Ints
  by value can't.
- The index annotation is checked for presence *before* parsing
  (the reference parses first, gpu_node.go:91-96, so annotation-less pods hit
  the error path and the has-annotation branch below is dead code).
- Whole-chip pods may request N>1 chips (a v5p host pod typically owns all 4);
  the reference only warns when gpu limit != 1 and still assigns one index
  (gpu_node.go:80-82, flex_gpu.go:198-206). Here the annotation carries a
  comma-separated index list.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Tuple

from ...api.core import Pod
from ...api.resources import TPU, TPU_MEMORY
from ...api.topology import ACCELERATORS, LABEL_ACCELERATOR
from ...fwk.nodeinfo import NodeInfo
from ...util import klog

CHIP_INDEX_ANNOTATION = "tpuslice.scheduling.tpu.dev/chip-index"


def pod_tpu_limits(pod: Pod) -> Tuple[int, bool, int, bool]:
    """Sum container limits for (chips, chips_set, hbm_mb, hbm_set).

    The reference sums container *limits* (flex_gpu.go podResourceLimit:120-130);
    extended resources require requests==limits in k8s, so falling back to
    requests when limits are unset is behavior-preserving for well-formed pods.
    """
    chips = mem = 0
    chips_set = mem_set = False
    for c in pod.spec.containers:
        src = c.limits if (TPU in c.limits or TPU_MEMORY in c.limits) else c.requests
        if TPU in src:
            chips_set = True
            chips += src[TPU]
        if TPU_MEMORY in src:
            mem_set = True
            mem += src[TPU_MEMORY]
    return chips, chips_set, mem, mem_set


def parse_chip_indexes(s: str) -> Optional[List[int]]:
    try:
        return [int(p) for p in s.split(",") if p != ""]
    except ValueError:
        return None


@dataclass
class Chip:
    index: int
    hbm_mb: int         # capacity of this chip
    used_mb: int = 0    # fractional usage by tpu-memory pods
    monopoly: bool = False  # owned wholly by a tpu-chips pod


class ChipNode:
    """Chip occupancy for one node, derived purely from the node's allocatable
    and its pods' annotations — the restart-safe annotations-as-truth model
    (SURVEY §5 checkpoint/resume)."""

    def __init__(self, chips: List[Chip]):
        self.chips = chips
        self.hbm_total_mb = sum(c.hbm_mb for c in chips)
        # node-level limit sums over ALL resident TPU pods (with or without
        # index annotations) — the Filter's capacity check input
        # (flex_gpu.go:96-119)
        self.used_chips_limit = 0
        self.used_mem_limit = 0

    @classmethod
    def cached(cls, node_info: NodeInfo) -> Optional["ChipNode"]:
        """Generation-keyed memo on the NodeInfo: Filter/Score/Reserve in one
        cycle (and later cycles, while the node is unchanged) share one
        build. ChipNode is derived purely from (node, pods), the
        derived-cache contract."""
        return node_info.derived("TpuSlice/chip-node", cls.from_node_info)

    @classmethod
    def from_node_info(cls, node_info: NodeInfo) -> Optional["ChipNode"]:
        node = node_info.node
        alloc = node.status.allocatable
        count = alloc.get(TPU, 0)
        if count <= 0:
            return None
        mem_total = alloc.get(TPU_MEMORY, 0)
        if mem_total <= 0:
            acc = ACCELERATORS.get(node.meta.labels.get(LABEL_ACCELERATOR, ""))
            mem_total = acc.hbm_mb_per_chip * count if acc else 0
        hbm_each = mem_total // count if count else 0
        out = cls([Chip(i, hbm_each) for i in range(count)])
        chips = out.chips

        for pod in node_info.pods:
            chips_req, chips_set, mem_req, mem_set = pod_tpu_limits(pod)
            if not chips_set and not mem_set:
                continue
            out.used_chips_limit += chips_req
            out.used_mem_limit += mem_req
            ann = pod.meta.annotations.get(CHIP_INDEX_ANNOTATION)
            if ann is None:
                klog.warning_s("TPU pod has no chip-index annotation", pod=pod.key)
                continue
            indexes = parse_chip_indexes(ann)
            if indexes is None or any(i < 0 or i >= count for i in indexes):
                klog.warning_s("invalid chip-index annotation", pod=pod.key, value=ann)
                continue
            if chips_set:
                for i in indexes:
                    chips[i].monopoly = True
            if mem_set:
                # fractional pods occupy exactly one chip
                chips[indexes[0]].used_mb += mem_req
        return out

    # -- fitting --------------------------------------------------------------

    def mem_fit_indexes(self, mem_mb: int) -> List[int]:
        """Chips that can host a fractional pod of mem_mb, sorted by least
        remaining HBM after placement (bin-pack; gpu_node.go:122-161)."""
        fits = []
        for u in self.chips:
            if u.monopoly and u.used_mb:
                klog.warning_s("conflicting chip usage", index=u.index)
            if not u.monopoly and u.used_mb + mem_mb <= u.hbm_mb:
                fits.append((u.hbm_mb - u.used_mb - mem_mb, u.index))
        fits.sort()
        return [i for _, i in fits]

    def free_chip_indexes(self) -> List[int]:
        """Wholly-free chips, eligible for monopoly pods (gpu_node.go:163-177)."""
        return [u.index for u in self.chips if not u.monopoly and u.used_mb == 0]

    # -- scoring --------------------------------------------------------------

    def chip_score(self) -> int:
        return len(self.free_chip_indexes())

    def mem_score(self) -> int:
        return sum(u.hbm_mb - u.used_mb for u in self.chips)
