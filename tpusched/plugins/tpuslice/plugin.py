"""TpuSlice plugin: fractional-TPU placement.

Successor of the reference fork's core feature, pkg/flexgpu
(/root/reference/pkg/flexgpu/flex_gpu.go). Extended resources:

- ``google.com/tpu``         — whole chips (monopoly), N ≥ 1 per pod;
- ``google.com/tpu-memory``  — HBM megabytes on a single shared chip.

Extension points mirror the reference exactly:
Filter (node capacity + per-chip fit, mutual exclusion of the two resource
kinds, flex_gpu.go:41-119) → Score (free chips / free HBM, :142-166) →
NormalizeScore (reverse default-normalize ⇒ node-level bin-pack, :172-176) →
Reserve (choose chip index(es), write annotation, :178-223) → Unreserve
(delete it, :225-228) → Bind (Binding carries the annotations so the on-node
device plugin reads the assignment, :230-242).
"""
from __future__ import annotations

from typing import List, Optional, Tuple

from ...api.core import Binding, Pod, node_health_error
from ...api.resources import TPU, TPU_MEMORY
from ...fwk import CycleState, Status
from ...fwk.interfaces import (BindPlugin, FilterPlugin, NodeScore,
                               ReservePlugin, ScorePlugin)
from ...fwk.nodeinfo import MAX_NODE_SCORE, NodeInfo
from ...util import klog
from ...config.types import TpuSliceArgs
from .chip_node import (CHIP_INDEX_ANNOTATION, ChipNode, pod_tpu_limits)


def default_normalize(scores: List[NodeScore], reverse: bool) -> None:
    """Upstream helper.DefaultNormalizeScore: scale to [0,100]; reverse flips
    (the reference passes reverse=true, flex_gpu.go:172-176, so fuller nodes
    win — bin-pack across nodes)."""
    max_score = max((s.score for s in scores), default=0)
    for s in scores:
        if max_score > 0:
            s.score = s.score * MAX_NODE_SCORE // max_score
        if reverse:
            s.score = MAX_NODE_SCORE - s.score


class TpuSlice(FilterPlugin, ScorePlugin, ReservePlugin, BindPlugin):
    NAME = "TpuSlice"

    def __init__(self, args: Optional[TpuSliceArgs], handle):
        self.args = args or TpuSliceArgs()
        self.handle = handle

    @classmethod
    def new(cls, args, handle) -> "TpuSlice":
        return cls(args, handle)

    # -- Filter ---------------------------------------------------------------

    def filter(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Status:
        chips_req, chips_set, mem_req, mem_set = pod_tpu_limits(pod)
        if not chips_set and not mem_set:
            return Status.success()
        # NotReady/cordoned hardware never takes a NEW chip placement, even
        # in profiles that do not wire NodeUnschedulable — the post-failure
        # retry must land on healthy silicon (node updates bump the cache's
        # mutation cursor, so equivalence entries stay exact)
        health = node_health_error(node_info.node)
        if health is not None:
            return Status.unresolvable(health)
        if chips_set and mem_set:
            # a pod may not mix whole-chip and fractional requests
            # (flex_gpu.go:58-61)
            return Status.unresolvable("pod conflict resources")

        alloc = node_info.node.status.allocatable
        if alloc.get(TPU, 0) <= 0:
            return Status.unresolvable(f"unknown resource type {TPU}")

        # node-level capacity check over the *limit sums* of resident pods
        # (flex_gpu.go:96-119), precomputed at ChipNode build
        cn = ChipNode.cached(node_info)
        if cn is None:
            return Status.unresolvable(f"unknown resource type {TPU}")
        if cn.used_chips_limit + chips_req > alloc.get(TPU, 0):
            return Status.unschedulable(f"insufficient resource {TPU}")
        if cn.used_mem_limit + mem_req > cn.hbm_total_mb:
            return Status.unschedulable(f"insufficient resource {TPU_MEMORY}")

        if mem_set and not cn.mem_fit_indexes(mem_req):
            return Status.unschedulable(f"no fit indexes resource {TPU_MEMORY}")
        if chips_set and len(cn.free_chip_indexes()) < chips_req:
            return Status.unschedulable(f"no fit indexes resource {TPU}")
        return Status.success()

    # -- Score ----------------------------------------------------------------

    def score(self, state: CycleState, pod: Pod, node_name: str) -> Tuple[int, Status]:
        node_info = self.handle.snapshot_shared_lister().get(node_name)
        if node_info is None:
            return 0, Status.error(f"node {node_name} not in snapshot")
        chips_req, chips_set, mem_req, mem_set = pod_tpu_limits(pod)
        if not chips_set and not mem_set:
            return 0, Status.success()
        cn = ChipNode.cached(node_info)
        if cn is None:
            return 0, Status.success()
        raw = cn.chip_score() if chips_set else cn.mem_score()
        return raw, Status.success()

    def normalize_score(self, state: CycleState, pod: Pod,
                        scores: List[NodeScore]) -> Optional[Status]:
        default_normalize(scores, reverse=(self.args.score_mode == "binpack"))
        klog.V(6).info_s("normalized scores", pod=pod.key)
        return Status.success()

    # -- Reserve --------------------------------------------------------------

    def reserve(self, state: CycleState, pod: Pod, node_name: str) -> Status:
        node_info = self.handle.snapshot_shared_lister().get(node_name)
        if node_info is None:
            return Status.error(f"node {node_name} not in snapshot")
        chips_req, chips_set, mem_req, mem_set = pod_tpu_limits(pod)
        if not chips_set and not mem_set:
            return Status.success()
        if chips_set and mem_set:
            return Status.unresolvable("pod conflict resources")
        cn = ChipNode.cached(node_info)
        if cn is None:
            return Status.unschedulable(f"no {TPU} on node {node_name}")
        if chips_set:
            fits = cn.free_chip_indexes()
            if len(fits) < chips_req:
                return Status.unschedulable(f"allocate index fail {TPU}")
            chosen = fits[:chips_req]
        else:
            fits = cn.mem_fit_indexes(mem_req)
            if not fits:
                return Status.unschedulable(f"allocate index fail {TPU_MEMORY}")
            chosen = [fits[0]]  # bin-pack: least remaining first
        pod.meta.annotations[CHIP_INDEX_ANNOTATION] = ",".join(map(str, chosen))
        klog.V(6).info_s("reserved chips", pod=pod.key, node=node_name,
                         chips=pod.meta.annotations[CHIP_INDEX_ANNOTATION])
        return Status.success()

    def unreserve(self, state: CycleState, pod: Pod, node_name: str) -> None:
        pod.meta.annotations.pop(CHIP_INDEX_ANNOTATION, None)

    # -- Bind -----------------------------------------------------------------

    def bind(self, state: CycleState, pod: Pod, node_name: str) -> Status:
        klog.V(3).info_s("attempting to bind pod to node", pod=pod.key,
                         node=node_name)
        from ..defaults import bind_with_annotations
        return bind_with_annotations(self.handle, pod, node_name)
