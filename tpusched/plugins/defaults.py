"""Built-in default plugins.

The reference leans on upstream in-tree plugins for basic feasibility (its
fork disables most but the hosting framework still provides fit/priority/
binder). These are the minimal equivalents: priority queue sort, resource
fit, unschedulable/taints/selector filters, and the default binder.
"""
from __future__ import annotations

from typing import List, Tuple

from ..api.core import Binding, Node, Pod, tolerates
from ..api.resources import resources_fit
from ..fwk import (CycleState, Status)
from ..fwk.interfaces import (BindPlugin, FilterPlugin, QueueSortPlugin)
from ..fwk.nodeinfo import NodeInfo
from ..util.podutil import pod_effective_request


class PrioritySort(QueueSortPlugin):
    """Upstream PrioritySort: priority desc, then queue arrival time."""
    NAME = "PrioritySort"

    def less(self, pi1, pi2) -> bool:
        p1, p2 = pi1.pod.priority, pi2.pod.priority
        if p1 != p2:
            return p1 > p2
        return pi1.timestamp < pi2.timestamp


class NodeResourcesFit(FilterPlugin):
    """cpu/memory/pods/extended-resource fit against allocatable − requested."""
    NAME = "NodeResourcesFit"

    _REQ_KEY = "NodeResourcesFit/pod-request"

    def filter(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Status:
        if node_info.node is None:
            return Status.error("node not found")
        # the pod's request is cycle-invariant: compute once per cycle
        # (upstream stashes it in PreFilter; memoizing on first Filter call
        # needs no profile wiring)
        request = state.try_read(self._REQ_KEY)
        if request is None:
            req = pod_effective_request(pod)
            req["pods"] = 1
            request = tuple((k, v) for k, v in req.items() if v > 0)
            state.write(self._REQ_KEY, request)
        alloc = node_info.allocatable
        requested = node_info.requested
        insufficient = [k for k, v in request
                        if requested.get(k, 0) + v > alloc.get(k, 0)]
        if insufficient:
            return Status.unschedulable(
                *[f"Insufficient {k}" for k in insufficient])
        return Status.success()


class NodeUnschedulable(FilterPlugin):
    NAME = "NodeUnschedulable"

    def filter(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Status:
        if node_info.node.spec.unschedulable:
            return Status.unresolvable("node(s) were unschedulable")
        return Status.success()


class TaintToleration(FilterPlugin):
    NAME = "TaintToleration"

    def filter(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Status:
        for taint in node_info.node.spec.taints:
            if taint.effect in ("NoSchedule", "NoExecute") and not tolerates(pod, taint):
                return Status.unresolvable(
                    f"node(s) had untolerated taint {{{taint.key}: {taint.value}}}")
        return Status.success()


class NodeName(FilterPlugin):
    NAME = "NodeName"

    def filter(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Status:
        if pod.spec.node_name and pod.spec.node_name != node_info.node.name:
            return Status.unresolvable("node didn't match requested node name")
        return Status.success()


class NodeSelector(FilterPlugin):
    NAME = "NodeSelector"

    def filter(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Status:
        selector = pod.spec.node_selector
        if not selector:
            return Status.success()
        labels = node_info.node.meta.labels
        for k, v in selector.items():
            if labels.get(k) != v:
                return Status.unresolvable("node(s) didn't match node selector")
        return Status.success()


def bind_with_annotations(handle, pod: Pod, node_name: str) -> Status:
    """POST the Binding carrying the pod's current annotations, so
    Reserve-time device/coord annotations survive to the API server — the
    contract the reference's custom FlexGPU Bind establishes
    (flex_gpu.go:230-242). Shared by DefaultBinder and TpuSlice.bind."""
    try:
        handle.clientset.pods.bind(Binding(
            pod_key=pod.key, node_name=node_name,
            annotations=dict(pod.meta.annotations)))
    except Exception as e:
        return Status.error(f"bind failed: {e}")
    return Status.success()


class DefaultBinder(BindPlugin):
    NAME = "DefaultBinder"

    def __init__(self, handle):
        self.handle = handle

    def bind(self, state: CycleState, pod: Pod, node_name: str) -> Status:
        return bind_with_annotations(self.handle, pod, node_name)
