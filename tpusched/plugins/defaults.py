"""Built-in default plugins.

The reference leans on upstream in-tree plugins for basic feasibility (its
fork disables most but the hosting framework still provides fit/priority/
binder). These are the minimal equivalents: priority queue sort, resource
fit, unschedulable/taints/selector filters, and the default binder.
"""
from __future__ import annotations

from typing import List, Tuple

from typing import Optional

import numpy as np

from ..api.core import Binding, Node, Pod, tolerates
from ..api.resources import resources_fit
from ..fwk import (CycleState, Status)
from ..fwk.interfaces import (BatchFilterPlugin, BindPlugin, FilterPlugin,
                              QueueSortPlugin)
from ..fwk.nodeinfo import NodeInfo
from ..util.podutil import pod_effective_request


class PrioritySort(QueueSortPlugin):
    """Upstream PrioritySort: priority desc, then queue arrival time."""
    NAME = "PrioritySort"

    def less(self, pi1, pi2) -> bool:
        p1, p2 = pi1.pod.priority, pi2.pod.priority
        if p1 != p2:
            return p1 > p2
        return pi1.timestamp < pi2.timestamp


class NodeResourcesFit(BatchFilterPlugin):
    """cpu/memory/pods/extended-resource fit against allocatable − requested.

    Implements the vectorized fleet-wide path (filter_batch): the per-node
    check is three dict lookups per resource, which at 1000+ hosts is pure
    Python dispatch overhead — one numpy comparison over (nodes × resources)
    matrices does the same work GIL-free."""
    NAME = "NodeResourcesFit"

    _REQ_KEY = "NodeResourcesFit/pod-request"

    def _pod_request(self, state: CycleState, pod: Pod):
        # the pod's request is cycle-invariant: compute once per cycle
        # (upstream stashes it in PreFilter; memoizing on first Filter call
        # needs no profile wiring)
        def build():
            req = pod_effective_request(pod)
            req["pods"] = 1
            return tuple((k, v) for k, v in req.items() if v > 0)
        return state.read_or_init(self._REQ_KEY, build)

    def filter(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Status:
        if node_info.node is None:
            return Status.error("node not found")
        request = self._pod_request(state, pod)
        alloc = node_info.allocatable
        requested = node_info.requested
        insufficient = [k for k, v in request
                        if requested.get(k, 0) + v > alloc.get(k, 0)]
        if insufficient:
            return Status.unschedulable(
                *[f"Insufficient {k}" for k in insufficient])
        return Status.success()

    def filter_batch(self, state: CycleState, pod: Pod,
                     node_infos) -> List[Optional[Status]]:
        request = self._pod_request(state, pod)
        n = len(node_infos)
        out: List[Optional[Status]] = [None] * n
        # (resources × nodes) headroom matrix; one vectorized compare per
        # resource replaces n per-node Python filter calls
        fail = np.zeros(n, dtype=bool)
        fail_by_res = []
        for k, v in request:
            alloc = np.fromiter(
                (inf.allocatable.get(k, 0) for inf in node_infos),
                dtype=np.float64, count=n)
            used = np.fromiter(
                (inf.requested.get(k, 0) for inf in node_infos),
                dtype=np.float64, count=n)
            res_fail = used + v > alloc
            fail_by_res.append((k, res_fail))
            fail |= res_fail
        for i in np.flatnonzero(fail):
            out[i] = Status.unschedulable(
                *[f"Insufficient {k}" for k, rf in fail_by_res if rf[i]])
        return out


class NodeUnschedulable(FilterPlugin):
    NAME = "NodeUnschedulable"

    def filter(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Status:
        if node_info.node.spec.unschedulable:
            return Status.unresolvable("node(s) were unschedulable")
        return Status.success()


class TaintToleration(FilterPlugin):
    NAME = "TaintToleration"

    def filter(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Status:
        for taint in node_info.node.spec.taints:
            if taint.effect in ("NoSchedule", "NoExecute") and not tolerates(pod, taint):
                return Status.unresolvable(
                    f"node(s) had untolerated taint {{{taint.key}: {taint.value}}}")
        return Status.success()


class NodeName(FilterPlugin):
    NAME = "NodeName"

    def filter(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Status:
        if pod.spec.node_name and pod.spec.node_name != node_info.node.name:
            return Status.unresolvable("node didn't match requested node name")
        return Status.success()


class NodeSelector(FilterPlugin):
    NAME = "NodeSelector"

    def filter(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Status:
        selector = pod.spec.node_selector
        if not selector:
            return Status.success()
        labels = node_info.node.meta.labels
        for k, v in selector.items():
            if labels.get(k) != v:
                return Status.unresolvable("node(s) didn't match node selector")
        return Status.success()


def bind_with_annotations(handle, pod: Pod, node_name: str) -> Status:
    """POST the Binding carrying the pod's current annotations, so
    Reserve-time device/coord annotations survive to the API server — the
    contract the reference's custom FlexGPU Bind establishes
    (flex_gpu.go:230-242). Shared by DefaultBinder and TpuSlice.bind."""
    try:
        handle.clientset.pods.bind(Binding(
            pod_key=pod.key, node_name=node_name,
            annotations=dict(pod.meta.annotations)))
    except Exception as e:
        return Status.error(f"bind failed: {e}")
    return Status.success()


class DefaultBinder(BindPlugin):
    NAME = "DefaultBinder"

    def __init__(self, handle):
        self.handle = handle

    def bind(self, state: CycleState, pod: Pod, node_name: str) -> Status:
        return bind_with_annotations(self.handle, pod, node_name)
