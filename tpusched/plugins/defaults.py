"""Built-in default plugins.

The reference leans on upstream in-tree plugins for basic feasibility (its
fork disables most but the hosting framework still provides fit/priority/
binder). These are the minimal equivalents: priority queue sort, resource
fit, unschedulable/taints/selector filters, and the default binder.
"""
from __future__ import annotations

from typing import List, Tuple

from typing import Optional

from ..api.core import Binding, Node, Pod, node_health_error, tolerates
from ..api.resources import resources_fit
from ..fwk import (CycleState, Status, UNSCHEDULABLE)
from ..fwk.interfaces import (BatchFilterPlugin, BindPlugin, FilterPlugin,
                              QueueSortPlugin)
from ..fwk.nodeinfo import NodeInfo
from ..util.podutil import pod_effective_request


class PrioritySort(QueueSortPlugin):
    """Upstream PrioritySort: priority desc, then queue arrival time."""
    NAME = "PrioritySort"

    def less(self, pi1, pi2) -> bool:
        p1, p2 = pi1.pod.priority, pi2.pod.priority
        if p1 != p2:
            return p1 > p2
        return pi1.timestamp < pi2.timestamp


class NodeResourcesFit(BatchFilterPlugin):
    """cpu/memory/pods/extended-resource fit against allocatable − requested.

    Implements the batch fleet-wide path (filter_batch): one fused pass over
    all candidates with shared Status instances, replacing per-node plugin
    dispatch — see filter_batch's docstring for why this beats a numpy
    (nodes × resources) matrix here."""
    NAME = "NodeResourcesFit"

    _REQ_KEY = "NodeResourcesFit/pod-request"

    def _pod_request(self, state: CycleState, pod: Pod):
        # the pod's request is cycle-invariant: compute once per cycle
        # (upstream stashes it in PreFilter; memoizing on first Filter call
        # needs no profile wiring)
        def build():
            req = pod_effective_request(pod)
            req["pods"] = 1
            return tuple((k, v) for k, v in req.items() if v > 0)
        return state.read_or_init(self._REQ_KEY, build)

    def filter(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Status:
        if node_info.node is None:
            return Status.error("node not found")
        request = self._pod_request(state, pod)
        alloc = node_info.allocatable
        requested = node_info.requested
        insufficient = [k for k, v in request
                        if requested.get(k, 0) + v > alloc.get(k, 0)]
        if insufficient:
            return Status.unschedulable(
                *[f"Insufficient {k}" for k in insufficient])
        return Status.success()

    def filter_batch(self, state: CycleState, pod: Pod,
                     node_infos) -> List[Optional[Status]]:
        """One pass over all candidates. Two things make this the fast path
        at fleet scale (measured against a numpy (resources × nodes) matrix
        variant — converting Python dicts into arrays each cycle cost 4×
        what the comparison saved):

        - a single fused loop: per node, all resources checked with plain
          dict lookups, no per-node plugin dispatch or Status plumbing;
        - shared Status instances per failing-resource combination, tagged
          with this plugin's name so the sweep's ``with_plugin`` is the
          return-self no-op — on a 1024-host cluster a full-pool burst
          otherwise allocates ~0.5M identical Status objects."""
        request = self._pod_request(state, pod)
        n = len(node_infos)
        out: List[Optional[Status]] = [None] * n
        shared: dict = {}
        for i, inf in enumerate(node_infos):
            alloc = inf.allocatable
            used = inf.requested
            bad = None
            for k, v in request:
                if used.get(k, 0) + v > alloc.get(k, 0):
                    if bad is None:
                        bad = [k]
                    else:
                        bad.append(k)
            if bad is not None:
                key = tuple(bad)
                st = shared.get(key)
                if st is None:
                    st = Status(UNSCHEDULABLE,
                                [f"Insufficient {k}" for k in bad],
                                plugin=self.NAME)
                    shared[key] = st
                out[i] = st
        return out


class NodeUnschedulable(FilterPlugin):
    """Cordon + node-health gate: spec.unschedulable, a NotReady Ready
    condition, or the lifecycle controller's not-ready taint all reject the
    node (api.core.node_health_error is the one shared judgement — the
    verify-node-health-filters lint holds every placement-producing Filter
    to it)."""
    NAME = "NodeUnschedulable"
    # reads only node.spec + node.status.conditions: byte-identical while an
    # equivalence entry is armed (any node update bumps the mutation cursor)
    EQUIV_DYNAMIC = False

    def filter(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Status:
        err = node_health_error(node_info.node)
        if err is not None:
            return Status.unresolvable(err)
        return Status.success()


class TaintToleration(FilterPlugin):
    NAME = "TaintToleration"
    # node taints + pod tolerations only: both pinned by cursor/equiv key
    EQUIV_DYNAMIC = False

    def filter(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Status:
        for taint in node_info.node.spec.taints:
            if taint.effect in ("NoSchedule", "NoExecute") and not tolerates(pod, taint):
                return Status.unresolvable(
                    f"node(s) had untolerated taint {{{taint.key}: {taint.value}}}")
        return Status.success()


class NodeName(FilterPlugin):
    NAME = "NodeName"
    # pod.spec.node_name vs node name only
    EQUIV_DYNAMIC = False

    def filter(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Status:
        if pod.spec.node_name and pod.spec.node_name != node_info.node.name:
            return Status.unresolvable("node didn't match requested node name")
        return Status.success()


class NodeSelector(FilterPlugin):
    NAME = "NodeSelector"
    # node labels + pod selector only
    EQUIV_DYNAMIC = False

    def filter(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Status:
        selector = pod.spec.node_selector
        if not selector:
            return Status.success()
        labels = node_info.node.meta.labels
        for k, v in selector.items():
            if labels.get(k) != v:
                return Status.unresolvable("node(s) didn't match node selector")
        return Status.success()


def bind_with_annotations(handle, pod: Pod, node_name: str) -> Status:
    """POST the Binding carrying the pod's current annotations, so
    Reserve-time device/coord annotations survive to the API server — the
    contract the reference's custom FlexGPU Bind establishes
    (flex_gpu.go:230-242). Shared by DefaultBinder and TpuSlice.bind."""
    try:
        handle.clientset.pods.bind(Binding(
            pod_key=pod.key, node_name=node_name,
            annotations=dict(pod.meta.annotations)))
    except Exception as e:
        return Status.error(f"bind failed: {e}")
    return Status.success()


class DefaultBinder(BindPlugin):
    NAME = "DefaultBinder"

    def __init__(self, handle):
        self.handle = handle

    def bind(self, state: CycleState, pod: Pod, node_name: str) -> Status:
        return bind_with_annotations(self.handle, pod, node_name)
