from .plugin import TopologyMatch, COORD_ANNOTATION, POOL_ANNOTATION

__all__ = ["TopologyMatch", "COORD_ANNOTATION", "POOL_ANNOTATION"]
