"""TopologyMatch plugin: ICI-torus slice-shape fitting for gangs.

TPU-native successor of the reference's NodeResourceTopologyMatch plugin
(/root/reference/pkg/noderesourcetopology): where that plugin simulates the
kubelet TopologyManager's single-NUMA-node admission with 1-D bitmasks
(filter.go:84-150) fed by the NodeResourceTopology CRD, this plugin fits a
PodGroup's requested chip shape (PodGroupSpec.tpu_slice_shape, e.g. "4x4x4")
onto a contiguous free block of a pool's ICI torus published as a TpuTopology
CR — axis permutations allowed, wraparound only on wrapped axes.

Mechanics per scheduling cycle:
- PreFilter: resolve the pod's gang slice request; enumerate feasible
  placements on every matching pool given hosts already occupied and hosts
  already ASSIGNED to gang siblings (the incremental all-or-nothing
  constraint); stash per-node feasibility + scoring info in CycleState.
  Non-slice pods return Skip (the filter is bypassed entirely, like the
  reference skips BestEffort pods, filter.go:194-196).
- Filter: membership test against the stash.
- Score: corner-packing — prefer the node appearing in the FEWEST surviving
  placements (most-constrained-first keeps the torus defragmented for future
  gangs), with the configured strategy over pool utilization as a tiebreak.
- Reserve/Unreserve: write/remove the pool + chip-coordinate annotations the
  on-host runtime (and jaxbridge mesh builder) consumes.
"""
from __future__ import annotations

import math
import os

from typing import Dict, FrozenSet, List, Optional, Tuple

from ...api.core import Pod, node_health_error
from ...api.resources import TPU
from ...api.scheduling import POD_GROUP_LABEL, pod_group_label
from ...api.topology import (ACCELERATORS, TOPOLOGY_GROUP, format_coord,
                             parse_shape)
from ...config.types import TopologyMatchArgs
from ...fwk import CycleState, Status
from ...fwk.interfaces import (ClusterEvent, EnqueueExtensions,
                               EquivalenceAware, EVENT_ADD, EVENT_DELETE,
                               EVENT_UPDATE, FilterPlugin, NodeScore,
                               PostFilterPlugin, PostFilterResult,
                               ReservePlugin, ScorePlugin,
                               PreFilterPlugin, RESOURCE_NODE, RESOURCE_POD,
                               RESOURCE_POD_GROUP, RESOURCE_TPU_TOPOLOGY)
from ...fwk.nodeinfo import MAX_NODE_SCORE, NodeInfo
from ... import native
from ...topology.engine import (MaskGrid, PlacementSet,
                                enumerate_placement_masks,
                                feasible_membership)
from ...topology.torus import HostGrid, validate_slice_shape
from ...sched.preemption import (atomic_set_eviction_vetoed,
                                 filter_pods_with_pdb_violation,
                                 gang_min_member)
from ...util import klog
from ...util.metrics import (preemption_attempts, slice_preemption_victims,
                             torus_index_differential_mismatches,
                             torus_index_queries)
from ...util.ttlcache import TTLCache
from ..defaults import (NodeName, NodeResourcesFit, NodeSelector,
                        NodeUnschedulable, TaintToleration)
from ..preemptiontoleration import exempted_from_preemption
from ..tpuslice.chip_node import pod_tpu_limits

COORD_ANNOTATION = TOPOLOGY_GROUP + "/coord"
POOL_ANNOTATION = TOPOLOGY_GROUP + "/pool"

_STATE_KEY = "TopologyMatch/state"
_CLAIMS_KEY = "TopologyMatch/claimed-hosts"

# stateless node filters used by the slice-preemption dry-run — every
# node-scoped filter of the full-stack profile, or the dry-run evicts a
# window the preemptor's own selector/name constraints can never use
_VIABILITY_CHECKS = (NodeUnschedulable(), NodeName(), NodeSelector(),
                     TaintToleration(), NodeResourcesFit())


class _CycleStash:
    """Per-cycle feasibility: node → (pool, membership count, pool util)."""

    def __init__(self):
        self.allowed: Dict[str, Tuple[str, int, float]] = {}
        self.max_membership = 1
        # total surviving placements across every swept pool — the
        # equivalence cache's participation gate (see equiv_fingerprint)
        self.survivors = 0

    def clone(self):
        return self  # read-only after PreFilter


class TopologyMatch(PreFilterPlugin, FilterPlugin, PostFilterPlugin,
                    ScorePlugin, ReservePlugin, EnqueueExtensions,
                    EquivalenceAware):
    NAME = "TopologyMatch"
    # filter() is a membership probe against the PreFilter stash — on a
    # cache hit the stash IS the memoized artifact, so re-running the probe
    # over the cached feasible set (feasible ⊆ allowed by construction)
    # would be a no-op. Stash validity is the fingerprint's job.
    EQUIV_DYNAMIC = False

    def __init__(self, args: Optional[TopologyMatchArgs], handle):
        self.args = args or TopologyMatchArgs()
        self.handle = handle
        self.pg_informer = handle.informer_factory.podgroups()
        self.pg_informer.add_event_handler(
            on_delete=self._pg_deleted, replay=False)
        self.topo_informer = handle.informer_factory.tputopologies()
        # caches keyed by CR resource_version (grids) / + block (placements)
        self._grid_cache: Dict[Tuple[str, int], Tuple[HostGrid, MaskGrid]] = {}
        self._placement_cache: Dict[Tuple[str, int, Tuple[int, ...]],
                                    PlacementSet] = {}
        # one eviction burst per gang while victims drain (add-if-absent:
        # sibling failures during the drain must not evict a second window)
        self._recent_evictions = TTLCache(
            self.args.slice_preemption_drain_seconds)
        # freed-window claims: gang full-name → (topo key, host mask). While
        # a claim is live, OTHER gangs' PreFilter treats the window's hosts
        # as unavailable — the nominatedNodeName analog for gangs (without
        # it, the victim-delete events requeue every pending gang and an
        # older equal-priority rival pops first and steals the window)
        self._window_claims = TTLCache(
            self.args.slice_preemption_drain_seconds)
        # gang full-name → pool name, set at Reserve: once any sibling is
        # placed, later siblings' PreFilter sweeps only that pool
        self._gang_pool: Dict[str, str] = {}
        # window-index differential oracle sampling (ISSUE 13): every Nth
        # index-served pool sweep is re-run through the Python full
        # recompute and compared; env overrides the profile knob so gates
        # (replay-smoke) can force it without a config fork
        env_period = os.environ.get("TPUSCHED_INDEX_DIFFERENTIAL")
        self._index_diff_period = int(env_period) if env_period \
            else self.args.index_differential_period
        self._index_diff_count = 0
        # warm the native engine at construction — its first load may compile
        # the C++ source, which must not stall a scheduling cycle
        native.load()

    def _window_index(self):
        return getattr(self.handle, "window_index", None)

    @classmethod
    def new(cls, args, handle) -> "TopologyMatch":
        return cls(args, handle)

    def _pg_deleted(self, pg) -> None:
        # a deleted claimant releases its freed-window claim immediately —
        # without this the evicted capacity idles until the drain TTL
        self._window_claims.delete(pg.meta.key)
        self._gang_pool.pop(pg.meta.key, None)

    def events_to_register(self) -> List[ClusterEvent]:
        return [
            ClusterEvent(RESOURCE_POD, EVENT_ADD | EVENT_DELETE),
            ClusterEvent(RESOURCE_NODE, EVENT_ADD | EVENT_UPDATE),
            ClusterEvent(RESOURCE_TPU_TOPOLOGY, EVENT_ADD | EVENT_UPDATE),
            ClusterEvent(RESOURCE_POD_GROUP, EVENT_ADD | EVENT_UPDATE),
        ]

    # -- gang slice request resolution ---------------------------------------

    def _slice_request(self, pod: Pod):
        """Returns (pg, chip_shape, accelerator_name) or None."""
        name = pod_group_label(pod)
        if not name:
            return None
        pg = self.pg_informer.get(f"{pod.namespace}/{name}")
        if pg is None or not pg.spec.tpu_slice_shape:
            return None
        try:
            shape = parse_shape(pg.spec.tpu_slice_shape)
        except ValueError:
            return "invalid"
        return pg, shape, pg.spec.tpu_accelerator

    def _matching_pools(self, shape, want_acc):
        """Pools whose accelerator matches and whose torus could hold the
        shape: yields (topo, acc, grids, validation_error) — error is a
        string when the shape can never fit that pool, None otherwise."""
        for topo in self.topo_informer.items():
            spec = topo.spec
            if want_acc and spec.accelerator != want_acc:
                continue
            acc = ACCELERATORS.get(spec.accelerator)
            if acc is None:
                continue
            err = validate_slice_shape(shape, acc, tuple(spec.dims))
            if err:
                yield topo, acc, None, err
                continue
            grids = self._grid(topo)
            if grids is None:
                continue
            yield topo, acc, grids, None

    # -- PreFilter ------------------------------------------------------------

    def pre_filter(self, state: CycleState, pod: Pod) -> Status:
        req = self._slice_request(pod)
        if req is None:
            # Skip suppresses our Filter entirely (state.skip_filter_plugins)
            # — but while freed-window claims are live, TPU-consuming pods
            # must still pass through filter()'s claim guard, or a plain pod
            # lands on a claimed host and re-breaks the claimant's window.
            # The guarded-host set is computed ONCE here (the per-node
            # filter sweep must stay a set lookup, not a cache scan).
            chips, chips_set, mem, mem_set = pod_tpu_limits(pod)
            if chips_set or mem_set:
                claims = self._window_claims.items()
                if claims:
                    mine = pod_group_label(pod)
                    mine_full = f"{pod.namespace}/{mine}" if mine else None
                    guarded = frozenset().union(*(
                        names for full, (_, names) in claims
                        if full != mine_full)) if claims else frozenset()
                    if guarded:
                        state.write(_CLAIMS_KEY, guarded)
                        return Status.success()
            return Status.skip()
        if req == "invalid":
            return Status.unresolvable("invalid tpu_slice_shape on PodGroup")
        pg, shape, want_acc = req

        chips_req, chips_set, _, _ = pod_tpu_limits(pod)
        chips_needed = chips_req if chips_set else None
        snapshot = self.handle.snapshot_shared_lister()
        full = f"{pod.namespace}/{pg.meta.name}"
        validation_errors: List[str] = []
        any_pool = False
        any_valid_pool = False

        matching = []
        for topo, acc, grids, err in self._matching_pools(shape, want_acc):
            any_pool = True
            if err:
                validation_errors.append(f"pool {topo.spec.pool}: {err}")
                continue
            any_valid_pool = True
            matching.append((topo, acc, grids))

        # Window-index fast path (ISSUE 13): when no freed-window claims
        # are live and a pool's index plane is provably at this snapshot's
        # cursor epoch, the whole occupancy scan + feasibility sweep below
        # collapses into one table lookup.  Any doubt — claims live, plane
        # version mismatch, topology CR drift — falls back to the Python
        # full recompute, which stays the oracle.
        index = self._window_index()
        claims_live = bool(self._window_claims.items())
        gang_key = (pod.namespace, pg.meta.name)
        publish = getattr(self.handle, "telemetry", True)

        def pool_answer(topo, acc, grids):
            """(index_result_or_None, occupancy_or_None) for one pool."""
            need = chips_needed if chips_needed is not None \
                else acc.chips_per_host
            q = None
            if index is not None and not claims_live:
                q = index.query(topo, shape, gang_key, need,
                                snapshot.pool_cursors.get(topo.spec.pool))
                if publish:
                    torus_index_queries.with_labels(
                        "served" if q is not None else "fallback").inc()
                if q is not None and self._index_diff_due():
                    q = self._index_differential(q, topo, grids, shape,
                                                 need, snapshot, pg, pod)
            if q is not None:
                return q, None
            return None, self._occupancy(grids[0], snapshot, pg.meta.name,
                                         pod.namespace, need)

        def sweep(pools) -> _CycleStash:
            stash = _CycleStash()
            candidates = []
            for topo, acc, grids in pools:
                q, occ = pool_answer(topo, acc, grids)
                candidates.append((topo, acc, grids, q, occ))
            # A gang must live in ONE torus: once any sibling is assigned in
            # a pool, every other pool is off the table (a "slice" spanning
            # two disjoint ICI fabrics would be unusable).
            pinned = [c for c in candidates
                      if (c[3].assigned if c[3] is not None else c[4][0])]
            if pinned:
                candidates = pinned
            for topo, acc, (grid, mgrid), q, occ in candidates:
                if q is not None:
                    n_survivors, membership, pool_util = \
                        q.survivors, q.membership, q.pool_util
                else:
                    assigned, free, eligible, pool_util = occ
                    pset = self._placements(topo, mgrid, shape)
                    claimed = self._claimed_mask(mgrid, grid, topo.key,
                                                 exclude=full)
                    n_survivors, membership = feasible_membership(
                        pset, mgrid.mask_of(assigned),
                        mgrid.mask_of(free) & ~claimed,
                        mgrid.mask_of(eligible) & ~claimed)
                if not n_survivors:
                    continue
                stash.survivors += n_survivors
                for node, count in membership.items():
                    prev = stash.allowed.get(node)
                    if prev is None or count < prev[1]:
                        stash.allowed[node] = (grid.pool, count, pool_util)
                    stash.max_membership = max(stash.max_membership, count)
            return stash

        # pool pin (set at the first sibling's Reserve): sweep only the
        # gang's pool; a stale/failed pin falls back to the full sweep
        pin = self._gang_pool.get(full)
        stash = _CycleStash()
        if pin is not None:
            pool_match = [m for m in matching if m[0].spec.pool == pin]
            if pool_match:
                stash = sweep(pool_match)
            if not stash.allowed:
                self._gang_pool.pop(full, None)
        if not stash.allowed:
            stash = sweep(matching)

        if not stash.allowed:
            from ... import trace
            trace.record_rejection(
                self.NAME, "no feasible slice placement",
                pod_group=full, shape=pg.spec.tpu_slice_shape,
                accelerator=want_acc or "(any)",
                matching_pools=len(matching), pool_pin=pin or "",
                validation_errors="; ".join(validation_errors))
            if not any_pool:
                return Status.unresolvable(
                    f"no TpuTopology pool matches accelerator "
                    f"{want_acc or '(any)'}")
            # only permanent if EVERY matching pool failed validation; a
            # transiently-full valid pool keeps the pod retriable
            if validation_errors and not any_valid_pool:
                return Status.unresolvable("; ".join(validation_errors))
            return Status.unschedulable(
                f"no feasible {pg.spec.tpu_slice_shape} slice placement "
                f"in any pool")
        state.write(_STATE_KEY, stash)
        from ... import trace
        trace.annotate("topology_surviving_placements", stash.survivors)
        # PreFilterResult.NodeNames analog: only hosts inside a surviving
        # placement can take this pod — hand the scheduler the exact
        # candidate set so the per-node sweep never visits the rest of the
        # fleet (the Filter membership check stays as the correctness net)
        state.restrict_nodes(stash.allowed.keys())
        return Status.success()

    def _grid(self, topo) -> Optional[Tuple[HostGrid, MaskGrid]]:
        key = (topo.key, topo.meta.resource_version)
        grids = self._grid_cache.get(key)
        if grids is None:
            grid = HostGrid.from_spec(topo.spec)
            if grid is None:
                return None
            grids = (grid, MaskGrid(grid))
            if len(self._grid_cache) > 16:
                self._grid_cache.clear()
            self._grid_cache[key] = grids
        return grids

    def _placements(self, topo, mgrid: MaskGrid, chip_shape) -> PlacementSet:
        index = self._window_index()
        if index is not None:
            # ONE enumeration fleet-wide: the index's per-(pool, shape)
            # placement sets are shared by PreFilter, this plugin's
            # PostFilter window sweep and the capacity ladder
            return index.placement_set(topo, mgrid, tuple(chip_shape))
        key = (topo.key, topo.meta.resource_version, tuple(chip_shape))
        got = self._placement_cache.get(key)
        if got is None:
            got = enumerate_placement_masks(mgrid, chip_shape)
            if len(self._placement_cache) > 64:
                self._placement_cache.clear()
            self._placement_cache[key] = got
        return got

    # -- window-index differential oracle (ISSUE 13) --------------------------

    def _index_diff_due(self) -> bool:
        if self._index_diff_period <= 0:
            return False
        self._index_diff_count += 1
        return self._index_diff_count % self._index_diff_period == 0

    def _index_differential(self, q, topo, grids, shape, need, snapshot,
                            pg, pod):
        """Re-run the Python full recompute for one index-served pool sweep
        and compare.  On mismatch: count, quarantine the pool's plane (it
        reseeds from the cache) and return None so the caller uses the
        oracle's answer this cycle."""
        grid, mgrid = grids
        assigned, free, eligible, util = self._occupancy(
            grid, snapshot, pg.meta.name, pod.namespace, need)
        pset = self._placements(topo, mgrid, shape)
        n_survivors, membership = feasible_membership(
            pset, mgrid.mask_of(assigned), mgrid.mask_of(free),
            mgrid.mask_of(eligible))
        if (n_survivors == q.survivors and membership == q.membership
                and frozenset(assigned) == q.assigned
                and abs(util - q.pool_util) < 1e-12):
            return q
        torus_index_differential_mismatches.inc()
        klog.error_s(
            RuntimeError("torus window index drift"),
            "index answer differs from the Python oracle; quarantining "
            "pool plane", pool=topo.spec.pool, pod=pod.key,
            index_survivors=q.survivors, oracle_survivors=n_survivors)
        index = self._window_index()
        if index is not None:
            index.mark_stale(topo.spec.pool)
            resync = getattr(self.handle, "window_index_resync", None)
            if resync is not None:
                resync()
        return None

    @staticmethod
    def _node_pg_usage(info: NodeInfo):
        """Per-node TPU usage grouped by owning gang: {(ns, pg_label): chips}
        plus the node's total TPU chips in use. Memoized on the NodeInfo via
        its generation (fwk/nodeinfo.py derived()): during a 256-member gang
        burst only the node that just took a sibling changes, so the other
        63+ hosts answer every later cycle's occupancy query without
        re-walking their pods."""
        usage: Dict[Tuple[str, Optional[str]], int] = {}
        total = 0
        for p in info.pods:
            c, _, _, _ = pod_tpu_limits(p)
            k = (p.meta.namespace, p.meta.labels.get(POD_GROUP_LABEL))
            usage[k] = usage.get(k, 0) + c
            total += c
        return usage, total

    def _occupancy(self, grid: HostGrid, snapshot, pg_name: str,
                   namespace: str, chips_needed: int):
        """Returns (assigned, free, eligible, pool_utilization):

        - assigned: hosts any gang sibling already occupies (assumed/bound);
        - free: hosts a placement may CLAIM — no foreign TPU usage at all
          (a placement owns the host's whole chip block; a single foreign
          chip inside the slice breaks ICI exclusivity);
        - eligible: hosts THIS pod may land on — no foreign usage and enough
          chips left after siblings (covers sub-host pods packing a host);
        - pool_utilization: used/allocatable chips (for the score strategy),
          computed in the same walk."""
        assigned = set()
        free = set()
        eligible = set()
        total_alloc = total_used = 0
        me = (namespace, pg_name)
        for node, coord in grid.coord_of.items():
            info = snapshot.get(node)
            if info is None:
                continue
            usage, node_used = info.derived("TopologyMatch/pg-usage",
                                            self._node_pg_usage)
            ent = usage.get(me)
            has_sibling = ent is not None
            sibling_used = ent or 0
            foreign_used = node_used - sibling_used
            alloc = info.allocatable.get(TPU, 0)
            total_alloc += alloc
            total_used += node_used
            if has_sibling:
                assigned.add(coord)
            if foreign_used:
                continue
            # a NotReady/cordoned host must not anchor a NEW window: a
            # window containing it would pass enumeration, fail the
            # per-node health filter, and wedge the gang on a placement
            # that can never complete (sibling-occupied hosts stay counted
            # as assigned above — API truth until eviction/repair acts)
            if node_health_error(info.node) is not None:
                continue
            if not has_sibling:
                free.add(coord)
            if alloc - sibling_used >= chips_needed:
                eligible.add(coord)
        util = total_used / total_alloc if total_alloc else 1.0
        return frozenset(assigned), frozenset(free), frozenset(eligible), util

    # -- Filter ---------------------------------------------------------------

    def filter(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Status:
        stash = state.try_read(_STATE_KEY)
        if stash is not None:
            # belt-and-braces behind the _occupancy exclusion: a readiness
            # flip between PreFilter's window sweep and this node's visit
            # must still reject (the cursor bump invalidates any armed
            # equivalence entry, so the two layers cannot disagree)
            health = node_health_error(node_info.node)
            if health is not None:
                # unresolvable, same severity as NodeUnschedulable/TpuSlice:
                # no preemption can revive dead hardware
                return Status.unresolvable(health)
        if stash is None:
            # PreFilter skipped (non-slice pod) — but a freed-window claim
            # still guards its hosts: a plain TPU pod grabbing one host of
            # a just-evicted window would re-break the claimant's placement
            # (guarded set precomputed once per cycle in pre_filter)
            guarded = state.try_read(_CLAIMS_KEY)
            if guarded and node_info.node.name in guarded:
                return Status.unschedulable(
                    "host is claimed by an in-flight slice preemption")
            return Status.success()
        if node_info.node.name not in stash.allowed:
            return Status.unschedulable(
                "node is not part of any feasible slice placement")
        return Status.success()

    # -- equivalence cache (sched/equivcache.py) ------------------------------

    def equiv_fingerprint(self, pod: Pod, state):
        """Key material for the inputs the mutation cursor cannot see:
        TpuTopology CR versions (grid/placement geometry), live freed-window
        claims (TTL'd), and the gang's pool pin. Occupancy itself is
        cursor-guarded.

        Participation gate (creation only): a slice pod's cycle must have
        ended with EXACTLY ONE surviving placement. That is the regime where
        the stash is provably stable under same-class sibling assumes —
        assigned grows inside the unique window (it keeps surviving:
        assigned ⊆ mask, and a host moving free→assigned stays covered),
        hosts that fill up are re-rejected by the dynamic chip/resource
        filters exactly as the full path's eligibility test would, and the
        Score inputs (membership ≡ 1, one shared pool util) shift uniformly
        across the window so the argmax cannot move. With ≥ 2 surviving
        windows a sibling could land outside the window the first member
        chose — the multi-window cycle takes the full path (in practice the
        pool pin set at first Reserve collapses the next cycle to one
        window, and THAT cycle's entry serves the rest of the gang)."""
        claims = tuple(sorted(
            (full, tk, tuple(sorted(names)))
            for full, (tk, names) in self._window_claims.items()))
        req = self._slice_request(pod)
        if req is None:
            return ("nonslice", claims)
        if req == "invalid":
            return None
        pg, shape, want_acc = req
        full = f"{pod.namespace}/{pg.meta.name}"
        pin = self._gang_pool.get(full)
        if state is not None:
            stash = state.try_read(_STATE_KEY)
            if stash is None or stash.survivors != 1:
                return None
            if pin is None and stash.allowed:
                # normalize the pin across the arming boundary: this cycle's
                # Reserve is about to pin the gang to the single surviving
                # window's pool, so fingerprint the pool the NEXT sibling's
                # lookup will see — without this the first entry of every
                # gang dies at its first lookup (pin None → pin set) and the
                # second member pays a wasted full sweep. A pinned sweep of
                # that one pool produces the identical stash, so the two
                # states are genuinely equivalent.
                pin = next(iter(stash.allowed.values()))[0]
        topos = tuple(sorted((t.key, t.meta.resource_version)
                             for t in self.topo_informer.items()))
        return ("slice", full, pg.meta.resource_version, tuple(shape),
                want_acc, pin, claims, topos)


    # -- PostFilter: slice preemption -----------------------------------------
    #
    # Single-node preemption (the upstream Evaluator the capacity plugin
    # drives) can never help a slice-shaped gang: freeing ONE node does not
    # free a contiguous torus window. This preempts window-wise — pick the
    # cheapest placement whose resident foreign pods are ALL eligible
    # victims, evict them, and let the gang's retry (pod-delete events
    # requeue it) find the freed window. No reference analog: the reference
    # ships cross-node preemption disabled and its NRT plugin has no
    # preemption at all; this is the TPU-native composition of the two.

    def post_filter(self, state: CycleState, pod: Pod,
                    filtered_node_status_map) -> Tuple[Optional[PostFilterResult], Status]:
        if not self.args.enable_slice_preemption:
            return None, Status.unschedulable("slice preemption disabled")
        req = self._slice_request(pod)

        if req is None or req == "invalid":
            return None, Status.unschedulable("not a slice-shaped pod")
        pg, shape, want_acc = req
        full = f"{pod.namespace}/{pg.meta.name}"
        if full in self._recent_evictions:
            # drain window: report progress (PostFilter success semantics)
            # so Coscheduling's mass-reject doesn't deny the gang while the
            # victims it is waiting for terminate
            return PostFilterResult(), Status.success()

        snapshot = self.handle.snapshot_shared_lister()
        cs = self.handle.clientset
        pdbs = cs.pdbs.list()
        pcs = {pc.meta.name: pc for pc in cs.priorityclasses.list()}
        usage, quotas = self._namespace_tpu_usage(snapshot)
        gang_chips = math.prod(shape)
        # preemptor-side quota gate, invariant across windows: cross-quota
        # eviction is allowed only while the gang reclaims its own
        # guaranteed min (assumed siblings already inside the usage sum)
        peq = quotas.get(pod.namespace)
        if peq is None:
            preemptor_within_min = True  # no quota governs the preemptor
        else:
            after = (usage.get(pod.namespace, 0)
                     - self._assumed_gang_chips(pod, snapshot) + gang_chips)
            preemptor_within_min = after <= peq.spec.min.get(TPU, 0)

        # candidate pools with the SAME one-torus pinning rule as PreFilter:
        # once a sibling is assigned in a pool, windows elsewhere are useless
        # (the window index answers the assigned-set probe as a dict lookup
        # when its plane matches this snapshot's cursor epoch)
        index = self._window_index()
        candidates = []
        for topo, acc, grids, err in self._matching_pools(shape, want_acc):
            if err:
                continue
            assigned = None
            if index is not None:
                assigned = index.assigned_view(
                    topo, (pod.namespace, pg.meta.name),
                    snapshot.pool_cursors.get(topo.spec.pool))
            if assigned is None:
                assigned, _, _, _ = self._occupancy(
                    grids[0], snapshot, pg.meta.name, pod.namespace,
                    acc.chips_per_host)
            candidates.append((topo, grids, assigned))
        pinned = [c for c in candidates if c[2]]
        if pinned:
            candidates = pinned

        best = None  # (rank key, victims)
        for topo, (grid, mgrid), assigned in candidates:
            assigned_mask = mgrid.mask_of(assigned)
            for mask in self._placements(topo, mgrid, shape).masks:
                if assigned_mask and (mask & assigned_mask) != assigned_mask:
                    continue  # must contain already-placed siblings
                victims = self._window_victims(grid, mgrid, mask, snapshot,
                                               pg.meta.name, pod.namespace)
                if not victims:
                    # victimless window: TopologyMatch found it feasible, so
                    # this pod's failure came from ANOTHER plugin (cordon,
                    # cpu pressure) — evicting elsewhere would not help it,
                    # but other windows may still be worth ranking
                    continue
                if not self._window_viable_after_eviction(
                        pod, grid, mgrid, mask, snapshot, victims):
                    continue  # eviction would not make the hosts usable
                partial_gangs = self._window_eligible(
                    victims, pod, pcs, usage, quotas, preemptor_within_min,
                    snapshot)
                if partial_gangs is None:
                    continue
                violating, _ = filter_pods_with_pdb_violation(victims, pdbs)
                # rank: fewest PDB violations → fewest gangs split by the
                # window → fewest victims → lowest total priority → NEWEST
                # victims (upstream MoreImportantPod: earlier start = more
                # important) → mask for full determinism
                key = (len(violating), partial_gangs, len(victims),
                       sum(v.priority for v in victims),
                       -sum(v.meta.creation_timestamp for v in victims),
                       mask)
                if best is None or key < best[0]:
                    window_nodes = frozenset(
                        grid.node_of[c] for c in mgrid.coords_of(mask)
                        if c in grid.node_of)
                    best = (key, victims, topo.key, window_nodes)

        if best is None:
            return None, Status.unschedulable(
                "no slice window has an evictable victim set")
        (violations, _, n, _, _, _), victims, best_topo_key, best_nodes = best
        if violations:
            klog.warning_s("slice preemption violates PDBs",
                           pod=pod.key, violations=violations)
        self._recent_evictions.add(full)
        for v in victims:
            if not self.handle.reject_waiting_pod(
                    v.meta.uid, self.NAME, f"slice-preempted by {full}"):
                try:
                    cs.pods.delete(v.key)
                except srv.NotFound:   # raced an external delete: fine
                    pass
            cs.record_event(v.key, "Pod", "Normal", "Preempted",
                            f"Slice-preempted by gang {full}")
        self._window_claims.set(full, (best_topo_key, best_nodes))
        preemption_attempts.inc()
        slice_preemption_victims.inc(n)
        klog.V(2).info_s("slice preemption evicted a window",
                         podGroup=full, victims=n)
        # success (upstream PostFilter contract: preemption made progress,
        # no nominated node — a gang has no single node): stops the chain,
        # so the gang is NOT mass-denied; victim deletions requeue it
        return PostFilterResult(), Status.success()

    def _window_viable_after_eviction(self, pod: Pod, grid, mgrid, mask,
                                      snapshot, victims) -> bool:
        """Dry-run the stateless node filters (cordon, taints, resource fit)
        on every window host with the victims removed — upstream preemption
        re-runs filters over the post-eviction state the same way
        (capacity_scheduling.go:581); evicting a window whose hosts still
        fail other plugins would destroy workloads for zero progress."""
        gone = {id(v) for v in victims}
        state = CycleState()
        for coord in mgrid.coords_of(mask):
            info = snapshot.get(grid.node_of.get(coord))
            if info is None:
                return False
            stripped = NodeInfo(info.node,
                                [p for p in info.pods if id(p) not in gone])
            for chk in _VIABILITY_CHECKS:
                if not chk.filter(state, pod, stripped).is_success():
                    return False
        return True

    def _claimed_mask(self, mgrid, grid, topo_key: str, exclude: str) -> int:
        """Mask of live window claims on this pool from OTHER gangs. Claims
        store node NAMES: grid-independent, so a TpuTopology update during
        the drain (new strides/dims) cannot misdirect the guard."""
        m = 0
        for full, (tk, names) in self._window_claims.items():
            if full == exclude or tk != topo_key:
                continue
            for n in names:
                coord = grid.coord_of.get(n)
                if coord is not None:
                    m |= 1 << mgrid.cell(coord)
        return m

    def _namespace_tpu_usage(self, snapshot):
        """(namespace → whole chips used, namespace → ElasticQuota) — the
        borrowing-rule inputs (capacity_scheduling.go:526-553 semantics,
        window-wise). Counts whole-chip pods only: fractional tpu-memory
        pods are governed by the priority rule, not chip borrowing (their
        occupancy is sub-chip and quota min/max here are chip counts)."""
        usage: Dict[str, int] = {}
        for info in snapshot.list():
            for p in info.pods:
                chips, chips_set, _, _ = pod_tpu_limits(p)
                if chips_set:
                    usage[p.meta.namespace] = \
                        usage.get(p.meta.namespace, 0) + chips
        quotas = {eq.meta.namespace: eq
                  for eq in self.handle.clientset.elasticquotas.list()}
        return usage, quotas

    def _window_victims(self, grid, mgrid, mask, snapshot, pg_name,
                        namespace):
        """Foreign TPU pods resident on the window's hosts, or None when a
        host is missing from the snapshot (stale CR)."""
        victims: List[Pod] = []
        for coord in mgrid.coords_of(mask):
            node = grid.node_of.get(coord)
            info = snapshot.get(node) if node else None
            if info is None:
                return None
            for p in info.pods:
                chips, chips_set, mem, mem_set = pod_tpu_limits(p)
                if not chips_set and not mem_set:
                    continue  # non-TPU pods don't block chips
                if (p.meta.labels.get(POD_GROUP_LABEL) == pg_name
                        and p.meta.namespace == namespace):
                    continue  # own sibling
                victims.append(p)
        return victims

    def _window_eligible(self, victims, preemptor: Pod, pcs, usage, quotas,
                         preemptor_within_min: bool,
                         snapshot) -> Optional[int]:
        """Window-wise eligibility — returns the number of running gangs the
        window would SPLIT (a ranking penalty), or None if any victim is
        ineligible. The composition contract with CapacityScheduling's
        borrowing rules (capacity_scheduling.go:526-553) and
        PreemptionToleration's policy annotations:

        - same-namespace victims: priority rule (victim < preemptor);
        - foreign victims under NO quota: priority rule;
        - foreign victims under a quota: evictable only while the preemptor
          reclaims its own guaranteed min (within-min after accounting for
          its already-assumed siblings), and only up to the victim team's
          overage (usage - min): another team's min is never broken, not
          even by priority;
        - toleration-exempt victims veto the window outright.
        """
        pns = preemptor.namespace
        foreign_chips: Dict[str, int] = {}
        for v in victims:
            if exempted_from_preemption(v, preemptor,
                                        lambda name: pcs.get(name),
                                        now=self.handle.clock()):
                return None
            chips, chips_set, _, _ = pod_tpu_limits(v)
            if v.meta.namespace == pns or quotas.get(v.meta.namespace) is None:
                if not v.priority < preemptor.priority:
                    return None
                continue
            # foreign, quota-governed
            if not preemptor_within_min:
                return None
            if not chips_set:
                # fractional pod: chip borrowing doesn't govern it
                if not v.priority < preemptor.priority:
                    return None
                continue
            foreign_chips[v.meta.namespace] = \
                foreign_chips.get(v.meta.namespace, 0) + chips
        for ns, evicted in foreign_chips.items():
            overage = usage.get(ns, 0) - quotas[ns].spec.min.get(TPU, 0)
            if evicted > overage:
                return None  # would break the team's guaranteed min

        # gang minMember disruption floor (shared contract with the
        # single-node evaluators, sched/preemption.GangDisruptionFloor):
        # a window whose eviction leaves any victim gang strictly between
        # zero and minMember bound members is VETOED — the survivors would
        # burn their chips below quorum (the stranded-gang state the
        # randomized soak caught: a 1-host window evicting 1 of 16).
        # Gangs still above min after the eviction, or taken to exactly
        # zero, remain eligible; the partial count stays a ranking penalty
        # among the survivors.
        by_gang: Dict[Tuple[str, str], Tuple[int, Pod]] = {}
        for v in victims:
            g = v.meta.labels.get(POD_GROUP_LABEL)
            if g:
                k = (v.meta.namespace, g)
                n, _ = by_gang.get(k, (0, v))
                by_gang[k] = (n + 1, v)
        partial = 0
        for (ns, g), (n, rep) in by_gang.items():
            live = snapshot.assigned_live_count(g, ns)
            min_member = gang_min_member(self.handle, rep, f"{ns}/{g}")
            if live < min_member:
                continue            # already sub-quorum: nothing to protect
            remaining = live - n
            if remaining > 0:
                if remaining < min_member:
                    return None     # would strand a live gang below quorum
                partial += 1
        # SET disruption floor (atomic multislice): a window taking one
        # slice of a bound set to zero strands its sibling slices on other
        # pools — all-or-nothing in admission must be all-or-nothing in
        # disruption (soak seed 7)
        if atomic_set_eviction_vetoed(
                self.handle, snapshot,
                {k: n for k, (n, _) in by_gang.items()}):
            return None
        return partial

    def _assumed_gang_chips(self, pod: Pod, snapshot) -> int:
        """Whole chips already held by this gang's assumed/bound siblings —
        they are inside the namespace usage sum and must not be counted a
        second time through gang_chips. Walks the SNAPSHOT (not the
        informer): siblings parked at Permit are assumed — node-assigned in
        the scheduler cache only, invisible as bound in the API. Runs once
        per post_filter call (cold failure path)."""
        name = pod_group_label(pod)
        if not name:
            return 0
        total = 0
        for info in snapshot.list():
            for p in info.pods:
                if (p.meta.namespace == pod.namespace
                        and p.meta.labels.get(POD_GROUP_LABEL) == name):
                    chips, chips_set, _, _ = pod_tpu_limits(p)
                    if chips_set:
                        total += chips
        return total

    # -- Score ----------------------------------------------------------------

    def score(self, state: CycleState, pod: Pod, node_name: str) -> Tuple[int, Status]:
        stash = state.try_read(_STATE_KEY)
        if stash is None:
            return 0, Status.success()
        entry = stash.allowed.get(node_name)
        if entry is None:
            return 0, Status.success()
        _, membership, pool_util = entry
        # corner-packing: fewest surviving placements wins
        constraint = MAX_NODE_SCORE * (stash.max_membership - membership) \
            // max(1, stash.max_membership)
        strategy = self._strategy_score(pool_util)
        w = self.args.packing_weight  # range-checked at config decode
        return int(constraint * w + strategy * (1.0 - w)), Status.success()

    def _strategy_score(self, util: float) -> int:
        """NRT scoring strategies over the pool 'zone'
        (least_allocated.go:25-55, most_allocated.go:25-54,
        balanced_allocation.go:28-55)."""
        s = self.args.scoring_strategy
        if s == "MostAllocated":
            return int(util * MAX_NODE_SCORE)
        if s == "BalancedAllocation":
            return int((1.0 - abs(util - 0.5) * 2) * MAX_NODE_SCORE)
        return int((1.0 - util) * MAX_NODE_SCORE)  # LeastAllocated default

    # -- Reserve --------------------------------------------------------------

    def reserve(self, state: CycleState, pod: Pod, node_name: str) -> Status:
        stash = state.try_read(_STATE_KEY)
        if stash is None:
            return Status.success()
        entry = stash.allowed.get(node_name)
        if entry is None:
            return Status.unschedulable(
                f"node {node_name} not in a feasible slice placement")
        pool = entry[0]
        topo = next((t for t in self.topo_informer.items()
                     if t.spec.pool == pool), None)
        if topo is None:
            return Status.error(f"TpuTopology for pool {pool} vanished")
        chip_coord = topo.spec.hosts.get(node_name)
        if chip_coord is None:
            return Status.error(f"node {node_name} missing from pool {pool}")
        pod.meta.annotations[POOL_ANNOTATION] = pool
        pod.meta.annotations[COORD_ANNOTATION] = format_coord(chip_coord)
        name = pod_group_label(pod)
        if name:
            full = f"{pod.namespace}/{name}"
            # pin the gang to this pool: siblings' PreFilter needs only this
            # pool's occupancy from now on (a gang lives in ONE torus
            # anyway — at fleet scale this is the difference between
            # sweeping 16 pools per sibling and sweeping 1). Dropped on
            # unreserve/PG delete; a stale pin costs one fall-back sweep.
            self._gang_pool[full] = pool
            # gang landed OUTSIDE its claimed window (another window freed
            # first): release the claim so the evicted capacity reopens now
            # instead of at the drain TTL
            claim, ok = self._window_claims.get(full)
            if ok and node_name not in claim[1]:
                self._window_claims.delete(full)
                klog.V(3).info_s("released freed-window claim: gang landed "
                                 "elsewhere", podGroup=full)
        klog.V(5).info_s("reserved slice coordinate", pod=pod.key,
                         pool=pool, coord=pod.meta.annotations[COORD_ANNOTATION])
        return Status.success()

    def unreserve(self, state: CycleState, pod: Pod, node_name: str) -> None:
        pod.meta.annotations.pop(POOL_ANNOTATION, None)
        pod.meta.annotations.pop(COORD_ANNOTATION, None)
        # drop the pool pin: the gang's placement is in doubt (denied quorum,
        # failed bind) — the next cycle re-derives it from a full sweep
        name = pod_group_label(pod)
        if name:
            self._gang_pool.pop(f"{pod.namespace}/{name}", None)
