"""TopologyMatch plugin: ICI-torus slice-shape fitting for gangs.

TPU-native successor of the reference's NodeResourceTopologyMatch plugin
(/root/reference/pkg/noderesourcetopology): where that plugin simulates the
kubelet TopologyManager's single-NUMA-node admission with 1-D bitmasks
(filter.go:84-150) fed by the NodeResourceTopology CRD, this plugin fits a
PodGroup's requested chip shape (PodGroupSpec.tpu_slice_shape, e.g. "4x4x4")
onto a contiguous free block of a pool's ICI torus published as a TpuTopology
CR — axis permutations allowed, wraparound only on wrapped axes.

Mechanics per scheduling cycle:
- PreFilter: resolve the pod's gang slice request; enumerate feasible
  placements on every matching pool given hosts already occupied and hosts
  already ASSIGNED to gang siblings (the incremental all-or-nothing
  constraint); stash per-node feasibility + scoring info in CycleState.
  Non-slice pods return Skip (the filter is bypassed entirely, like the
  reference skips BestEffort pods, filter.go:194-196).
- Filter: membership test against the stash.
- Score: corner-packing — prefer the node appearing in the FEWEST surviving
  placements (most-constrained-first keeps the torus defragmented for future
  gangs), with the configured strategy over pool utilization as a tiebreak.
- Reserve/Unreserve: write/remove the pool + chip-coordinate annotations the
  on-host runtime (and jaxbridge mesh builder) consumes.
"""
from __future__ import annotations

from typing import Dict, FrozenSet, List, Optional, Tuple

from ...api.core import Pod
from ...api.resources import TPU
from ...api.scheduling import POD_GROUP_LABEL, pod_group_label
from ...api.topology import (ACCELERATORS, TOPOLOGY_GROUP, format_coord,
                             parse_shape)
from ...config.types import TopologyMatchArgs
from ...fwk import CycleState, Status
from ...fwk.interfaces import (ClusterEvent, EnqueueExtensions, EVENT_ADD,
                               EVENT_DELETE, EVENT_UPDATE, FilterPlugin,
                               NodeScore, ReservePlugin, ScorePlugin,
                               PreFilterPlugin, RESOURCE_NODE, RESOURCE_POD,
                               RESOURCE_POD_GROUP, RESOURCE_TPU_TOPOLOGY)
from ...fwk.nodeinfo import MAX_NODE_SCORE, NodeInfo
from ... import native
from ...topology.engine import (MaskGrid, PlacementSet,
                                enumerate_placement_masks,
                                feasible_membership)
from ...topology.torus import HostGrid, validate_slice_shape
from ...util import klog
from ..tpuslice.chip_node import pod_tpu_limits

COORD_ANNOTATION = TOPOLOGY_GROUP + "/coord"
POOL_ANNOTATION = TOPOLOGY_GROUP + "/pool"

_STATE_KEY = "TopologyMatch/state"


class _CycleStash:
    """Per-cycle feasibility: node → (pool, membership count, pool util)."""

    def __init__(self):
        self.allowed: Dict[str, Tuple[str, int, float]] = {}
        self.max_membership = 1

    def clone(self):
        return self  # read-only after PreFilter


class TopologyMatch(PreFilterPlugin, FilterPlugin, ScorePlugin, ReservePlugin,
                    EnqueueExtensions):
    NAME = "TopologyMatch"

    def __init__(self, args: Optional[TopologyMatchArgs], handle):
        self.args = args or TopologyMatchArgs()
        self.handle = handle
        self.pg_informer = handle.informer_factory.podgroups()
        self.topo_informer = handle.informer_factory.tputopologies()
        # caches keyed by CR resource_version (grids) / + block (placements)
        self._grid_cache: Dict[Tuple[str, int], Tuple[HostGrid, MaskGrid]] = {}
        self._placement_cache: Dict[Tuple[str, int, Tuple[int, ...]],
                                    PlacementSet] = {}
        # warm the native engine at construction — its first load may compile
        # the C++ source, which must not stall a scheduling cycle
        native.load()

    @classmethod
    def new(cls, args, handle) -> "TopologyMatch":
        return cls(args, handle)

    def events_to_register(self) -> List[ClusterEvent]:
        return [
            ClusterEvent(RESOURCE_POD, EVENT_ADD | EVENT_DELETE),
            ClusterEvent(RESOURCE_NODE, EVENT_ADD | EVENT_UPDATE),
            ClusterEvent(RESOURCE_TPU_TOPOLOGY, EVENT_ADD | EVENT_UPDATE),
            ClusterEvent(RESOURCE_POD_GROUP, EVENT_ADD | EVENT_UPDATE),
        ]

    # -- gang slice request resolution ---------------------------------------

    def _slice_request(self, pod: Pod):
        """Returns (pg, chip_shape, accelerator_name) or None."""
        name = pod_group_label(pod)
        if not name:
            return None
        pg = self.pg_informer.get(f"{pod.namespace}/{name}")
        if pg is None or not pg.spec.tpu_slice_shape:
            return None
        try:
            shape = parse_shape(pg.spec.tpu_slice_shape)
        except ValueError:
            return "invalid"
        return pg, shape, pg.spec.tpu_accelerator

    # -- PreFilter ------------------------------------------------------------

    def pre_filter(self, state: CycleState, pod: Pod) -> Status:
        req = self._slice_request(pod)
        if req is None:
            return Status.skip()
        if req == "invalid":
            return Status.unresolvable("invalid tpu_slice_shape on PodGroup")
        pg, shape, want_acc = req

        chips_req, chips_set, _, _ = pod_tpu_limits(pod)
        chips_needed = chips_req if chips_set else None
        snapshot = self.handle.snapshot_shared_lister()
        stash = _CycleStash()
        validation_errors: List[str] = []
        any_pool = False

        candidates = []
        any_valid_pool = False
        for topo in self.topo_informer.items():
            spec = topo.spec
            if want_acc and spec.accelerator != want_acc:
                continue
            acc = ACCELERATORS.get(spec.accelerator)
            if acc is None:
                continue
            any_pool = True
            err = validate_slice_shape(shape, acc, tuple(spec.dims))
            if err:
                validation_errors.append(f"pool {spec.pool}: {err}")
                continue
            grids = self._grid(topo)
            if grids is None:
                continue
            any_valid_pool = True
            grid, _ = grids
            occ = self._occupancy(grid, snapshot, pg.meta.name, pod.namespace,
                                  chips_needed if chips_needed is not None
                                  else acc.chips_per_host)
            candidates.append((topo, acc, grids, occ))

        # A gang must live in ONE torus: once any sibling is assigned in a
        # pool, every other pool is off the table (a "slice" spanning two
        # disjoint ICI fabrics would be unusable).
        pinned = [c for c in candidates if c[3][0]]
        if pinned:
            candidates = pinned

        for topo, acc, (grid, mgrid), (assigned, free, eligible,
                                       pool_util) in candidates:
            pset = self._placements(topo, mgrid, shape)
            n_survivors, membership = feasible_membership(
                pset, mgrid.mask_of(assigned), mgrid.mask_of(free),
                mgrid.mask_of(eligible))
            if not n_survivors:
                continue
            for node, count in membership.items():
                prev = stash.allowed.get(node)
                if prev is None or count < prev[1]:
                    stash.allowed[node] = (grid.pool, count, pool_util)
                stash.max_membership = max(stash.max_membership, count)

        if not stash.allowed:
            if not any_pool:
                return Status.unresolvable(
                    f"no TpuTopology pool matches accelerator "
                    f"{want_acc or '(any)'}")
            # only permanent if EVERY matching pool failed validation; a
            # transiently-full valid pool keeps the pod retriable
            if validation_errors and not any_valid_pool:
                return Status.unresolvable("; ".join(validation_errors))
            return Status.unschedulable(
                f"no feasible {pg.spec.tpu_slice_shape} slice placement "
                f"in any pool")
        state.write(_STATE_KEY, stash)
        return Status.success()

    def _grid(self, topo) -> Optional[Tuple[HostGrid, MaskGrid]]:
        key = (topo.key, topo.meta.resource_version)
        grids = self._grid_cache.get(key)
        if grids is None:
            grid = HostGrid.from_spec(topo.spec)
            if grid is None:
                return None
            grids = (grid, MaskGrid(grid))
            if len(self._grid_cache) > 16:
                self._grid_cache.clear()
            self._grid_cache[key] = grids
        return grids

    def _placements(self, topo, mgrid: MaskGrid, chip_shape) -> PlacementSet:
        key = (topo.key, topo.meta.resource_version, tuple(chip_shape))
        got = self._placement_cache.get(key)
        if got is None:
            got = enumerate_placement_masks(mgrid, chip_shape)
            if len(self._placement_cache) > 64:
                self._placement_cache.clear()
            self._placement_cache[key] = got
        return got

    def _occupancy(self, grid: HostGrid, snapshot, pg_name: str,
                   namespace: str, chips_needed: int):
        """Returns (assigned, free, eligible, pool_utilization):

        - assigned: hosts any gang sibling already occupies (assumed/bound);
        - free: hosts a placement may CLAIM — no foreign TPU usage at all
          (a placement owns the host's whole chip block; a single foreign
          chip inside the slice breaks ICI exclusivity);
        - eligible: hosts THIS pod may land on — no foreign usage and enough
          chips left after siblings (covers sub-host pods packing a host);
        - pool_utilization: used/allocatable chips (for the score strategy),
          computed in the same walk."""
        assigned = set()
        free = set()
        eligible = set()
        total_alloc = total_used = 0
        for node, coord in grid.coord_of.items():
            info = snapshot.get(node)
            if info is None:
                continue
            sibling_used = foreign_used = 0
            has_sibling = False
            for p in info.pods:
                c, _, _, _ = pod_tpu_limits(p)
                if (p.meta.labels.get(POD_GROUP_LABEL) == pg_name
                        and p.meta.namespace == namespace):
                    has_sibling = True
                    sibling_used += c
                else:
                    foreign_used += c
            alloc = info.allocatable.get(TPU, 0)
            total_alloc += alloc
            total_used += sibling_used + foreign_used
            if has_sibling:
                assigned.add(coord)
            if foreign_used:
                continue
            if not has_sibling:
                free.add(coord)
            if alloc - sibling_used >= chips_needed:
                eligible.add(coord)
        util = total_used / total_alloc if total_alloc else 1.0
        return frozenset(assigned), frozenset(free), frozenset(eligible), util

    # -- Filter ---------------------------------------------------------------

    def filter(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Status:
        stash = state.try_read(_STATE_KEY)
        if stash is None:
            return Status.success()  # PreFilter skipped (non-slice pod)
        if node_info.node.name not in stash.allowed:
            return Status.unschedulable(
                "node is not part of any feasible slice placement")
        return Status.success()

    # -- Score ----------------------------------------------------------------

    def score(self, state: CycleState, pod: Pod, node_name: str) -> Tuple[int, Status]:
        stash = state.try_read(_STATE_KEY)
        if stash is None:
            return 0, Status.success()
        entry = stash.allowed.get(node_name)
        if entry is None:
            return 0, Status.success()
        _, membership, pool_util = entry
        # corner-packing: fewest surviving placements wins
        constraint = MAX_NODE_SCORE * (stash.max_membership - membership) \
            // max(1, stash.max_membership)
        strategy = self._strategy_score(pool_util)
        w = self.args.packing_weight  # range-checked at config decode
        return int(constraint * w + strategy * (1.0 - w)), Status.success()

    def _strategy_score(self, util: float) -> int:
        """NRT scoring strategies over the pool 'zone'
        (least_allocated.go:25-55, most_allocated.go:25-54,
        balanced_allocation.go:28-55)."""
        s = self.args.scoring_strategy
        if s == "MostAllocated":
            return int(util * MAX_NODE_SCORE)
        if s == "BalancedAllocation":
            return int((1.0 - abs(util - 0.5) * 2) * MAX_NODE_SCORE)
        return int((1.0 - util) * MAX_NODE_SCORE)  # LeastAllocated default

    # -- Reserve --------------------------------------------------------------

    def reserve(self, state: CycleState, pod: Pod, node_name: str) -> Status:
        stash = state.try_read(_STATE_KEY)
        if stash is None:
            return Status.success()
        entry = stash.allowed.get(node_name)
        if entry is None:
            return Status.unschedulable(
                f"node {node_name} not in a feasible slice placement")
        pool = entry[0]
        topo = next((t for t in self.topo_informer.items()
                     if t.spec.pool == pool), None)
        if topo is None:
            return Status.error(f"TpuTopology for pool {pool} vanished")
        chip_coord = topo.spec.hosts.get(node_name)
        if chip_coord is None:
            return Status.error(f"node {node_name} missing from pool {pool}")
        pod.meta.annotations[POOL_ANNOTATION] = pool
        pod.meta.annotations[COORD_ANNOTATION] = format_coord(chip_coord)
        klog.V(5).info_s("reserved slice coordinate", pod=pod.key,
                         pool=pool, coord=pod.meta.annotations[COORD_ANNOTATION])
        return Status.success()

    def unreserve(self, state: CycleState, pod: Pod, node_name: str) -> None:
        pod.meta.annotations.pop(POOL_ANNOTATION, None)
        pod.meta.annotations.pop(COORD_ANNOTATION, None)
