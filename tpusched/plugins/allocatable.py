"""NodeResourcesAllocatable: score by weighted node allocatable.

Rebuild of /root/reference/pkg/noderesources/allocatable.go: score = weighted
sum of node ALLOCATABLE (not free) resources, Least mode negates so smaller
nodes win (:119-138); default weights 1<<20 per cpu millicore ≈ 1 per memory
byte (resource_allocation.go:38); min-max normalized to [0,100] (:141-166).
"""
from __future__ import annotations

from typing import List, Optional, Tuple

from ..api.core import Pod
from ..config.types import NodeResourcesAllocatableArgs
from ..fwk import CycleState, Status
from ..fwk.interfaces import NodeScore, ScorePlugin
from ..fwk.nodeinfo import minmax_normalize


class NodeResourcesAllocatable(ScorePlugin):
    NAME = "NodeResourcesAllocatable"

    def __init__(self, args: Optional[NodeResourcesAllocatableArgs], handle):
        self.args = args or NodeResourcesAllocatableArgs()
        if self.args.mode not in ("Least", "Most"):
            raise ValueError(f"invalid mode {self.args.mode!r}")
        self.handle = handle

    @classmethod
    def new(cls, args, handle) -> "NodeResourcesAllocatable":
        return cls(args, handle)

    def score(self, state: CycleState, pod: Pod, node_name: str) -> Tuple[int, Status]:
        info = self.handle.snapshot_shared_lister().get(node_name)
        if info is None:
            return 0, Status.error(f"node {node_name} not in snapshot")
        total = 0
        for spec in self.args.resources:
            total += info.allocatable.get(spec["name"], 0) * int(spec["weight"])
        if self.args.mode == "Least":
            total = -total
        # raw scores are normalized below; stash per-node raw in state
        # (read_or_init: score runs across nodes in parallel)
        raw = state.read_or_init("NodeResourcesAllocatable/raw", dict)
        raw[node_name] = total
        return 0, Status.success()   # real value applied in normalize

    def normalize_score(self, state: CycleState, pod: Pod,
                        scores: List[NodeScore]) -> Optional[Status]:
        minmax_normalize(state.try_read("NodeResourcesAllocatable/raw") or {},
                         scores)
        return Status.success()
