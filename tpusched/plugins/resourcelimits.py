"""NodeResourceLimits: limit-aware spreading (KEP-217 analog, implemented).

The reference ships KEP-217 as design only — no code exists in its tree
(/root/reference/kep/217-resource-limit-aware-scoring/README.md:1). This
implements the proposal: burstable pods can carry limits far above requests,
so request-based scoring happily over-subscribes a node's LIMITS (the KEP's
production observation: limit/allocatable from 0.1 to 6). Score spreads by
the post-placement limit-to-allocatable ratio — the node whose limits are
least oversubscribed wins.

TPU-native twist: ``tpu-memory`` (fractional HBM serving pods, KEP-1) joins
cpu/memory in the ratio — HBM over-subscription is exactly the burstable
failure mode on an accelerator host, and the chip model already tracks
resident limit sums.

score(node) = MAX_NODE_SCORE · (1 − min(r, CAP)/CAP), where r is the max
over resources of (Σ resident pod limits + this pod's limit)/allocatable and
CAP=2.0 bounds the useful range (a node past 2× oversubscription scores 0 —
beyond that, degree no longer matters).
"""
from __future__ import annotations

from typing import Dict, List, Tuple

from ..api.core import Pod
from ..api.resources import TPU_MEMORY
from ..fwk import CycleState, Status
from ..fwk.interfaces import ScorePlugin
from ..fwk.nodeinfo import MAX_NODE_SCORE, NodeInfo

_RATIO_CAP = 2.0
_RESOURCES = ("cpu", "memory")


def _pod_limits(pod: Pod) -> Dict[str, int]:
    out: Dict[str, int] = {}
    for c in pod.spec.containers:
        for k, v in c.limits.items():
            out[k] = out.get(k, 0) + v
    return out


def _node_limit_sums(info: NodeInfo) -> Dict[str, int]:
    sums: Dict[str, int] = {}
    for p in info.pods:
        for k, v in _pod_limits(p).items():
            sums[k] = sums.get(k, 0) + v
    return sums


class NodeResourceLimits(ScorePlugin):
    NAME = "NodeResourceLimits"

    _LIMITS_KEY = "NodeResourceLimits/pod-limits"

    def __init__(self, handle):
        self.handle = handle
        # bound once: score() is the per-node hot loop (the deferred import
        # exists only to avoid a plugins-package import cycle)
        from .tpuslice.chip_node import ChipNode
        self._chip_node = ChipNode

    @classmethod
    def new(cls, args, handle) -> "NodeResourceLimits":
        return cls(handle)

    def score(self, state: CycleState, pod: Pod,
              node_name: str) -> Tuple[int, Status]:
        info = self.handle.snapshot_shared_lister().get(node_name)
        if info is None:
            return 0, Status.error(f"node {node_name} not in snapshot")
        pod_limits = state.read_or_init(self._LIMITS_KEY,
                                        lambda: _pod_limits(pod))
        # resident limit sums are derived purely from (node, pods): memoized
        # on the NodeInfo generation so repeat scoring cycles stay O(1)
        sums = info.derived("NodeResourceLimits/sums", _node_limit_sums)
        ratio = 0.0
        for res in (*_RESOURCES, TPU_MEMORY):
            limit = pod_limits.get(res, 0) + sums.get(res, 0)
            if limit <= 0:
                continue
            alloc = info.allocatable.get(res, 0)
            if res == TPU_MEMORY:
                # HBM allocatable is published via the chip model, not the
                # node resource list
                cn = self._chip_node.cached(info)
                alloc = cn.hbm_total_mb if cn is not None else 0
            if alloc <= 0:
                continue
            ratio = max(ratio, limit / alloc)
        capped = min(ratio, _RATIO_CAP) / _RATIO_CAP
        return int(MAX_NODE_SCORE * (1.0 - capped)), Status.success()
