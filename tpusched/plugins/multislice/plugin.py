"""MultiSlice plugin: DCN-aware cross-slice scoring.

New TPU-native capability with no reference analog (SURVEY §7.7, BASELINE
eval config #5): a multi-slice job (e.g. Llama-3-70B on 4× v5p-64) is N
PodGroups sharing ``PodGroupSpec.multislice_set``, one gang per slice. Each
slice lands on one ICI torus (TopologyMatch guarantees that); the slices
communicate gradients over DCN. This scorer pulls sibling slices toward the
same DCN proximity domain so the cross-slice all-reduce rides the shortest
data-center paths:

- nodes in a pool whose ``dcn-domain`` equals a domain already hosting a
  sibling slice score ``same_domain_score``;
- nodes whose domain shares the same top-level zone (prefix before "/")
  score ``adjacent_domain_score``;
- everything else scores 0. Non-multislice pods skip.
"""
from __future__ import annotations

from typing import List, Optional, Tuple

from ...api.core import Pod
from ...api.scheduling import (POD_GROUP_INDEX, pod_group_index_key,
                               pod_group_label)
from ...api.topology import LABEL_DCN_DOMAIN
from ...config.types import MultiSliceArgs
from ...fwk import CycleState, Status
from ...fwk.interfaces import NodeScore, PreScorePlugin, ScorePlugin
from ...fwk.nodeinfo import MAX_NODE_SCORE

_STATE_KEY = "MultiSlice/domains"


class _Domains:
    def __init__(self, domains: set):
        self.domains = domains
        self.zones = {d.split("/")[0] for d in domains}

    def clone(self):
        return self


class MultiSlice(PreScorePlugin, ScorePlugin):
    NAME = "MultiSlice"

    def __init__(self, args: Optional[MultiSliceArgs], handle):
        self.args = args or MultiSliceArgs()
        self.handle = handle
        self.pg_informer = handle.informer_factory.podgroups()
        self.pod_informer = handle.informer_factory.pods()
        self.pod_informer.add_index(POD_GROUP_INDEX, pod_group_index_key)

    @classmethod
    def new(cls, args, handle) -> "MultiSlice":
        return cls(args, handle)

    # -- PreScore: collect DCN domains of already-placed sibling slices -------

    def pre_score(self, state: CycleState, pod: Pod, nodes) -> Status:
        name = pod_group_label(pod)
        if not name:
            return Status.skip()
        pg = self.pg_informer.get(f"{pod.namespace}/{name}")
        if pg is None or not pg.spec.multislice_set:
            return Status.skip()
        sibling_pgs = [
            g for g in self.pg_informer.items(namespace=pod.namespace)
            if g.spec.multislice_set == pg.spec.multislice_set
            and g.meta.name != pg.meta.name]
        domains = set()
        snapshot = self.handle.snapshot_shared_lister()
        for g in sibling_pgs:
            for p in self.pod_informer.by_index(
                    POD_GROUP_INDEX, f"{pod.namespace}/{g.meta.name}"):
                if not p.spec.node_name:
                    continue
                info = snapshot.get(p.spec.node_name)
                if info is None:
                    continue
                d = info.node.meta.labels.get(LABEL_DCN_DOMAIN, "")
                if d:
                    domains.add(d)
        if not domains:
            return Status.skip()  # first slice of the set: nothing to pull toward
        state.write(_STATE_KEY, _Domains(domains))
        return Status.success()

    # -- Score ----------------------------------------------------------------

    def score(self, state: CycleState, pod: Pod, node_name: str) -> Tuple[int, Status]:
        doms = state.try_read(_STATE_KEY)
        if doms is None:
            return 0, Status.success()
        info = self.handle.snapshot_shared_lister().get(node_name)
        if info is None:
            return 0, Status.success()
        d = info.node.meta.labels.get(LABEL_DCN_DOMAIN, "")
        if not d:
            return 0, Status.success()
        if d in doms.domains:
            return min(MAX_NODE_SCORE, self.args.same_domain_score), Status.success()
        if d.split("/")[0] in doms.zones:
            return min(MAX_NODE_SCORE, self.args.adjacent_domain_score), Status.success()
        return 0, Status.success()
