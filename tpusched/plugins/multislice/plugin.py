"""MultiSlice plugin: DCN-aware cross-slice scoring and set-level atomic
admission.

New TPU-native capability with no reference analog (SURVEY §7.7, BASELINE
eval config #5): a multi-slice job (e.g. Llama-3-70B on 4× v5p-64) is N
PodGroups sharing ``PodGroupSpec.multislice_set``, one gang per slice. Each
slice lands on one ICI torus (TopologyMatch guarantees that); the slices
communicate gradients over DCN.

Two cooperating capabilities:

**Scoring (always on).** Pull sibling slices toward the same DCN proximity
domain so the cross-slice all-reduce rides the shortest data-center paths:
nodes in a pool whose ``dcn-domain`` equals a domain already hosting a
sibling slice score ``same_domain_score``; nodes whose domain shares the
same top-level zone (prefix before "/") score ``adjacent_domain_score``;
everything else 0. Non-multislice pods skip. Sibling placements are read
from the cycle snapshot, so slices held at the permit barrier (assumed but
not bound) already exert pull.

**Set-level atomic admission (opt-in via
``PodGroupSpec.multislice_set_size > 1``).** The gang barrier one level up:
the Coscheduling quorum machinery
(/root/reference/pkg/coscheduling/coscheduling.go:184-216) guarantees
all-or-nothing *within* a gang, but a 4-slice set admitting slice by slice
can strand 3 bound slices forever when the 4th can never fit — exactly the
resource stranding the pod-level barrier exists to prevent. With a declared
set size:

- *Permit*: every member pod waits until ALL ``multislice_set_size`` member
  gangs have quorum (own-gang in-flight pod counted +1, same snapshot
  convention as core.go:209-215). No slice binds before the whole set is
  placed, so unwinding never has to touch bound pods.
- *PreFilter*: a set-level cluster-capacity dry-run (the per-gang
  CheckClusterResource lifted to the summed set request) fails the whole
  set fast — before any chip is reserved — when the fleet can never hold
  it; a denied-set TTL makes retries cheap.
- *PostFilter*: when one member gang is rejected (Coscheduling has already
  swept its own waiters by the time we run — profile order), the remaining
  member gangs' waiting pods are rejected too, releasing their
  reservations immediately instead of waiting out the set timeout.
- *Unreserve*: any member pod's failure past Reserve tears down the whole
  set's waiters (cascade-guarded by the denied-set cache).

**Hard DCN constraint (``hard_domain_policy`` arg).** ``same-domain`` /
``same-zone`` turn the scoring preference into a Filter-level gate: once
any sibling slice is placed (assumed or bound), nodes outside its DCN
domain/zone are Unschedulable for later slices. The first slice is
unconstrained. When paired with set-level atomic admission, the capacity
dry-run becomes domain-wise: a set that no single DCN domain/zone (plus
unlabeled nodes) can hold is denied in ONE cycle — it does not burn the
set timeout discovering the fleet-wide headroom cannot be used together.
"""
from __future__ import annotations

import threading
from typing import FrozenSet, List, Optional, Set, Tuple

from ...api.core import Pod, node_health_error
from ...api.resources import PODS
from ...api.scheduling import (POD_GROUP_INDEX, PodGroup,
                               pod_group_index_key, pod_group_label)
from ...api.topology import LABEL_DCN_DOMAIN
from ...config.types import MultiSliceArgs
from ...fwk import CycleState, Status
from ...fwk.interfaces import (ClusterEvent, EnqueueExtensions,
                               EquivalenceAware, EVENT_ADD,
                               EVENT_DELETE, EVENT_UPDATE, FilterPlugin,
                               NodeScore, PermitPlugin, PostFilterPlugin,
                               PostFilterResult, PreFilterPlugin,
                               PreScorePlugin, ReservePlugin, ScorePlugin,
                               RESOURCE_NODE, RESOURCE_POD,
                               RESOURCE_POD_GROUP)
from ...fwk.nodeinfo import MAX_NODE_SCORE, NodeInfo
from ...util import klog
from ...util.ttlcache import TTLCache
from ..coscheduling.core import check_cluster_resource

_SCORE_KEY = "MultiSlice/domains"
_FILTER_KEY = "MultiSlice/hard-domains"

HARD_SAME_DOMAIN = "same-domain"
HARD_SAME_ZONE = "same-zone"


class _Domains:
    def __init__(self, domains: Set[str]):
        self.domains = domains
        self.zones = {d.split("/")[0] for d in domains}

    def clone(self):
        return self


def _node_pg_keys(info: NodeInfo) -> FrozenSet[str]:
    """Gang full-names with a pod assigned on this node (derived-pure:
    recomputed only when the node's generation moves)."""
    out = set()
    for p in info.pods:
        name = pod_group_label(p)
        if name and p.spec.node_name:
            out.add(f"{p.meta.namespace}/{name}")
    return frozenset(out)


class MultiSlice(PreFilterPlugin, FilterPlugin, PostFilterPlugin,
                 PreScorePlugin, ScorePlugin, ReservePlugin, PermitPlugin,
                 EnqueueExtensions, EquivalenceAware):
    NAME = "MultiSlice"
    # filter() reads only the PreFilter-stashed sibling-domain set; entries
    # exist only for non-set pods (see equiv_fingerprint), whose stash is
    # absent and whose filter is a constant pass.
    EQUIV_DYNAMIC = False

    def equiv_fingerprint(self, pod, state):
        """Veto for multislice-set members: the set barrier reads sibling
        PG existence, TTL'd denied/permitted-set windows, and cross-gang
        DCN domains — none of which the mutation cursor tracks. Pods
        outside any set never enter this plugin's logic (PreFilter skips),
        so their fingerprint is the empty constant."""
        return None if self._pod_set_pg(pod) is not None else ()

    def events_to_register(self) -> List[ClusterEvent]:
        """Events that can unstick a pod THIS plugin rejected: a sibling
        slice's PodGroup appearing completes an incomplete set; pod churn
        or new capacity can clear a failed set dry-run or hard-domain
        filter."""
        return [
            ClusterEvent(RESOURCE_POD_GROUP, EVENT_ADD | EVENT_UPDATE),
            ClusterEvent(RESOURCE_POD, EVENT_ADD | EVENT_DELETE),
            ClusterEvent(RESOURCE_NODE, EVENT_ADD | EVENT_UPDATE),
        ]

    def __init__(self, args: Optional[MultiSliceArgs], handle):
        self.args = args or MultiSliceArgs()
        self.handle = handle
        self.pg_informer = handle.informer_factory.podgroups()
        self.pod_informer = handle.informer_factory.pods()
        self.pod_informer.add_index(POD_GROUP_INDEX, pod_group_index_key)
        # Denied sets: like the coscheduling denied-PG cache, the window runs
        # from the FIRST denial (TTLCache.add is add-if-absent) so cascading
        # unreserves and event-driven retries cannot extend it.
        self._denied_sets = TTLCache(
            float(self.args.denied_set_expiration_time_seconds))
        # Memoized set-level capacity dry-runs (coscheduling permitted_pg
        # analog): one dry-run per set per permit window, not per cycle.
        self._permitted_sets = TTLCache(
            float(self.args.set_schedule_timeout_seconds))
        # serializes the allow sweep against the deny sweep: without it, a
        # set completing on a scheduling thread can race a member's permit
        # timeout (sweeper thread) and release half the set after the
        # other half was torn down. The residual per-pod window (a pod
        # resolving between our denied-check and its allow) mirrors the
        # upstream coscheduling permit race and heals the same way — the
        # rejected member's freed reservation re-admits it.
        self._set_sweep_lock = threading.Lock()

    @classmethod
    def new(cls, args, handle) -> "MultiSlice":
        return cls(args, handle)

    # -- set lookups ----------------------------------------------------------

    def _pod_set_pg(self, pod: Pod) -> Optional[PodGroup]:
        name = pod_group_label(pod)
        if not name:
            return None
        pg = self.pg_informer.get(f"{pod.namespace}/{name}")
        if pg is None or not pg.spec.multislice_set:
            return None
        return pg

    def _member_pgs(self, namespace: str, set_name: str) -> List[PodGroup]:
        return [g for g in self.pg_informer.items(namespace=namespace)
                if g.spec.multislice_set == set_name]

    @staticmethod
    def _set_key(namespace: str, set_name: str) -> str:
        return f"{namespace}/{set_name}"

    @staticmethod
    def _barrier_enabled(pg: PodGroup) -> bool:
        return bool(pg.spec.multislice_set) and pg.spec.multislice_set_size > 1

    def _sibling_domains(self, namespace: str, set_name: str,
                         own_pg_name: str) -> Set[str]:
        """DCN domains hosting a sibling slice (assumed OR bound — the cycle
        snapshot contains pods the cache has assumed, which is what makes
        the pull/gate work while siblings are parked at the permit
        barrier). O(nodes) per cycle: the per-node gang sweep is
        generation-memoized."""
        member_keys = {f"{namespace}/{g.meta.name}"
                       for g in self._member_pgs(namespace, set_name)
                       if g.meta.name != own_pg_name}
        if not member_keys:
            return set()
        domains: Set[str] = set()
        for info in self.handle.snapshot_shared_lister().list():
            if info.node is None:
                continue
            keys = info.derived("MultiSlice/pg-keys", _node_pg_keys)
            if keys and not member_keys.isdisjoint(keys):
                d = info.node.meta.labels.get(LABEL_DCN_DOMAIN, "")
                if d:
                    domains.add(d)
        return domains

    # -- PreFilter: denied-set gate + set capacity dry-run + hard-mode state --

    def pre_filter(self, state: CycleState, pod: Pod) -> Status:
        pg = self._pod_set_pg(pod)
        if pg is None:
            return Status.skip()
        set_name = pg.spec.multislice_set
        set_key = self._set_key(pod.namespace, set_name)
        if self._barrier_enabled(pg):
            if set_key in self._denied_sets:
                from ... import trace
                trace.record_rejection(
                    self.NAME, "multislice set inside denied window",
                    multislice_set=set_key,
                    denied_remaining_s=round(
                        self._denied_sets.remaining(set_key), 3))
                return Status.unresolvable(
                    f"multislice set {set_key} was denied within the "
                    f"denied-set expiration window").with_retry_after(
                        self._denied_sets.remaining(set_key) + 0.05)
            members = self._member_pgs(pod.namespace, set_name)
            if len(members) < pg.spec.multislice_set_size:
                # the barrier can engage only once every member PG exists;
                # WAITING at Permit here would reserve this slice's chips
                # and hold them for a full set timeout per retry, forever,
                # for a set that may never be fully submitted (or whose
                # sibling was deleted mid-flight). Park reservation-free;
                # a PodGroup add/update event requeues us.
                from ... import trace
                trace.record_rejection(
                    self.NAME, "multislice set incomplete",
                    multislice_set=set_key, members_present=len(members),
                    set_size=pg.spec.multislice_set_size)
                return Status.unresolvable(
                    f"multislice set {set_key} incomplete: "
                    f"{len(members)}/{pg.spec.multislice_set_size} member "
                    f"PodGroups exist")
            status = self._check_set_capacity(pod.namespace, set_name,
                                              set_key, members)
            if status is not None:
                return status
        if self.args.hard_domain_policy not in (HARD_SAME_DOMAIN,
                                                HARD_SAME_ZONE):
            return Status.skip()
        domains = self._sibling_domains(pod.namespace, set_name, pg.meta.name)
        if not domains:
            return Status.skip()   # first slice of the set: unconstrained
        state.write(_FILTER_KEY, _Domains(domains))
        return Status.success()

    def _check_set_capacity(self, namespace: str, set_name: str,
                            set_key: str,
                            members: List[PodGroup]) -> Optional[Status]:
        """Summed-set CheckClusterResource (core.go:322-342 one level up).
        Caller guarantees every member PG exists; runs only when every
        member declares min_resources; memoized for the permit window.
        Returns a failure Status, or None to proceed."""
        if set_key in self._permitted_sets:
            return None
        if not all(g.spec.min_resources for g in members):
            return None
        total: dict = {}
        for g in members:
            for k, v in g.spec.min_resources.items():
                total[k] = total.get(k, 0) + v
            total[PODS] = total.get(PODS, 0) + g.spec.min_member
        nodes = self.handle.snapshot_shared_lister().list()
        member_keys = frozenset(f"{namespace}/{g.meta.name}" for g in members)
        err = self._set_capacity_gap(nodes, total, member_keys)
        if err:
            self._deny_set(set_key, namespace, set_name,
                           f"set capacity dry-run failed: {err}")
            from ... import trace
            trace.record_anomaly("multislice_set_denied",
                                 multislice_set=set_key, gap=err)
            return Status.unresolvable(
                f"multislice set {set_key} cannot fit the fleet: {err}"
            ).with_retry_after(self._denied_sets.remaining(set_key) + 0.05)
        self._permitted_sets.set(set_key)
        return None

    def _set_capacity_gap(self, nodes, total, member_keys) -> Optional[str]:
        """Fleet-wide aggregate dry-run — or, under a hard DCN policy, the
        stricter per-domain form: the whole set must fit inside ONE
        domain (same-domain) / zone (same-zone). Unlabeled nodes count
        with every candidate — the hard Filter never excludes them, since
        only labeled sibling hosts ever constrain a later slice — so a
        set spanning one domain plus unlabeled spill is still admitted.
        Without this, a set larger than every domain passes the fleet-wide
        dry-run and burns a full set timeout discovering the headroom
        cannot be used together."""
        policy = self.args.hard_domain_policy
        if policy not in (HARD_SAME_DOMAIN, HARD_SAME_ZONE):
            return check_cluster_resource(nodes, total, member_keys)

        def group_of(info) -> str:
            d = info.node.meta.labels.get(LABEL_DCN_DOMAIN, "")
            return d if policy == HARD_SAME_DOMAIN else d.split("/")[0]

        labeled: dict = {}
        unlabeled = []
        for info in nodes:
            if info is None or info.node is None:
                continue
            k = group_of(info)
            (labeled.setdefault(k, []) if k else unlabeled).append(info)
        if not labeled:
            return check_cluster_resource(unlabeled, total, member_keys)
        gaps = []
        for k in sorted(labeled):
            err = check_cluster_resource(labeled[k] + unlabeled, total,
                                         member_keys)
            if err is None:
                return None
            gaps.append(f"{k}: {err}")
        kind = "domain" if policy == HARD_SAME_DOMAIN else "zone"
        return f"no single DCN {kind} can hold the set ({'; '.join(gaps)})"

    # -- Filter: hard DCN constraint ------------------------------------------

    def filter(self, state: CycleState, pod: Pod,
               node_info: NodeInfo) -> Status:
        # degraded/NotReady hardware is rejected before any DCN-domain
        # arithmetic: a retrying slice must land on healthy hosts. Cheap by
        # construction — this Filter only runs for multislice-set pods
        # (pre_filter Skips everyone else into skip_filter_plugins), and
        # set members are always equivalence-cache vetoed (equiv_fingerprint
        # returns None), so no armed entry can outlive a readiness flip.
        health = node_health_error(node_info.node)
        if health is not None:
            # unresolvable, matching NodeUnschedulable/TpuSlice: preemption
            # cannot make dead hardware Ready, so PostFilter must not keep
            # this node in its victim dry-run candidate set
            return Status.unresolvable(health)
        doms = state.try_read(_FILTER_KEY)
        if doms is None:
            return Status.success()
        d = node_info.node.meta.labels.get(LABEL_DCN_DOMAIN, "")
        if self.args.hard_domain_policy == HARD_SAME_DOMAIN:
            if d in doms.domains:
                return Status.success()
            return Status.unschedulable(
                "node outside the set's DCN domain (hard same-domain policy)")
        if d.split("/")[0] in doms.zones:
            return Status.success()
        return Status.unschedulable(
            "node outside the set's DCN zone (hard same-zone policy)")

    # -- PostFilter: proactive whole-set teardown -----------------------------

    def post_filter(self, state: CycleState, pod: Pod,
                    filtered_node_status_map
                    ) -> Tuple[Optional[PostFilterResult], Status]:
        """Runs after Coscheduling's PostFilter (profile order), which has
        already swept this pod's OWN gang and denied it unless the quorum
        gap was small. Mirror that judgement one level up: if this member
        gang is genuinely stuck, the sibling slices' reservations are doing
        nothing but stranding chips — release them now rather than when the
        set timeout expires."""
        pg = self._pod_set_pg(pod)
        if pg is None or not self._barrier_enabled(pg):
            return PostFilterResult(), Status.unschedulable()
        assigned = self.handle.snapshot_shared_lister().assigned_count(
            pg.meta.name, pod.namespace)
        if pg.spec.min_member > 0:
            gap = (pg.spec.min_member - assigned) / pg.spec.min_member
            if gap <= 0.1:
                # same ≤10% grace as Coscheduling: the gang is nearly there,
                # let its remaining members try before nuking the whole set
                return PostFilterResult(), Status.unschedulable()
        set_key = self._set_key(pod.namespace, pg.spec.multislice_set)
        self._deny_set(set_key, pod.namespace, pg.spec.multislice_set,
                       f"member gang {pg.meta.name} unschedulable "
                       f"(pod {pod.name})")
        from ... import trace
        trace.record_anomaly("multislice_set_torn_down",
                             multislice_set=set_key,
                             member_gang=pg.meta.name, trigger_pod=pod.key,
                             assigned=assigned,
                             min_member=pg.spec.min_member)
        return PostFilterResult(), Status.unschedulable(
            f"multislice set {set_key} torn down: member gang "
            f"{pg.meta.name} is unschedulable")

    # -- PreScore / Score: DCN proximity preference ---------------------------

    def pre_score(self, state: CycleState, pod: Pod, nodes) -> Status:
        pg = self._pod_set_pg(pod)
        if pg is None:
            return Status.skip()
        # hard mode already swept the snapshot for this cycle in pre_filter;
        # reuse its stash instead of a second O(nodes) walk
        stashed = state.try_read(_FILTER_KEY)
        if stashed is not None:
            state.write(_SCORE_KEY, stashed)
            return Status.success()
        domains = self._sibling_domains(pod.namespace, pg.spec.multislice_set,
                                        pg.meta.name)
        if not domains:
            return Status.skip()  # first slice of the set: nothing to pull toward
        state.write(_SCORE_KEY, _Domains(domains))
        return Status.success()

    def score(self, state: CycleState, pod: Pod,
              node_name: str) -> Tuple[int, Status]:
        doms = state.try_read(_SCORE_KEY)
        if doms is None:
            return 0, Status.success()
        info = self.handle.snapshot_shared_lister().get(node_name)
        if info is None:
            return 0, Status.success()
        d = info.node.meta.labels.get(LABEL_DCN_DOMAIN, "")
        if not d:
            return 0, Status.success()
        if d in doms.domains:
            return min(MAX_NODE_SCORE, self.args.same_domain_score), Status.success()
        if d.split("/")[0] in doms.zones:
            return min(MAX_NODE_SCORE, self.args.adjacent_domain_score), Status.success()
        return 0, Status.success()

    # -- Permit: the set barrier ----------------------------------------------

    def permit(self, state: CycleState, pod: Pod,
               node_name: str) -> Tuple[Status, float]:
        pg = self._pod_set_pg(pod)
        if pg is None or not self._barrier_enabled(pg):
            return Status.success(), 0.0
        if self._set_complete(pod, pg):
            set_key = self._set_key(pod.namespace, pg.spec.multislice_set)
            with self._set_sweep_lock:
                if set_key not in self._denied_sets:
                    self._allow_set_waiters(pod.namespace,
                                            pg.spec.multislice_set)
                    return Status.success(), 0.0
            # completed and denied simultaneously (a member timed out as
            # the last quorum formed). WAITing would hold this pod's
            # reservation for the whole set timeout — the deny sweep ran
            # before the framework parks us, so nothing would reject us
            # sooner. Fail the cycle NOW (reservation released on the
            # permit failure path) and retry when the window lapses.
            return Status.unschedulable(
                f"multislice set {set_key} denied as its last quorum "
                f"formed").with_retry_after(
                    self._denied_sets.remaining(set_key) + 0.05), 0.0
        set_key = self._set_key(pod.namespace, pg.spec.multislice_set)
        with self._set_sweep_lock:
            if set_key in self._denied_sets:
                # the set was denied after this pod's pre_filter (its cycle
                # was in Score/Reserve when the reject sweep ran, so the
                # sweep could not see it). WAITing would strand this pod's
                # reservation for the full set timeout. Fail the cycle now,
                # same as the complete-and-denied branch above. (Cheap early
                # exit; a denial landing after this check is caught by
                # on_pod_waiting below — between them every ordering is
                # covered.)
                return Status.unschedulable(
                    f"multislice set {set_key} denied while this pod's "
                    f"cycle was in flight").with_retry_after(
                        self._denied_sets.remaining(set_key) + 0.05), 0.0
            klog.V(3).info_s("pod waiting for its multislice set",
                             pod=pod.key, set=pg.spec.multislice_set,
                             setSize=pg.spec.multislice_set_size)
            return Status.wait(), float(self.args.set_schedule_timeout_seconds)

    def on_pod_waiting(self, waiting_pod) -> None:
        """Closes the park-after-sweep race: permit() returned Wait, the
        framework registered the pod, and only now do we learn whether a
        denial slipped into that window. The denial flag is written and
        read under _set_sweep_lock, so exactly one of {the deny sweep saw
        the registered pod, this hook sees the denial} holds — either way
        the pod resolves instead of stranding its reservation for the set
        timeout."""
        pg = self._pod_set_pg(waiting_pod.pod)
        if pg is None or not self._barrier_enabled(pg):
            return
        set_key = self._set_key(waiting_pod.pod.namespace,
                                pg.spec.multislice_set)
        with self._set_sweep_lock:
            if set_key not in self._denied_sets:
                return
        waiting_pod.reject(
            self.NAME,
            f"multislice set {set_key} denied while this pod was being "
            f"parked at the barrier")

    def _set_complete(self, pod: Pod, pg: PodGroup) -> bool:
        """Every member gang of the set has quorum. The in-flight pod is not
        in the cycle snapshot, so its own gang counts +1 (the coscheduling
        convention, core.go:209-215); sibling gangs' members are all either
        bound or assumed-at-the-barrier, so the snapshot sees them."""
        members = self._member_pgs(pod.namespace, pg.spec.multislice_set)
        if len(members) < pg.spec.multislice_set_size:
            return False
        snapshot = self.handle.snapshot_shared_lister()
        from ...fwk.nodeinfo import quorum_count_with_inflight
        for g in members:
            if g.meta.name == pg.meta.name:
                # own gang: the in-flight pod counts once, on either
                # snapshot flavor (live index vs frozen +1)
                assigned = quorum_count_with_inflight(
                    snapshot, g.meta.name, pod.namespace)
            else:
                assigned = snapshot.assigned_count(g.meta.name,
                                                   pod.namespace)
            if assigned < g.spec.min_member:
                return False
        return True

    def _allow_set_waiters(self, namespace: str, set_name: str) -> None:
        member_names = {g.meta.name
                        for g in self._member_pgs(namespace, set_name)}

        def allow(waiting_pod):
            wp = waiting_pod.pod
            if (wp.namespace == namespace
                    and pod_group_label(wp) in member_names):
                klog.V(3).info_s("multislice set complete, allowing",
                                 pod=wp.key, set=set_name)
                waiting_pod.allow(self.NAME)
        self.handle.iterate_over_waiting_pods(allow)

    # -- Reserve / Unreserve: whole-set unwind --------------------------------

    def reserve(self, state: CycleState, pod: Pod, node_name: str) -> Status:
        return Status.success()

    def unreserve(self, state: CycleState, pod: Pod, node_name: str) -> None:
        pg = self._pod_set_pg(pod)
        if pg is None or not self._barrier_enabled(pg):
            return
        set_key = self._set_key(pod.namespace, pg.spec.multislice_set)
        if set_key in self._denied_sets:
            return   # cascade guard: a sweep already ran for this denial
        self._deny_set(set_key, pod.namespace, pg.spec.multislice_set,
                       f"member pod {pod.key} unreserved")

    def _deny_set(self, set_key: str, namespace: str, set_name: str,
                  reason: str) -> None:
        """Deny the set (TTL from first denial) and reject every member
        gang's waiting pods. Each rejection resolves that pod's permit
        barrier; the scheduler's resolution callback runs the pod's
        unreserve chain on the bind pool, which re-enters unreserve() above
        and stops at the cascade guard."""
        with self._set_sweep_lock:
            self._denied_sets.add(set_key)
            self._permitted_sets.delete(set_key)
            klog.V(3).info_s("multislice set denied", set=set_key,
                             reason=reason)
            member_names = {g.meta.name
                            for g in self._member_pgs(namespace, set_name)}

            def reject(waiting_pod):
                wp = waiting_pod.pod
                if (wp.namespace == namespace
                        and pod_group_label(wp) in member_names):
                    klog.V(3).info_s("rejecting multislice set member",
                                     pod=wp.key, set=set_key)
                    waiting_pod.reject(
                        self.NAME,
                        f"multislice set {set_key} denied: {reason}")
            self.handle.iterate_over_waiting_pods(reject)
