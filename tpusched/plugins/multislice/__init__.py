from .plugin import MultiSlice

__all__ = ["MultiSlice"]
