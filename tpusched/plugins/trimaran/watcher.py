"""Load-watcher client model — replacement for the vendored
github.com/paypal/load-watcher dependency (SURVEY §2 vendored deps).

The reference consumes cluster load metrics either from a load-watcher HTTP
service or an in-process library client
(/root/reference/pkg/trimaran/targetloadpacking/targetloadpacking.go:82-96).
Same here: ``ServiceClient`` GETs JSON from a local endpoint, ``LibraryClient``
wraps a provider callable. ``Collector`` caches metrics behind a lock and
refreshes every 30 s (collector.go:45-99).

TPU-native extension: metric type "TPU" (tensorcore duty-cycle %) rides the
same pipeline so load-aware scoring can see accelerator pressure, not just
host CPU.
"""
from __future__ import annotations

import json
import threading
import time
import urllib.request
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from ...util import klog

CPU_TYPE = "CPU"
MEMORY_TYPE = "Memory"
TPU_TYPE = "TPU"

AVERAGE = "Average"
STD = "Std"
LATEST = "Latest"

METRICS_AGENT_REPORTING_INTERVAL_S = 60   # handler.go:37


@dataclass
class Metric:
    name: str = ""
    type: str = CPU_TYPE
    operator: str = AVERAGE
    rollup: str = ""
    value: float = 0.0   # percent of capacity


@dataclass
class NodeMetrics:
    metrics: List[Metric] = field(default_factory=list)


@dataclass
class Window:
    duration: str = "15m"
    start: float = 0.0
    end: float = 0.0


@dataclass
class WatcherMetrics:
    timestamp: float = 0.0
    window: Window = field(default_factory=Window)
    data: Dict[str, NodeMetrics] = field(default_factory=dict)

    @staticmethod
    def from_json(doc: dict) -> "WatcherMetrics":
        window = doc.get("window", {})
        data = {}
        for node, nm in (doc.get("data", {}).get("NodeMetricsMap", {})).items():
            data[node] = NodeMetrics(metrics=[
                Metric(name=m.get("name", ""), type=m.get("type", CPU_TYPE),
                       operator=m.get("operator", ""), value=float(m.get("value", 0)))
                for m in nm.get("metrics", [])])
        return WatcherMetrics(
            timestamp=float(doc.get("timestamp", 0)),
            window=Window(duration=window.get("duration", ""),
                          start=float(window.get("start", 0)),
                          end=float(window.get("end", 0))),
            data=data)


class LibraryClient:
    """In-process metrics provider (the reference's library-mode watcher)."""

    def __init__(self, provider: Callable[[], Optional[WatcherMetrics]]):
        self._provider = provider

    def get_latest_watcher_metrics(self) -> Optional[WatcherMetrics]:
        return self._provider()


class ServiceClient:
    """HTTP watcher client (GET <address>/watcher, JSON)."""

    def __init__(self, address: str):
        self.address = address.rstrip("/")

    def get_latest_watcher_metrics(self) -> Optional[WatcherMetrics]:
        try:
            with urllib.request.urlopen(self.address + "/watcher", timeout=5) as r:
                return WatcherMetrics.from_json(json.loads(r.read()))
        except Exception as e:
            klog.error_s(e, "load-watcher fetch failed", address=self.address)
            return None


class Collector:
    """Cached metrics + refresh loop (collector.go:45-99). Each plugin owns
    its own Collector — deliberately not shared (collector.go:38-44)."""

    def __init__(self, client, refresh_interval_s: float = 30.0,
                 auto_refresh: bool = True):
        self._client = client
        self._interval = refresh_interval_s
        self._lock = threading.RLock()
        self._metrics: Optional[WatcherMetrics] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.update_metrics()
        if auto_refresh:
            self._thread = threading.Thread(target=self._loop, daemon=True,
                                            name="trimaran-collector")
            self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self._interval):
            if self._stop.is_set():
                return
            self.update_metrics()

    def stop(self) -> None:
        """Signal and JOIN the refresh thread: an in-flight fetch logging
        after the caller tears down (pytest closing capture streams) shows
        up as spurious '--- Logging error ---' noise that masks real
        failures."""
        self._stop.set()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=6.0)  # fetch timeout is 5s; outlast it
            self._thread = None

    def update_metrics(self) -> None:
        m = self._client.get_latest_watcher_metrics()
        if m is not None:
            with self._lock:
                self._metrics = m

    def get_all_metrics(self) -> Optional[WatcherMetrics]:
        with self._lock:
            return self._metrics

    def get_node_metrics(self, node_name: str) -> Optional[List[Metric]]:
        with self._lock:
            if self._metrics is None:
                return None
            nm = self._metrics.data.get(node_name)
            return nm.metrics if nm else None


def make_collector(args, provider=None) -> Collector:
    """Shared client-selection + Collector construction for the trimaran
    plugins: explicit provider > watcher_address HTTP service > dead client."""
    if provider is not None:
        client = LibraryClient(provider)
    elif getattr(args, "watcher_address", ""):
        client = ServiceClient(args.watcher_address)
    else:
        client = LibraryClient(lambda: None)
    return Collector(client,
                     refresh_interval_s=args.metrics_refresh_interval_seconds)


def get_resource_data(metrics: List[Metric], resource_type: str):
    """(avg, stddev, found) — backward-compatible operator handling
    (analysis.go getResourceData)."""
    avg = std = 0.0
    found = avg_found = False
    for m in metrics:
        if m.type != resource_type:
            continue
        if m.operator == AVERAGE:
            avg = m.value
            avg_found = True
        elif m.operator == STD:
            std = m.value
        elif m.operator in ("", LATEST) and not avg_found:
            avg = m.value
        found = True
    return avg, std, found
