"""TargetLoadPacking: best-fit bin-packing around a target CPU utilization.

Rebuild of /root/reference/pkg/trimaran/targetloadpacking/targetloadpacking.go:
Score = predicted node CPU% after placing this pod (measured average +
this pod's predicted use + recently-bound-but-unmeasured pods from the
assign handler), mapped to a score that rises linearly from `target` at 0%
to 100 at the target utilization, then falls linearly to 0 at 100%
(:253-269). Missing metrics ⇒ MinScore (:192-203). Pod prediction: limits,
else requests × multiplier (1.5), else a 1-core default (:286-294).
"""
from __future__ import annotations

from typing import Optional, Tuple

from ...api.core import Container, Pod
from ...api.resources import CPU
from ...config.types import TargetLoadPackingArgs
from ...fwk import CycleState, Status
from ...fwk.nodeinfo import MIN_NODE_SCORE
from ...fwk.interfaces import ScorePlugin
from ...util import klog
from .handler import PodAssignEventHandler
from .watcher import (AVERAGE, CPU_TYPE, LATEST,
                      METRICS_AGENT_REPORTING_INTERVAL_S, make_collector)


class TargetLoadPacking(ScorePlugin):
    NAME = "TargetLoadPacking"

    def __init__(self, args: Optional[TargetLoadPackingArgs], handle,
                 provider=None):
        self.args = args or TargetLoadPackingArgs()
        self.handle = handle
        self.collector = make_collector(self.args, provider)
        self.event_handler = PodAssignEventHandler(handle.informer_factory,
                                                   clock=handle.clock)

    @classmethod
    def new(cls, args, handle) -> "TargetLoadPacking":
        return cls(args, handle)

    def close(self) -> None:
        self.collector.stop()
        self.event_handler.stop()

    # -- prediction (targetloadpacking.go:286-294) ----------------------------

    def predict_utilisation(self, container: Container) -> float:
        if CPU in container.limits:
            return float(container.limits[CPU])
        if CPU in container.requests:
            return round(container.requests[CPU] * self.args.default_requests_multiplier)
        return float(self.args.default_requests_cpu_millis)

    def _pod_predicted_millis(self, pod: Pod) -> float:
        total = sum(self.predict_utilisation(c) for c in pod.spec.containers)
        total += pod.spec.overhead.get(CPU, 0)
        return total

    # -- Score ----------------------------------------------------------------

    def score(self, state: CycleState, pod: Pod, node_name: str) -> Tuple[int, Status]:
        node_info = self.handle.snapshot_shared_lister().get(node_name)
        if node_info is None:
            return MIN_NODE_SCORE, Status.error(f"node {node_name} not in snapshot")
        metrics = self.collector.get_all_metrics()
        if metrics is None or not metrics.data:
            klog.V(5).info_s("metrics not available, min score", node=node_name)
            return MIN_NODE_SCORE, Status.success()
        node_metrics = metrics.data.get(node_name)
        if node_metrics is None:
            return MIN_NODE_SCORE, Status.success()

        cpu_util_percent = None
        for m in node_metrics.metrics:
            if m.type == CPU_TYPE and m.operator in (AVERAGE, LATEST):
                cpu_util_percent = m.value
        if cpu_util_percent is None:
            klog.error_s(None, "cpu metric not found", node=node_name)
            return MIN_NODE_SCORE, Status.success()

        cap_millis = float(node_info.node.status.capacity.get(CPU, 0))
        if cap_millis == 0:
            return MIN_NODE_SCORE, Status.success()
        util_millis = cpu_util_percent / 100.0 * cap_millis

        # recently-assigned pods whose load the watcher can't see yet
        # (:234-251)
        missing_millis = 0.0
        for ts, p in self.event_handler.recent_pods(node_name):
            if ts > metrics.window.end or \
                    (metrics.window.end - ts) < METRICS_AGENT_REPORTING_INTERVAL_S:
                missing_millis += self._pod_predicted_millis(p)

        predicted = 100.0 * (util_millis + self._pod_predicted_millis(pod)
                             + missing_millis) / cap_millis
        target = float(self.args.target_utilization)
        if predicted > target:
            if predicted > 100.0:
                return MIN_NODE_SCORE, Status.success()
            return int(round(target * (100.0 - predicted) / (100.0 - target))), \
                Status.success()
        return int(round((100.0 - target) * predicted / target + target)), \
            Status.success()
