"""PodAssignEventHandler: recently-bound pods not yet visible in metrics.

Rebuild of /root/reference/pkg/trimaran/handler.go: a node→[(timestamp, pod)]
cache fed by pod informer Add/Update (:43-111), background cleanup every
5 minutes dropping entries older than the metrics reporting window
(:33-38,114-138). Bridges real metrics and just-scheduled pods.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, List, Tuple

from ...api.core import Pod
from ...util.podutil import assigned
from .watcher import METRICS_AGENT_REPORTING_INTERVAL_S

CLEANUP_INTERVAL_S = 300.0


class PodAssignEventHandler:
    def __init__(self, informer_factory, clock=time.time,
                 auto_cleanup: bool = True):
        self.clock = clock
        self.lock = threading.RLock()
        # node name → [(assign timestamp, pod)]
        self.scheduled_pods_cache: Dict[str, List[Tuple[float, Pod]]] = {}
        self._informer = informer_factory.pods()
        self._registration = self._informer.add_event_handler(
            on_add=self._on_add, on_update=self._on_update,
            on_delete=self._on_delete)
        self._stop = threading.Event()
        if auto_cleanup:
            t = threading.Thread(target=self._cleanup_loop, daemon=True,
                                 name="trimaran-handler-gc")
            t.start()

    def _on_add(self, pod: Pod) -> None:
        if assigned(pod):
            self._record(pod)

    def _on_update(self, old: Pod, new: Pod) -> None:
        if not assigned(old) and assigned(new):
            self._record(new)

    def _on_delete(self, pod: Pod) -> None:
        if not assigned(pod):
            return
        with self.lock:
            entries = self.scheduled_pods_cache.get(pod.spec.node_name)
            if entries:
                self.scheduled_pods_cache[pod.spec.node_name] = [
                    (t, p) for (t, p) in entries if p.key != pod.key]

    def _record(self, pod: Pod) -> None:
        with self.lock:
            self.scheduled_pods_cache.setdefault(pod.spec.node_name, []).append(
                (self.clock(), pod))

    def recent_pods(self, node_name: str) -> List[Tuple[float, Pod]]:
        with self.lock:
            return list(self.scheduled_pods_cache.get(node_name, ()))

    def _cleanup_loop(self) -> None:
        while not self._stop.wait(CLEANUP_INTERVAL_S):
            self.cleanup()

    def cleanup(self) -> None:
        horizon = self.clock() - METRICS_AGENT_REPORTING_INTERVAL_S
        with self.lock:
            for node in list(self.scheduled_pods_cache):
                kept = [(t, p) for (t, p) in self.scheduled_pods_cache[node]
                        if t > horizon]
                if kept:
                    self.scheduled_pods_cache[node] = kept
                else:
                    del self.scheduled_pods_cache[node]

    def stop(self) -> None:
        self._stop.set()
        self._informer.remove_event_handler(self._registration)
