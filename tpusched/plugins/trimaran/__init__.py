from .watcher import (Metric, NodeMetrics, WatcherMetrics, Window,
                      LibraryClient, ServiceClient, Collector,
                      CPU_TYPE, MEMORY_TYPE, TPU_TYPE, AVERAGE, STD, LATEST)
from .handler import PodAssignEventHandler
from .targetloadpacking import TargetLoadPacking
from .loadvariationriskbalancing import LoadVariationRiskBalancing

__all__ = ["Metric", "NodeMetrics", "WatcherMetrics", "Window",
           "LibraryClient", "ServiceClient", "Collector",
           "PodAssignEventHandler", "TargetLoadPacking",
           "LoadVariationRiskBalancing",
           "CPU_TYPE", "MEMORY_TYPE", "TPU_TYPE", "AVERAGE", "STD", "LATEST"]
