"""LoadVariationRiskBalancing: score by mean+stddev load risk.

Rebuild of /root/reference/pkg/trimaran/loadvariationriskbalancing:
risk = (mu + margin·sigma^(1/sensitivity)) / 2 where mu = (avg+req)/capacity
and sigma = stddev/capacity, score = (1 − risk)·100 (analysis.go:48-78);
CPU and memory combined via min when both metrics are valid, else max
(loadvariationriskbalancing.go:104-129). Owns its own Collector — the
reference deliberately does not share it with TargetLoadPacking
(collector.go:38-44).

TPU-native extension: when a TPU duty-cycle metric is present, its score
joins the min() — a TPU host hot on tensorcore gets deprioritized even when
its CPU looks idle.
"""
from __future__ import annotations

import math
from typing import List, Optional, Tuple

from ...api.core import Pod
from ...api.resources import CPU, MEMORY, TPU
from ...config.types import LoadVariationRiskBalancingArgs
from ...fwk import CycleState, Status
from ...fwk.nodeinfo import MAX_NODE_SCORE, MIN_NODE_SCORE
from ...fwk.interfaces import ScorePlugin
from ...util import klog
from ...util.podutil import pod_effective_request
from .watcher import (CPU_TYPE, MEMORY_TYPE, Metric, TPU_TYPE,
                      get_resource_data, make_collector)


class ResourceStats:
    """analysis.go resourceStats."""

    __slots__ = ("used_avg", "used_stdev", "req", "capacity")

    def __init__(self, used_avg: float, used_stdev: float, req: float,
                 capacity: float):
        self.used_avg = used_avg
        self.used_stdev = used_stdev
        self.req = req
        self.capacity = capacity

    def compute_score(self, margin: float, sensitivity: float) -> float:
        if self.capacity <= 0:
            klog.error_s(None, "invalid resource capacity", capacity=self.capacity)
            return 0.0
        req = max(self.req, 0.0)
        used_avg = max(min(self.used_avg, self.capacity), 0.0)
        used_stdev = max(min(self.used_stdev, self.capacity), 0.0)
        mu = max(min((used_avg + req) / self.capacity, 1.0), 0.0)
        sigma = max(min(used_stdev / self.capacity, 1.0), 0.0)
        if sensitivity > 0:
            sigma = math.pow(sigma, 1.0 / sensitivity)
        elif sensitivity == 0:
            # Go semantics: pow(sigma, +Inf) → 0 for sigma<1, 1 at sigma=1
            sigma = 0.0 if sigma < 1.0 else 1.0
        sigma = max(min(sigma * margin, 1.0), 0.0)
        risk = (mu + sigma) / 2.0
        return (1.0 - risk) * MAX_NODE_SCORE


def create_resource_stats(metrics: List[Metric], node, pod_req,
                          resource_name: str, watcher_type: str
                          ) -> Tuple[Optional[ResourceStats], bool]:
    avg, std, found = get_resource_data(metrics, watcher_type)
    if not found:
        return None, False
    capacity = float(node.status.allocatable.get(resource_name, 0))
    req = float(pod_req.get(resource_name, 0))
    if resource_name == MEMORY:
        mega = 1.0 / (1024.0 * 1024.0)
        capacity *= mega
        req *= mega
    rs = ResourceStats(used_avg=avg * capacity / 100.0,
                       used_stdev=std * capacity / 100.0,
                       req=req, capacity=capacity)
    return rs, True


class LoadVariationRiskBalancing(ScorePlugin):
    NAME = "LoadVariationRiskBalancing"

    def __init__(self, args: Optional[LoadVariationRiskBalancingArgs], handle,
                 provider=None):
        self.args = args or LoadVariationRiskBalancingArgs()
        self.handle = handle
        self.collector = make_collector(self.args, provider)

    @classmethod
    def new(cls, args, handle) -> "LoadVariationRiskBalancing":
        return cls(args, handle)

    def close(self) -> None:
        self.collector.stop()

    def score(self, state: CycleState, pod: Pod, node_name: str) -> Tuple[int, Status]:
        node_info = self.handle.snapshot_shared_lister().get(node_name)
        if node_info is None:
            return MIN_NODE_SCORE, Status.error(f"node {node_name} not in snapshot")
        metrics = self.collector.get_node_metrics(node_name)
        if metrics is None:
            klog.V(5).info_s("no metrics for node; min score", node=node_name)
            return MIN_NODE_SCORE, Status.success()
        pod_req = pod_effective_request(pod)
        node = node_info.node
        margin = self.args.safe_variance_margin
        sens = self.args.safe_variance_sensitivity

        scores = {}
        cpu_stats, cpu_ok = create_resource_stats(metrics, node, pod_req, CPU, CPU_TYPE)
        if cpu_ok:
            scores["cpu"] = cpu_stats.compute_score(margin, sens)
        mem_stats, mem_ok = create_resource_stats(metrics, node, pod_req, MEMORY, MEMORY_TYPE)
        if mem_ok:
            scores["memory"] = mem_stats.compute_score(margin, sens)
        tpu_stats, tpu_ok = create_resource_stats(metrics, node, pod_req, TPU, TPU_TYPE)
        if tpu_ok:
            scores["tpu"] = tpu_stats.compute_score(margin, sens)

        if not scores:
            return MIN_NODE_SCORE, Status.success()
        # two or more valid dimensions combine via min (the cautious bound —
        # a node hot on ANY measured dimension is deprioritized); a single
        # valid dimension stands alone
        if len(scores) >= 2:
            total = min(scores.values())
        else:
            total = next(iter(scores.values()))
        return int(round(total)), Status.success()
