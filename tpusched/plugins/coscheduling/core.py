"""PodGroupManager — the gang-scheduling state machine.

Rebuild of /root/reference/pkg/coscheduling/core/core.go: denied/permitted
PodGroup TTL caches (:79-81,103-104), PreFilter with sibling-count and
cluster-capacity dry-run (:149-196, CheckClusterResource :322-342), Permit
quorum check over the snapshot (:199-216 — assigned+1 because the in-flight
pod is not in the cycle snapshot), sibling activation through
PodsToActivate (:111-143), PostBind status patching (:220-252).

Deliberate fixes over the reference (SURVEY §2 quirks):
- ``check_cluster_resource`` does not mutate its input request map
  (core.go:329-336 mutates the caller's map);
- PostBind patches atomically through the API server and always persists the
  Scheduled count (the reference's read-modify-write only patches when the
  *phase* changes, core.go:237-251, silently dropping count increments and
  racing concurrent binds).
"""
from __future__ import annotations

import threading
from typing import List, Optional, Tuple

from ...api.core import Pod
from ...api.resources import PODS, ResourceList
from ...api.scheduling import (MIN_AVAILABLE_LABEL, PG_SCHEDULED,
                               PG_SCHEDULING, POD_GROUP_INDEX, PodGroup,
                               pod_group_full_name, pod_group_index_key,
                               pod_group_label)
from ...apiserver import server as srv
from ...fwk import CycleState
from ...fwk.nodeinfo import NodeInfo
from ...fwk.runtime import PODS_TO_ACTIVATE_KEY
from ...util import klog
from ...util.metrics import pod_group_to_bound_seconds
from ...util.podutil import pod_effective_request
from ...util.ttlcache import TTLCache

# Manager Permit verdicts (core.go Status values).
POD_GROUP_NOT_SPECIFIED = "PodGroupNotSpecified"
POD_GROUP_NOT_FOUND = "PodGroupNotFound"
WAIT = "Wait"
SUCCESS = "Success"

DEFAULT_WAIT_TIME_S = 60.0


def get_wait_time_duration(pg: Optional[PodGroup], default_timeout_s: float) -> float:
    """Wait-time precedence: PG.spec > plugin arg > 60s default
    (/root/reference/pkg/util/podgroup.go:53-76)."""
    if pg is not None and pg.spec.schedule_timeout_seconds:
        return float(pg.spec.schedule_timeout_seconds)
    if default_timeout_s > 0:
        return default_timeout_s
    return DEFAULT_WAIT_TIME_S


class PodGroupManager:
    def __init__(self, handle, schedule_timeout_s: float,
                 denied_pg_expiration_s: float,
                 pg_status_flush_s: float = 0.0):
        from ...util.clock import WALL
        self.handle = handle
        self.schedule_timeout_s = schedule_timeout_s
        self.pg_informer = handle.informer_factory.podgroups()
        self.pod_informer = handle.informer_factory.pods()
        self.pod_informer.add_index(POD_GROUP_INDEX, pod_group_index_key)
        # gate clocks route through the scheduler's injected handle clock
        # (util/clock): the denial window is THE retry gate a Gavel-style
        # policy replay must reproduce — arming its expiry lets a
        # virtual-time replay fire the lapse at its recorded-timeline
        # instant instead of zeroing the window (sim/replay.py)
        clk = getattr(handle, "clock_handle", None) or WALL
        self._clock_handle = clk
        self._now = clk.now
        self.last_denied_pg = TTLCache(
            denied_pg_expiration_s, clock=self._now,
            arm=lambda exp: clk.arm("denied-window", exp))
        self.permitted_pg = TTLCache(schedule_timeout_s, clock=self._now)
        # PG status patch coalescing (ISSUE 14 satellite): gang full-name
        # → increments not yet patched.  Partial-progress increments within
        # the flush window fold into one patch per gang (a gang's bind
        # burst is N members on N bind-pool threads — per-member patches
        # were per-bind API fan-out on the hot path); quorum completion
        # flushes inline so PG_SCHEDULED and the north-star observation
        # keep their exact clock.  0 disables (patch per bind).
        self._status_flush_s = max(0.0, pg_status_flush_s)
        self._status_lock = threading.Lock()
        self._status_pending: dict = {}
        self._status_last_flush = self._now()
        # gang → cumulative increments noted since the gang was first
        # seen (NOT since the last flush): quorum-completion detection
        # must not depend on the informer's view of status.scheduled,
        # which lags its own patches over a real API transport.  TTL'd
        # like the synthesized-PG cache; pruned at quorum flush.
        self._status_seen = TTLCache(max(3600.0, 60 * schedule_timeout_s),
                                     clock=self._now)
        # KEP-2 lightweight gangs: one synthesized PodGroup instance per
        # "ns/name", created on first sight. Sharing the instance gives every
        # member the same QueueSort timestamp (gangs drain contiguously),
        # keeps the hot queue comparator allocation-free, and lets post_bind
        # track status/metrics for groups that have no CR to patch. TTL'd so
        # abandoned CRD-less gang names don't accumulate forever.
        self._synthesized_pgs = TTLCache(max(3600.0, 60 * schedule_timeout_s),
                                         clock=self._now)
        self._synthesized_status_lock = threading.Lock()

    # -- lookups --------------------------------------------------------------

    def get_pod_group(self, pod: Pod) -> Tuple[str, Optional[PodGroup]]:
        name = pod_group_label(pod)
        if not name:
            return "", None
        full = f"{pod.namespace}/{name}"
        pg = self.pg_informer.get(full)
        if pg is None:
            pg = self._synthesize_pod_group(pod, name)
        return full, pg

    def _synthesize_pod_group(self, pod: Pod, name: str) -> Optional[PodGroup]:
        """Lightweight (CRD-less) gang admission, KEP-2: a pod labeled with a
        group name plus MIN_AVAILABLE_LABEL gets an in-memory PodGroup with
        that quorum. Without the min-available label this returns None and
        the pod is held at Permit (reference parity: PodGroupNotFound ⇒
        Unschedulable, coscheduling.go:191-192)."""
        raw = pod.meta.labels.get(MIN_AVAILABLE_LABEL, "")
        try:
            min_available = int(raw)
        except ValueError:
            return None
        if min_available <= 0:
            return None
        full = f"{pod.namespace}/{name}"
        cached, ok = self._synthesized_pgs.get(full)
        if ok:
            return cached
        from ...api.meta import ObjectMeta
        pg = PodGroup(meta=ObjectMeta(name=name, namespace=pod.namespace,
                                      creation_timestamp=pod.meta.creation_timestamp))
        pg.spec.min_member = min_available
        self._synthesized_pgs.set(full, pg)
        return pg

    def siblings(self, pod: Pod) -> List[Pod]:
        name = pod_group_label(pod)
        if not name:
            return []
        return self.pod_informer.by_index(POD_GROUP_INDEX,
                                          f"{pod.namespace}/{name}")

    def get_creation_timestamp(self, pod: Pod, default_ts: float) -> float:
        _, pg = self.get_pod_group(pod)
        return pg.meta.creation_timestamp if pg else default_ts

    # -- extension-point logic ------------------------------------------------

    def pre_filter(self, pod: Pod) -> Optional[str]:
        """Returns an error string (⇒ UnschedulableAndUnresolvable) or None.
        Each failure site also records its structured WHY (gang identity +
        the arithmetic behind the message) on the active cycle trace."""
        from ... import trace
        # residue drain for the status batcher: a retrying sibling's cycle
        # is a natural, event-driven flush point (no timer thread; cheap
        # no-op while nothing is pending)
        self.flush_status_if_due()
        full, pg = self.get_pod_group(pod)
        if pg is None:
            return None
        if full in self.last_denied_pg:
            trace.record_rejection(
                "Coscheduling", "gang inside denied-PodGroup window",
                pod_group=full,
                denied_remaining_s=round(
                    self.last_denied_pg.remaining(full), 3))
            return (f"pod with pgName {full} last failed within "
                    f"the denied-PodGroup expiration window, deny")
        pods = self.siblings(pod)
        if len(pods) < pg.spec.min_member:
            trace.record_rejection(
                "Coscheduling", "not enough sibling pods exist",
                pod_group=full, siblings=len(pods),
                min_member=pg.spec.min_member)
            return (f"pre-filter pod {pod.name} cannot find enough sibling pods, "
                    f"current pods number: {len(pods)}, minMember of group: "
                    f"{pg.spec.min_member}")
        if not pg.spec.min_resources:
            return None
        # cluster-capacity dry-run, memoized while the group is "permitted"
        if full in self.permitted_pg:
            return None
        min_resources = dict(pg.spec.min_resources)
        min_resources[PODS] = pg.spec.min_member
        nodes = self.handle.snapshot_shared_lister().list()
        err = check_cluster_resource(nodes, min_resources, full)
        if err:
            # partition-scoped cycles (a dispatch shard's pool-restricted
            # view) must NOT promote their shortfall into the process-
            # global denied window: "this shard's pools are too small" is
            # not "the fleet is too small", and the escalated retry on
            # the global lane would otherwise bounce off its own shard's
            # verdict for the whole denial TTL
            if self.handle.dispatch_scope() != "partition":
                self.add_denied_pod_group(full)
            trace.record_rejection(
                "Coscheduling", "cluster-capacity dry-run failed",
                pod_group=full, gap=err,
                min_member=pg.spec.min_member)
            return err
        self.permitted_pg.set(full, ttl=self.schedule_timeout_s)
        return None

    def permit(self, pod: Pod) -> str:
        full, pg = self.get_pod_group(pod)
        if not full:
            return POD_GROUP_NOT_SPECIFIED
        if pg is None:
            return POD_GROUP_NOT_FOUND
        # in-flight accounting is snapshot-flavor-aware: frozen snapshots
        # need the upstream +1 (core.go:209-215), the cache's live-indexed
        # persistent snapshots already count this cycle's own assume
        if self.quorum_with_inflight(pg.meta.name, pg.meta.namespace) \
                >= pg.spec.min_member:
            return SUCCESS
        return WAIT

    def activate_siblings(self, pod: Pod, state: CycleState) -> None:
        """Stash the gang's other pods under PodsToActivate so the scheduler
        force-moves them to activeQ at cycle end (core.go:111-143)."""
        name = pod_group_label(pod)
        if not name:
            return
        # Assigned siblings (assumed or bound) have nothing left to schedule —
        # re-activating them is wasted queue work that grows O(n²) over a
        # gang's bind burst (upstream stashes all siblings; the queue's
        # absent-key probe makes the difference invisible there, costly here).
        pods = [p for p in self.siblings(pod)
                if p.meta.uid != pod.meta.uid and not p.spec.node_name]
        if not pods:
            return
        stash = state.try_read(PODS_TO_ACTIVATE_KEY)
        if stash is None:
            return
        with stash.lock:
            for p in pods:
                stash.map[p.key] = p

    def calculate_assigned_pods(self, pg_name: str, namespace: str) -> int:
        """Members with a node assigned (assumed or bound), from the snapshot
        (core.go:301-318; O(1) via the snapshot's lazy gang index)."""
        return self.handle.snapshot_shared_lister().assigned_count(pg_name, namespace)

    def quorum_with_inflight(self, pg_name: str, namespace: str) -> int:
        """Assigned members counting the in-flight pod exactly once, on
        either snapshot flavor (fwk.nodeinfo.quorum_count_with_inflight)."""
        from ...fwk.nodeinfo import quorum_count_with_inflight
        return quorum_count_with_inflight(
            self.handle.snapshot_shared_lister(), pg_name, namespace)

    def post_bind(self, pod: Pod, node_name: str) -> None:
        full, pg = self.get_pod_group(pod)
        if not full or pg is None:
            return
        if self._status_flush_s <= 0:
            self._patch_status(full, pg, pod, 1)
            return
        mono = self._now()
        with self._status_lock:
            pending = self._status_pending.get(full)
            if pending is None:
                pending = self._status_pending[full] = [0, pod]
                # first increment of a fresh batch: arm the flush horizon
                # so a virtual-time replay drains the window on schedule
                # (the residue drains via pre_filter / on_clock_tick)
                self._clock_handle.arm(
                    "pg-status-flush",
                    self._status_last_flush + self._status_flush_s)
            pending[0] += 1
            pending[1] = pod              # a live member for the sweep
            # quorum completion always flushes INLINE: PG_SCHEDULED (and
            # the north-star PodGroup-to-Bound observation inside the
            # patch) must land at the real completion instant, not a
            # window later.  Completion is judged from the batcher's OWN
            # cumulative count — the informer's status.scheduled lags its
            # own patches over a real API transport, and judging from it
            # can strand the final increments in the batch forever.
            seen, _ = self._status_seen.get(full)
            seen = (seen or 0) + 1
            self._status_seen.set(full, seen)
            complete = seen >= pg.spec.min_member
            if complete:
                self._status_seen.delete(full)
            window_due = mono - self._status_last_flush >= \
                self._status_flush_s
            if not complete and not window_due:
                return
            due = [(full, pending[0], pending[1])] if not window_due else \
                [(f, p[0], p[1]) for f, p in self._status_pending.items()]
            for f, _, _ in due:
                self._status_pending.pop(f, None)
            if window_due:
                # only a WINDOW flush resets the clock: a stream of
                # quorum-inline flushes (each draining only its own gang)
                # must not keep deferring everyone else's batched partial
                # progress past the window forever
                self._status_last_flush = mono
        for f, inc, member in due:
            _, g = self.get_pod_group(member)
            if g is not None:
                self._patch_status(f, g, member, inc)

    def flush_status(self) -> None:
        """Drain every pending PG status increment now — the residue path
        (a gang whose binds stopped short of quorum must still show its
        partial progress; called opportunistically from pre_filter and on
        plugin close)."""
        if self._status_flush_s <= 0:
            return
        with self._status_lock:
            due = [(f, p[0], p[1]) for f, p in self._status_pending.items()]
            self._status_pending.clear()
            self._status_last_flush = self._now()
        for f, inc, member in due:
            _, g = self.get_pod_group(member)
            if g is not None:
                self._patch_status(f, g, member, inc)

    def flush_status_if_due(self) -> None:
        if self._status_flush_s <= 0 or not self._status_pending:
            return
        if self._now() - self._status_last_flush \
                >= self._status_flush_s:
            self.flush_status()

    def _patch_status(self, full: str, pg: PodGroup, pod: Pod,
                      increments: int) -> None:
        now = self.handle.clock()

        def mutate(g: PodGroup):
            g.status.scheduled += increments
            lister = self.handle.snapshot_shared_lister()
            if (g.status.scheduled >= g.spec.min_member
                    and g.status.phase != PG_SCHEDULED
                    and getattr(lister, "live_pg_assigned", False)):
                live = lister.assigned_count(pg.meta.name,
                                             pg.meta.namespace)
                if live < g.spec.min_member:
                    # count says complete but the LIVE assigned index
                    # disagrees: a repair/reset (controllers/gangrepair
                    # rewrites status.scheduled absolutely on member
                    # loss) interleaved with increments batched before
                    # it — double-counted survivors must not flip a
                    # damaged gang to PG_SCHEDULED or record a false
                    # north-star observation.  Clamp toward the reset
                    # baseline / live reality; the next real bind
                    # re-patches from there.  Guarded on live_pg_assigned
                    # so hand-built (frozen, possibly empty) test listers
                    # keep the count-driven behavior.
                    g.status.scheduled = min(
                        g.status.scheduled,
                        max(live, g.status.scheduled - increments))
            if g.status.scheduled >= g.spec.min_member:
                if g.status.phase != PG_SCHEDULED:
                    # quorum complete: record the north-star latency
                    # (BASELINE.md PodGroup-to-Bound). Interval start: first
                    # member SEEN (earliest sibling creation), not first
                    # bound — the Permit barrier releases all binds at once,
                    # so first-bind→last-bind would only measure the burst.
                    # Computed here, once per gang: an O(members) sweep on
                    # every bind is O(n²) over the release burst.
                    first_seen = min(
                        (p.meta.creation_timestamp for p in self.siblings(pod)),
                        default=pg.meta.creation_timestamp)
                    bound_s = max(0.0, now - first_seen)
                    # the north-star histogram and the gang-bound SLO
                    # objective share this one clock read — and both are
                    # LIVE-fleet data: a SHADOW scheduler's (what-if/
                    # defrag trial) simulated binds must neither skew the
                    # PodGroup-to-Bound distribution nor burn the SLO
                    if getattr(self.handle, "telemetry", True):
                        pod_group_to_bound_seconds.observe(bound_s)
                        from ... import obs
                        obs.observe_gang_bound(bound_s)
                g.status.phase = PG_SCHEDULED
            else:
                g.status.phase = PG_SCHEDULING
                if g.status.schedule_start_time is None:
                    g.status.schedule_start_time = now
        try:
            self.handle.clientset.podgroups.patch(full, mutate)
        except srv.NotFound:
            # KEP-2 synthesized group: no CR to patch — track status on the
            # memoized instance so quorum completion (and the north-star
            # PodGroup-to-Bound observation inside mutate) still happens.
            synthesized, ok = self._synthesized_pgs.get(full)
            if ok:
                # binding cycles run on their own threads; the CR path is
                # serialized by the API server, this one needs its own lock
                with self._synthesized_status_lock:
                    mutate(synthesized)
        except Exception as e:
            klog.error_s(e, "failed to patch PodGroup", podGroup=full)

    # -- deny/permit caches ---------------------------------------------------

    def denied_remaining(self, pod: Pod) -> float:
        """Seconds left on the pod's gang denial window (0 if not denied)."""
        full = pod_group_full_name(pod)
        return self.last_denied_pg.remaining(full) if full else 0.0

    def add_denied_pod_group(self, full: str) -> None:
        # add-if-absent (go-cache Add, core.go:268-270): the denial window
        # runs from the FIRST denial; repeat denials during retries must not
        # extend it, or event-driven retries re-deny the gang indefinitely
        self.last_denied_pg.add(full)

    def delete_permitted_pod_group(self, full: str) -> None:
        self.permitted_pg.delete(full)


def check_cluster_resource(node_list: List[NodeInfo],
                           resource_request: ResourceList,
                           desired_pg_full_names) -> Optional[str]:
    """Can the cluster's aggregate free capacity hold `resource_request`?

    Walks nodes subtracting each node's free resources (with the group's own
    pods removed first, so a retrying gang doesn't double-count itself —
    getNodeResource, core.go:349-382). Returns a gap description or None.
    Operates on a private copy (reference mutates the caller's map).

    ``desired_pg_full_names``: one gang full-name, or a set of them (the
    MultiSlice set-level dry-run excludes every member gang's pods)."""
    if isinstance(desired_pg_full_names, str):
        desired_pg_full_names = frozenset((desired_pg_full_names,))
    remaining = {k: v for k, v in resource_request.items() if v > 0}
    for info in node_list:
        if info is None or info.node is None:
            continue
        left = _node_left_resource(info, desired_pg_full_names)
        for name in list(remaining):
            remaining[name] -= left.get(name, 0)
            if remaining[name] <= 0:
                del remaining[name]
        if not remaining:
            return None
    return f"resource gap: {remaining}"


def _node_left_resource(info: NodeInfo,
                        desired_pg_full_names: frozenset) -> ResourceList:
    alloc = dict(info.allocatable)
    requested: ResourceList = {}
    own_pods = 0
    for p in info.pods:
        if pod_group_full_name(p) in desired_pg_full_names:
            own_pods += 1
            continue
        for k, v in pod_effective_request(p).items():
            requested[k] = requested.get(k, 0) + v
    left = {k: alloc.get(k, 0) - requested.get(k, 0)
            for k in set(alloc) | set(requested)}
    left[PODS] = alloc.get(PODS, 0) - (len(info.pods) - own_pods)
    return left
