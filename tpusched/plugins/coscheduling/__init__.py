from .plugin import Coscheduling
from .core import PodGroupManager

__all__ = ["Coscheduling", "PodGroupManager"]
