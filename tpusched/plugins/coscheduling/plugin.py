"""Coscheduling plugin: all-or-nothing PodGroup admission.

Rebuild of /root/reference/pkg/coscheduling/coscheduling.go:
QueueSort by priority → gang creation time → key (:112-124); PreFilter
delegates to the manager and maps errors to UnschedulableAndUnresolvable so
preemption is not attempted (:129-137); PostFilter optimistically rejects the
whole waiting gang when one member fails, with a ≤10% quorum-gap grace
(:140-176); Permit waits until assigned+1 ≥ MinMember then Allows all waiting
siblings (:184-216); Unreserve rejects all siblings on timeout (:224-237);
PostBind patches PG status (:240-243); cluster events registered for requeue
(:93-101).
"""
from __future__ import annotations

from typing import List, Optional, Tuple

from ... import trace
from ...api.core import Pod
from ...api.scheduling import POD_GROUP_LABEL, pod_group_full_name, pod_group_label
from ...config.types import CoschedulingArgs
from ...fwk import CycleState, GANG_ROLLBACK_STATE_KEY, Status
from ...fwk.interfaces import (ClusterEvent, EnqueueExtensions,
                               EquivalenceAware, EVENT_ADD, EVENT_DELETE,
                               EVENT_UPDATE, PermitPlugin,
                               PostBindPlugin, PostFilterPlugin,
                               PostFilterResult, PreFilterPlugin,
                               QueueSortPlugin, ReservePlugin, RESOURCE_POD,
                               RESOURCE_POD_GROUP)
from ...util import klog
from .core import (POD_GROUP_NOT_FOUND, POD_GROUP_NOT_SPECIFIED, SUCCESS, WAIT,
                   PodGroupManager, get_wait_time_duration)


class Coscheduling(QueueSortPlugin, PreFilterPlugin, PostFilterPlugin,
                   PermitPlugin, ReservePlugin, PostBindPlugin,
                   EnqueueExtensions, EquivalenceAware):
    NAME = "Coscheduling"

    def __init__(self, args: Optional[CoschedulingArgs], handle):
        self.args = args or CoschedulingArgs()
        self.handle = handle
        self.pg_mgr = PodGroupManager(
            handle,
            schedule_timeout_s=float(self.args.permit_waiting_time_seconds),
            denied_pg_expiration_s=float(self.args.denied_pg_expiration_time_seconds),
            pg_status_flush_s=float(getattr(
                self.args, "pg_status_flush_seconds", 0.0)))

    def close(self) -> None:
        """Framework shutdown: drain any coalesced PG status increments so
        a stopped scheduler never swallows partial gang progress."""
        self.pg_mgr.flush_status()

    def on_clock_tick(self) -> None:
        """Timer hook (Scheduler.run_timers_once): the virtual-time replay
        driver fires this after advancing the clock so the PG-status flush
        window drains at its armed deadline, not only on the next
        pre_filter cycle."""
        self.pg_mgr.flush_status_if_due()

    @classmethod
    def new(cls, args, handle) -> "Coscheduling":
        return cls(args, handle)

    # -- EnqueueExtensions (coscheduling.go:93-101) ---------------------------

    def events_to_register(self) -> List[ClusterEvent]:
        return [
            # a new/deleted sibling can make a gang schedulable
            ClusterEvent(RESOURCE_POD, EVENT_ADD | EVENT_DELETE),
            # PG created/updated (e.g. minMember lowered)
            ClusterEvent(RESOURCE_POD_GROUP, EVENT_ADD | EVENT_UPDATE),
            # capacity appearing can satisfy MinResources
            ClusterEvent("Node", EVENT_ADD | EVENT_UPDATE),
        ]

    # -- QueueSort ------------------------------------------------------------

    def less(self, pi1, pi2) -> bool:
        if pi1.pod.priority != pi2.pod.priority:
            return pi1.pod.priority > pi2.pod.priority
        t1 = self.pg_mgr.get_creation_timestamp(pi1.pod, pi1.initial_attempt_timestamp)
        t2 = self.pg_mgr.get_creation_timestamp(pi2.pod, pi2.initial_attempt_timestamp)
        if t1 != t2:
            return t1 < t2
        return pi1.pod.key < pi2.pod.key

    # -- PreFilter ------------------------------------------------------------

    def pre_filter(self, state: CycleState, pod: Pod) -> Status:
        # structured rejection detail is recorded by the manager at the
        # exact failure site (core.pre_filter), where the quorum arithmetic
        # is already in hand — re-deriving it here would re-walk the
        # sibling index on every denied retry
        err = self.pg_mgr.pre_filter(pod)
        if err is not None:
            klog.V(4).info_s("PreFilter failed", pod=pod.key, reason=err)
            status = Status.unresolvable(err)
            # denial-window rejections are time-bounded: tell the queue when
            # a retry can actually succeed (nothing emits an event when a
            # TTL lapses, so event-driven requeue alone strands the gang
            # until the periodic flush)
            remaining = self.pg_mgr.denied_remaining(pod)
            if remaining > 0:
                status.with_retry_after(remaining + 0.05)
            return status
        return Status.success()

    # -- equivalence cache (sched/equivcache.py) ------------------------------

    def equiv_fingerprint(self, pod: Pod, state):
        """PreFilter inputs invisible to the mutation cursor: the PodGroup
        spec (minMember / minResources can change without any node or pod
        mutation), the live sibling COUNT (unassigned pod churn never
        touches the scheduler cache), and the TTL'd denial/permit windows
        (which lapse on the clock, announced by no event). Recomputing this
        at every lookup means a lapsed denial window or a deleted sibling
        invalidates the entry exactly when the full path's verdict would
        change."""
        full, pg = self.pg_mgr.get_pod_group(pod)
        if pg is None:
            return ("", full)
        mgr = self.pg_mgr
        min_resources = pg.spec.min_resources or {}
        return (full, pg.meta.resource_version, pg.spec.min_member,
                tuple(sorted(min_resources.items())),
                len(mgr.siblings(pod)),
                full in mgr.last_denied_pg,
                full in mgr.permitted_pg if min_resources else None)

    # -- PostFilter -----------------------------------------------------------

    def post_filter(self, state: CycleState, pod: Pod,
                    filtered_node_status_map) -> Tuple[Optional[PostFilterResult], Status]:
        full, pg = self.pg_mgr.get_pod_group(pod)
        if pg is None:
            klog.V(4).info_s("pod does not belong to any group", pod=pod.key)
            return PostFilterResult(), Status.unschedulable("can not find pod group")

        assigned = self.pg_mgr.calculate_assigned_pods(pg.meta.name, pod.namespace)
        if assigned >= pg.spec.min_member:
            # quorum already satisfied; no need to reject the gang
            return PostFilterResult(), Status.unschedulable()

        # ≤10% quorum gap: let subsequent members try before mass rejection
        if pg.spec.min_member > 0:
            not_assigned_pct = (pg.spec.min_member - assigned) / pg.spec.min_member
            if not_assigned_pct <= 0.1:
                klog.V(4).info_s("small quorum gap, not rejecting gang",
                                 podGroup=full, gap=not_assigned_pct)
                return PostFilterResult(), Status.unschedulable()

        # one member failed ⇒ its siblings would very likely fail too
        def reject(waiting_pod):
            wp = waiting_pod.pod
            if (wp.namespace == pod.namespace
                    and wp.meta.labels.get(POD_GROUP_LABEL) == pg.meta.name):
                klog.V(3).info_s("PostFilter rejects the pod", podGroup=full,
                                 pod=wp.key)
                waiting_pod.reject(self.NAME, "optimistic rejection in PostFilter")
        self.handle.iterate_over_waiting_pods(reject)
        self.pg_mgr.add_denied_pod_group(full)
        self.pg_mgr.delete_permitted_pod_group(full)
        # gang denial is a flight-recorder anomaly: the member that
        # triggered the optimistic whole-gang rejection pins its trace
        trace.record_anomaly("gang_denied", pod_group=full,
                             trigger_pod=pod.key, assigned=assigned,
                             min_member=pg.spec.min_member)
        return PostFilterResult(), Status.unschedulable(
            f"PodGroup {full} gets rejected due to Pod {pod.name} is "
            f"unschedulable even after PostFilter")

    # -- Permit ---------------------------------------------------------------

    def permit(self, state: CycleState, pod: Pod,
               node_name: str) -> Tuple[Status, float]:
        verdict = self.pg_mgr.permit(pod)
        if verdict == POD_GROUP_NOT_SPECIFIED:
            return Status.success(), 0.0
        if verdict == POD_GROUP_NOT_FOUND:
            return Status.unschedulable("PodGroup not found"), 0.0
        if verdict == WAIT:
            _, pg = self.pg_mgr.get_pod_group(pod)
            wait_s = get_wait_time_duration(
                pg, float(self.args.permit_waiting_time_seconds))
            klog.V(3).info_s("pod is waiting to be scheduled", pod=pod.key,
                             node=node_name, waitSeconds=wait_s)
            # quorum progress into the cycle trace: in-flight-inclusive
            # count of min_member, so a wedged barrier's dump shows exactly
            # how far the gang got (guarded: the count lookup + format is
            # only worth paying when a trace is live)
            if trace.current() is not None:
                quorum = self.pg_mgr.quorum_with_inflight(
                    pg.meta.name, pod.namespace)
                trace.annotate("coscheduling_quorum",
                               f"{quorum}/{pg.spec.min_member}")
            # pull the siblings into activeQ so the quorum can form
            self.pg_mgr.activate_siblings(pod, state)
            return Status.wait(), wait_s
        # SUCCESS: quorum reached — release every waiting sibling
        full = pod_group_full_name(pod)

        def allow(waiting_pod):
            if pod_group_full_name(waiting_pod.pod) == full:
                klog.V(3).info_s("Permit allows", pod=waiting_pod.pod.key)
                waiting_pod.allow(self.NAME)
        self.handle.iterate_over_waiting_pods(allow)
        return Status.success(), 0.0

    # -- Reserve/Unreserve ----------------------------------------------------

    def reserve(self, state: CycleState, pod: Pod, node_name: str) -> Status:
        return Status.success()

    def unreserve(self, state: CycleState, pod: Pod, node_name: str) -> None:
        full, pg = self.pg_mgr.get_pod_group(pod)
        if pg is None:
            return

        def reject(waiting_pod):
            wp = waiting_pod.pod
            if (wp.namespace == pod.namespace
                    and wp.meta.labels.get(POD_GROUP_LABEL) == pg.meta.name):
                klog.V(3).info_s("Unreserve rejects", pod=wp.key, podGroup=full)
                waiting_pod.reject(self.NAME, "rejection in Unreserve")
        self.handle.iterate_over_waiting_pods(reject)
        # gang-bind-rollback cycles (scheduler-marked) failed on an API
        # outage, not on schedulability: the denial window would only stall
        # the gang's re-admission after the faults clear — skip it and let
        # pod backoff pace the retry
        if not state.try_read(GANG_ROLLBACK_STATE_KEY):
            self.pg_mgr.add_denied_pod_group(full)
        self.pg_mgr.delete_permitted_pod_group(full)

    # -- PostBind -------------------------------------------------------------

    def post_bind(self, state: CycleState, pod: Pod, node_name: str) -> None:
        klog.V(5).info_s("PostBind", pod=pod.key)
        self.pg_mgr.post_bind(pod, node_name)
