"""Sharded dispatch core: per-pool parallel scheduling lanes with
optimistic cross-pool conflict resolution (ROADMAP item 1).

The PR 7 arrival-storm baseline proved the single dispatch loop is the
wall: 81.8 binds/s at 32 pools / 2048 hosts with a p99 pod-e2e that is
almost pure queue wait.  The reference scheduler runs parallel scheduler
profiles for exactly this reason; this module is the partitioning and
routing half of that design — the scheduler (sched/scheduler.py) runs one
dispatch worker per lane, and everything here decides WHICH lane owns a
pod and which pools a lane may place into.

Model
-----

- Pools (``tpu.dev/pool``) are statically partitioned over N shard lanes
  by a stable hash (``crc32(pool) % N``): adding or removing pools never
  reshuffles the survivors, and two processes (or two replays) always
  agree on the partition — ``sched/ha.py``'s replica identity names the
  process, the hash names the shard, so shard ownership needs no
  coordination protocol.
- Scheduling units (a gang, or a singleton pod) are routed to lanes by
  the same stable hash over the unit key, so every member of a gang lands
  in ONE lane and the equivalence cache's sibling burst survives
  sharding.  A shard lane's cycles filter ONLY over its own pools'
  nodes — the per-cycle sweep shrinks by ~N×, which is where most of the
  throughput multiplier comes from; the lanes running concurrently is
  the rest.
- Pods whose feasible pools span shards fall back to the serialized
  GLOBAL lane, which sweeps the whole fleet exactly like the pre-sharding
  loop: multislice sets (their member gangs must coordinate placement
  across pools), pods pinned by an explicit pool selector are routed to
  that pool's shard instead, and nominated preemptors (their nomination
  may point anywhere).  ElasticQuota fleets no longer serialize
  wholesale (ISSUE 14): quota admission commits through the cache's
  quota-epoch compare-and-reserve (``Cache.assume_pod_guarded`` with a
  ``quota_guard``), so quota'd pods dispatch on their shard lanes and a
  raced quota verdict re-derives exactly like a pool conflict.  Only
  cross-quota BORROWERS (admission that spends another quota's spare
  min) escalate to the global lane — CapacityScheduling's PreFilter
  rejects them on partition-scoped cycles and the standard escalation
  hop carries the unit over.  The pre-14 wholesale serialization
  survives only as the opt-in ``quota_serialize_dispatch`` profile knob
  (the bench baseline arm and an operational escape hatch).
- A shard-restricted cycle that comes up unschedulable ESCALATES its
  unit to the global lane (bounded TTL, so capacity returning to the
  unit's home shard eventually pulls it back): the shard attempt costs
  one cheap restricted sweep, and nothing a single loop could place is
  ever lost to partitioning.

Conflict resolution is the cache's job (sched/cache.py): every structural
mutation bumps a per-pool cursor, a cycle captures its partition's
cursors atomically with its snapshot (``Cache.snapshot_view``), and the
commit point is the optimistic ``Cache.assume_pod_guarded`` — reusing the
equivalence cache's arming-guard idea ("the cursor advanced by exactly my
own assume") as a compare-and-assume keyed on the chosen pool's cursor.
A raced cycle re-derives on fresh state instead of binding a stale
placement.  Gang admission needs nothing new: the permit barrier and the
Coscheduling quorum clock are process-global state shared by all lanes.
"""
from __future__ import annotations

import collections
import threading
import zlib
from typing import Callable, Dict, List, Optional

from ..api.core import Pod
from ..api.scheduling import pod_group_full_name
from ..api.topology import LABEL_POOL

__all__ = ["GLOBAL_LANE", "shard_lane", "pool_shard", "unit_key_of",
           "ShardRouter", "ShardStats", "attribute_placement_diff"]

GLOBAL_LANE = "global"

# An escalated unit returns to its home shard after this long: pool
# capacity churns on the scale of seconds under a storm, and a unit pinned
# to the serialized global lane forever would re-create the single-loop
# wall one unit at a time.
ESCALATION_TTL_S = 30.0

# Bounded memory for the cumulative escalated-unit set the replay
# equivalence gate reads (attribution of shard-vs-global placement moves).
_ESCALATED_EVER_CAP = 16384


def shard_lane(index: int) -> str:
    return f"s{index}"


def pool_shard(pool: str, shards: int) -> int:
    """Stable pool → shard assignment.  crc32 (not hash()) so replays,
    restarts and HA replicas all agree."""
    return zlib.crc32(pool.encode("utf-8")) % shards


def unit_key_of(pod: Pod) -> str:
    """The scheduling unit a pod belongs to: its gang's full name, or its
    own key for singletons.  Routing by unit keeps gang siblings in one
    lane (the equivalence-cache burst) and makes escalation gang-wide."""
    return pod_group_full_name(pod) or pod.key


class ShardRouter:
    """Deterministic pod → dispatch-lane routing with an escalation
    registry.  Cheap by contract: one informer dict get plus a couple of
    hashes per call — it runs once per (re)enqueue and once per pop."""

    def __init__(self, shards: int,
                 pg_lookup: Optional[Callable[[str], object]] = None,
                 clock=None,
                 escalation_ttl_s: float = ESCALATION_TTL_S,
                 quota_serialize: bool = False):
        from ..util.clock import as_clock
        self.shards = shards
        self._pg_lookup = pg_lookup or (lambda key: None)
        # escalation TTLs are scheduler gates: route them through the
        # injected handle clock (util/clock) so a virtual-time replay can
        # jump to the lapse — the lapse re-routes the unit home, which is
        # exactly the retry dynamic zeroed-gate replay used to erase
        self._clock_handle = as_clock(clock)
        self._clock = self._clock_handle.now
        self._ttl = escalation_ttl_s
        self._quota_serialize = quota_serialize
        self._lock = threading.Lock()
        # unit key → escalation deadline (monotonic); pruned lazily
        self._escalated: "collections.OrderedDict[str, float]" = \
            collections.OrderedDict()
        # cumulative escalated units (bounded), for post-hoc attribution
        # of placement differences in the replay equivalence gate; at the
        # cap the set stops growing and the TRUNCATED flag flips so a
        # consumer never mistakes "not recorded" for "never escalated"
        self._escalated_ever: set = set()
        self._escalated_overflow = False
        self._escalations = 0
        # fleet-has-quotas flag: routing consults it ONLY under the legacy
        # quota_serialize mode; otherwise it is health-report context
        # (quota'd fleets dispatch sharded via the epoch-guarded commit)
        self._quota_mode = False

    # -- fleet-condition inputs ----------------------------------------------

    def set_quota_mode(self, on: bool) -> None:
        self._quota_mode = bool(on)

    def quota_mode(self) -> bool:
        return self._quota_mode

    def quota_serialized(self) -> bool:
        """True iff quota presence currently serializes routing (the
        legacy ``quota_serialize_dispatch`` arm is on AND quotas exist)."""
        return self._quota_serialize and self._quota_mode

    # -- escalation -----------------------------------------------------------

    def escalate(self, pod: Pod) -> str:
        """Route ``pod``'s whole unit to the global lane for the TTL.
        Returns the unit key."""
        unit = unit_key_of(pod)
        now = self._clock()
        self._clock_handle.arm("escalation", now + self._ttl)
        with self._lock:
            self._escalated[unit] = now + self._ttl
            self._escalated.move_to_end(unit)
            if len(self._escalated_ever) < _ESCALATED_EVER_CAP:
                self._escalated_ever.add(unit)
            elif unit not in self._escalated_ever:
                self._escalated_overflow = True
            self._escalations += 1
            # lazy prune from the oldest end — entries are in rough
            # deadline order because the TTL is constant
            while self._escalated:
                first = next(iter(self._escalated))
                if self._escalated[first] > now:
                    break
                del self._escalated[first]
        return unit

    def is_escalated(self, unit: str) -> bool:
        with self._lock:
            deadline = self._escalated.get(unit)
            if deadline is None:
                return False
            if deadline <= self._clock():
                del self._escalated[unit]
                return False
            return True

    def escalated_units(self) -> List[str]:
        """Every unit routed fleet-wide over this router's lifetime —
        escalations plus nominated preemptors — bounded; the replay
        equivalence gate's attribution input."""
        with self._lock:
            return sorted(self._escalated_ever)

    def escalated_truncated(self) -> bool:
        """True iff the cumulative escalated-unit set overflowed its cap —
        absence from escalated_units() is then inconclusive, and an
        attribution consumer must not treat it as "never escalated"."""
        with self._lock:
            return self._escalated_overflow

    def escalations(self) -> int:
        with self._lock:
            return self._escalations

    # -- the routing decision -------------------------------------------------

    def lane_for(self, pod: Pod) -> str:
        if self.shards <= 1 or (self._quota_serialize and self._quota_mode):
            return GLOBAL_LANE
        gang = pod_group_full_name(pod)
        unit = gang or pod.key
        if getattr(pod.status, "nominated_node_name", ""):
            # a nominated preemptor's placement may land anywhere — note
            # the unit in the globally-routed set so the replay diff can
            # attribute its fleet-wide placement like an escalation
            with self._lock:
                if len(self._escalated_ever) < _ESCALATED_EVER_CAP:
                    self._escalated_ever.add(unit)
                elif unit not in self._escalated_ever:
                    self._escalated_overflow = True
            return GLOBAL_LANE
        if self.is_escalated(unit):
            return GLOBAL_LANE
        if gang:
            pg = self._pg_lookup(gang)
            spec = getattr(pg, "spec", None)
            if spec is not None and (
                    getattr(spec, "multislice_set", "")
                    or getattr(spec, "multislice_set_size", 0) > 1):
                # a multislice member gang must co-place with sibling
                # gangs whose pools may hash anywhere: feasible pools
                # span shards ⇒ the serialized lane owns it
                return GLOBAL_LANE
            # gang members NEVER route by a per-member pool pin: one unit
            # = one lane is the invariant (sibling equivcache bursts,
            # unit-wide escalation).  A member whose pinned pool is
            # outside its unit's partition simply fails the restricted
            # cycle and escalates the whole unit to the global lane.
            return shard_lane(pool_shard(unit, self.shards))
        pinned = pod.spec.node_selector.get(LABEL_POOL, "") \
            if pod.spec.node_selector else ""
        if pinned:
            return shard_lane(pool_shard(pinned, self.shards))
        return shard_lane(pool_shard(unit, self.shards))

    def partition(self, pools: List[str], lane: str) -> List[str]:
        """The pools a shard lane owns out of the fleet's current pool
        set.  The global lane owns everything (returns the input)."""
        if lane == GLOBAL_LANE:
            return pools
        idx = int(lane[1:])
        return [p for p in pools if pool_shard(p, self.shards) == idx]


class ShardStats:
    """Per-lane dispatch accounting, published as ``health.shards`` in
    /debug/flightrecorder (the hot/starved-shard diagnosis surface next to
    the per-shard metrics)."""

    __slots__ = ("_lock", "_lanes", "_clock")

    def __init__(self, lanes: List[str], clock=None):
        from ..util.clock import as_clock
        self._lock = threading.Lock()
        self._clock = as_clock(clock).now
        self._lanes: Dict[str, Dict[str, float]] = {
            lane: {"cycles": 0, "binds": 0, "conflicts": 0,
                   "quota_conflicts": 0, "escalations": 0,
                   "last_cycle_mono": 0.0}
            for lane in lanes}

    def on_cycle(self, lane: str) -> None:
        with self._lock:
            row = self._lanes.get(lane)
            if row is not None:
                row["cycles"] += 1
                row["last_cycle_mono"] = self._clock()

    def on_bind(self, lane: str) -> None:
        with self._lock:
            row = self._lanes.get(lane)
            if row is not None:
                row["binds"] += 1

    def on_conflict(self, lane: str, quota: bool = False) -> None:
        with self._lock:
            row = self._lanes.get(lane)
            if row is not None:
                row["conflicts"] += 1
                if quota:
                    row["quota_conflicts"] += 1

    def on_escalation(self, lane: str) -> None:
        with self._lock:
            row = self._lanes.get(lane)
            if row is not None:
                row["escalations"] += 1

    def snapshot(self, queue_depths: Optional[Dict[str, Dict[str, int]]]
                 = None,
                 partitions: Optional[Dict[str, int]] = None) -> Dict:
        """The health.shards payload: per-lane counters + idle age, plus
        the caller-supplied queue depths and partition sizes."""
        now = self._clock()
        with self._lock:
            lanes = {}
            for lane, row in self._lanes.items():
                ent = {"cycles": int(row["cycles"]),
                       "binds": int(row["binds"]),
                       "conflicts": int(row["conflicts"]),
                       "quota_conflicts": int(row["quota_conflicts"]),
                       "escalations": int(row["escalations"]),
                       "idle_s": round(now - row["last_cycle_mono"], 3)
                       if row["last_cycle_mono"] else None}
                if queue_depths and lane in queue_depths:
                    ent["queue"] = queue_depths[lane]
                if partitions and lane in partitions:
                    ent["pools"] = partitions[lane]
                lanes[lane] = ent
        return {"lanes": lanes, "shard_count": len(self._lanes)}


def attribute_placement_diff(diff: Dict, *, shards: int,
                             pool_of_node: Callable[[str], str],
                             gang_of: Callable[[str], Optional[str]],
                             escalated_units: Optional[List[str]] = None,
                             pinned_pool_of: Optional[
                                 Callable[[str], Optional[str]]] = None,
                             escalated_truncated: bool = False) -> Dict:
    """Attribute a shards=1 vs shards=N lockstep placement diff
    (sim/replay.diff_placements output) to the sharding policy.

    A move is ATTRIBUTED when the sharded run's node sits in the pod's
    routed shard's partition (the partition argmax differs from the fleet
    argmax by design) or when the pod's unit is in the sharded run's
    escalated set (the global lane placed it fleet-wide).  Anything else
    — a move to a pool the router could never have offered the pod, a pod
    bound in only one run, a bind-count delta — is UNATTRIBUTED: the
    sharded core placed something the partitioning rule cannot explain,
    i.e. a real divergence the replay gate must fail on.

    ``pinned_pool_of`` mirrors the router's pool-selector rule for
    SINGLETONS (a non-gang pod pinned to pool P dispatches on P's shard;
    gang members always route by unit).  ``escalated_truncated`` (from
    ``ShardRouter.escalated_truncated()``) marks the escalated set as
    lossy: the report carries the flag and gates must fail on it rather
    than trust absence."""
    escalated = set(escalated_units or ())
    moved_out = []
    unattributed = []
    for ent in diff.get("placement_diff", ()):
        pod = ent["pod"]
        gang = gang_of(pod)
        unit = gang or pod
        pinned = pinned_pool_of(pod) if (pinned_pool_of is not None
                                         and not gang) else None
        lane_idx = pool_shard(pinned, shards) if pinned \
            else pool_shard(unit, shards)
        pool_b = pool_of_node(ent["b"])
        ann = dict(ent)
        ann["unit"] = unit
        ann["routed_shard"] = shard_lane(lane_idx)
        ann["pool_b"] = pool_b
        if unit in escalated:
            ann["attributed"] = "escalated-global"
        elif pool_shard(pool_b, shards) == lane_idx:
            ann["attributed"] = "shard-partition"
        else:
            ann["attributed"] = ""
            unattributed.append(ann)
        moved_out.append(ann)
    out = dict(diff)
    out["placement_diff"] = moved_out
    out["unattributed"] = unattributed
    out["escalated_set_truncated"] = escalated_truncated
    out["unattributed_count"] = (
        len(unattributed)
        + len(diff.get("only_in_a", ()))
        + len(diff.get("only_in_b", ()))
        + (0 if diff.get("binds_a") == diff.get("binds_b") else 1)
        + (1 if escalated_truncated else 0))
    return out
