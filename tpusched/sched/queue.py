"""Priority scheduling queue: activeQ (heap over the QueueSort plugin's Less),
backoffQ (exponential per-pod backoff), and unschedulableQ with event-driven
requeue.

Rebuild of upstream SchedulingQueue as the reference uses it: QueueSort
ordering (coscheduling.Less, /root/reference/pkg/coscheduling/coscheduling.go:112-124),
PodsToActivate sibling activation (core.go:111-143), and cluster-event moves
declared via EnqueueExtensions (coscheduling.go:93-101).
"""
from __future__ import annotations

import heapq
import itertools
import time
from typing import Callable, Dict, List, Optional

from ..api.core import Pod
from ..api.scheduling import POD_GROUP_LABEL
from ..fwk.interfaces import ClusterEvent
from ..util import klog
from ..util.locking import GuardedCondition, GuardedLock, guarded_by

INITIAL_BACKOFF_S = 1.0
MAX_BACKOFF_S = 10.0
UNSCHEDULABLE_Q_FLUSH_S = 30.0


def _gang_of(info: "QueuedPodInfo"):
    """(namespace, gang) of a queued pod, or None for singletons."""
    pod = info.pod
    name = pod.meta.labels.get(POD_GROUP_LABEL)
    return (pod.meta.namespace, name) if name else None


class QueuedPodInfo:
    __slots__ = ("pod", "timestamp", "initial_attempt_timestamp", "attempts",
                 "unschedulable_plugins")

    def __init__(self, pod: Pod, clock=time.time):
        self.pod = pod
        self.timestamp = clock()              # last enqueue time
        self.initial_attempt_timestamp = self.timestamp
        self.attempts = 0
        self.unschedulable_plugins: set = set()

    def backoff_duration(self, initial: float = INITIAL_BACKOFF_S,
                         maximum: float = MAX_BACKOFF_S) -> float:
        d = initial
        for _ in range(self.attempts - 1):
            d *= 2
            if d >= maximum:
                return maximum
        return d


class _Heap:
    """Stable heap with a less(a, b) comparator and O(1) membership."""

    def __init__(self, less: Callable[[QueuedPodInfo, QueuedPodInfo], bool]):
        self._less = less
        self._seq = itertools.count()
        self._heap: List = []
        self._entries: Dict[str, list] = {}   # key → entry; entry[2] None ⇒ removed
        # (ns, gang) → live member keys: lets pop() drain a gang's siblings
        # back-to-back (the equivalence cache only hits while the cursor
        # chain is unbroken by foreign assumes)
        self._gangs: Dict[tuple, set] = {}

    class _Item:
        __slots__ = ("info", "less", "seq")

        def __init__(self, info, less, seq):
            self.info, self.less, self.seq = info, less, seq

        def __lt__(self, other):
            if self.less(self.info, other.info):
                return True
            if self.less(other.info, self.info):
                return False
            return self.seq < other.seq

    def push(self, info: QueuedPodInfo) -> None:
        key = info.pod.key
        self.remove(key)
        item = self._Item(info, self._less, next(self._seq))
        entry = [item, key, info]
        self._entries[key] = entry
        heapq.heappush(self._heap, (item, entry))
        gang = _gang_of(info)
        if gang is not None:
            self._gangs.setdefault(gang, set()).add(key)

    def _gang_discard(self, key: str, info: QueuedPodInfo) -> None:
        gang = _gang_of(info)
        if gang is None:
            return
        members = self._gangs.get(gang)
        if members is not None:
            members.discard(key)
            if not members:
                del self._gangs[gang]

    def pop(self) -> Optional[QueuedPodInfo]:
        while self._heap:
            _, entry = heapq.heappop(self._heap)
            if entry[2] is not None:
                del self._entries[entry[1]]
                self._gang_discard(entry[1], entry[2])
                return entry[2]
        return None

    def peek(self) -> Optional[QueuedPodInfo]:
        while self._heap:
            _, entry = self._heap[0]
            if entry[2] is not None:
                return entry[2]
            heapq.heappop(self._heap)
        return None

    def get(self, key: str) -> Optional[QueuedPodInfo]:
        entry = self._entries.get(key)
        return entry[2] if entry is not None else None

    def gang_member(self, gang: tuple) -> Optional[str]:
        """Deterministic (smallest-key) live member of ``gang``, if any."""
        members = self._gangs.get(gang)
        return min(members) if members else None

    def remove(self, key: str) -> Optional[QueuedPodInfo]:
        entry = self._entries.pop(key, None)
        if entry is None:
            return None
        info = entry[2]
        entry[2] = None
        self._gang_discard(key, info)
        return info

    def __contains__(self, key: str) -> bool:
        return key in self._entries

    def __len__(self) -> int:
        return len(self._entries)

    def items(self) -> List[QueuedPodInfo]:
        return [e[2] for e in self._entries.values() if e[2] is not None]


@guarded_by("_lock", "_active", "_backoff", "_backoff_keys",
            "_unschedulable", "_pending_moves", "_cycle_moves", "_last_gang",
            "_closed", "_in_cycle")
class SchedulingQueue:
    def __init__(self, less: Callable[[QueuedPodInfo, QueuedPodInfo], bool],
                 cluster_event_map: Optional[Dict[str, List[ClusterEvent]]] = None,
                 clock=time.time,
                 initial_backoff_s: Optional[float] = None,
                 max_backoff_s: Optional[float] = None,
                 arrival_cb: Optional[Callable[[], None]] = None,
                 unschedulable_flush_s: Optional[float] = None,
                 handle_clock=None):
        from ..util.clock import WALL
        self._clock = clock
        # the full Clock object (util/clock): every backoff expiry and
        # unschedulableQ flush horizon is ARMED on it, so a virtual-time
        # replay jumps straight to the release instant instead of zeroing
        # the window.  Queue timestamps are wall-flavored (they feed the
        # scheduler's wall latency math) — hence wall=True on the arms.
        self._handle_clock = handle_clock if handle_clock is not None \
            else WALL
        # throughput telemetry hook (obs/throughput.ThroughputTelemetry
        # .on_arrival): fired once per NEW pending pod entering the queue —
        # requeues/updates/activations are not arrivals
        self._arrival_cb = arrival_cb or (lambda: None)
        # upstream podInitialBackoffSeconds / podMaxBackoffSeconds;
        # None = default, explicit 0 = retry immediately
        self._initial_backoff_s = (INITIAL_BACKOFF_S if initial_backoff_s
                                   is None else initial_backoff_s)
        self._max_backoff_s = (MAX_BACKOFF_S if max_backoff_s is None
                               else max_backoff_s)
        # periodic unschedulableQ flush: a pure wall-clock SAFETY NET now
        # that the move drains are event-logical (see _cycle_moves below) —
        # a pod no event would ever unstick still gets a retry.  None =
        # default 30 s; explicit 0 disables it (deterministic replay: a
        # lockstep run packs recorded seconds into milliseconds, so a wall
        # flush lands on a run-dependent event boundary and forks the
        # placement sequence).
        self._flush_s = (UNSCHEDULABLE_Q_FLUSH_S if unschedulable_flush_s
                         is None else unschedulable_flush_s)
        # the Condition's underlying lock is the named guard — debug
        # mode instruments it, off mode is a plain RLock inside; the
        # GuardedCondition flavor lets the interleaving explorer
        # (tpusched/verify) model wait/notify hand-offs deterministically
        self._lock = GuardedCondition(
            GuardedLock("sched.SchedulingQueue"))
        self._active = _Heap(less)
        self._backoff: List = []           # (expiry, seq, info)
        self._backoff_seq = itertools.count()
        # live (non-tombstoned) keys in _backoff, with multiplicity — lets
        # activate()/update() skip the O(backoff) scan for absent keys, which
        # matters because PodsToActivate probes every gang sibling each cycle
        self._backoff_keys: Dict[str, int] = {}
        self._unschedulable: Dict[str, QueuedPodInfo] = {}
        # plugin name → events that plugin said can unstick its rejections
        self._cluster_event_map = cluster_event_map or {}
        # coalesced cluster-event moves: resource → OR'd action mask. A
        # 256-member gang's informer storm is 256 identical scans over the
        # parked pods if applied per event; buffering them here and draining
        # once per pop cycle (or observer read) makes the storm one scan.
        self._pending_moves: Dict[str, int] = {}
        # EVENT-LOGICAL at-least-once for in-flight cycles (ISSUE 14
        # satellite): every buffered move is ALSO OR'd here, and the mask
        # is cleared at each pop — so when the popped pod's failing cycle
        # parks, add_unschedulable_if_not_present can check, synchronously,
        # whether any event since its pop would have unstuck it.  Before
        # this, an event drained while the cycle was mid-flight was lost to
        # the parking pod until a wall-clock tick (the 0.2 s pop poll or
        # the 30 s periodic flush) — timing that made sharded lockstep
        # replay pin the pre-index sweep path (sim/replay.py).
        self._cycle_moves: Dict[str, int] = {}
        # gang of the most recently popped pod: pop() prefers its remaining
        # same-priority siblings so the equivalence cache actually hits
        self._last_gang: Optional[tuple] = None
        self._closed = False
        # pods popped but whose scheduling cycle has not completed
        # (cycle_done), counted ATOMICALLY with the pop itself: a popped
        # pod is otherwise invisible to both queue depths and (until a
        # bind lands) the store, and the replay driver's lockstep barrier
        # needs "nothing pending AND nothing mid-cycle" to be one
        # gap-free observation (sim/replay._quiesce)
        self._in_cycle = 0

    def _bk_add_locked(self, key: str) -> None:
        self._backoff_keys[key] = self._backoff_keys.get(key, 0) + 1

    def _bk_del_locked(self, key: str) -> None:
        n = self._backoff_keys.get(key, 0) - 1
        if n <= 0:
            self._backoff_keys.pop(key, None)
        else:
            self._backoff_keys[key] = n

    def cycle_done(self) -> None:
        """Pair of pop(): the popped pod's scheduling cycle completed (it
        either resolved or re-entered a queue on its failure path)."""
        with self._lock:
            self._in_cycle -= 1

    def in_cycle(self) -> int:
        """Pods popped but not yet cycle_done — the mid-cycle population
        invisible to pending_counts (GIL-atomic read)."""
        return self._in_cycle

    def pending_counts(self) -> Dict[str, int]:
        """Queue depths for the pending_pods{queue=...} gauges (upstream
        kube-scheduler metric). (pending_pods() below returns the pod
        objects themselves — the introspection API.)"""
        with self._lock:
            self._apply_pending_moves_locked()
            # _backoff_keys counts LIVE entries; len(_backoff) would also
            # count tombstones left by activate() until the heap drains
            return {"active": len(self._active),
                    "backoff": sum(self._backoff_keys.values()),
                    "unschedulable": len(self._unschedulable)}

    # -- producers ------------------------------------------------------------

    def add(self, pod: Pod) -> None:
        with self._lock:
            info = QueuedPodInfo(pod, self._clock)
            self._active.push(info)
            self._lock.notify_all()
        self._arrival_cb()   # outside the lock: telemetry never extends it

    def update(self, pod: Pod) -> None:
        """Pod object changed while queued: refresh the copy wherever it is."""
        key = pod.key
        with self._lock:
            info = self._active.remove(key)
            if info is not None:
                info.pod = pod
                self._active.push(info)
                self._lock.notify_all()
                return
            if key in self._backoff_keys:
                for i, (exp, seq, binfo) in enumerate(self._backoff):
                    if binfo is not None and binfo.pod.key == key:
                        binfo.pod = pod
                        return
            if key in self._unschedulable:
                self._unschedulable[key].pod = pod

    def delete(self, pod: Pod) -> None:
        key = pod.key
        with self._lock:
            self._active.remove(key)
            self._unschedulable.pop(key, None)
            if key in self._backoff_keys:
                before = len(self._backoff)
                self._backoff = [(e, s, i) for (e, s, i) in self._backoff
                                 if i is None or i.pod.key != key]
                heapq.heapify(self._backoff)
                for _ in range(before - len(self._backoff)):
                    self._bk_del_locked(key)

    def add_unschedulable_if_not_present(self, info: QueuedPodInfo) -> None:
        with self._lock:
            key = info.pod.key
            if key in self._active or key in self._unschedulable:
                return
            info.timestamp = self._clock()
            # park-time move check (event-logical at-least-once): an event
            # that arrived since this pod was popped — still buffered, or
            # already drained to the pods parked at the time — must not
            # strand THIS pod until a wall-clock tick.  Apply it now,
            # synchronously, through the same backoff-expiry routing the
            # drain itself uses.
            moves = dict(self._cycle_moves)
            for r, m in self._pending_moves.items():
                moves[r] = moves.get(r, 0) | m
            if moves and any(self._event_unsticks(info, r, m)
                             for r, m in moves.items()):
                expiry = info.timestamp + info.backoff_duration(
                    self._initial_backoff_s, self._max_backoff_s)
                if expiry <= info.timestamp:
                    self._active.push(info)
                else:
                    heapq.heappush(self._backoff,
                                   (expiry, next(self._backoff_seq), info))
                    self._bk_add_locked(key)
                    self._handle_clock.arm("backoff", expiry, wall=True)
                self._lock.notify_all()
                return
            self._unschedulable[key] = info
            if self._flush_s > 0:
                self._handle_clock.arm("unsched-flush",
                                       info.timestamp + self._flush_s,
                                       wall=True)

    def push_active(self, info: QueuedPodInfo) -> None:
        """Inject an in-flight QueuedPodInfo straight into activeQ
        (attempt count and first-enqueue timestamp preserved).  The
        sharded dispatcher's escalation hop: a pod whose shard-restricted
        cycle came up empty re-enters the GLOBAL lane immediately — no
        backoff, no waiting for a cluster event that may never describe
        "another shard had room"."""
        with self._lock:
            info.timestamp = self._clock()
            self._active.push(info)
            self._lock.notify_all()

    def requeue_after_failure(self, info: QueuedPodInfo,
                              to_backoff: bool = False,
                              delay_s: Optional[float] = None) -> None:
        """After a failed attempt: park in unschedulableQ; cluster events (or
        the periodic flush) move it back through backoff. `attempts` was
        already incremented by pop().

        to_backoff=True short-circuits straight to backoffQ — used for pods
        that just won preemption (nominated node set): their victim-delete
        events fired synchronously inside their own cycle, before parking, so
        no later event would unstick them.

        delay_s (implies to_backoff) overrides the exponential backoff with
        an exact delay — used for time-bounded rejections (denial windows,
        Status.retry_after_s): the pod becomes schedulable when the WINDOW
        lapses, which no cluster event announces."""
        if to_backoff or delay_s is not None:
            with self._lock:
                key = info.pod.key
                if key in self._active or key in self._unschedulable:
                    return
                info.timestamp = self._clock()
                delay = delay_s if delay_s is not None else \
                    info.backoff_duration(self._initial_backoff_s,
                                          self._max_backoff_s)
                heapq.heappush(self._backoff,
                               (info.timestamp + delay,
                                next(self._backoff_seq), info))
                self._bk_add_locked(key)
                self._handle_clock.arm("backoff", info.timestamp + delay,
                                       wall=True)
                self._lock.notify_all()
            return
        self.add_unschedulable_if_not_present(info)

    # -- activation / moves ---------------------------------------------------

    def activate(self, pods: List[Pod]) -> None:
        """PodsToActivate: force the listed pods into activeQ
        (core.go:111-143 / upstream scheduler.go activate)."""
        with self._lock:
            self._apply_pending_moves_locked()
            # Nothing parked means nothing to move: during a healthy gang
            # burst every sibling is active or in-flight, and PodsToActivate
            # probes all of them every cycle — this O(1) exit is what keeps
            # that probe from being O(members²) per gang.
            if not self._unschedulable and not self._backoff_keys:
                return
            moved = False
            for pod in pods:
                key = pod.key
                info = self._unschedulable.pop(key, None)
                if info is None and key in self._backoff_keys:
                    for i, (exp, seq, binfo) in enumerate(self._backoff):
                        if binfo is not None and binfo.pod.key == key:
                            self._backoff[i] = (exp, seq, None)
                            self._bk_del_locked(key)
                            info = binfo
                            break
                if info is not None:
                    self._active.push(info)
                    moved = True
            if moved:
                self._lock.notify_all()

    def move_all_to_active_or_backoff(self, resource: str, action: int) -> None:
        """Cluster event: requeue unschedulable pods whose rejector plugins
        registered a matching event (or that have no recorded rejector).

        Coalesced: the event is buffered (actions OR'd per resource) and the
        parked-pod scan runs once when the buffer drains — at the consumer's
        next pop cycle or any observer read — so a gang-sized informer storm
        costs one scan instead of one per member. Merging actions is exact:
        ClusterEvent.matches tests bitmask overlap, i.e. "some buffered
        event would have unstuck this pod".

        Nothing-parked notify suppression: the event is ALWAYS buffered
        (a pod whose failing cycle is in flight right now parks after
        this event and must still be moved at the buffer's next drain —
        the pre-existing at-least-once contract), but when no pod is
        parked the notify is skipped: the consumer's own pop poll
        (≤0.2 s) drains the buffer soon enough for a parked-later pod,
        and under sharded dispatch a notify_all per event per lane wakes
        N idle dispatch workers into a GIL stampede that costs more than
        the scheduling work itself."""
        with self._lock:
            self._pending_moves[resource] = \
                self._pending_moves.get(resource, 0) | action
            # the cycle-scoped mask makes the at-least-once contract
            # SYNCHRONOUS for the in-flight pod: whenever its failing
            # cycle parks, the park-time check replays every event
            # recorded here since its pop (add_unschedulable_if_not_
            # present) — no wall-clock drain tick involved
            if self._in_cycle > 0:
                self._cycle_moves[resource] = \
                    self._cycle_moves.get(resource, 0) | action
            if self._unschedulable or self._backoff_keys:
                self._lock.notify_all()

    def _apply_pending_moves_locked(self) -> None:
        if not self._pending_moves:
            return
        pending, self._pending_moves = self._pending_moves, {}
        now = self._clock()
        moved = []
        for key, info in list(self._unschedulable.items()):
            if any(self._event_unsticks(info, resource, mask)
                   for resource, mask in pending.items()):
                del self._unschedulable[key]
                moved.append(info)
        for info in moved:
            expiry = info.timestamp + info.backoff_duration(
                self._initial_backoff_s, self._max_backoff_s)
            if expiry <= now:
                self._active.push(info)
            else:
                heapq.heappush(self._backoff,
                               (expiry, next(self._backoff_seq), info))
                self._bk_add_locked(info.pod.key)
                self._handle_clock.arm("backoff", expiry, wall=True)
        if moved:
            self._lock.notify_all()

    def _event_unsticks(self, info: QueuedPodInfo, resource: str, action: int) -> bool:
        if not info.unschedulable_plugins:
            return True
        for plugin in info.unschedulable_plugins:
            for ev in self._cluster_event_map.get(plugin, []):
                if ev.matches(resource, action):
                    return True
        return False

    # -- consumer -------------------------------------------------------------

    def _flush_locked(self) -> None:
        self._apply_pending_moves_locked()
        now = self._clock()
        while self._backoff and self._backoff[0][0] <= now:
            _, _, info = heapq.heappop(self._backoff)
            if info is not None:
                self._bk_del_locked(info.pod.key)
                self._active.push(info)
        if self._flush_s <= 0:
            return          # event-driven retries only (replay determinism)
        for key, info in list(self._unschedulable.items()):
            if now - info.timestamp > self._flush_s:
                del self._unschedulable[key]
                self._active.push(info)

    def _pop_preferred_locked(self) -> Optional[QueuedPodInfo]:
        """Pop the next pod, preferring a remaining sibling of the gang the
        LAST pop served (so the equivalence cache's cursor chain stays
        unbroken across the gang's burst). The preference never jumps the
        priority order: a sibling is taken over the heap top only when both
        have the same priority — within one priority band QueueSort order is
        a throughput policy, not a correctness contract."""
        top = self._active.peek()
        if top is None:
            return None
        last = self._last_gang
        info = None
        if last is not None and _gang_of(top) != last:
            key = self._active.gang_member(last)
            if key is None:
                self._last_gang = None
            else:
                sibling = self._active.get(key)
                if (sibling is not None
                        and sibling.pod.priority == top.pod.priority):
                    info = self._active.remove(key)
        if info is None:
            info = self._active.pop()
        self._last_gang = _gang_of(info) if info is not None else None
        return info

    def pop(self, timeout: Optional[float] = None) -> Optional[QueuedPodInfo]:
        # tpulint: disable=monotonic-clock — the pop timeout bounds REAL
        # blocking of the consumer thread (live surface), not a
        # scheduling gate; virtual replay drives pop(timeout=0)
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            while True:
                if self._closed:
                    return None
                self._flush_locked()
                info = self._pop_preferred_locked()
                if info is not None:
                    info.attempts += 1
                    self._in_cycle += 1
                    # a fresh cycle starts: the park-time move check
                    # covers events from HERE on (one consumer per lane
                    # by design, so the mask is this cycle's)
                    self._cycle_moves = {}
                    return info
                wait = 0.2
                if self._backoff:
                    wait = min(wait, max(0.0, self._backoff[0][0] - self._clock()))
                if deadline is not None:
                    # tpulint: disable=monotonic-clock — same real-wait
                    # bound as the deadline computation above
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return None
                    wait = min(wait, remaining)
                self._lock.wait(wait)

    def close(self) -> None:
        with self._lock:
            self._closed = True
            self._lock.notify_all()

    # -- introspection --------------------------------------------------------

    def pending_pods(self) -> List[Pod]:
        with self._lock:
            self._apply_pending_moves_locked()
            out = [i.pod for i in self._active.items()]
            out += [i.pod for (_, _, i) in self._backoff if i is not None]
            out += [i.pod for i in self._unschedulable.values()]
            return out


class ShardedQueues:
    """Per-lane SchedulingQueue fan-out for the sharded dispatch core
    (sched/shards.py): one full SchedulingQueue per dispatch lane — the
    shard lanes plus the serialized global lane — behind the exact
    producer/observer surface the single queue exposes, so the scheduler's
    informer wiring, watchdog, gauges and failure paths are lane-agnostic.

    Routing happens at the producer boundary: ``add`` and
    ``requeue_after_failure`` ask the injected ``route(pod) -> lane``
    (sched/shards.ShardRouter) where the pod belongs NOW — escalations and
    quota-mode flips change a pod's lane between attempts, and re-routing
    on every (re)enqueue is what carries the pod across.  Broadcast
    operations (cluster-event moves, activation, update, delete) fan out
    to every lane: each inner call is O(1)-ish when the pod is absent, and
    lane count is single digits.  A pod lives in at most one lane at a
    time because every enqueue path routes first.

    Consumers pop from THEIR lane only (``pop(lane, ...)``); each lane
    keeps the single queue's full semantics — gang-sibling pop preference,
    coalesced moves, backoff, periodic flush."""

    def __init__(self, lanes: List[str], make_queue, route):
        self._order = list(lanes)
        self._queues: Dict[str, SchedulingQueue] = {
            lane: make_queue() for lane in lanes}
        self._route = route
        # pod key → lane last enqueued into: update/delete touch ONE
        # lane's lock instead of broadcasting across all of them — the
        # informer fan-out (which runs pod deletes inline on the watch
        # thread) must not pay lane-count × lock hops per event.  GIL-
        # atomic dict ops; a racy read at worst falls back to broadcast.
        self._where: Dict[str, str] = {}
        self._closed = False

    # -- producers (routed) ---------------------------------------------------

    def add(self, pod: Pod) -> None:
        lane = self._route(pod)
        self._where[pod.key] = lane
        self._queues[lane].add(pod)

    def requeue_after_failure(self, info: QueuedPodInfo,
                              to_backoff: bool = False,
                              delay_s: Optional[float] = None) -> None:
        lane = self._route(info.pod)
        self._where[info.pod.key] = lane
        self._queues[lane].requeue_after_failure(
            info, to_backoff=to_backoff, delay_s=delay_s)

    def push_active(self, info: QueuedPodInfo, lane: str) -> None:
        """Escalation / re-route hop: inject straight into ``lane``'s
        activeQ."""
        self._where[info.pod.key] = lane
        self._queues[lane].push_active(info)

    # -- keyed (single-lane via the location map) -----------------------------

    def update(self, pod: Pod) -> None:
        lane = self._where.get(pod.key)
        if lane is not None:
            self._queues[lane].update(pod)
            return
        for q in self._queues.values():
            q.update(pod)

    def delete(self, pod: Pod) -> None:
        lane = self._where.pop(pod.key, None)
        if lane is not None:
            self._queues[lane].delete(pod)
            return
        for q in self._queues.values():
            q.delete(pod)

    def activate(self, pods: List[Pod]) -> None:
        for q in self._queues.values():
            q.activate(pods)

    def move_all_to_active_or_backoff(self, resource: str,
                                      action: int) -> None:
        for q in self._queues.values():
            q.move_all_to_active_or_backoff(resource, action)

    def close(self) -> None:
        self._closed = True
        for q in self._queues.values():
            q.close()

    # -- consumers ------------------------------------------------------------

    def pop(self, timeout: Optional[float] = None,
            lane: Optional[str] = None) -> Optional[QueuedPodInfo]:
        """Pop from one lane.  ``lane=None`` (compatibility callers:
        tests driving cycles by hand) serves the first non-empty lane;
        like the single queue, ``timeout=None`` blocks until a pod
        arrives or the queues close."""
        if lane is not None:
            return self._queues[lane].pop(timeout=timeout)
        if timeout is None:
            deadline = None
        else:
            # tpulint: disable=monotonic-clock — real-wait bound for the
            # compatibility polling pop (live surface, not a gate)
            deadline = time.monotonic() + timeout
        while True:
            for name in self._order:
                info = self._queues[name].pop(timeout=0)
                if info is not None:
                    return info
            if self._closed:
                return None
            # tpulint: disable=monotonic-clock — same real-wait bound
            if deadline is not None and time.monotonic() >= deadline:
                return None
            time.sleep(0.005)

    # -- introspection --------------------------------------------------------

    def lanes(self) -> List[str]:
        return list(self._order)

    def lane_queue(self, lane: str) -> SchedulingQueue:
        return self._queues[lane]

    def cycle_done(self, lane: Optional[str] = None) -> None:
        """Pair of pop(lane=...): dispatch loops report cycle completion
        back to the lane they popped from.  (lane=None compatibility pops
        have no dispatch loop and never report; their counter drift is
        invisible outside the replay barrier, which drives real loops.)"""
        if lane is not None:
            self._queues[lane].cycle_done()

    def in_cycle(self) -> int:
        return sum(q.in_cycle() for q in self._queues.values())

    def pending_counts(self) -> Dict[str, int]:
        total = {"active": 0, "backoff": 0, "unschedulable": 0}
        for q in self._queues.values():
            for k, v in q.pending_counts().items():
                total[k] += v
        return total

    def pending_counts_by_lane(self) -> Dict[str, Dict[str, int]]:
        return {lane: q.pending_counts()
                for lane, q in self._queues.items()}

    def pending_pods(self) -> List[Pod]:
        out: List[Pod] = []
        for name in self._order:
            out.extend(self._queues[name].pending_pods())
        return out
