"""Preemption evaluator — rebuild of the vendored upstream
k8s.io/kubernetes/pkg/scheduler/framework/preemption the reference's
CapacityScheduling and PreemptionToleration plug into (SURVEY §3.3).

Flow (preemption.Evaluator.Preempt):
1. re-fetch the preemptor; plugin-specific PodEligibleToPreemptOthers;
2. dry-run candidates on every node the filters called Unschedulable (not
   Unresolvable): clone CycleState + NodeInfo, plugin SelectVictimsOnNode;
3. pick the best candidate (fewest PDB violations → lowest max victim
   priority → lowest priority sum → fewest victims → name);
4. prepare: delete victims (rejecting waiting ones), clear lower-priority
   nominations on the node;
5. return the nominated node.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple

from ..api.core import Pod, PodDisruptionBudget
from ..apiserver import server as srv
from ..fwk import CycleState, Status
from ..fwk.interfaces import PostFilterResult
from ..fwk.nodeinfo import NodeInfo
from ..fwk.status import UNSCHEDULABLE, UNSCHEDULABLE_AND_UNRESOLVABLE
from ..util import klog
from ..util.metrics import preemption_attempts


def more_important_pod(p1: Pod, p2: Pod) -> bool:
    """upstream schedutil.MoreImportantPod: higher priority, then earlier
    start time."""
    if p1.priority != p2.priority:
        return p1.priority > p2.priority
    t1 = p1.status.start_time or p1.meta.creation_timestamp
    t2 = p2.status.start_time or p2.meta.creation_timestamp
    return t1 < t2


class GangDisruptionFloor:
    """PodGroup.minMember as a hard disruption floor for SINGLE-NODE victim
    selection: evicting one member of a running gang leaves the survivors
    burning their chips below quorum — the stranded-gang failure the
    randomized soak caught (I3: a 16-member slice gang degraded to 15/16 by
    a quota preemption of one pod). The rule: a victim may be evicted only
    if its gang stays ≥ minMember afterwards, or drops to exactly ZERO
    bound members (all-or-nothing both ways). Whole-gang eviction remains
    the WINDOW path's job (TopologyMatch slice preemption, which takes a
    gang's entire torus block coherently); the single-node evaluators must
    not produce the in-between states.

    Instantiate per select_victims_on_node call: the running count makes
    multiple same-gang victims on one node compose correctly, and the
    reprieve loop can only REDUCE evictions, so the floor holds through it.
    No reference analog — upstream's evaluator is gang-blind (its
    coscheduling KEP lists exactly this as an open problem)."""

    def __init__(self, handle):
        self.handle = handle
        self._remaining: dict = {}      # gang full name → assigned still left
        self._set_veto: dict = {}       # gang full name → memoized set veto

    def may_evict(self, victim: Pod) -> bool:
        from ..api.scheduling import POD_GROUP_LABEL
        name = victim.meta.labels.get(POD_GROUP_LABEL)
        if not name:
            return True
        full = f"{victim.meta.namespace}/{name}"
        # memoized per gang per plan: the set membership sweep is
        # O(namespace PodGroups) and a 16-member victim gang would
        # otherwise pay it 16 times per candidate node
        vetoed = self._set_veto.get(full)
        if vetoed is None:
            vetoed = atomic_set_eviction_vetoed(
                self.handle, self.handle.snapshot_shared_lister(),
                {(victim.meta.namespace, name): 1})
            self._set_veto[full] = vetoed
        if vetoed:
            return False
        min_member = gang_min_member(self.handle, victim, full)
        remaining = self._remaining.get(full)
        if remaining is None:
            # LIVE members only: a member evicted by an earlier cycle but
            # still draining is not a quorum survivor — counting it would
            # let back-to-back preemptions on different hosts each think
            # the gang can spare one more
            remaining = self.handle.snapshot_shared_lister() \
                .assigned_live_count(name, victim.meta.namespace)
        if remaining < min_member:
            # already below quorum: the gang provides nothing to protect,
            # and an unpreemptable sub-quorum gang would pin its chips
            # forever — freely evictable
            self._remaining[full] = remaining - 1
            return True
        if remaining - 1 >= min_member or remaining <= 1:
            self._remaining[full] = remaining - 1
            return True
        return False


def atomic_set_eviction_vetoed(handle, snapshot, victim_counts) -> bool:
    """The SET-level disruption floor (the gang floor one level up): a gang
    belonging to an atomic multislice set (multislice_set_size > 1) may
    only lose members if every OTHER member gang of its set is also going
    to zero — otherwise the surviving slices burn their chips waiting for
    a sibling that admission's all-or-nothing barrier will never replace
    piecemeal. Caught by the randomized soak (seed 7: window preemption
    evicted one slice of a bound 2-slice set; the survivor strands
    forever — I5).

    ``victim_counts``: {(namespace, gang_name): members evicted by this
    plan}. Returns True when the plan must be vetoed.

    Only an INTACT set is protected — every member gang at or above its
    own quorum. A set with any member already sub-quorum (node crash, job
    that never recreated its pods) provides nothing to protect, and
    vetoing there would pin the survivors' chips below every priority
    forever — the exact pinned-sub-quorum state the gang floor's
    freely-evictable rule exists to prevent, one level up. Its members
    fall through to the plain gang-floor rules (whole-gang-to-zero
    eviction stays possible, so cleanup of a half-dead set works)."""
    if not victim_counts:
        return False
    pgs = handle.informer_factory.podgroups()
    for (ns, g), _n in victim_counts.items():
        pg = pgs.get(f"{ns}/{g}")
        if pg is None or not pg.spec.multislice_set \
                or pg.spec.multislice_set_size <= 1:
            continue
        members = [sib for sib in pgs.items(namespace=ns)
                   if sib.spec.multislice_set == pg.spec.multislice_set]
        intact = len(members) >= pg.spec.multislice_set_size and all(
            snapshot.assigned_live_count(sib.meta.name, ns)
            >= sib.spec.min_member for sib in members)
        if not intact:
            continue
        for sib in members:
            if sib.meta.name == g:
                continue
            evicted = victim_counts.get((ns, sib.meta.name), 0)
            if snapshot.assigned_live_count(sib.meta.name, ns) - evicted > 0:
                return True
    return False


def gang_min_member(handle, member: Pod, full: str) -> int:
    """A gang's quorum: the PodGroup CR's minMember, or — for KEP-2
    label-only synthesized gangs (no CR) — the member's min-available
    label. Shared by the single-node floor and the window veto so the two
    can never diverge on which gangs are protected."""
    from ..api.scheduling import MIN_AVAILABLE_LABEL
    pg = handle.informer_factory.podgroups().get(full)
    if pg is not None:
        return pg.spec.min_member
    try:
        return int(member.meta.labels.get(MIN_AVAILABLE_LABEL, "0"))
    except ValueError:
        return 0


def filter_pods_with_pdb_violation(pods: List[Pod],
                                   pdbs: List[PodDisruptionBudget]
                                   ) -> Tuple[List[Pod], List[Pod]]:
    """Split into (violating, non-violating). A pod violates if some matching
    PDB has no disruptions left (capacity_scheduling.go:857-902)."""
    violating, ok = [], []
    disruptions = {pdb.meta.key: pdb.disruptions_allowed for pdb in pdbs}
    for pod in pods:
        hit = False
        for pdb in pdbs:
            if pdb.matches(pod):
                if disruptions.get(pdb.meta.key, 0) <= 0:
                    hit = True
                else:
                    disruptions[pdb.meta.key] -= 1
        (violating if hit else ok).append(pod)
    return violating, ok


class Candidate:
    __slots__ = ("node_name", "victims", "num_pdb_violations")

    def __init__(self, node_name: str, victims: List[Pod], num_pdb_violations: int):
        self.node_name = node_name
        self.victims = victims
        self.num_pdb_violations = num_pdb_violations


class PreemptionInterface:
    """The plugin-provided policy (upstream preemption.Interface)."""

    def pod_eligible_to_preempt_others(self, pod: Pod,
                                       nominated_node_status: Optional[Status]) -> bool:
        return True

    def select_victims_on_node(self, state: CycleState, pod: Pod,
                               node_info: NodeInfo,
                               pdbs: List[PodDisruptionBudget]
                               ) -> Tuple[List[Pod], int, Status]:
        raise NotImplementedError


class Evaluator:
    def __init__(self, plugin_name: str, handle, state: CycleState,
                 interface: PreemptionInterface):
        self.plugin_name = plugin_name
        self.handle = handle
        self.state = state
        self.interface = interface

    # -- main entry -----------------------------------------------------------

    def preempt(self, pod: Pod, diagnosis: Dict[str, Status]
                ) -> Tuple[Optional[PostFilterResult], Status]:
        preemption_attempts.inc()
        live = self.handle.clientset.pods.try_get(pod.key)
        if live is None:
            return None, Status.unschedulable(f"pod {pod.key} not found")
        pod = live

        nominated_status = diagnosis.get(pod.status.nominated_node_name)
        if not self.interface.pod_eligible_to_preempt_others(pod, nominated_status):
            return None, Status.unschedulable(
                f"pod {pod.key} is not eligible for preemption")

        candidates = self._find_candidates(pod, diagnosis)
        if not candidates:
            return None, Status.unschedulable(
                "preemption: 0/%d nodes are available" % max(1, len(diagnosis)))

        best = self._select_candidate(candidates)
        status = self._prepare_candidate(best, pod)
        if not status.is_success():
            return None, status
        return PostFilterResult(nominated_node_name=best.node_name), Status.success()

    # -- candidate search -----------------------------------------------------

    def _find_candidates(self, pod: Pod,
                         diagnosis: Dict[str, Status]) -> List[Candidate]:
        snapshot = self.handle.snapshot_shared_lister()
        pdbs = self.handle.clientset.pdbs.list()
        candidates: List[Candidate] = []
        for node_name, st in diagnosis.items():
            # preemption cannot resolve Unresolvable rejections
            if st.code != UNSCHEDULABLE:
                continue
            info = snapshot.get(node_name)
            if info is None or info.node is None:
                continue
            state_copy = self.state.clone()
            info_copy = info.clone()
            victims, violations, vs = self.interface.select_victims_on_node(
                state_copy, pod, info_copy, pdbs)
            if vs.is_success() and victims:
                candidates.append(Candidate(node_name, victims, violations))
        return candidates

    def _select_candidate(self, candidates: List[Candidate]) -> Candidate:
        """upstream pickOneNodeForPreemption ordering."""
        def key(c: Candidate):
            max_prio = max((v.priority for v in c.victims), default=0)
            sum_prio = sum(v.priority for v in c.victims)
            return (c.num_pdb_violations, max_prio, sum_prio,
                    len(c.victims), c.node_name)
        return min(candidates, key=key)

    # -- execution ------------------------------------------------------------

    def _prepare_candidate(self, candidate: Candidate, pod: Pod) -> Status:
        cs = self.handle.clientset
        for victim in candidate.victims:
            # a waiting gang member is rejected in place; others are deleted
            if self.handle.reject_waiting_pod(
                    victim.meta.uid, self.plugin_name,
                    f"preempted by {pod.key}"):
                klog.V(3).info_s("rejected waiting victim", victim=victim.key)
            else:
                try:
                    cs.pods.delete(victim.key)
                except srv.NotFound:
                    pass
            cs.record_event(victim.key, "Pod", "Normal", "Preempted",
                            f"Preempted by {pod.key} on node {candidate.node_name}")
            klog.V(3).info_s("preempted victim", victim=victim.key,
                             node=candidate.node_name, preemptor=pod.key)
        # lower-priority nominated pods on this node lose their nomination
        for np in self.handle.pod_nominator.nominated_pods_for_node(candidate.node_name):
            if np.priority < pod.priority:
                self.handle.pod_nominator.delete_nominated_pod_if_exists(np)
                try:
                    cs.pods.patch(np.key, lambda p: setattr(
                        p.status, "nominated_node_name", ""))
                except srv.NotFound:
                    pass
        return Status.success()


# -- shared victim-selection helpers (used by plugin Interfaces) --------------

def dry_run_remove(handle, state: CycleState, preemptor: Pod, victim: Pod,
                   node_info: NodeInfo) -> Optional[Status]:
    if not node_info.remove_pod(victim):
        return Status.error(f"victim {victim.key} not on node")
    s = handle.framework.run_pre_filter_extension_remove_pod(
        state, preemptor, victim, node_info)
    return None if s.is_success() else s


def dry_run_add(handle, state: CycleState, preemptor: Pod, victim: Pod,
                node_info: NodeInfo) -> Optional[Status]:
    node_info.add_pod(victim)
    s = handle.framework.run_pre_filter_extension_add_pod(
        state, preemptor, victim, node_info)
    return None if s.is_success() else s


def reprieve_victims(handle, state: CycleState, pod: Pod, node_info: NodeInfo,
                     potential: List[Pod], pdbs: List[PodDisruptionBudget],
                     extra_infeasible: Optional[Callable[[], bool]] = None,
                     ) -> Tuple[List[Pod], int, Status]:
    """The PDB-aware reprieve loop shared by quota preemption and preemption
    toleration (the reference's defaultpreemption bottom half,
    capacity_scheduling.go:597-642 / preemption_toleration.go:285-407):
    add candidates back highest-priority-first; a candidate stays reprieved if
    the preemptor still fits (and `extra_infeasible`, e.g. the quota-max
    check, stays false); otherwise it becomes a victim. Returns
    (victims, num_violating_pdb, status). `potential` must already be removed
    from `node_info` via dry_run_remove."""
    victims: List[Pod] = []
    num_violating = 0
    potential.sort(key=lambda p: (-p.priority,
                                  p.status.start_time or p.meta.creation_timestamp))
    violating, non_violating = filter_pods_with_pdb_violation(potential, pdbs)

    def reprieve(p: Pod) -> bool:
        err = dry_run_add(handle, state, pod, p, node_info)
        if err:
            raise _ReprieveError(err.message())
        fits = handle.run_filter_plugins_with_nominated_pods(
            state, pod, node_info).is_success()
        ok = fits and not (extra_infeasible() if extra_infeasible else False)
        if not ok:
            err = dry_run_remove(handle, state, pod, p, node_info)
            if err:
                raise _ReprieveError(err.message())
            victims.append(p)
        return ok

    try:
        for p in violating:
            if not reprieve(p):
                num_violating += 1
        for p in non_violating:
            reprieve(p)
    except _ReprieveError as e:
        return [], 0, Status.error(str(e))
    return victims, num_violating, Status.success()


class _ReprieveError(RuntimeError):
    pass
