"""Scheduler: queue, cache, scheduleOne loop (the hot path of SURVEY §3.2)."""
from .queue import QueuedPodInfo, SchedulingQueue
from .cache import Cache
from .scheduler import Scheduler

__all__ = ["QueuedPodInfo", "SchedulingQueue", "Cache", "Scheduler"]
